"""Distributed linalg vs local numpy golden values, on an 8-device CPU mesh
(the reference's local-partitions-stand-in-for-cluster strategy)."""

import numpy as np
import pytest

import jax

from keystone_tpu.parallel import linalg
from keystone_tpu.parallel.mesh import make_mesh, use_mesh
from keystone_tpu.utils.testing import assert_about_eq


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def rand(shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


def test_mesh_has_8_devices(mesh):
    assert len(jax.devices()) == 8
    assert mesh.shape["data"] == 8


def test_gram(mesh):
    a = rand((64, 12))
    b = rand((64, 3), seed=1)
    with use_mesh(mesh):
        A = linalg.prepare_row_sharded(a)
        B = linalg.prepare_row_sharded(b)
        ata, atb = linalg.gram(A, B)
    np.testing.assert_allclose(np.asarray(ata), a.T @ a, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(atb), a.T @ b, rtol=1e-4, atol=1e-4)


def test_gram_with_padding(mesh):
    a = rand((61, 5))  # 61 not divisible by 8 → zero-padded
    with use_mesh(mesh):
        A = linalg.prepare_row_sharded(a)
        assert A.shape[0] == 64
        ata, _ = linalg.gram(A)
    np.testing.assert_allclose(np.asarray(ata), a.T @ a, rtol=1e-4, atol=1e-4)


def test_normal_equations_solve(mesh):
    a = rand((128, 10))
    x_true = rand((10, 4), seed=2)
    b = a @ x_true
    with use_mesh(mesh):
        A = linalg.prepare_row_sharded(a)
        B = linalg.prepare_row_sharded(b)
        x = linalg.normal_equations_solve(A, B, reg=0.0)
    np.testing.assert_allclose(np.asarray(x), x_true, rtol=1e-2, atol=1e-3)


def test_ridge_matches_closed_form(mesh):
    a = rand((96, 8))
    b = rand((96, 2), seed=3)
    lam = 0.5
    expected = np.linalg.solve(a.T @ a + lam * np.eye(8), a.T @ b)
    with use_mesh(mesh):
        x = linalg.normal_equations_solve(
            linalg.prepare_row_sharded(a), linalg.prepare_row_sharded(b), reg=lam
        )
    np.testing.assert_allclose(np.asarray(x), expected, rtol=1e-3, atol=1e-3)


def test_tsqr_r_gram_identity(mesh):
    """RᵀR must equal AᵀA (QR correctness without fixing R's sign)."""
    a = rand((80, 6))
    with use_mesh(mesh):
        r = linalg.tsqr_r(linalg.prepare_row_sharded(a))
    np.testing.assert_allclose(np.asarray(r.T @ r), a.T @ a, rtol=1e-3, atol=1e-3)


def test_tsqr_svd_matches_local(mesh):
    a = rand((120, 7))
    _, s_expected, vt_expected = np.linalg.svd(a, full_matrices=False)
    with use_mesh(mesh):
        s, vt = linalg.tsqr_svd(linalg.prepare_row_sharded(a))
    np.testing.assert_allclose(np.asarray(s), s_expected, rtol=1e-3, atol=1e-3)
    # columns defined up to sign
    for i in range(7):
        vi, wi = np.asarray(vt)[i], vt_expected[i]
        assert min(np.linalg.norm(vi - wi), np.linalg.norm(vi + wi)) < 1e-2


def test_bcd_converges_to_ridge_solution(mesh):
    a = rand((160, 12))
    x_true = rand((12, 3), seed=5)
    y = a @ x_true
    lam = 0.1
    expected = np.linalg.solve(a.T @ a + lam * np.eye(12), a.T @ y)
    with use_mesh(mesh):
        w = linalg.block_coordinate_descent(
            linalg.prepare_row_sharded(a),
            linalg.prepare_row_sharded(y),
            reg=lam,
            num_epochs=30,
            block_size=4,
        )
    np.testing.assert_allclose(np.asarray(w), expected, rtol=5e-2, atol=5e-3)


def test_bcd_single_block_equals_exact(mesh):
    """One epoch, one block == exact normal-equation solve."""
    a = rand((64, 6))
    y = rand((64, 2), seed=7)
    lam = 0.3
    expected = np.linalg.solve(a.T @ a + lam * np.eye(6), a.T @ y)
    with use_mesh(mesh):
        w = linalg.block_coordinate_descent(
            linalg.prepare_row_sharded(a),
            linalg.prepare_row_sharded(y),
            reg=lam,
            num_epochs=1,
            block_size=6,
        )
    np.testing.assert_allclose(np.asarray(w), expected, rtol=1e-3, atol=1e-3)


# --------------------------------------------------------- hybrid (DCN) mesh


def test_hybrid_mesh_hierarchical_gram():
    """A (replica, data) mesh reduces over both tiers — the multi-slice
    (ICI + DCN) layout of SURVEY §2.10 on virtual devices."""
    import jax
    import numpy as np

    from keystone_tpu.parallel import linalg
    from keystone_tpu.parallel.mesh import (
        REPLICA_AXIS,
        make_hybrid_mesh,
        row_axes,
        row_shard_count,
    )

    mesh = make_hybrid_mesh(num_replicas=2, devices=jax.devices()[:8])
    assert mesh.shape[REPLICA_AXIS] == 2
    assert row_axes(mesh) == (REPLICA_AXIS, "data")
    assert row_shard_count(mesh) == 8

    rng = np.random.default_rng(0)
    a = rng.standard_normal((64, 12)).astype(np.float32)
    b = rng.standard_normal((64, 3)).astype(np.float32)
    asd = linalg.prepare_row_sharded(a, mesh)
    bsd = linalg.prepare_row_sharded(b, mesh)
    ata, atb = linalg.gram(asd, bsd, mesh=mesh)
    np.testing.assert_allclose(np.asarray(ata), a.T @ a, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(atb), a.T @ b, rtol=1e-4, atol=1e-4)


def test_hybrid_mesh_bcd_matches_closed_form():
    import jax
    import numpy as np

    from keystone_tpu.parallel import linalg
    from keystone_tpu.parallel.mesh import make_hybrid_mesh

    mesh = make_hybrid_mesh(num_replicas=2, devices=jax.devices()[:8])
    rng = np.random.default_rng(1)
    a = rng.standard_normal((64, 8)).astype(np.float32)
    y = rng.standard_normal((64, 2)).astype(np.float32)
    asd = linalg.prepare_row_sharded(a, mesh)
    ysd = linalg.prepare_row_sharded(y, mesh)
    w = np.asarray(
        linalg.block_coordinate_descent(
            asd, ysd, reg=0.1, num_epochs=30, block_size=4, mesh=mesh
        )
    )
    want = np.linalg.solve(a.T @ a + 0.1 * np.eye(8), a.T @ y)
    np.testing.assert_allclose(w, want, rtol=1e-3, atol=1e-3)


def test_hybrid_mesh_tsqr():
    import jax
    import numpy as np

    from keystone_tpu.parallel import linalg
    from keystone_tpu.parallel.mesh import make_hybrid_mesh

    mesh = make_hybrid_mesh(num_replicas=2, devices=jax.devices()[:8])
    rng = np.random.default_rng(2)
    a = rng.standard_normal((64, 6)).astype(np.float32)
    r = np.asarray(linalg.tsqr_r(linalg.prepare_row_sharded(a, mesh), mesh=mesh))
    # RᵀR == AᵀA exactly (QR sign ambiguity cancels in the product)
    np.testing.assert_allclose(r.T @ r, a.T @ a, rtol=1e-3, atol=1e-3)


def test_all_to_all_shard_transpose():
    """all_to_all = the Spark shuffle analog (SURVEY §2.10)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from keystone_tpu.parallel.collectives import all_to_all, shard_map
    from keystone_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(devices=jax.devices()[:4])
    x = np.arange(16, dtype=np.float32).reshape(16, 1)

    def f(x_local):  # (4, 1) per device
        return all_to_all(x_local, split_axis=0, concat_axis=0)

    out = jax.jit(
        shard_map(f, mesh=mesh, in_specs=P("data", None), out_specs=P("data", None))
    )(x)
    # device i ends with rows [i, 4+i, 8+i, 12+i] — a (4,4) shard transpose
    got = np.asarray(out).reshape(4, 4)
    want = np.arange(16, dtype=np.float32).reshape(4, 4).T
    np.testing.assert_array_equal(got, want)


# ------------------------------------------------------ 2-D (data, model) mesh


@pytest.fixture(scope="module")
def mesh2d():
    from keystone_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    return make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS), devices=jax.devices()[:8])


def test_bcd_2d_matches_closed_form(mesh2d):
    """Column-sharded A + model-sharded W converge to the same ridge
    solution as the closed form — the VERDICT item 4 acceptance test."""
    a = rand((64, 32), seed=11)
    x_true = rand((32, 3), seed=12)
    y = a @ x_true
    lam = 0.1
    expected = np.linalg.solve(a.T @ a + lam * np.eye(32), a.T @ y)
    asd = linalg.prepare_block_sharded(a, mesh2d)
    ysd = linalg.prepare_block_sharded(y, mesh2d, fine_rows=True)
    w = np.asarray(
        linalg.block_coordinate_descent_2d(
            asd, ysd, reg=lam, num_epochs=40, block_size=8, mesh=mesh2d
        )
    )
    assert_about_eq(w, expected, thresh=5e-2)


def test_bcd_2d_w_is_model_sharded(mesh2d):
    from jax.sharding import PartitionSpec as P

    from keystone_tpu.parallel.mesh import MODEL_AXIS

    a = rand((32, 16), seed=13)
    y = rand((32, 2), seed=14)
    asd = linalg.prepare_block_sharded(a, mesh2d)
    ysd = linalg.prepare_block_sharded(y, mesh2d, fine_rows=True)
    w = linalg.block_coordinate_descent_2d(
        asd, ysd, reg=0.2, num_epochs=5, block_size=4, mesh=mesh2d
    )
    assert w.sharding.is_equivalent_to(
        jax.sharding.NamedSharding(mesh2d, P(MODEL_AXIS, None)), w.ndim
    )


def test_bcd_2d_single_pass_matches_1d_order(mesh2d):
    """With one block per model group the 2-D update order degenerates to
    the sequential order, so a single epoch must match the 1-D solver
    bit-for-tolerance."""
    a = rand((64, 8), seed=15)
    y = rand((64, 2), seed=16)
    lam = 0.3
    mesh1d = make_mesh(devices=jax.devices()[:8])
    w1 = np.asarray(
        linalg.block_coordinate_descent(
            linalg.prepare_row_sharded(a, mesh1d),
            linalg.prepare_row_sharded(y, mesh1d),
            reg=lam, num_epochs=1, block_size=4, mesh=mesh1d,
        )
    )
    w2 = np.asarray(
        linalg.block_coordinate_descent_2d(
            linalg.prepare_block_sharded(a, mesh2d),
            linalg.prepare_block_sharded(y, mesh2d, fine_rows=True),
            reg=lam, num_epochs=1, block_size=4, mesh=mesh2d,
        )
    )
    assert_about_eq(w2, w1, thresh=1e-3)


def test_block_sharded_apply_matches_matmul(mesh2d):
    a = rand((48, 16), seed=17)
    w = rand((16, 5), seed=18)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.parallel.mesh import MODEL_AXIS

    asd = linalg.prepare_block_sharded(a, mesh2d)
    wsd = jax.device_put(w, NamedSharding(mesh2d, P(MODEL_AXIS, None)))
    got = np.asarray(linalg.block_sharded_apply(asd, wsd, mesh=mesh2d))
    assert_about_eq(got, a @ w)


def test_block_estimator_on_2d_mesh(mesh2d):
    """BlockLeastSquaresEstimator transparently uses the 2-D path when the
    active mesh has a model axis, and matches the centered closed form."""
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator

    a = rand((64, 16), seed=19)
    x_true = rand((16, 3), seed=20)
    y = a @ x_true
    with use_mesh(mesh2d):
        model = BlockLeastSquaresEstimator(8, num_iter=30, reg=0.1).fit(
            ArrayDataset(a), ArrayDataset(y)
        )
        preds = np.asarray(model.apply_arrays(a))
    ac = a - a.mean(axis=0)
    yc = y - y.mean(axis=0)
    w_want = np.linalg.solve(ac.T @ ac + 0.1 * np.eye(16), ac.T @ yc)
    want = ac @ w_want + y.mean(axis=0)
    np.testing.assert_allclose(preds, want, rtol=5e-2, atol=5e-2)


# ------------------------------------------------------------ streaming BCD


def test_streaming_bcd_matches_in_core():
    """Host-streamed feature blocks (beyond-HBM path) solve to the same
    weights as the in-core compiled BCD, including centering and a short
    last block."""
    import jax.numpy as jnp

    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator

    rng = np.random.default_rng(0)
    n, d, k = 200, 50, 4  # d=50, block 16 -> short last block
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, k)).astype(np.float32)
    mesh = make_mesh(devices=jax.devices()[:8])
    with use_mesh(mesh):
        m_core = BlockLeastSquaresEstimator(
            16, num_iter=3, reg=0.1, host_streaming=False
        ).fit(ArrayDataset(x), ArrayDataset(y))
        m_stream = BlockLeastSquaresEstimator(
            16, num_iter=3, reg=0.1, host_streaming=True
        ).fit(ArrayDataset(x), ArrayDataset(y))
        p1 = np.asarray(m_core.apply_arrays(jnp.asarray(x)))
        p2 = np.asarray(m_stream.apply_arrays(jnp.asarray(x)))
    np.testing.assert_allclose(p1, p2, atol=1e-5)


def test_streaming_bcd_improves_residual_over_epochs():
    from keystone_tpu.parallel import linalg

    rng = np.random.default_rng(1)
    n, d, k = 160, 24, 2
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    y = x @ w_true
    mesh = make_mesh(devices=jax.devices()[:8])
    with use_mesh(mesh):
        w1, mu_a, mu_b = linalg.block_coordinate_descent_streaming(
            x, y, reg=1e-6, num_epochs=1, block_size=8, mesh=mesh
        )
        w5, _, _ = linalg.block_coordinate_descent_streaming(
            x, y, reg=1e-6, num_epochs=5, block_size=8, mesh=mesh
        )
    xc = x - np.asarray(mu_a)
    yc = y - np.asarray(mu_b)
    r1 = np.linalg.norm(xc @ np.asarray(w1) - yc)
    r5 = np.linalg.norm(xc @ np.asarray(w5) - yc)
    assert r5 < r1
    assert r5 < 1e-2 * np.linalg.norm(yc)


def test_centered_solve_refined_matches_unrefined_when_well_conditioned(mesh):
    a = rand((120, 10))
    b = rand((120, 3), seed=4)
    with use_mesh(mesh):
        A = linalg.prepare_row_sharded(a)
        B = linalg.prepare_row_sharded(b)
        w0, mu_a, mu_b = linalg.centered_solve_refined(A, B, 120, 0.1)
        w2, _, _ = linalg.centered_solve_refined(A, B, 120, 0.1, refine_steps=2)
    # float64 centered ridge reference
    a64, b64 = a.astype(np.float64), b.astype(np.float64)
    ac, bc = a64 - a64.mean(0), b64 - b64.mean(0)
    expect = np.linalg.solve(ac.T @ ac + 0.1 * np.eye(10), ac.T @ bc)
    np.testing.assert_allclose(np.asarray(w0), expect, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(w2), expect, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(mu_a), a.mean(0), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(mu_b), b.mean(0), rtol=1e-4, atol=1e-5)


def test_refinement_recovers_ill_conditioned_accuracy(mesh):
    """The mixed-precision IR mechanism: with an ill-conditioned A, the
    fp32 Cholesky's forward error is large; two refinement steps (residual
    recomputed from A itself) must shrink it by orders of magnitude —
    the same mechanism that recovers the fast-Gram error on TPU."""
    rng = np.random.default_rng(0)
    n, d, k = 512, 32, 4
    u, _ = np.linalg.qr(rng.normal(size=(n, d)))
    v, _ = np.linalg.qr(rng.normal(size=(d, d)))
    a = ((u * np.logspace(0, -3, d)) @ v.T).astype(np.float32)
    b = (a @ rng.normal(size=(d, k)) + 0.01 * rng.normal(size=(n, k))).astype(
        np.float32
    )
    a64, b64 = a.astype(np.float64), b.astype(np.float64)
    ac, bc = a64 - a64.mean(0), b64 - b64.mean(0)
    lam = 1e-8
    w64 = np.linalg.solve(ac.T @ ac + lam * np.eye(d), ac.T @ bc)
    with use_mesh(mesh):
        A = linalg.prepare_row_sharded(a)
        B = linalg.prepare_row_sharded(b)
        w0, _, _ = linalg.centered_solve_refined(A, B, n, lam, refine_steps=0)
        w2, _, _ = linalg.centered_solve_refined(A, B, n, lam, refine_steps=2)
    e0 = np.linalg.norm(np.asarray(w0) - w64) / np.linalg.norm(w64)
    e2 = np.linalg.norm(np.asarray(w2) - w64) / np.linalg.norm(w64)
    assert e2 < 0.05 * e0, (e0, e2)
    assert e2 < 1e-4


def test_rematerialized_bcd_matches_materialized(mesh):
    """block_coordinate_descent_rematerialized with a seeded generator
    must equal ordinary BCD on the materialized matrix the generator
    describes (the full-n TIMIT-wide path: features never exist)."""
    import jax.numpy as jnp

    n, d, k, bs = 64, 24, 3, 8
    num_blocks = d // bs
    key = jax.random.PRNGKey(5)

    def block_fn(b, row_offset, rows):
        # Row-offset-keyed generation so every shard produces its own
        # rows of the same global matrix.
        def one_row(r):
            kk = jax.random.fold_in(jax.random.fold_in(key, b), r)
            return jax.random.normal(kk, (bs,), jnp.float32)

        return jax.vmap(one_row)(row_offset + jnp.arange(rows))

    # Materialize the identical matrix on host for the oracle run.
    blocks = [
        np.asarray(block_fn(b, jnp.int32(0), n)) for b in range(num_blocks)
    ]
    a = np.concatenate(blocks, axis=1)
    y = rand((n, k), seed=9)

    with use_mesh(mesh):
        ys = linalg.prepare_row_sharded(y)
        w_remat = linalg.block_coordinate_descent_rematerialized(
            block_fn, ys, reg=0.1, num_epochs=2, block_size=bs,
            num_blocks=num_blocks,
        )
        a_s = linalg.prepare_row_sharded(a)
        w_mat = linalg.block_coordinate_descent(
            a_s, ys, reg=0.1, num_epochs=2, block_size=bs
        )
    np.testing.assert_allclose(
        np.asarray(w_remat), np.asarray(w_mat), rtol=1e-5, atol=1e-6
    )


def test_refine_guard_falls_back_to_highest_on_stalled_refinement(mesh):
    """ADVICE r3 (medium): IR with a bad fast-Gram factor can stall and
    silently return weights worse than a HIGHEST solve. The guard tracks
    the true residual norm and redoes the solve from a HIGHEST-precision
    Gram (same compiled program, lax.cond) when refinement fails to halve
    it. Host CPU ignores matmul precision flags, so the fast Gram is
    corrupted through the _TEST_GRAM_PERTURB seam instead."""
    a = rand((160, 10))
    b = rand((160, 3), seed=9)
    a64, b64 = a.astype(np.float64), b.astype(np.float64)
    ac, bc = a64 - a64.mean(0), b64 - b64.mean(0)
    expect = np.linalg.solve(ac.T @ ac + 0.1 * np.eye(10), ac.T @ bc)
    try:
        linalg._TEST_GRAM_PERTURB = 100.0
        with use_mesh(mesh):
            A = linalg.prepare_row_sharded(a)
            B = linalg.prepare_row_sharded(b)
            # Control: the corrupted Gram with no refinement produces
            # garbage (proves the seam corrupts), no guard to rescue it.
            w_bad, _, _ = linalg.centered_solve_refined(
                A, B, 160, 0.1, gram_precision=jax.lax.Precision.DEFAULT,
                refine_steps=0,
            )
            # Guarded refine path: IR stalls against the corrupted factor,
            # the guard must detect it and return the HIGHEST-Gram solve.
            w, _, _ = linalg.centered_solve_refined(
                A, B, 160, 0.1, gram_precision=jax.lax.Precision.DEFAULT,
                refine_steps=2,
            )
    finally:
        linalg._TEST_GRAM_PERTURB = 0.0
    bad_err = np.linalg.norm(np.asarray(w_bad) - expect) / np.linalg.norm(expect)
    guard_err = np.linalg.norm(np.asarray(w) - expect) / np.linalg.norm(expect)
    assert bad_err > 0.2, bad_err  # seam really corrupted the fast solve
    np.testing.assert_allclose(np.asarray(w), expect, rtol=1e-4, atol=1e-5)
    assert guard_err < 1e-3 * bad_err, (bad_err, guard_err)


def test_centered_solve_refined_with_row_padding(mesh):
    a = rand((61, 6))  # 61 not divisible by 8 → zero-padded rows
    b = rand((61, 2), seed=5)
    with use_mesh(mesh):
        A = linalg.prepare_row_sharded(a)
        B = linalg.prepare_row_sharded(b)
        w, mu_a, mu_b = linalg.centered_solve_refined(
            A, B, 61, 0.05, refine_steps=2
        )
    a64, b64 = a.astype(np.float64), b.astype(np.float64)
    ac, bc = a64 - a64.mean(0), b64 - b64.mean(0)
    expect = np.linalg.solve(ac.T @ ac + 0.05 * np.eye(6), ac.T @ bc)
    np.testing.assert_allclose(np.asarray(w), expect, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(mu_a), a.mean(0), rtol=1e-5, atol=1e-6)
