"""Direct unit coverage for parallel/mesh.py helpers (row_axes,
row_shard_count, hybrid replica meshes, the ambient-mesh machinery) —
the conventions every partitioner decision and sharded solver relies on.
Runs on the 8-virtual-device CPU mesh from tests/conftest.py."""

import numpy as np
import pytest

import jax

from keystone_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    REPLICA_AXIS,
    data_axis_size,
    get_mesh,
    make_hybrid_mesh,
    make_mesh,
    row_axes,
    row_shard_count,
    set_mesh,
    use_mesh,
)


def test_default_mesh_covers_every_device_on_data_axis():
    mesh = make_mesh()
    assert mesh.shape[DATA_AXIS] == len(jax.devices())
    assert row_axes(mesh) == (DATA_AXIS,)
    assert row_shard_count(mesh) == len(jax.devices())


def test_make_mesh_shape_must_cover_devices():
    with pytest.raises(ValueError, match="does not cover"):
        make_mesh((3,), devices=jax.devices()[:8])


def test_make_mesh_2d_data_model_axes():
    mesh = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS), devices=jax.devices()[:8])
    assert mesh.shape[DATA_AXIS] == 4
    assert mesh.shape[MODEL_AXIS] == 2
    # the model axis is NOT a row axis: rows shard over data only
    assert row_axes(mesh) == (DATA_AXIS,)
    assert row_shard_count(mesh) == 4


def test_hybrid_mesh_rows_span_replica_and_data():
    hmesh = make_hybrid_mesh(num_replicas=2, devices=jax.devices()[:8])
    assert hmesh.shape[REPLICA_AXIS] == 2
    assert hmesh.shape[DATA_AXIS] == 4
    assert row_axes(hmesh) == (REPLICA_AXIS, DATA_AXIS)
    assert row_shard_count(hmesh) == 8


def test_hybrid_mesh_rejects_indivisible_replica_count():
    with pytest.raises(ValueError, match="do not divide"):
        make_hybrid_mesh(num_replicas=3, devices=jax.devices()[:8])


def test_hybrid_mesh_defaults_to_process_count_on_cpu():
    # single-process CPU: slice_index is absent, so replicas default to
    # max(1, process_count) == 1 — every device on the data axis.
    hmesh = make_hybrid_mesh(devices=jax.devices()[:4])
    assert hmesh.shape[REPLICA_AXIS] == 1
    assert hmesh.shape[DATA_AXIS] == 4


def test_use_mesh_scopes_and_restores_ambient_mesh():
    outer = get_mesh()
    sub = make_mesh(devices=jax.devices()[:2])
    with use_mesh(sub) as m:
        assert m is sub
        assert get_mesh() is sub
        assert data_axis_size() == 2
    assert get_mesh() is outer


def test_set_mesh_none_rebuilds_default():
    set_mesh(None)
    mesh = get_mesh()
    assert row_shard_count(mesh) == len(jax.devices())


def test_row_sharded_gram_parity_1_vs_8_devices_under_psum():
    """The collective identity the sharded solvers stand on: a row-sharded
    AᵀA psummed over the row axes equals the single-device product."""
    from jax.sharding import PartitionSpec as P

    from keystone_tpu.parallel.collectives import allreduce_sum, shard_map

    rng = np.random.default_rng(0)
    a = rng.normal(size=(16, 6)).astype(np.float32)

    mesh8 = make_mesh(devices=jax.devices()[:8])
    gram8 = jax.jit(
        shard_map(
            lambda x: allreduce_sum(x.T @ x),
            mesh=mesh8,
            in_specs=P(DATA_AXIS, None),
            out_specs=P(None, None),
        )
    )(a)
    want = a.T @ a
    np.testing.assert_allclose(np.asarray(gram8), want, rtol=1e-5, atol=1e-5)
