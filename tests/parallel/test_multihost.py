"""2-process CPU rehearsal of the multi-host launch path (r4 verdict
item 5): ``distributed_init`` with an explicit coordinator, a global mesh
spanning both processes, and a real cross-process psum through
``linalg.gram`` — so the multi-host entry point is exercised code, not
dead code. Runbook: docs/MULTIHOST.md."""

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_rehearsal():
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS", "XLA_FLAGS")}
    # The rehearsal must work from a bare checkout too (a fresh machine
    # loses the editable install; sys.path[0] is scripts/, not the repo).
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "scripts/multihost_rehearsal.py"),
             "--coordinator", f"127.0.0.1:{port}",
             "--num-hosts", "2", "--host-id", str(i),
             "--virtual-devices", "4"],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        for i in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        assert "REHEARSAL_OK" in out, out[-2000:]
        # both processes must see the 8-device GLOBAL mesh (4 local each)
        assert "4 local / 8 global" in out, out[-2000:]


def test_partial_manual_config_raises(monkeypatch):
    """Half a manual-cluster config (host id without coordinator) must
    fail loudly, not silently degrade to an uncoordinated single host."""
    import pytest

    from keystone_tpu.parallel.mesh import distributed_init

    monkeypatch.delenv("KEYSTONE_COORDINATOR", raising=False)
    monkeypatch.setenv("KEYSTONE_NUM_HOSTS", "4")
    monkeypatch.setenv("KEYSTONE_HOST_ID", "1")
    with pytest.raises(ValueError, match="KEYSTONE_COORDINATOR"):
        distributed_init()
