"""Partitioner unit suite: eligibility decisions and fallback reasons,
row-sharding placement, the plan report, and 1-vs-8-virtual-device
parity of the gram_stream_init/step/finish protocol when chunks are
split across the mesh with per-shard partial carries reduced at finish
(the sharded chunk plan's algebra, docs/PARTITIONING.md)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.parallel import linalg
from keystone_tpu.parallel.mesh import make_mesh, use_mesh
from keystone_tpu.parallel.partitioner import (
    PartitionDecision,
    Partitioner,
    R_BELOW_FLOOR,
    R_BUCKETS_INDIVISIBLE,
    R_CHUNK_TOO_NARROW,
    R_DISABLED,
    R_SINGLE_SHARD,
    R_UNKNOWN_ROWS,
    SHARDED,
    last_partition_report,
    partition_disabled,
    reset_partition_report,
    shard_rows,
)


@pytest.fixture
def mesh8():
    mesh = make_mesh(devices=jax.devices()[:8])
    with use_mesh(mesh):
        yield mesh


@pytest.fixture
def mesh1():
    mesh = make_mesh(devices=jax.devices()[:1])
    with use_mesh(mesh):
        yield mesh


# ------------------------------------------------------------------ decisions


def test_fit_decision_eligible_records_mesh_and_spec(mesh8):
    reset_partition_report()
    d = Partitioner().decide_fit("est", 4096)
    assert d.eligible and d.reason == SHARDED
    assert d.shards == 8
    assert d.mesh is mesh8
    assert d.mesh_shape == (8,)
    assert "data" in d.spec
    assert [r.to_json() for r in last_partition_report()] == [d.to_json()]


@pytest.mark.parametrize(
    "rows,reason",
    [(None, R_UNKNOWN_ROWS), (-1, R_UNKNOWN_ROWS), (7, R_BELOW_FLOOR)],
)
def test_fit_fallback_reasons(mesh8, rows, reason):
    d = Partitioner().decide_fit("est", rows)
    assert not d.eligible
    assert d.reason == reason
    assert d.shards == 1 and d.mesh is None


def test_single_device_mesh_falls_back(mesh1):
    d = Partitioner().decide_fit("est", 4096)
    assert not d.eligible and d.reason == R_SINGLE_SHARD


def test_disabled_falls_back(mesh8):
    with partition_disabled():
        d = Partitioner().decide_fit("est", 4096)
    assert not d.eligible and d.reason == R_DISABLED


def test_stream_decision_rounds_chunk_to_shard_multiple(mesh8):
    d = Partitioner().decide_stream("sf", 100)
    assert d.eligible and d.chunk_rows == 104  # next multiple of 8
    narrow = Partitioner().decide_stream("sf", 4)
    assert not narrow.eligible and narrow.reason == R_CHUNK_TOO_NARROW


def test_serve_decision_needs_a_divisible_bucket(mesh8):
    ok = Partitioner().decide_serve("m", [1, 2, 4, 8])
    assert ok.eligible and "8" in ok.detail
    bad = Partitioner().decide_serve("m", [1, 2, 4])
    assert not bad.eligible and bad.reason == R_BUCKETS_INDIVISIBLE


def test_record_false_keeps_report_untouched(mesh8):
    reset_partition_report()
    Partitioner().decide_fit("est", 4096, record=False)
    assert last_partition_report() == []


def test_min_rows_env_knob(mesh8, monkeypatch):
    monkeypatch.setenv("KEYSTONE_PARTITION_MIN_ROWS", "100")
    d = Partitioner().decide_fit("est", 128)  # < 8 shards × 100
    assert not d.eligible and d.reason == R_BELOW_FLOOR


# ------------------------------------------------------------------ placement


def test_shard_rows_places_divisible_leaves_only(mesh8):
    d = Partitioner().decide_fit("est", 4096)
    tree = {
        "a": np.zeros((16, 3), np.float32),  # 16 % 8 == 0 → sharded
        "b": np.zeros((6, 3), np.float32),  # 6 < 8 shards → untouched
    }
    placed = shard_rows(d, tree)
    a_sharding = placed["a"].sharding
    assert {dev.id for dev in a_sharding.device_set} == {
        dev.id for dev in mesh8.devices.flat
    }
    assert isinstance(placed["b"], np.ndarray)


def test_shard_rows_noop_for_ineligible_decision(mesh8):
    d = PartitionDecision(kind="fit", node="x", eligible=False, reason="r")
    tree = np.zeros((16, 3), np.float32)
    assert shard_rows(d, tree) is tree or isinstance(
        shard_rows(d, tree), np.ndarray
    )


# ------------------------------------------- gram stream parity 1 vs 8 devices


def _sequential_gram(x, y, chunk):
    carry = linalg.gram_stream_init(x.shape[1], y.shape[1])
    for s in range(0, x.shape[0], chunk):
        carry = linalg.gram_stream_step(
            carry, jnp.asarray(x[s : s + chunk]), jnp.asarray(y[s : s + chunk])
        )
    return linalg.gram_stream_finish(carry, x.shape[0])


def test_gram_stream_sharded_partials_match_single_device(mesh8):
    """Per-shard partial carries + one finish-time reduction == the
    sequential single-device accumulation (the identity behind the
    sharded fit_stream plan), to streaming-parity tolerance."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.parallel.collectives import shard_map

    rng = np.random.default_rng(3)
    n, d, k, chunk, shards = 64, 8, 3, 16, 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, k)).astype(np.float32)

    spec = P(("data",))
    sharding = NamedSharding(mesh8, spec)
    carry = jax.tree_util.tree_map(
        lambda a: jax.device_put(
            jnp.zeros((shards,) + a.shape, a.dtype), sharding
        ),
        linalg.gram_stream_init(d, k),
    )

    def local(c, xb, yb):
        c0 = jax.tree_util.tree_map(lambda a: a[0], c)
        c1 = linalg.gram_stream_step(c0, xb, yb)
        return jax.tree_util.tree_map(lambda a: a[None], c1)

    step = jax.jit(
        shard_map(
            local, mesh=mesh8, in_specs=(spec, spec, spec), out_specs=spec
        )
    )
    for s in range(0, n, chunk):
        xb = jax.device_put(x[s : s + chunk], sharding)
        yb = jax.device_put(y[s : s + chunk], sharding)
        carry = step(carry, xb, yb)

    reduced = jax.tree_util.tree_map(lambda a: jnp.sum(a, axis=0), carry)
    got = linalg.gram_stream_finish(reduced, n)
    want = _sequential_gram(x, y, chunk)
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=1e-5, atol=1e-5
        )
