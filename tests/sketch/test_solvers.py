"""SketchedLeastSquaresEstimator: sketched-vs-exact parity in both
finish regimes, the sketch-and-precondition in-core path (divergence
guard included), and the kind="sketch" state contract — merge/scaled/
resume round-trips under GLOBAL row-index semantics (docs/SOLVERS.md)."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.refit.state import (
    StateMismatch,
    StreamState,
    merge_stream_states,
)
from keystone_tpu.sketch.core import (
    MASK_INDEX_EXACT_ROWS,
    sketch_stream_init,
    sketch_stream_step,
)
from keystone_tpu.sketch.solvers import (
    SketchedLeastSquaresEstimator,
    default_sketch_size,
)
from keystone_tpu.workflow.streaming import ChunkStream, StreamingFallback

pytestmark = pytest.mark.sketch

N, D, K, CHUNK = 512, 32, 3, 64


def _stream(x, y, chunk=CHUNK):
    return ChunkStream(ArrayDataset(x), ArrayDataset(y), (), chunk_rows=chunk)


def _rel(a, b):
    return float(np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-30))


def _realizable(n=N, d=D, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, K)).astype(np.float32)
    return x, (x @ w).astype(np.float32)


# ------------------------------------------------------------ parity bounds


@pytest.mark.parametrize("variant", ["countsketch", "srht"])
def test_streamed_primal_matches_exact_small_d(variant):
    """s ≥ d regime: on noiseless realizable data rank(SA) = d pins the
    sketched solution to the exact one — streamed sketch-and-solve vs
    the exact Gram rung, parity ≤ 1e-4 on predictions."""
    x, y = _realizable()
    exact = LinearMapEstimator(reg=1e-6).fit_stream(_stream(x, y))
    ep = np.asarray(exact.apply_arrays(x))
    est = SketchedLeastSquaresEstimator(
        reg=1e-6, sketch_size=2 * D, variant=variant, seed=1
    )
    preds = np.asarray(est.fit_stream(_stream(x, y)).apply_arrays(x))
    assert _rel(preds, ep) <= 1e-4
    state = est.export_stream_state()
    assert state.kind == "sketch" and state.num_examples == N
    assert state.meta["sketch_variant"] == variant


def test_streamed_dual_bounded_on_low_rank_rows():
    """s < d regime (the tier's point — no d×d state): a row-space
    sketch recovers predictions up to the row-space energy it captures,
    so with effective rank ≪ s the train error stays small."""
    rng = np.random.default_rng(2)
    n, d, r, s = 512, 128, 16, 64
    z = rng.normal(size=(n, r)).astype(np.float32)
    basis = rng.normal(size=(r, d)).astype(np.float32) / np.sqrt(r)
    x = (z @ basis + 0.01 * rng.normal(size=(n, d))).astype(np.float32)
    w = rng.normal(size=(d, K)).astype(np.float32) / np.sqrt(d)
    y = (x @ w).astype(np.float32)
    est = SketchedLeastSquaresEstimator(reg=1e-4, sketch_size=s, seed=1)
    preds = np.asarray(est.fit_stream(_stream(x, y)).apply_arrays(x))
    assert np.isfinite(preds).all()
    assert _rel(preds, y) < 0.05


def test_incore_precondition_matches_exact():
    """Sketch-and-precondition on materialized data: PCG refinement on
    the full normal operator reaches solver-grade parity with the exact
    ridge even at modest s."""
    rng = np.random.default_rng(3)
    x, y0 = _realizable(seed=3)
    y = y0 + 0.05 * rng.normal(size=y0.shape).astype(np.float32)
    exact = LinearMapEstimator(reg=1e-3).fit(ArrayDataset(x), ArrayDataset(y))
    ep = np.asarray(exact.apply_arrays(x))
    est = SketchedLeastSquaresEstimator(reg=1e-3, sketch_size=2 * D, seed=1)
    preds = np.asarray(est.fit(ArrayDataset(x), ArrayDataset(y)).apply_arrays(x))
    assert _rel(preds, ep) <= 1e-3


def test_incore_divergence_guard_stays_finite():
    """When s undersamples the row space (underdetermined fit, s well
    below rank) PCG can run away; the residual guard falls back to the
    bounded sketch-only solve — never NaN, never inf."""
    rng = np.random.default_rng(4)
    x = rng.normal(size=(64, 256)).astype(np.float32)
    y = rng.normal(size=(64, 2)).astype(np.float32)
    for iters in (0, 16):
        est = SketchedLeastSquaresEstimator(
            reg=1e-3, sketch_size=32, seed=0, refine_iters=iters
        )
        preds = np.asarray(
            est.fit(ArrayDataset(x), ArrayDataset(y)).apply_arrays(x)
        )
        assert np.isfinite(preds).all(), f"iters={iters}"


# -------------------------------------------------------- state contract


def _manual_state(x, y, s, seed, index_base, est):
    """A kind="sketch" envelope folded with GLOBAL row indices starting
    at index_base — what the sharded / durable-cursor paths produce for
    a row range (a fresh ChunkStream restarts indexing at 0, so disjoint
    halves of one dataset are sketched at their true offsets here)."""
    import jax.numpy as jnp

    step = sketch_stream_step(est.variant, seed)
    n, d = x.shape
    carry = sketch_stream_init(s, d, y.shape[1])
    mask = jnp.arange(
        index_base + 1, index_base + n + 1, dtype=jnp.float32
    )[:, None]
    carry = step(carry, jnp.asarray(x), jnp.asarray(y), mask)
    return StreamState(
        kind="sketch",
        estimator="manual",
        num_examples=n,
        carry=tuple(np.asarray(c) for c in carry),
        meta={"sketch_variant": est.variant, "sketch_seed": seed},
    )


def test_merge_at_global_offsets_matches_oneshot():
    """Halves sketched at their true global offsets merge to EXACTLY the
    one-shot streamed carry (parity ≤ 1e-6) — the additivity the
    sharded reduce and shard-loss salvage rest on."""
    x, y = _realizable(seed=5)
    s = 2 * D
    est = SketchedLeastSquaresEstimator(reg=1e-3, sketch_size=s, seed=7)
    ref = est.fit_stream(_stream(x, y))
    ref_out = np.asarray(ref.apply_arrays(x))

    half = N // 2
    a = _manual_state(x[:half], y[:half], s, 7, 0, est)
    b = _manual_state(x[half:], y[half:], s, 7, half, est)
    merged = merge_stream_states(a, b)
    assert merged.num_examples == N
    fitted = SketchedLeastSquaresEstimator(
        reg=1e-3, sketch_size=s, seed=7
    ).finish_from_state(merged)
    assert _rel(np.asarray(fitted.apply_arrays(x)), ref_out) <= 1e-6


def test_scaled_state_finishes_to_same_model():
    """scaled(γ) is exponential forgetting: every leaf and the count
    scale together, so the decayed state still solves to the same map.
    reg=None (the scale-aware floor, λ ∝ tr(K)/s) keeps the algebra
    EXACTLY homogeneous — a fixed absolute λ would shift ~1e-5 under γ
    because the ridge no longer tracks the shrunken statistics."""
    x, y = _realizable(seed=6)
    est = SketchedLeastSquaresEstimator(reg=None, sketch_size=2 * D, seed=0)
    est.fit_stream(_stream(x, y))
    state = est.export_stream_state()
    half = state.scaled(0.5)
    assert half.num_examples == state.num_examples // 2
    np.testing.assert_allclose(half.carry[0], state.carry[0] * 0.5)
    a = np.asarray(est.finish_from_state(state).apply_arrays(x))
    b = np.asarray(est.finish_from_state(half).apply_arrays(x))
    assert _rel(b, a) <= 1e-5


def test_mismatched_sketch_maps_refused():
    """Sums across different (variant, seed) maps are algebra on
    unrelated projections: merge AND resume must fail loudly."""
    x, y = _realizable(seed=7)
    est = SketchedLeastSquaresEstimator(reg=1e-3, sketch_size=2 * D, seed=0)
    a = _manual_state(x, y, 2 * D, 0, 0, est)
    b_seed = _manual_state(x, y, 2 * D, 1, 0, est)
    with pytest.raises(StateMismatch, match="sketch_seed"):
        merge_stream_states(a, b_seed)
    b_var = StreamState(
        kind="sketch", estimator="manual", num_examples=N, carry=a.carry,
        meta={"sketch_variant": "srht", "sketch_seed": 0},
    )
    with pytest.raises(StateMismatch, match="sketch_variant"):
        merge_stream_states(a, b_var)
    # A Gram-kind state never seeds a sketched fold.
    gram = StreamState(
        kind="gram", estimator="manual", num_examples=N, carry=a.carry
    )
    with pytest.raises(StateMismatch, match="kind|gram|sketch"):
        est.fit_stream(_stream(x, y), state=gram)


def test_resume_adopts_state_map():
    """fit_stream(state=…) adopts the state's (variant, seed): the
    combined sketch stays ONE coherent linear map even when the resuming
    estimator was constructed with different defaults."""
    x, y = _realizable(seed=8)
    est = SketchedLeastSquaresEstimator(
        reg=1e-3, sketch_size=2 * D, variant="countsketch", seed=0
    )
    state = _manual_state(x, y, 2 * D, 5, 0, est)
    resumed = SketchedLeastSquaresEstimator(
        reg=1e-3, sketch_size=2 * D, variant="countsketch", seed=0
    )
    resumed.fit_stream(_stream(x, y), state=state)
    assert resumed.seed == 5
    assert resumed.export_stream_state().num_examples == 2 * N


def test_row_index_cap_falls_back():
    """Streams longer than the float32-exact index range refuse loudly
    (StreamingFallback) instead of silently colliding hash inputs."""

    class HugeStream:
        num_examples = MASK_INDEX_EXACT_ROWS + 1

    est = SketchedLeastSquaresEstimator(reg=1e-3)
    with pytest.raises(StreamingFallback, match="float32-exact"):
        est.fit_stream(HugeStream())


def test_default_sketch_size_bounds():
    assert default_sketch_size(10) == 128
    assert default_sketch_size(1000) == 1000
    assert default_sketch_size(100_000) == 4096
