"""Core sketch-operator identities (docs/SOLVERS.md): both variants are
linear maps of the rows keyed on ABSOLUTE row indices, so sketching
block-by-block equals sketching whole, centering is algebraic at finish
time, and pad rows contribute nothing."""

import numpy as np
import pytest

from keystone_tpu.sketch.core import (
    MASK_INDEX_EXACT_ROWS,
    VARIANTS,
    sketch_rows,
    sketch_state_bytes,
    sketch_stream_finish,
    sketch_stream_init,
    sketch_stream_step,
    srht_sample_rows,
)

pytestmark = pytest.mark.sketch

S, D, K = 64, 24, 3


def _rows(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, D)).astype(np.float32)
    y = rng.normal(size=(n, K)).astype(np.float32)
    return x, y


def _fold(x, y, variant, seed, chunk, s=S, index_base=0):
    """Fold (x, y) through the stream step in `chunk`-row pieces whose
    mask lanes carry the rows' absolute indices (index_base offset)."""
    import jax.numpy as jnp

    step = sketch_stream_step(variant, seed)
    carry = sketch_stream_init(s, D, K)
    for start in range(0, x.shape[0], chunk):
        stop = min(start + chunk, x.shape[0])
        mask = jnp.arange(
            index_base + start + 1, index_base + stop + 1, dtype=jnp.float32
        )[:, None]
        carry = step(carry, x[start:stop], y[start:stop], mask)
    return tuple(np.asarray(c) for c in carry)


@pytest.mark.parametrize("variant", VARIANTS)
@pytest.mark.parametrize("chunk", [7, 32, 128])
def test_chunked_equals_whole(variant, chunk):
    """Additivity over arbitrary chunk boundaries: the property that lets
    one carry ride chunking, sharding, merge, and resume unchanged."""
    x, y = _rows(128)
    whole = _fold(x, y, variant, seed=5, chunk=128)
    pieces = _fold(x, y, variant, seed=5, chunk=chunk)
    for a, b in zip(whole, pieces):
        np.testing.assert_allclose(a, b, rtol=0, atol=1e-4)


@pytest.mark.parametrize("variant", VARIANTS)
def test_index_base_shifts_the_map(variant):
    """The sketch is a function of ABSOLUTE indices: the same rows at a
    different offset land differently (why resume must ride the durable
    cursor), while split-at-the-true-offset sums back to the whole."""
    x, y = _rows(96, seed=1)
    whole = _fold(x, y, variant, seed=2, chunk=96)
    shifted = _fold(x, y, variant, seed=2, chunk=96, index_base=96)
    assert not np.allclose(whole[0], shifted[0])
    half = 48
    a = _fold(x[:half], y[:half], variant, seed=2, chunk=half)
    b = _fold(x[half:], y[half:], variant, seed=2, chunk=half, index_base=half)
    for w, (pa, pb) in zip(whole, zip(a, b)):
        np.testing.assert_allclose(w, pa + pb, rtol=0, atol=1e-4)


@pytest.mark.parametrize("variant", VARIANTS)
def test_centering_identity(variant):
    """S·(A − 1μᵀ) = SA − s1·μᵀ: finish-time centering equals sketching
    pre-centered rows, no second data pass."""
    x, y = _rows(80, seed=3)
    carry = _fold(x, y, variant, seed=0, chunk=80)
    n = x.shape[0]
    sa_c, sy_c, mu_a, mu_b = sketch_stream_finish(carry, n)
    np.testing.assert_allclose(np.asarray(mu_a), x.mean(axis=0), atol=1e-5)
    centered = _fold(
        x - x.mean(axis=0), y - y.mean(axis=0), variant, seed=0, chunk=80
    )
    np.testing.assert_allclose(np.asarray(sa_c), centered[0], atol=1e-3)
    np.testing.assert_allclose(np.asarray(sy_c), centered[1], atol=1e-3)


@pytest.mark.parametrize("variant", VARIANTS)
def test_pad_rows_contribute_nothing(variant):
    """Mask lane 0 marks padding: a padded tail (zero rows, zero mask)
    leaves every carry leaf untouched — chunk-boundary padding can never
    leak into the statistics."""
    import jax.numpy as jnp

    x, y = _rows(40, seed=4)
    clean = _fold(x, y, variant, seed=9, chunk=40)
    step = sketch_stream_step(variant, 9)
    pad = 24
    xp = np.concatenate([x, np.zeros((pad, D), np.float32)])
    yp = np.concatenate([y, np.zeros((pad, K), np.float32)])
    mask = jnp.concatenate(
        [jnp.arange(1, 41, dtype=jnp.float32), jnp.zeros((pad,), jnp.float32)]
    )[:, None]
    padded = step(sketch_stream_init(S, D, K), xp, yp, mask)
    for a, b in zip(clean, padded):
        np.testing.assert_allclose(a, np.asarray(b), rtol=0, atol=1e-4)


def test_sketch_rows_matches_stream_step():
    """The in-core block sketcher is the stream step at the same absolute
    indices — one hashing, two entry points."""
    x, _ = _rows(48, seed=6)
    sa, s1 = sketch_rows(x, start_index=16, variant="countsketch", seed=3, s=S)
    y = np.zeros((48, K), np.float32)
    carry = _fold(x, y, "countsketch", seed=3, chunk=48, index_base=16)
    np.testing.assert_allclose(np.asarray(sa), carry[0], atol=1e-4)
    np.testing.assert_allclose(np.asarray(s1), carry[2], atol=1e-4)


def test_unknown_variant_raises():
    with pytest.raises(ValueError, match="unknown sketch variant"):
        sketch_stream_step("gaussian", 0)


def test_srht_sample_rows_deterministic():
    """Sampled WH rows regenerate from (s, seed) alone — they are never
    persisted; resume rebuilds them from the envelope's meta."""
    a = srht_sample_rows(32, 7)
    assert a.dtype == np.uint32 and a.shape == (32,)
    np.testing.assert_array_equal(a, srht_sample_rows(32, 7))
    assert not np.array_equal(a, srht_sample_rows(32, 8))


def test_state_bytes_formula_and_index_cap():
    assert sketch_state_bytes(256, 8192, 8) == 4 * (
        256 * 8192 + 256 * 8 + 256 + 8192 + 8
    )
    assert MASK_INDEX_EXACT_ROWS == 1 << 24
