"""The tier's headline acceptance: a d ≥ 64k streamed fit holds in a
memory budget the Gram tier refuses at plan time (docs/SOLVERS.md) —
the O(s·d) carry vs the O(d²) wall, end to end on real data."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.sketch.core import sketch_state_bytes
from keystone_tpu.sketch.solvers import SketchedLeastSquaresEstimator
from keystone_tpu.workflow.streaming import ChunkStream

pytestmark = [pytest.mark.sketch, pytest.mark.slow]

N, D, K, R, S, CHUNK = 1024, 65536, 4, 64, 512, 256
BUDGET = 1 << 30  # 1 GiB device budget


def test_very_wide_streamed_fit_where_gram_refuses(monkeypatch):
    from keystone_tpu.ops.learning.linear import LinearMapEstimator
    from keystone_tpu.workflow.operators import EstimatorOperator
    from keystone_tpu.workflow.streaming import StreamingFitOperator
    from keystone_tpu.workflow.verify import verify_graph

    monkeypatch.setenv("KEYSTONE_SKETCH_SIZE", str(S))

    # --- plan level: the Gram tier is refused, the sketched tier fits.
    def streamed_graph(est):
        pipe = est.with_data(
            ArrayDataset(np.zeros((8, D), dtype=np.float32)),
            ArrayDataset(np.zeros((8, K), dtype=np.float32)),
        )
        graph = pipe.graph
        node = next(
            n
            for n in graph.nodes
            if isinstance(graph.get_operator(n), EstimatorOperator)
            and not hasattr(graph.get_operator(n), "dataset")
        )
        return graph.set_operator(
            node, StreamingFitOperator(graph.get_operator(node), members=())
        )

    gram_report = verify_graph(
        streamed_graph(LinearMapEstimator(reg=1e-3)),
        device_memory_bytes=BUDGET,
    )
    assert gram_report.by_code("KV303"), "Gram tier must refuse d=64k"
    sketch_report = verify_graph(
        streamed_graph(SketchedLeastSquaresEstimator(reg=1e-3)),
        device_memory_bytes=BUDGET,
    )
    assert sketch_report.by_code("KV308") == []
    assert 2 * sketch_state_bytes(S, D, K) < BUDGET

    # --- and the fit actually runs, bounded and accurate: low-effective-
    # rank rows (the regime the tier is for), train rel err < 5%.
    rng = np.random.default_rng(11)
    z = rng.normal(size=(N, R)).astype(np.float32)
    basis = rng.normal(size=(R, D)).astype(np.float32) / np.sqrt(R)
    x = (z @ basis + 0.01 * rng.normal(size=(N, D))).astype(np.float32)
    w = rng.normal(size=(D, K)).astype(np.float32) / np.sqrt(D)
    y = (x @ w).astype(np.float32)

    est = SketchedLeastSquaresEstimator(reg=1e-4)
    model = est.fit_stream(
        ChunkStream(ArrayDataset(x), ArrayDataset(y), (), chunk_rows=CHUNK)
    )
    state = est.export_stream_state()
    assert state.kind == "sketch"
    carry_bytes = sum(a.nbytes for a in state.carry)
    assert carry_bytes == sketch_state_bytes(S, D, K)

    preds = np.asarray(model.apply_arrays(x[:CHUNK]))
    rel = float(np.linalg.norm(preds - y[:CHUNK]) / np.linalg.norm(y[:CHUNK]))
    assert np.isfinite(preds).all() and rel < 0.05, rel
