"""The solver ladder's sketched rung: eligibility (width floor), the
cost crossover, and the resolved-sketch-size pricing — the argmin must
charge the rung for the s that will actually run, not the width default
(docs/SOLVERS.md)."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.learning.least_squares import LeastSquaresEstimator
from keystone_tpu.sketch.solvers import SketchedLeastSquaresEstimator
from keystone_tpu.workflow.optimize import DataStats

pytestmark = pytest.mark.sketch


def _pick(n, d, k=8, machines=1, est=None):
    """The meta-solver's argmin rung for given shape stats (the same
    path NodeOptimizationRule drives at plan time)."""
    est = est or LeastSquaresEstimator(reg=1e-3, num_machines=machines)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, d)).astype(np.float32)
    y = rng.normal(size=(32, k)).astype(np.float32)
    return est.optimize(
        [ArrayDataset(x), ArrayDataset(y)],
        DataStats(n_total=n, num_shards=1, n_per_shard=[n]),
    )


def test_sketched_rung_wins_past_crossover(monkeypatch):
    """With the env knob pinning a small s, the sketched rung undercuts
    every Gram/LBFGS rung at the smoke leg's shape (n=4096, d=8192) —
    the crossover scripts/sketch_smoke.sh rides."""
    monkeypatch.setenv("KEYSTONE_SKETCH_SIZE", "256")
    picked = _pick(n=4096, d=8192)
    assert isinstance(picked, SketchedLeastSquaresEstimator)


def test_width_floor_gates_the_rung(monkeypatch):
    """Below KEYSTONE_SKETCH_MIN_WIDTH the sketched rung prices at inf:
    even a tiny pinned s must never win at moderate width (the floor IS
    the eligibility gate, accuracy-motivated)."""
    monkeypatch.setenv("KEYSTONE_SKETCH_SIZE", "256")
    picked = _pick(n=4096, d=4096)
    assert not isinstance(picked, SketchedLeastSquaresEstimator)


def test_pricing_uses_resolved_sketch_size(monkeypatch):
    """The bench leg's regression: at n=2048/d=8192 the width-default
    s=4096 prices the rung OUT (a Gram/LBFGS rung wins), while the env
    knob's s=512 prices it IN — so optimize() must resolve s exactly the
    way the fit will."""
    monkeypatch.delenv("KEYSTONE_SKETCH_SIZE", raising=False)
    default_pick = _pick(n=2048, d=8192, machines=8)
    assert not isinstance(default_pick, SketchedLeastSquaresEstimator)
    monkeypatch.setenv("KEYSTONE_SKETCH_SIZE", "512")
    pinned_pick = _pick(n=2048, d=8192, machines=8)
    assert isinstance(pinned_pick, SketchedLeastSquaresEstimator)


def test_tuned_sketch_size_rides_the_pricing_and_the_pick(monkeypatch):
    """A MeasuredKnobRule winner (_tuned_sketch_size) must steer the
    argmin exactly like the env knob AND ride onto the chosen estimator
    so the fit runs at the priced s."""
    monkeypatch.delenv("KEYSTONE_SKETCH_SIZE", raising=False)
    est = LeastSquaresEstimator(reg=1e-3, num_machines=8)
    est._tuned_sketch_size = 512
    picked = _pick(n=2048, d=8192, machines=8, est=est)
    assert isinstance(picked, SketchedLeastSquaresEstimator)
    assert picked._resolve_sketch_size(8192) == 512


def test_every_candidate_priced_for_explain(monkeypatch):
    """Losing rungs stay in the provenance with their costs/reasons —
    `keystone-tpu explain` shows the whole ladder, including WHY the
    sketched rung lost below the width floor."""
    monkeypatch.delenv("KEYSTONE_SKETCH_SIZE", raising=False)
    picked = _pick(n=100_000, d=1024)
    pred = picked.predicted_cost
    names = {name for name, _, _ in pred.candidates}
    assert {"sparse_lbfgs", "dense_lbfgs", "block", "exact", "sketched"} <= names
    reason = next(r for name, _, r in pred.candidates if name == "sketched")
    assert "KEYSTONE_SKETCH_MIN_WIDTH" in reason


def test_stream_solver_collapse_by_width(monkeypatch):
    """Under streaming the meta-choice collapses by width: Gram rungs up
    to the sketch floor, the sketched rung past it, and a tuned s rides
    the delegation."""
    monkeypatch.delenv("KEYSTONE_SKETCH_SIZE", raising=False)
    est = LeastSquaresEstimator(reg=1e-3)
    assert not isinstance(
        est._stream_solver(4096), SketchedLeastSquaresEstimator
    )
    inner = est._stream_solver(8192)
    assert isinstance(inner, SketchedLeastSquaresEstimator)
    est._tuned_sketch_size = 384
    assert est._stream_solver(8192)._resolve_sketch_size(8192) == 384
