"""Pressure-aware refit cadence (satellite of the co-scheduler PR): the
fixed daemon sleep becomes backlog/pressure-driven. The interval law is
pure in its inputs, so these tests are fully deterministic — no clocks,
no threads, no sleeping."""

import numpy as np
import pytest

from keystone_tpu.refit.daemon import RefitConfig, RefitDaemon
from keystone_tpu.refit.tap import TrafficTap
from keystone_tpu.sched.scheduler import MeshScheduler, pressure_aware_interval

pytestmark = pytest.mark.sched

BASE = 30.0


def test_interval_law_shape():
    # Empty tap, idle mesh: the configured cadence stands.
    assert pressure_aware_interval(BASE, 0.0, False) == BASE
    # Filling tap drains sooner, down to base/8 at the drop-oldest bound.
    assert pressure_aware_interval(BASE, 0.5, False) == BASE / 2
    assert pressure_aware_interval(BASE, 1.0, False) == BASE / 8
    # SLO pressure backs off — serving owns the mesh right now…
    assert pressure_aware_interval(BASE, 0.0, True) == BASE * 2
    # …even when the tap is nearly full: pressure wins the argument.
    assert pressure_aware_interval(BASE, 0.95, True) == BASE * 2
    # Explicit clamps bound both directions.
    assert pressure_aware_interval(BASE, 0.0, True, max_s=45.0) == 45.0
    assert pressure_aware_interval(BASE, 0.999, False, min_s=5.0) == 5.0
    # Out-of-range fill fractions are clamped, not trusted.
    assert pressure_aware_interval(BASE, -1.0, False) == BASE
    assert pressure_aware_interval(BASE, 7.0, False) == BASE / 8


def test_interval_monotone_in_fill():
    prev = None
    for fill in (0.0, 0.25, 0.5, 0.75, 1.0):
        cur = pressure_aware_interval(BASE, fill, False)
        assert prev is None or cur <= prev
        prev = cur


def _daemon(tap, scheduler):
    return RefitDaemon(
        estimator=None,
        tap=tap,
        publisher=None,
        scheduler=scheduler,
        config=RefitConfig(name="cadence", interval_s=BASE),
    )


def test_next_interval_unscheduled_keeps_fixed_sleep():
    tap = TrafficTap(capacity_rows=1024)
    tap.feed(np.zeros((1024, 4), np.float32), np.zeros((1024,), np.float32))
    # Even a full tap: an unscheduled daemon is byte-for-byte the old
    # fixed-cadence loop.
    assert _daemon(tap, None)._next_interval() == BASE


def test_next_interval_tracks_tap_fill_and_pressure():
    tap = TrafficTap(capacity_rows=1024)
    scheduler = MeshScheduler(name="cadence")
    daemon = _daemon(tap, scheduler)
    assert daemon._next_interval() == BASE  # empty tap, idle mesh
    tap.feed(np.zeros((512, 4), np.float32), np.zeros((512,), np.float32))
    assert daemon._next_interval() == BASE / 2  # half-full: drain sooner
    tap.feed(np.zeros((512, 4), np.float32), np.zeros((512,), np.float32))
    assert daemon._next_interval() == BASE / 8  # at the drop-oldest bound
    scheduler.force_pressure(True)
    assert daemon._next_interval() == BASE * 2  # pressure: back off
