"""Preemption correctness: seeded SLO pressure mid-fold preempts at a
chunk boundary with the durable cursor committed, the deferred round
resumes from the cursor to exact parity with an uninterrupted fold, and
a preempted round feeds NO partial-wall evidence into the profile store
or the cost drift sentinel (the PR-15 suffix-wall guard extended to
scheduler deferrals)."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.refit.daemon import RefitConfig, RefitDaemon
from keystone_tpu.refit.publish import InProcessPublisher
from keystone_tpu.refit.shadow import ShadowEvaluator
from keystone_tpu.refit.tap import TrafficTap
from keystone_tpu.reliability.checkpoint import CheckpointStore
from keystone_tpu.reliability.recovery import get_recovery_log
from keystone_tpu.sched.scheduler import MeshScheduler
from keystone_tpu.serving.config import ServingConfig
from keystone_tpu.serving.server import PipelineServer
from keystone_tpu.workflow.streaming import ChunkStream

pytestmark = pytest.mark.sched

D, K = 8, 3
RNG = np.random.default_rng(11)
W_TRUE = RNG.normal(size=(D, K)).astype(np.float32)


def _rows(n, seed):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, D)).astype(np.float32)
    return x, (x @ W_TRUE).astype(np.float32)


def _stream(x, y, chunk_rows=64):
    return ChunkStream(
        ArrayDataset(x), ArrayDataset(y), (), chunk_rows=chunk_rows
    )


def _pair(tmp_path):
    """A scheduler-governed daemon and an unscheduled control daemon
    publishing into one live server — same seed state, same rounds."""
    x0, y0 = _rows(512, seed=0)
    est = LinearMapEstimator(reg=1e-3)
    model = est.fit_stream(_stream(x0, y0))
    v1 = est.export_stream_state()
    server = PipelineServer(
        model=model, config=ServingConfig(max_batch=4, queue_depth=64), name="m"
    )
    server.registry.publish("m-ctrl", model, source="fit")
    server.start()
    server.warmup(np.zeros((D,), np.float32))

    scheduler = MeshScheduler(name="m", sustain_checks=2)

    def daemon(name, estimator, tap, sched, subdir):
        return RefitDaemon(
            estimator,
            tap,
            InProcessPublisher(
                server, name=name, example=np.zeros((D,), np.float32)
            ),
            store=CheckpointStore(str(tmp_path / subdir)),
            scheduler=sched,
            shadow=ShadowEvaluator(margin=0.5),
            config=RefitConfig(
                name=name,
                min_rows=128,
                chunk_rows=64,
                watch_margin=0.5,
                state_decay=1.0,
            ),
            state=v1,
        )

    tap = TrafficTap(capacity_rows=4096)
    ctrl_tap = TrafficTap(capacity_rows=4096)
    sched_daemon = daemon("m", LinearMapEstimator(reg=1e-3), tap, scheduler, "s")
    ctrl_daemon = daemon(
        "m-ctrl", LinearMapEstimator(reg=1e-3), ctrl_tap, None, "c"
    )
    return server, scheduler, (sched_daemon, tap), (ctrl_daemon, ctrl_tap)


def _sched_events(kind, label):
    return [e for e in get_recovery_log().events(kind) if e.label == label]


def test_seeded_preemption_resumes_to_parity(tmp_path):
    server, scheduler, (daemon, tap), (ctrl, ctrl_tap) = _pair(tmp_path)
    try:
        x, y = _rows(512, seed=1)
        tap.feed(x, y)
        ctrl_tap.feed(x, y)

        # One idle consultation (admission), then sustained pressure:
        # 512 rows − 128 eval = 384 train rows = 6 chunks of 64; with
        # sustain_checks=2 the fold yields at the 2nd chunk boundary.
        scheduler.seed_pressure_after(1)
        assert daemon.run_once() == "deferred"
        record = daemon.outcomes[-1]
        assert record["preempted_at_chunk"] == 2
        preempts = _sched_events("sched_preempt", "m:round-1")
        assert preempts and preempts[-1].detail["chunk_index"] == 2

        # The round journal is parked, not cleared: the next round must
        # find the drained rows and the cursor, not re-drain the tap.
        assert tap.stats()["labeled_depth"] == 0

        scheduler.seed_pressure_after(None)
        assert daemon.run_once() == "published"
        resumes = _sched_events("sched_resume", "m:round-2")
        assert resumes and resumes[-1].detail["resume_of"]

        # Parity: preempt→resume ≡ the uninterrupted control fold.
        assert ctrl.run_once() == "published"
        got = np.asarray(
            daemon.estimator.finish_from_state(daemon._state).weights,
            dtype=np.float64,
        )
        want = np.asarray(
            ctrl.estimator.finish_from_state(ctrl._state).weights,
            dtype=np.float64,
        )
        assert float(np.max(np.abs(got - want))) <= 1e-6

        outcomes = scheduler.stats()["outcomes"]
        assert outcomes.get("preempted") == 1
        assert outcomes.get("completed") == 1
    finally:
        server.stop(drain=True)


def test_preempted_round_feeds_no_observations(tmp_path, monkeypatch):
    """Satellite regression: a fold preempted at a chunk boundary ran a
    partial round — its wall must reach neither the profile store's
    chunk-winner observations nor the cost drift sentinel's rows/s
    stream (partial rows over partial wall would mis-score both)."""
    server, scheduler, (daemon, tap), _ = _pair(tmp_path)
    try:
        import keystone_tpu.obs.cost as cost

        calls = []
        monkeypatch.setattr(
            ChunkStream,
            "_record_observation",
            lambda self, report, shape: calls.append("store"),
        )
        real_note = cost.note_stream_result
        monkeypatch.setattr(
            cost,
            "note_stream_result",
            lambda *a, **k: calls.append("cost"),
        )

        x, y = _rows(512, seed=2)
        tap.feed(x, y)
        scheduler.seed_pressure_after(1)
        assert daemon.run_once() == "deferred"
        assert calls == []  # preempted: no evidence recorded

        scheduler.seed_pressure_after(None)
        assert daemon.run_once() == "published"
        # The RESUMED fold measured recovery, not steady state — the
        # original suffix-wall guard still holds on the resume leg.
        assert calls == []

        tap.feed(*_rows(512, seed=3))
        assert daemon.run_once() == "published"
        assert "cost" in calls  # a clean round records evidence again
        monkeypatch.setattr(cost, "note_stream_result", real_note)
    finally:
        server.stop(drain=True)


def test_cosched_demo_contract():
    """The demo the smoke script and bench leg gate on, at test scale:
    zero dropped requests under load, exactly one seeded preemption at
    a chunk boundary, resume parity, and the sched_* ledger trail."""
    from keystone_tpu.sched.demo import CoschedDemoConfig, run_cosched_demo

    evidence = run_cosched_demo(
        CoschedDemoConfig(
            d=D,
            classes=K,
            rounds=3,
            rows_per_round=2048,
            chunk_rows=256,
            serve_requests=32,
            serve_rps=400.0,
            pressure_round=2,
            slo_target_ms=5000.0,
            seed=0,
        )
    )
    assert evidence["dropped"] == 0
    assert evidence["preemptions"] == 1
    assert evidence["preempted_at_chunk"] is not None
    assert "sched_preempt" in evidence["ledger_kinds"]
    assert "sched_resume" in evidence["ledger_kinds"]
    assert evidence["parity_ok"], evidence["parity_max_abs_diff"]
    assert evidence["publishes"] >= 2
    assert evidence["deferred_rounds"] == 1
    assert evidence["leases"] == evidence["publishes"] + 1
