"""MeshScheduler unit behaviors: the pricing provenance ladder,
admission vs deferral, sustained chunk-boundary preemption, the
deterministic pressure doors, and roofline chunk placement."""

import types

import pytest

from keystone_tpu.reliability.recovery import get_recovery_log
from keystone_tpu.sched import pricing
from keystone_tpu.sched.pricing import (
    LeasePrice,
    choose_chunk_rows,
    gram_stream_facts,
    price_stream_fold,
)
from keystone_tpu.sched.scheduler import (
    LeaseRequest,
    MeshScheduler,
    get_scheduler,
    maybe_lease,
    set_scheduler,
)

pytestmark = pytest.mark.sched


class _Store:
    """Minimal ProfileStore face: just ``entries(key_prefix=...)``."""

    def __init__(self, entries):
        self._entries = entries

    def entries(self, key_prefix=""):
        return [e for e in self._entries if e[0].startswith(key_prefix)]


_TUNED = _Store(
    [
        (
            "stream:():cr2048",
            "2048x8",
            {
                "rows_per_s": 1_000_000.0,
                "source": "tune",
                "chunk_rows": 2048,
                "prefetch_depth": 3,
            },
        ),
        # A worse merely-observed rate: the best rate must win.
        (
            "stream:():cr512",
            "512x8",
            {"rows_per_s": 250_000.0, "chunk_rows": 512},
        ),
    ]
)


class _SLO:
    def __init__(self, rung=0, headroom=None):
        self.admission = types.SimpleNamespace(rung_index=rung)
        self._h = headroom

    def headroom(self):
        return self._h


def _sched_events(kind, label):
    return [e for e in get_recovery_log().events(kind) if e.label == label]


# ------------------------------------------------------------------- pricing


def test_gram_stream_facts_formula():
    flops, by = gram_stream_facts(100, 8, 3)
    assert flops == 100 * (2 * 64 + 2 * 24)
    assert by == 4 * 100 * 11 + 8 * (64 + 24)


def test_price_ladder_measured_beats_models():
    price = price_stream_fold(500_000, 8, 3, store=_TUNED)
    assert price.source == "tune"
    assert price.rows_per_s == 1_000_000.0
    assert price.seconds == pytest.approx(0.5)


def test_price_ladder_default_closes(monkeypatch):
    import keystone_tpu.obs.cost as cost

    monkeypatch.setattr(cost, "get_roofline", lambda: None)
    monkeypatch.setenv("KEYSTONE_SCHED_DEFAULT_ROWS_PER_S", "100000")
    price = price_stream_fold(200_000, 8, 3, store=None)
    assert price.source == "default"
    assert price.seconds == pytest.approx(2.0)


def test_choose_chunk_rows_tuned_entry_wins():
    assert choose_chunk_rows(1 << 20, 8, 3, store=_TUNED) == (
        2048,
        3,
        "tune",
    )


def test_choose_chunk_rows_memory_bound_grows(monkeypatch):
    width, classes = 31, 1  # per-row staged bytes = 4*(31+1) = 128
    monkeypatch.setattr(
        pricing,
        "price_stream_fold",
        lambda *a, **k: LeasePrice(
            seconds=1e-3, source="roofline", roofline="memory-bound"
        ),
    )
    # Budget sized so the cap lands exactly on 16384 rows across the
    # 5-deep staged pipeline (prefetch 4 + 1 in flight).
    monkeypatch.setenv(
        "KEYSTONE_SCHED_RESIDENCY_BYTES", str(128 * 5 * 16384)
    )
    assert choose_chunk_rows(1 << 20, width, classes) == (
        16384,
        4,
        "roofline",
    )


def test_choose_chunk_rows_compute_bound_keeps_default(monkeypatch):
    monkeypatch.setattr(
        pricing,
        "price_stream_fold",
        lambda *a, **k: LeasePrice(
            seconds=1e-3, source="roofline", roofline="compute-bound"
        ),
    )
    assert choose_chunk_rows(1 << 20, 8, 3) == (4096, 2, "roofline")
    # Always bounded by the dataset.
    assert choose_chunk_rows(100, 8, 3)[0] == 100


# ----------------------------------------------------------------- admission


def test_idle_mesh_admits_and_completes():
    sched = MeshScheduler(name="t1")
    lease = sched.submit(LeaseRequest(name="t1:a", rows=64, width=4, classes=2))
    assert lease.admitted and lease.state == "running"
    assert _sched_events("sched_admit", "t1:a")
    sched.release(lease)
    assert lease.state == "completed"
    stats = sched.stats()
    assert stats["leases"] == 1
    assert stats["outcomes"] == {"completed": 1}
    assert stats["idle_harvest_s"] >= 0.0
    assert sched.schedule()[0]["outcome"] == "completed"


def test_pressure_defers_without_wait_budget():
    sched = MeshScheduler(name="t2")
    sched.force_pressure(True)
    lease = sched.submit(LeaseRequest(name="t2:a", rows=64))
    assert not lease.admitted and lease.state == "deferred"
    assert lease.deferrals >= 1
    assert "forced pressure" in lease.displaced_by
    assert _sched_events("sched_defer", "t2:a")
    # The contextmanager face yields None for a deferred lease.
    with sched.lease(LeaseRequest(name="t2:b")) as handle:
        assert handle is None


def test_deferred_submit_admits_when_pressure_clears():
    consults = []

    def backlog():
        consults.append(1)
        return 99 if len(consults) <= 1 else 0

    sched = MeshScheduler(backlog_fn=backlog, name="t3", backlog_limit=8)
    lease = sched.submit(
        LeaseRequest(name="t3:a", rows=64), wait_s=10.0, poll_s=0.001
    )
    assert lease.admitted and lease.deferrals >= 1
    assert _sched_events("sched_defer", "t3:a")
    assert _sched_events("sched_admit", "t3:a")
    sched.release(lease)


def test_pressure_ladder_signals():
    assert MeshScheduler(slo=_SLO(rung=2)).pressure_reason() == (
        "serving-slo rung_index=2"
    )
    low = MeshScheduler(slo=_SLO(headroom=0.1)).pressure_reason()
    assert low is not None and "headroom" in low
    assert MeshScheduler(slo=_SLO(headroom=0.9)).pressure_reason() is None
    backlog = MeshScheduler(backlog_fn=lambda: 99).pressure_reason()
    assert backlog is not None and "backlog" in backlog
    assert MeshScheduler(backlog_fn=lambda: 3).pressure_reason() is None
    # No signals at all degrades to always-admit, never wedged.
    assert MeshScheduler().pressure_reason() is None


def test_seed_pressure_after_counts_consultations():
    sched = MeshScheduler(name="t4")
    sched.seed_pressure_after(2)
    assert sched.pressure_reason() is None
    assert sched.pressure_reason() is None
    assert sched.pressure_reason() == "seeded pressure (mid-fold)"
    assert sched.pressure_reason() is not None  # stays pressured
    sched.seed_pressure_after(None)
    assert sched.pressure_reason() is None


# ---------------------------------------------------------------- preemption


def test_should_yield_requires_sustained_pressure():
    sched = MeshScheduler(name="t5", sustain_checks=2)
    lease = sched.submit(LeaseRequest(name="t5:a", rows=64))
    sched.force_pressure(True)
    assert not lease.should_yield()  # streak 1 of 2
    assert lease.should_yield()  # sustained
    assert "forced pressure" in lease.displaced_by


def test_pressure_streak_resets_on_idle_boundary():
    sched = MeshScheduler(name="t6", sustain_checks=2)
    lease = sched.submit(LeaseRequest(name="t6:a", rows=64))
    sched.force_pressure(True)
    assert not lease.should_yield()
    sched.force_pressure(None)
    assert not lease.should_yield()  # idle boundary clears the streak
    sched.force_pressure(True)
    assert not lease.should_yield()  # streak restarts at 1
    assert lease.should_yield()


def test_preempted_release_ledgers_chunk_index():
    sched = MeshScheduler(name="t7")
    lease = sched.submit(LeaseRequest(name="t7:a", rows=64))
    lease.displaced_by = "test pressure"
    lease.mark_preempted(3)
    sched.release(lease)
    events = _sched_events("sched_preempt", "t7:a")
    assert events and events[-1].detail["chunk_index"] == 3
    assert sched.stats()["outcomes"] == {"preempted": 1}


def test_resume_lease_ledgers_sched_resume():
    sched = MeshScheduler(name="t8")
    lease = sched.submit(
        LeaseRequest(name="t8:a", rows=64, resume_of="t8-1")
    )
    assert lease.admitted
    events = _sched_events("sched_resume", "t8:a")
    assert events and events[-1].detail["resume_of"] == "t8-1"
    assert not _sched_events("sched_admit", "t8:a")


# ------------------------------------------------------------- global handle


def test_finish_reduction_opts_into_installed_scheduler():
    import numpy as np

    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.learning.linear import LinearMapEstimator
    from keystone_tpu.workflow.streaming import ChunkStream

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = rng.normal(size=(64, 2)).astype(np.float32)
    est = LinearMapEstimator(reg=1e-3)
    est.fit_stream(
        ChunkStream(ArrayDataset(x), ArrayDataset(y), (), chunk_rows=32)
    )
    state = est.export_stream_state()

    sched = MeshScheduler(name="t10")
    set_scheduler(sched)
    try:
        est.finish_from_state(state)
    finally:
        set_scheduler(None)
    log = sched.schedule()
    assert len(log) == 1
    assert log[0]["kind"] == "finish"
    assert log[0]["outcome"] == "completed"
    assert log[0]["rows"] == 64

    # Under pressure the solve still runs (callers need the model
    # synchronously) — the deferral is just ledgered.
    sched.force_pressure(True)
    set_scheduler(sched)
    try:
        model = est.finish_from_state(state)
    finally:
        set_scheduler(None)
    assert model is not None
    assert sched.schedule()[-1]["outcome"] == "deferred"


def test_global_handle_and_env_kill_switch(monkeypatch):
    sched = MeshScheduler(name="t9")
    set_scheduler(sched)
    try:
        assert get_scheduler() is sched
        with maybe_lease("t9:a", "tune_probe") as handle:
            assert handle is not None and handle.admitted
        monkeypatch.setenv("KEYSTONE_SCHED", "0")
        assert get_scheduler() is None
        with maybe_lease("t9:b", "tune_probe") as handle:
            assert handle is None  # unscheduled no-op path
    finally:
        set_scheduler(None)
