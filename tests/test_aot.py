"""AOT warm-compile utility: compiles the declared flagship shapes."""

from keystone_tpu.pipelines.imagenet import ImageNetSiftLcsFVConfig
from keystone_tpu.utils.aot import warm_flagship


def test_warm_flagship_compiles_declared_shapes(tmp_path, monkeypatch):
    # Point the persistent cache somewhere disposable so the test leaves
    # no shared state.
    monkeypatch.setenv("KEYSTONE_COMPILATION_CACHE", str(tmp_path / "cache"))
    out = warm_flagship(
        ImageNetSiftLcsFVConfig(desc_dim=8, vocab_size=2),
        bucket_shapes=((2, 48, 48),),
        solver_shapes=((32, 32, 4),),
    )
    assert "encode_2x48x48_s" in out and out["encode_2x48x48_s"] >= 0
    assert "solve_32x32x4_s" in out
