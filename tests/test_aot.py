"""AOT warm-compile utility: compiles the declared flagship shapes."""

from keystone_tpu.pipelines.imagenet import ImageNetSiftLcsFVConfig
from keystone_tpu.utils.aot import warm_flagship


def test_warm_buckets_covers_full_and_partial_batches(tmp_path, monkeypatch):
    """Serving warmup: every declared bucket compiles ahead of traffic,
    including the partial-batch pad-mask path (warmed at num_examples=1),
    so steady-state request sizes never compile (asserted end-to-end in
    tests/serving/test_server.py)."""
    import numpy as np

    from keystone_tpu.serving.synthetic import synthetic_fitted_pipeline
    from keystone_tpu.utils.aot import warm_buckets
    from keystone_tpu.utils.compilation_cache import (
        compile_count,
        install_compile_counter,
    )

    monkeypatch.setenv("KEYSTONE_COMPILATION_CACHE", str(tmp_path / "cache"))
    install_compile_counter()
    fp = synthetic_fitted_pipeline(d=4, seed=5)
    apply_fn = fp.compiled_apply()
    out = warm_buckets(apply_fn, np.zeros((4,), np.float32), (1, 2, 4))
    assert sorted(out) == ["bucket_1_s", "bucket_2_s", "bucket_4_s"]
    assert all(v >= 0 for v in out.values())
    # Re-warming the same buckets is pure cache hits: zero new compiles.
    before = compile_count()
    warm_buckets(apply_fn, np.zeros((4,), np.float32), (1, 2, 4))
    assert compile_count() == before


def test_warm_flagship_compiles_declared_shapes(tmp_path, monkeypatch):
    # Point the persistent cache somewhere disposable so the test leaves
    # no shared state.
    monkeypatch.setenv("KEYSTONE_COMPILATION_CACHE", str(tmp_path / "cache"))
    out = warm_flagship(
        ImageNetSiftLcsFVConfig(desc_dim=8, vocab_size=2),
        bucket_shapes=((2, 48, 48),),
        solver_shapes=((32, 32, 4),),
    )
    assert "encode_2x48x48_s" in out and out["encode_2x48x48_s"] >= 0
    assert "solve_32x32x4_s" in out
