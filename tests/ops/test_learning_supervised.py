"""LBFGS / logistic / weighted-BCD / meta-solver tests (reference:
LBFGSSuite, LogisticRegressionSuite, BlockWeightedLeastSquaresSuite,
LeastSquaresEstimatorSuite)."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset, ObjectDataset
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.ops.learning.lbfgs import DenseLBFGSEstimator, SparseLBFGSEstimator
from keystone_tpu.ops.learning.least_squares import LeastSquaresEstimator
from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.ops.learning.logistic import LogisticRegressionEstimator
from keystone_tpu.ops.learning.weighted import BlockWeightedLeastSquaresEstimator
from keystone_tpu.workflow.optimize import DataStats


def ridge_problem(n=256, d=12, k=3, seed=0, noise=0.1):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, k)).astype(np.float32)
    y = (x @ w + noise * rng.normal(size=(n, k))).astype(np.float32)
    return x, y, w


def test_dense_lbfgs_matches_ridge():
    x, y, _ = ridge_problem()
    reg = 0.5
    # note: lbfgs objective is ||XW-Y||^2/(2n) + reg/2 ||W||^2
    # closed form: (X'X/n + reg I)^-1 X'Y/n on centered data
    n = len(x)
    mu_a, mu_b = x.mean(0), y.mean(0)
    xc, yc = x - mu_a, y - mu_b
    expected = np.linalg.solve(xc.T @ xc / n + reg * np.eye(x.shape[1]), xc.T @ yc / n)
    model = DenseLBFGSEstimator(reg=reg, num_iterations=80).fit(ArrayDataset(x), ArrayDataset(y))
    np.testing.assert_allclose(np.asarray(model.weights), expected, rtol=5e-2, atol=5e-3)


def test_dense_lbfgs_prediction_quality():
    x, y, _ = ridge_problem(noise=0.0)
    model = DenseLBFGSEstimator(reg=1e-6, num_iterations=200).fit(ArrayDataset(x), ArrayDataset(y))
    pred = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    np.testing.assert_allclose(pred, y, rtol=5e-2, atol=5e-2)


def test_sparse_lbfgs_on_csr_rows():
    import scipy.sparse as sp

    rng = np.random.default_rng(1)
    n, d, k = 200, 30, 2
    x = (rng.random((n, d)) < 0.1) * rng.normal(size=(n, d))
    x = x.astype(np.float32)
    w = rng.normal(size=(d, k)).astype(np.float32)
    y = x @ w
    rows = [sp.csr_matrix(x[i : i + 1]) for i in range(n)]
    model = SparseLBFGSEstimator(reg=1e-4, num_iterations=100).fit(
        ObjectDataset(rows), ArrayDataset(y)
    )
    pred = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    np.testing.assert_allclose(pred, y, atol=0.2)


def test_logistic_regression_separates():
    rng = np.random.default_rng(2)
    n = 300
    x = rng.normal(size=(n, 5)).astype(np.float32)
    w_true = rng.normal(size=(5, 3))
    y = np.argmax(x @ w_true, axis=1).astype(np.int32)
    model = LogisticRegressionEstimator(3, reg=1e-4, num_iterations=100).fit(
        ArrayDataset(x), ArrayDataset(y)
    )
    scores = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    assert (scores.argmax(1) == y).mean() > 0.95


def numpy_weighted_reference(x, y, reg, mw, num_iter):
    """Direct numpy transcription of the reference's math (single block)."""
    n, d = x.shape
    C = y.shape[1]
    cls = np.argmax(y, 1)
    counts = np.bincount(cls, minlength=C).astype(np.float64)
    jlm = 2 * mw + 2 * (1 - mw) * counts / n - 1
    R = y - jlm
    W = np.zeros((d, C))
    pop_mean = x.mean(0)
    pop_cov = x.T @ x / n - np.outer(pop_mean, pop_mean)
    joint_means = np.zeros((C, d))
    for _ in range(num_iter):
        pop_xtr = x.T @ R / n
        res_mean = R.mean(0)
        dW = np.zeros_like(W)
        for c in range(C):
            xc = x[cls == c]
            rc = R[cls == c, c]
            nc = counts[c]
            cm = xc.mean(0)
            ccov = xc.T @ xc / nc - np.outer(cm, cm)
            cxtr = xc.T @ rc / nc
            delta = cm - pop_mean
            jm = mw * cm + (1 - mw) * pop_mean
            joint_means[c] = jm
            jxtx = (1 - mw) * pop_cov + mw * ccov + mw * (1 - mw) * np.outer(delta, delta)
            mean_mix = (1 - mw) * res_mean[c] + mw * rc.mean()
            jxtr = (1 - mw) * pop_xtr[:, c] + mw * cxtr - jm * mean_mix
            dW[:, c] = np.linalg.solve(jxtx + reg * np.eye(d), jxtr - reg * W[:, c])
        W += dW
        R = R - x @ dW
    b = jlm - np.einsum("cd,dc->c", joint_means, W)
    return W, b


def test_weighted_bcd_matches_numpy_reference():
    rng = np.random.default_rng(3)
    n, d, C = 120, 8, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    cls = rng.integers(0, C, size=n)
    y = np.full((n, C), -1.0, dtype=np.float32)
    y[np.arange(n), cls] = 1.0

    est = BlockWeightedLeastSquaresEstimator(block_size=8, num_iter=2, reg=0.3,
                                             mixture_weight=0.25)
    model = est.fit(ArrayDataset(x), ArrayDataset(y))
    w_ref, b_ref = numpy_weighted_reference(
        x.astype(np.float64), y.astype(np.float64), 0.3, 0.25, 2
    )
    np.testing.assert_allclose(np.asarray(model.weights)[:d], w_ref, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(model.intercept), b_ref, rtol=1e-3, atol=1e-3)


def test_weighted_bcd_woodbury_path_matches_dense_path():
    """The shared-factor Woodbury solve (auto-picked when the per-class
    update rank is small vs the block size — the flagship's 1000-class
    regime) must agree with the per-class dense Cholesky path to
    solver-grade accuracy."""
    import jax.numpy as jnp

    from keystone_tpu.ops.learning.weighted import _weighted_bcd

    rng = np.random.default_rng(7)
    n, d, C = 160, 96, 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    cls = rng.integers(0, C, size=n)
    y = np.full((n, C), -1.0, dtype=np.float32)
    y[np.arange(n), cls] = 1.0

    counts = np.bincount(cls, minlength=C).astype(np.int64)
    order = np.argsort(cls, kind="stable")
    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
    m = int(counts.max())
    xs = np.concatenate([x[order], np.zeros((m, d), np.float32)])
    onehot = np.zeros((n, C), np.float32)
    onehot[np.arange(n), cls] = 1.0

    args = (
        jnp.asarray(x), jnp.asarray(xs), jnp.asarray(y), jnp.asarray(onehot),
        jnp.asarray(offsets), jnp.asarray(counts.astype(np.float32)),
        jnp.float32(0.2), jnp.float32(0.25), 1, d, m, 2,
    )
    w_dense, jm_dense = _weighted_bcd(*args, "dense")
    w_wood, jm_wood = _weighted_bcd(*args, "woodbury")
    np.testing.assert_allclose(np.asarray(w_wood), np.asarray(w_dense),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(jm_wood), np.asarray(jm_dense),
                               rtol=1e-5, atol=1e-6)


def test_weighted_bcd_auto_woodbury_matches_numpy_reference():
    """At a flagship-like shape (block ≫ class counts) the estimator
    auto-selects Woodbury; the result must still match the independent
    numpy oracle."""
    rng = np.random.default_rng(11)
    n, d, C = 180, 384, 6  # max class count ~39 ≪ 384/6 → auto-Woodbury
    x = rng.normal(size=(n, d)).astype(np.float32)
    cls = rng.integers(0, C, size=n)
    y = np.full((n, C), -1.0, dtype=np.float32)
    y[np.arange(n), cls] = 1.0

    est = BlockWeightedLeastSquaresEstimator(block_size=384, num_iter=2,
                                             reg=0.3, mixture_weight=0.25)
    model = est.fit(ArrayDataset(x), ArrayDataset(y))
    w_ref, b_ref = numpy_weighted_reference(
        x.astype(np.float64), y.astype(np.float64), 0.3, 0.25, 2
    )
    np.testing.assert_allclose(np.asarray(model.weights)[:d], w_ref,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(model.intercept), b_ref,
                               rtol=2e-3, atol=2e-3)


def test_weighted_bcd_classifies():
    rng = np.random.default_rng(4)
    n, d, C = 300, 6, 3
    centers = rng.normal(size=(C, d)) * 4
    cls = rng.integers(0, C, size=n)
    x = (centers[cls] + rng.normal(size=(n, d))).astype(np.float32)
    y = np.full((n, C), -1.0, dtype=np.float32)
    y[np.arange(n), cls] = 1.0
    model = BlockWeightedLeastSquaresEstimator(3, 3, 0.1, 0.25).fit(
        ArrayDataset(x), ArrayDataset(y)
    )
    scores = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    assert (scores.argmax(1) == cls).mean() > 0.9


def test_meta_solver_picks_exact_for_small_dense():
    est = LeastSquaresEstimator(reg=0.1)
    x = np.random.default_rng(0).normal(size=(100, 8)).astype(np.float32)
    y = np.random.default_rng(1).normal(size=(100, 2)).astype(np.float32)
    stats = DataStats(n_total=100_000, num_shards=8, n_per_shard=[12500] * 8)
    chosen = est.optimize([ArrayDataset(x), ArrayDataset(y)], stats)
    assert isinstance(chosen, LinearMapEstimator)


def test_meta_solver_picks_sparse_for_sparse_data():
    import scipy.sparse as sp

    est = LeastSquaresEstimator(reg=0.1)
    rng = np.random.default_rng(0)
    rows = [sp.csr_matrix((rng.random((1, 20000)) < 0.004) * 1.0) for _ in range(50)]
    y = rng.normal(size=(50, 2)).astype(np.float32)
    stats = DataStats(n_total=65_000_000, num_shards=8, n_per_shard=[1] * 8)
    chosen = est.optimize([ObjectDataset(rows), ArrayDataset(y)], stats)
    assert isinstance(chosen, SparseLBFGSEstimator)


def test_meta_solver_choice_flips_at_tpu_crossover_shapes():
    """With the TPU cost weights the solver choice must flip from exact
    normal equations to block coordinate descent as d grows at the TIMIT
    shape — the behavior contract of the reference's cost-driven
    auto-selection (reference: LeastSquaresEstimator.scala:26-87) refit
    for this hardware (VERDICT round 1, item 3)."""
    from keystone_tpu.ops.learning.cost import tpu_weights

    rng = np.random.default_rng(0)
    y = rng.normal(size=(64, 2)).astype(np.float32)
    stats = DataStats(n_total=2_200_000, num_shards=8, n_per_shard=[275_000] * 8)

    def choice(d):
        est = LeastSquaresEstimator(reg=0.1, weights=tpu_weights(), num_machines=8)
        x = rng.normal(size=(64, d)).astype(np.float32)
        return est.optimize([ArrayDataset(x), ArrayDataset(y)], stats)

    from keystone_tpu.sketch.solvers import SketchedLeastSquaresEstimator

    assert isinstance(choice(1024), LinearMapEstimator)       # exact wins small-d
    assert isinstance(choice(4096), BlockLeastSquaresEstimator)   # block wins big-d
    # Past the sketch width floor the randomized rung tops the ladder
    # (docs/SOLVERS.md): O(s·d) state vs block's O(d²)-adjacent cost.
    assert isinstance(choice(16384), SketchedLeastSquaresEstimator)


def test_default_weights_resolve_by_backend():
    """weights=None resolves to the reference's constants on CPU and the
    TPU constants on accelerators (cost.default_cost_weights)."""
    from keystone_tpu.ops.learning.cost import (
        DEFAULT_COST_WEIGHTS,
        default_cost_weights,
        measured_tpu_weights,
        tpu_weights,
    )

    assert default_cost_weights("cpu") == DEFAULT_COST_WEIGHTS
    assert default_cost_weights("tpu") in (
        measured_tpu_weights() or tpu_weights(),
        tpu_weights(),
    )


def test_per_class_weighted_least_squares_learns():
    """reference: PerClassWeightedLeastSquares.scala:31-223 — per-class
    example-weighted solve recovers separable class prototypes."""
    from keystone_tpu.ops.learning.weighted import PerClassWeightedLeastSquaresEstimator

    rng = np.random.default_rng(0)
    n, d, C = 300, 12, 3
    labels = rng.integers(0, C, n)
    protos = rng.normal(size=(C, d)) * 2
    x = (protos[labels] + 0.5 * rng.normal(size=(n, d))).astype(np.float32)
    y = np.full((n, C), -1.0, np.float32)
    y[np.arange(n), labels] = 1.0

    est = PerClassWeightedLeastSquaresEstimator(
        block_size=4, num_iter=25, reg=1e-3, mixture_weight=0.25
    )
    model = est.fit(ArrayDataset(x), ArrayDataset(y))
    pred = np.asarray(model.apply_arrays(x)).argmax(axis=1)
    assert (pred == labels).mean() > 0.95


def test_per_class_weighted_matches_direct_weighted_solve():
    """Single-block, many-iteration BCD must converge to the closed-form
    weighted solution (X̃ᵀBX̃ + λI) \\ X̃ᵀBỹ per class."""
    from keystone_tpu.ops.learning.weighted import PerClassWeightedLeastSquaresEstimator

    rng = np.random.default_rng(1)
    n, d, C = 120, 6, 2
    labels = rng.integers(0, C, n)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.full((n, C), -1.0, np.float32)
    y[np.arange(n), labels] = 1.0
    mw, lam = 0.3, 1e-2

    est = PerClassWeightedLeastSquaresEstimator(
        block_size=d, num_iter=40, reg=lam, mixture_weight=mw
    )
    model = est.fit(ArrayDataset(x), ArrayDataset(y))
    w = np.asarray(model.weights)[:d]

    counts = np.bincount(labels, minlength=C).astype(np.float64)
    pop_mean = x.mean(axis=0)
    for c in range(C):
        cm = x[labels == c].mean(axis=0)
        jfm = mw * cm + (1 - mw) * pop_mean
        jlm = 2 * mw + 2 * (1 - mw) * counts[c] / n - 1
        b = np.full(n, (1 - mw) / n)
        b[labels == c] += mw / counts[c]
        xt = x - jfm
        yt = y[:, c] - jlm
        want = np.linalg.solve(
            xt.T @ (b[:, None] * xt) + lam * np.eye(d), xt.T @ (b * yt)
        )
        np.testing.assert_allclose(w[:, c], want, rtol=2e-2, atol=2e-3)


def test_sparse_lbfgs_at_amazon_feature_width():
    """Scale-shaped BCOO validation (VERDICT round 1, item 8): the sparse
    LBFGS path at the Amazon feature width d=16384, sparsity 0.005
    (reference: scripts/solver-comparisons-final.csv:12-13) — rows reduced
    to keep CI wall-clock sane, feature width and sparsity real. The data
    is never densified on the way in (one CSR matrix through the
    ObjectDataset path)."""
    import scipy.sparse as sp

    from keystone_tpu.ops.learning.lbfgs import SparseLBFGSEstimator

    n, d, k = 30_000, 16_384, 2
    rng = np.random.default_rng(0)
    x = sp.random(n, d, density=0.005, format="csr", dtype=np.float32,
                  random_state=0)
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    y = np.asarray(x @ w_true, dtype=np.float32)

    model = SparseLBFGSEstimator(reg=1e-4, num_iterations=6).fit(
        ObjectDataset([x]), ArrayDataset(y)
    )
    # the solve makes real progress over w=0 at full width
    pred = np.asarray(x[:4096] @ np.asarray(model.weights))
    base = np.mean(y[:4096] ** 2)
    mse = np.mean((pred - y[:4096]) ** 2)
    assert mse < 0.5 * base, f"mse {mse} vs baseline {base}"


def test_weighted_mixture_weight_endpoints_guarded():
    """r4 advisor: Woodbury's C diagonal divides by mw and mw(1-mw), so
    the endpoints must force the dense path (auto) or raise (explicit),
    and out-of-range values must raise in BOTH weighted estimators."""
    import pytest

    from keystone_tpu.ops.learning.weighted import (
        BlockWeightedLeastSquaresEstimator,
        PerClassWeightedLeastSquaresEstimator,
    )

    for mw in (0.0, 1.0):
        est = BlockWeightedLeastSquaresEstimator(
            16, num_iter=1, reg=0.1, mixture_weight=mw)
        assert est.solve_path == "dense"
        with pytest.raises(ValueError, match="woodbury"):
            BlockWeightedLeastSquaresEstimator(
                16, num_iter=1, reg=0.1, mixture_weight=mw,
                solve_path="woodbury")
    for bad in (-0.1, 1.5):
        with pytest.raises(ValueError, match="mixture_weight"):
            BlockWeightedLeastSquaresEstimator(
                16, num_iter=1, reg=0.1, mixture_weight=bad)
        with pytest.raises(ValueError, match="mixture_weight"):
            PerClassWeightedLeastSquaresEstimator(
                16, num_iter=1, reg=0.1, mixture_weight=bad)
