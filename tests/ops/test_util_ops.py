"""Utility operator tests: gather/combine, splitters, cache/shuffle,
sparse feature spaces, format conversions.

Mirrors the reference's per-node suites (reference:
nodes/util/*Suite.scala — VectorSplitterSuite, ClassLabelIndicatorsSuite,
TopKClassifierSuite, SparseFeatureVectorizerSuite etc.).
"""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset, ObjectDataset
from keystone_tpu.ops.stats.core import Sampler
from keystone_tpu.ops.util.labels import (
    ClassLabelIndicators,
    MaxClassifier,
    MultiLabelIndicators,
    TopKClassifier,
)
from keystone_tpu.ops.util.misc import CacherOperator, ShufflerOperator
from keystone_tpu.ops.util.sparse import (
    AllSparseFeatures,
    CommonSparseFeatures,
)
from keystone_tpu.ops.util.vectors import (
    Densify,
    MatrixVectorizer,
    Sparsify,
    VectorCombiner,
    VectorSplitter,
)
from keystone_tpu.workflow.pipeline import Pipeline, Transformer


# ------------------------------------------------------------------ labels


def test_class_label_indicators_pm_one():
    out = np.asarray(
        ClassLabelIndicators(4).apply_arrays(np.array([0, 2, 3]))
    )
    expected = np.full((3, 4), -1.0)
    expected[0, 0] = expected[1, 2] = expected[2, 3] = 1.0
    np.testing.assert_array_equal(out, expected)


def test_multi_label_indicators():
    out = np.asarray(MultiLabelIndicators(5).apply([1, 3]))
    expected = np.full(5, -1.0)
    expected[[1, 3]] = 1.0
    np.testing.assert_array_equal(out, expected)


def test_top_k_classifier_ordering():
    scores = np.array([[0.1, 0.9, 0.5, 0.3]])
    out = np.asarray(TopKClassifier(3).apply_arrays(scores))
    np.testing.assert_array_equal(out[0], [1, 2, 3])
    assert np.asarray(MaxClassifier().apply_arrays(scores))[0] == 1


# ------------------------------------------------------------- split/combine


def test_vector_splitter_blocks_and_roundtrip():
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    blocks = VectorSplitter(4).split(ArrayDataset(x))
    assert [b.data.shape[1] for b in blocks] == [4, 2]
    recombined = np.asarray(
        VectorCombiner().apply_arrays(tuple(b.data for b in blocks))
    )
    np.testing.assert_array_equal(recombined, x)


def test_vector_combiner_single_datum():
    out = VectorCombiner().apply([np.array([1.0, 2.0]), np.array([3.0])])
    np.testing.assert_array_equal(out, [1.0, 2.0, 3.0])


def test_matrix_vectorizer_flattens():
    x = np.arange(12).reshape(2, 3, 2)
    assert MatrixVectorizer().apply_arrays(x).shape == (2, 6)


# ---------------------------------------------------------------- gather


def test_pipeline_gather_merges_branches():
    doubler = Transformer.from_fn(lambda v: v * 2.0, name="double")
    negator = Transformer.from_fn(lambda v: -v, name="neg")
    gathered = Pipeline.gather([doubler, negator]) >> Transformer.from_fn(
        lambda pair: pair[0] + pair[1], name="sum"
    )
    out = gathered(ObjectDataset([1.0, 2.0])).get().collect()
    assert out == [1.0, 2.0]  # 2v + (−v) = v


# --------------------------------------------------------------- cache/shuffle


def test_cacher_is_identity_and_forces():
    ds = ObjectDataset([1, 2, 3])
    out = CacherOperator().batch_transform([ds])
    assert out.collect() == [1, 2, 3]


def test_shuffler_preserves_multiset():
    ds = ObjectDataset(list(range(20)))
    out = ShufflerOperator(seed=1).batch_transform([ds])
    assert sorted(out.collect()) == list(range(20))
    assert out.collect() != list(range(20))  # actually shuffled at n=20


def test_sampler_subsamples_without_replacement():
    ds = ObjectDataset(list(range(100)))
    out = Sampler(10, seed=0).apply_batch(ds).collect()
    assert len(out) == 10 == len(set(out))


# ------------------------------------------------------------------- sparse


def _docs():
    return ObjectDataset(
        [
            [("a", 1.0), ("b", 2.0)],
            [("a", 1.0), ("c", 3.0)],
            [("a", 2.0), ("b", 1.0), ("d", 4.0)],
        ]
    )


def test_common_sparse_features_top_k():
    # "a" appears 3x, "b" 2x; top-2 space is {a, b}
    vec = CommonSparseFeatures(2).fit(_docs())
    mat = vec.apply_batch(_docs())
    dense = np.asarray(Densify()(mat).get().data)
    assert dense.shape == (3, 2)
    # doc 1 has only "a" from the kept space
    assert (dense != 0).sum(axis=1).tolist() == [2, 1, 2]


def test_all_sparse_features_full_space():
    vec = AllSparseFeatures().fit(_docs())
    mat = vec.apply_batch(_docs())
    dense = np.asarray(Densify()(mat).get().data)
    assert dense.shape == (3, 4)


def test_sparsify_densify_roundtrip():
    x = np.zeros((3, 5), np.float32)
    x[0, 1] = 2.0
    x[2, 4] = -1.0
    sparse = Sparsify()(ArrayDataset(x))
    dense = np.asarray(Densify()(sparse.get() if hasattr(sparse, 'get') else sparse).get().data)
    np.testing.assert_array_equal(dense, x)
