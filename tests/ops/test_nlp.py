"""NLP operator tests (reference: nlp suites — NGramsFeaturizerSuite,
NGramsHashingTFSuite, StupidBackoffSuite, indexer suites)."""

import numpy as np

from keystone_tpu.data.dataset import ObjectDataset
from keystone_tpu.ops.nlp import (
    HashingTF,
    NaiveBitPackIndexer,
    NGramIndexer,
    NGramsCounts,
    NGramsFeaturizer,
    NGramsHashingTF,
    StupidBackoffEstimator,
    TermFrequency,
    Tokenizer,
    WordFrequencyEncoder,
)
from keystone_tpu.ops.util.sparse import AllSparseFeatures, CommonSparseFeatures


def test_tokenizer_splits_punct_and_space():
    assert Tokenizer().apply("Hello, world!  foo_bar") == ["Hello", "world", "foo", "bar"]


def test_ngrams_featurizer_orders():
    grams = NGramsFeaturizer([1, 2, 3]).apply(["a", "b", "c"])
    assert ("a",) in grams and ("a", "b") in grams and ("a", "b", "c") in grams
    assert ("b", "c") in grams and ("c",) in grams
    assert len(grams) == 6


def test_ngrams_counts_sorted():
    ds = ObjectDataset([[("a",), ("b",)], [("a",)]])
    pairs = NGramsCounts()(ds)
    assert pairs[0] == (("a",), 2)
    assert (("b",), 1) in pairs


def test_term_frequency():
    tf = dict(TermFrequency().apply(["x", "y", "x"]))
    assert tf[("x")] == 2.0 and tf["y"] == 1.0
    tf1 = dict(TermFrequency(lambda x: 1).apply(["x", "y", "x"]))
    assert tf1["x"] == 1.0


def test_ngrams_hashing_tf_equals_unfused():
    """The reference's contract: NGramsHashingTF == NGramsFeaturizer then
    HashingTF (reference: NGramsHashingTF.scala:17-21)."""
    line = "the quick brown fox jumps over the lazy dog the quick".split()
    for orders in ([1, 2], [2, 3], [1, 2, 3]):
        fused = NGramsHashingTF(orders, 512).apply(line)
        unfused = HashingTF(512).apply(NGramsFeaturizer(orders).apply(line))
        assert (fused != unfused).nnz == 0


def test_hashing_tf_deterministic_across_processes():
    # java_string_hash is salt-free; fixed expected column for a known term
    v = HashingTF(1000).apply(["hello"])
    v2 = HashingTF(1000).apply(["hello"])
    assert (v != v2).nnz == 0
    assert v.nnz == 1


def test_word_frequency_encoder():
    data = ObjectDataset([["a", "b", "a"], ["a", "c"]])
    enc = WordFrequencyEncoder().fit(data)
    assert enc.apply(["a", "b", "zzz"]) == [0, enc.word_index["b"], -1]
    assert enc.unigram_counts[0] == 3  # "a" is rank 0 with count 3


def test_bitpack_indexer_roundtrip():
    idx = NaiveBitPackIndexer()
    packed = idx.pack([3, 7, 11])
    assert idx.ngram_order(packed) == 3
    assert [idx.unpack(packed, p) for p in range(3)] == [3, 7, 11]
    # strip farthest: [7, 11]
    stripped = idx.remove_farthest_word(packed)
    assert idx.ngram_order(stripped) == 2
    assert idx.unpack(stripped, 0) == 7 and idx.unpack(stripped, 1) == 11
    # strip current: [3, 7]
    ctx = idx.remove_current_word(packed)
    assert idx.ngram_order(ctx) == 2
    assert idx.unpack(ctx, 0) == 3 and idx.unpack(ctx, 1) == 7


def test_stupid_backoff_scores():
    """Hand-checkable corpus: 'a a b' — unigrams a:2 b:1, bigrams (a,a):1,
    (a,b):1."""
    unigram_counts = {0: 2, 1: 1}  # a->0, b->1
    ngram_counts = [((0, 0), 1), ((0, 1), 1)]
    model = StupidBackoffEstimator(unigram_counts).fit(ngram_counts)
    # seen bigram: freq(a,a)/freq(a) = 1/2
    np.testing.assert_allclose(model.score((0, 0)), 0.5)
    np.testing.assert_allclose(model.score((0, 1)), 0.5)
    # unseen bigram (b, a): backoff alpha * freq(a)/N = 0.4 * 2/3
    np.testing.assert_allclose(model.score((1, 0)), 0.4 * 2 / 3)
    # unseen trigram (a, a, b): backoff to seen bigram (a,b): 0.4 * 1/2
    np.testing.assert_allclose(model.score((0, 0, 1)), 0.4 * 0.5)


def test_stupid_backoff_with_bitpack_indexer():
    """The bit-pack indexer path must produce identical scores to the tuple
    indexer (reference: StupidBackoffSuite uses NaiveBitPackIndexer)."""
    from keystone_tpu.ops.nlp.indexers import NaiveBitPackIndexer

    unigram_counts = {0: 2, 1: 1}
    ngram_counts = [((0, 0), 1), ((0, 1), 1)]
    idx = NaiveBitPackIndexer()
    model = StupidBackoffEstimator(unigram_counts, indexer=idx).fit(ngram_counts)
    np.testing.assert_allclose(model.score((0, 0)), 0.5)
    np.testing.assert_allclose(model.score((1, 0)), 0.4 * 2 / 3)
    np.testing.assert_allclose(model.score((0, 0, 1)), 0.4 * 0.5)
    # already-packed query gives the same answer
    np.testing.assert_allclose(model.score(idx.pack((0, 1))), 0.5)


def test_common_sparse_features_top_k():
    docs = ObjectDataset(
        [[("a", 1.0), ("b", 1.0)], [("a", 1.0), ("c", 2.0)], [("a", 1.0), ("b", 3.0)]]
    )
    vec = CommonSparseFeatures(2).fit(docs)
    assert set(vec.feature_space) == {"a", "b"}
    row = vec.apply([("a", 5.0), ("c", 7.0), ("b", 1.0)])
    assert row.shape == (1, 2)
    assert row[0, vec.feature_space["a"]] == 5.0
    assert row.nnz == 2  # "c" dropped


def test_all_sparse_features_order():
    docs = ObjectDataset([[("x", 1.0)], [("y", 1.0), ("x", 1.0)], [("z", 1.0)]])
    vec = AllSparseFeatures().fit(docs)
    assert vec.feature_space == {"x": 0, "y": 1, "z": 2}


# ------------------------------------------------------- CoreNLP analog


def test_corenlp_extractor_lemmatized_ngrams():
    from keystone_tpu.ops.nlp.corenlp import CoreNLPFeatureExtractor

    ext = CoreNLPFeatureExtractor(orders=[1, 2])
    out = ext.apply("The cats were running. Dogs barked loudly!")
    # lemmatization: cats->cat, were->be, running->run, dogs->dog,
    # barked->bark; sentence boundary respected (no "run dog" bigram)
    assert "cat" in out and "be" in out and "run" in out
    assert "dog" in out and "bark" in out
    assert "run dog" not in out and "run. dog" not in out
    assert "the cat" in out  # bigram within sentence 1


def test_corenlp_extractor_entity_tagging():
    from keystone_tpu.ops.nlp.corenlp import ENTITY_TAG, CoreNLPFeatureExtractor

    ext = CoreNLPFeatureExtractor(orders=[1])
    out = ext.apply("Yesterday we visited Paris together.")
    assert "LOCATION" in out          # gazetteer proper noun typed
    assert "paris" not in out
    assert "yesterday" in out         # sentence-initial word kept
    # Unknown mid-sentence proper noun falls back to the generic tag.
    out2 = ext.apply("Yesterday we visited Qozvix together.")
    assert ENTITY_TAG in out2 and "qozvix" not in out2


def test_corenlp_reference_suite_parity():
    """The reference's OWN committed test expectations
    (CoreNLPFeatureExtractorSuite.scala:10-63): lemmatization of its five
    words, entity-type substitution on its exact sentence, and the
    1-2-3-gram emission contract."""
    from keystone_tpu.ops.nlp.corenlp import CoreNLPFeatureExtractor

    ext = CoreNLPFeatureExtractor(orders=[1, 2, 3])

    tokens = set(ext.apply("jumping snakes lakes oceans hunted"))
    for lemma in ("jump", "snake", "lake", "ocean", "hunt"):
        assert lemma in tokens, lemma
    for raw in ("jumping", "snakes", "lakes", "oceans", "hunted"):
        assert raw not in tokens, raw

    tokens = set(ext.apply("John likes cake and he lives in Florida"))
    assert "PERSON" in tokens and "LOCATION" in tokens
    assert "john" not in tokens and "florida" not in tokens

    tokens = set(ext.apply("a b c d"))
    for gram in ("a", "b", "c", "d", "a b", "b c", "c d", "a b c", "b c d"):
        assert gram in tokens, gram


def test_corenlp_lemma_gold_fixture_agreement():
    """r4 verdict item 9: measured agreement against the committed lemma
    gold (tests/fixtures/corenlp_lemma_gold.json — curated to mirror
    Stanford Morphology / CoreNLP lemmatizer behavior on common English
    inflections, anchored on the reference suite's committed
    expectations; CoreNLP itself — a JVM dependency — cannot run in this
    environment, so the gold is hand-curated with that provenance stated
    rather than machine-generated). Target: >= 95% agreement."""
    import json
    import os

    from keystone_tpu.ops.nlp.corenlp import lemmatize

    path = os.path.join(os.path.dirname(__file__), "..", "fixtures",
                        "corenlp_lemma_gold.json")
    with open(path) as f:
        gold = json.load(f)
    assert len(gold) >= 300  # a real corpus-scale sample, not a toy list
    misses = {w: (lemmatize(w), g) for w, g in gold.items()
              if lemmatize(w) != g}
    agreement = 1.0 - len(misses) / len(gold)
    assert agreement >= 0.95, (agreement, dict(sorted(misses.items())[:20]))


def test_lemmatize_rules():
    from keystone_tpu.ops.nlp.corenlp import lemmatize

    assert lemmatize("studies") == "study"
    assert lemmatize("running") == "run"
    assert lemmatize("children") == "child"
    assert lemmatize("walked") == "walk"
    assert lemmatize("glasses") == "glass"


def test_corenlp_ambiguous_sentence_initial_names_not_tagged():
    """'Mark the boxes carefully.' — a gazetteer name that is also a
    common English word must NOT be entity-tagged on sentence-initial
    capitalization alone (mid-sentence capitalization still tags it)."""
    from keystone_tpu.ops.nlp.corenlp import CoreNLPFeatureExtractor

    ext = CoreNLPFeatureExtractor(orders=[1])
    out = ext.apply("Mark the boxes carefully.")
    assert "mark" in out and "PERSON" not in out
    out2 = ext.apply("We told Mark about it.")
    assert "PERSON" in out2 and "mark" not in out2
