"""Buffer donation in the solver hot loops (beyond conv_block.py's
donate_argnums=(3,)): the streaming-BCD ping-pong step aliases its
carried predictions/block weights in place, and the fused
normal-equation solves mark their private data copies as buffer donors.

Donation evidence, per the platform's capabilities:
- ``memory_analysis().alias_size_in_bytes > 0`` + input ``is_deleted()``
  where shapes allow true input/output aliasing (the ping-pong carries);
- ``jax.buffer_donor`` markers in the lowered IR where the donated
  buffer feeds temporaries rather than an output (the data matrices).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.parallel import linalg
from keystone_tpu.parallel.mesh import get_mesh


def test_stream_step_donates_pingpong_buffers():
    mesh = get_mesh()
    step = linalg._bcd_stream_step_fn(mesh)
    n_pad, bs, k = 64, 8, 3
    a = linalg.prepare_row_sharded(jnp.ones((n_pad, bs)), mesh)
    mask = linalg.prepare_row_sharded(jnp.ones((n_pad, 1)), mesh)
    y = linalg.prepare_row_sharded(jnp.ones((n_pad, k)), mesh)
    p = linalg.prepare_row_sharded(jnp.zeros((n_pad, k)), mesh)
    w = jnp.zeros((bs, k))
    mu = jnp.zeros((bs,))
    reg = jnp.float32(0.1)

    compiled = step.lower(a, mask, mu, y, p, w, reg).compile()
    assert compiled.memory_analysis().alias_size_in_bytes > 0, (
        "ping-pong carries must alias input→output"
    )

    w2, p2 = step(a, mask, mu, y, p, w, reg)
    # donated carries are dead; non-donated operands stay live
    assert p.is_deleted() and w.is_deleted()
    assert not y.is_deleted() and not mask.is_deleted()
    # and the next step consumes the returned buffers fine (ping-pong)
    a2 = linalg.prepare_row_sharded(jnp.ones((n_pad, bs)), mesh)
    w3, p3 = step(a2, mask, mu, y, p2, w2, reg)
    assert not w3.is_deleted() and not p3.is_deleted()


def _donor_count(lowered_text: str) -> int:
    return lowered_text.count("jax.buffer_donor") + lowered_text.count(
        "tf.aliasing_output"
    )


def test_centered_solve_marks_data_buffers_as_donors():
    mesh = get_mesh()
    x = linalg.prepare_row_sharded(jnp.ones((64, 16)), mesh)
    y = linalg.prepare_row_sharded(jnp.ones((64, 3)), mesh)
    args = (x, y, jnp.float32(64), jnp.float32(1e-6))

    donated = linalg._centered_solve_fused_fn(
        mesh, jax.lax.Precision.DEFAULT, 2, jax.lax.Precision.HIGHEST, 0.0, True
    )
    assert _donor_count(donated.lower(*args).as_text()) == 2

    plain = linalg._centered_solve_fused_fn(
        mesh, jax.lax.Precision.DEFAULT, 2, jax.lax.Precision.HIGHEST, 0.0, False
    )
    assert _donor_count(plain.lower(*args).as_text()) == 0


def test_bcd_donate_variants():
    mesh = get_mesh()
    a = linalg.prepare_row_sharded(jnp.ones((32, 8)), mesh)
    b = linalg.prepare_row_sharded(jnp.ones((32, 2)), mesh)
    bcd = linalg._bcd_fn(mesh, 1, 8, True)
    assert _donor_count(bcd.lower(a, b, jnp.float32(0.1)).as_text()) == 2
    bcd_plain = linalg._bcd_fn(mesh, 1, 8, False)
    assert _donor_count(bcd_plain.lower(a, b, jnp.float32(0.1)).as_text()) == 0


def test_streaming_fit_correct_with_donation():
    """End-to-end: block.py's streaming fit (ping-pong donated per step)
    still converges to the in-core solution."""
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator

    rng = np.random.default_rng(0)
    x = rng.normal(size=(48, 12)).astype(np.float32)
    w_true = rng.normal(size=(12, 2)).astype(np.float32)
    y = x @ w_true

    est = BlockLeastSquaresEstimator(block_size=4, num_iter=4, reg=1e-5)
    in_core = est.fit(ArrayDataset(x), ArrayDataset(y))
    est_stream = BlockLeastSquaresEstimator(
        block_size=4, num_iter=4, reg=1e-5, host_streaming=True
    )
    streamed = est_stream.fit(ArrayDataset(x), ArrayDataset(y))
    np.testing.assert_allclose(
        np.asarray(streamed.apply_arrays(jnp.asarray(x))),
        np.asarray(in_core.apply_arrays(jnp.asarray(x))),
        rtol=2e-4, atol=2e-4,
    )


def test_exact_solver_correct_with_donation():
    """LinearMapEstimator donates its row-sharded copies; the fit must
    stay exact and the source dataset must stay readable."""
    from keystone_tpu.ops.learning.linear import LinearMapEstimator

    rng = np.random.default_rng(1)
    x = rng.normal(size=(64, 10)).astype(np.float32)
    w_true = rng.normal(size=(10, 3)).astype(np.float32)
    y = x @ w_true
    data, labels = ArrayDataset(x), ArrayDataset(y)

    model = LinearMapEstimator(reg=1e-6).fit(data, labels)
    pred = np.asarray(model.apply_arrays(jnp.asarray(x)))
    rel = np.linalg.norm(pred - y) / np.linalg.norm(y)
    assert rel < 1e-4
    # the dataset's own buffers were never donated
    assert np.isfinite(np.asarray(data.data)).all()
    # refitting from the same dataset works (buffers still alive)
    LinearMapEstimator(reg=1e-6).fit(data, labels)
