"""Pallas kernel tests, run in interpret mode on CPU (on-TPU execution is
covered by bench/driver runs)."""

import numpy as np
import pytest

from keystone_tpu.ops.pallas.gaussian import (
    TILE_M,
    TILE_N,
    gaussian_kernel_block_pallas,
)


def _reference(xa, xb, gamma):
    an = np.sum(xa * xa, axis=1, keepdims=True)
    bn = np.sum(xb * xb, axis=1)
    sq = np.maximum(an - 2.0 * xa @ xb.T + bn, 0.0)
    return np.exp(-gamma * sq)


@pytest.mark.parametrize(
    "m,n",
    [
        (TILE_M, TILE_N),          # exact tiles
        (TILE_M + 37, TILE_N - 3),  # padding both ways
        (50, 70),                  # single partial tile
    ],
)
def test_pallas_gaussian_panel_matches_reference(m, n):
    rng = np.random.default_rng(0)
    d, gamma = 24, 0.135
    xa = rng.standard_normal((m, d)).astype(np.float32)
    xb = rng.standard_normal((n, d)).astype(np.float32)
    out = np.asarray(gaussian_kernel_block_pallas(xa, xb, gamma, interpret=True))
    np.testing.assert_allclose(out, _reference(xa, xb, gamma), rtol=2e-5, atol=2e-5)


def test_pallas_gaussian_self_panel_diag_is_one():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((40, 8)).astype(np.float32)
    out = np.asarray(gaussian_kernel_block_pallas(x, x, 0.5, interpret=True))
    np.testing.assert_allclose(np.diag(out), 1.0, atol=1e-5)
