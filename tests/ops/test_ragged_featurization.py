"""Native-resolution (ragged) featurization: size buckets + masked
extractors must reproduce the per-image native-size run exactly — the
reference featurizes every image at its own dimensions
(reference: src/main/cpp/VLFeat.cxx:170-186,
loaders/ImageLoaderUtils.scala:133-211), and this is the VERDICT round-1
item 5 acceptance suite.
"""

import io
import tarfile

import numpy as np
import pytest

from keystone_tpu.data.buckets import bucketize_images, bucketize_dataset
from keystone_tpu.ops.images.lcs import LCSExtractor
from keystone_tpu.ops.images.sift import SIFTExtractor
from keystone_tpu.utils.testing import assert_about_eq


def _records(sizes, seed=0, channels=1):
    rng = np.random.default_rng(seed)
    recs = []
    for i, (x, y) in enumerate(sizes):
        recs.append(
            {
                "image": rng.random((x, y, channels)).astype(np.float32) * 255.0,
                "label": i % 3,
                "filename": f"img{i}",
            }
        )
    return recs


def test_bucketize_groups_and_pads():
    recs = _records([(40, 40), (41, 44), (70, 40), (40, 40)])
    buckets = bucketize_images(recs, granularity=16)
    shapes = sorted(b.bucket_shape for b in buckets)
    # (40,40), (41,44) and the second (40,40) all round to one (48,48)
    # bucket; (70,40) → (80,48)
    assert shapes == [(48, 48), (80, 48)]
    assert sorted(len(b) for b in buckets) == [1, 3]
    big = max(buckets, key=lambda b: b.bucket_shape)
    assert np.array_equal(big.dims[0], [70, 40])
    # padding is edge-replicate: padded rows equal the last native row
    img = big.images[0]
    np.testing.assert_array_equal(img[70], img[69])
    np.testing.assert_array_equal(img[:, 40], img[:, 39])


def test_masked_sift_equals_native_size_run_per_image():
    """Valid descriptors from the bucketed masked run == a native-size
    apply_arrays run, per image, exactly (the 99.5%-within-1 vlfeat bar,
    VLFeatSuite.scala:47-52, met with equality)."""
    sift = SIFTExtractor()
    sizes = [(40, 40), (43, 47), (48, 41)]
    recs = _records(sizes, seed=1)
    (bucket,) = bucketize_images(recs, granularity=16)  # all → (48, 48)

    desc, valid = sift.apply_arrays_masked(bucket.images, bucket.dims)
    desc, valid = np.asarray(desc), np.asarray(valid)

    for i, (x, y) in enumerate(sizes):
        native = np.asarray(
            sift.apply_arrays(bucket.images[i : i + 1, :x, :y, 0])
        )[0]
        got = desc[i][valid[i]]
        assert got.shape == native.shape, f"image {i}: {got.shape} vs {native.shape}"
        assert_about_eq(got, native, thresh=1.5)  # uint8-quantized scale
        within1 = (np.abs(got - native) <= 1).mean()
        assert within1 > 0.995, f"image {i}: only {within1:.3%} within 1"


def test_masked_sift_valid_counts_match_grid_counts():
    sift = SIFTExtractor()
    sizes = [(40, 44), (48, 48)]
    recs = _records(sizes, seed=2)
    (bucket,) = bucketize_images(recs, granularity=16)
    _, valid = sift.apply_arrays_masked(bucket.images, bucket.dims)
    valid = np.asarray(valid)
    for i, (x, y) in enumerate(sizes):
        assert valid[i].sum() == sum(sift.grid_counts(x, y))


def test_masked_lcs_equals_native_size_run_per_image():
    lcs = LCSExtractor(stride=4, stride_start=16, sub_patch_size=6)
    sizes = [(40, 40), (44, 47), (48, 42)]
    recs = _records(sizes, seed=3, channels=3)
    (bucket,) = bucketize_images(recs, granularity=16)

    desc, valid = lcs.apply_arrays_masked(bucket.images, bucket.dims)
    desc, valid = np.asarray(desc), np.asarray(valid)

    for i, (x, y) in enumerate(sizes):
        native = np.asarray(lcs.apply_arrays(bucket.images[i : i + 1, :x, :y]))[0]
        got = desc[i][valid[i]]
        assert got.shape == native.shape, f"image {i}: {got.shape} vs {native.shape}"
        assert_about_eq(got, native, thresh=1e-2)


def test_loader_to_buckets_end_to_end(tmp_path):
    """Mixed-size JPEGs through load_imagenet(resize=None) → buckets →
    masked SIFT: the full native-resolution ingestion path."""
    PIL = pytest.importorskip("PIL")
    from PIL import Image as PILImage

    from keystone_tpu.data.loaders.imagenet import load_imagenet
    from keystone_tpu.ops.images.core import GrayScaler, PixelScaler

    rng = np.random.default_rng(0)

    def jpeg(w, h):
        arr = (rng.random((h, w, 3)) * 255).astype(np.uint8)
        buf = io.BytesIO()
        PILImage.fromarray(arr).save(buf, format="JPEG", quality=95)
        return buf.getvalue()

    tar_path = tmp_path / "shard.tar"
    with tarfile.open(tar_path, "w") as tar:
        for i, (w, h) in enumerate([(40, 40), (45, 41), (64, 50)]):
            payload = jpeg(w, h)
            info = tarfile.TarInfo(f"n01/img{i}.jpg")
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
    (tmp_path / "labels.txt").write_text("n01 0\n")

    ds = load_imagenet(str(tar_path), str(tmp_path / "labels.txt"), resize=None)
    buckets = bucketize_dataset(ds, granularity=16)
    assert sum(len(b) for b in buckets) == 3
    assert all(b.images.shape[1] % 16 == 0 for b in buckets)

    sift = SIFTExtractor()
    gray = GrayScaler()
    pix = PixelScaler()
    for b in buckets:
        g = gray.apply_arrays(pix.apply_arrays(b.images.astype(np.float32)))
        desc, valid = sift.apply_arrays_masked(g, b.dims)
        for i in range(len(b)):
            x, y = b.dims[i]
            assert np.asarray(valid)[i].sum() == sum(sift.grid_counts(int(x), int(y)))


def test_masked_fisher_vector_equals_per_image_encode():
    from keystone_tpu.ops.images.fisher import FisherVector
    from keystone_tpu.ops.learning.gmm import GaussianMixtureModel

    rng = np.random.default_rng(7)
    D, K, n_pad = 8, 4, 20
    gmm = GaussianMixtureModel(
        means=rng.normal(size=(D, K)).astype(np.float32),
        variances=(np.abs(rng.normal(size=(D, K))) + 0.5).astype(np.float32),
        weights=np.full((K,), 1.0 / K, np.float32),
    )
    fv = FisherVector(gmm)

    counts = [20, 13, 7]
    x = np.zeros((3, n_pad, D), np.float32)
    valid = np.zeros((3, n_pad), bool)
    for i, c in enumerate(counts):
        x[i, :c] = rng.normal(size=(c, D))
        x[i, c:] = 99.0  # garbage that must not leak into the encoding
        valid[i, :c] = True

    got = np.asarray(fv.apply_arrays_masked(x, valid))
    for i, c in enumerate(counts):
        want = np.asarray(fv.apply_arrays(x[i : i + 1, :c]))[0]
        assert_about_eq(got[i], want, thresh=1e-3)
