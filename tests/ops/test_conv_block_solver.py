"""Rematerialized conv-block BCD vs explicit featurize→standardize→BCD.

The ConvBlockLeastSquaresEstimator never materializes the feature matrix;
these tests verify it solves exactly the same problem as computing the
features (FusedConvFeaturizer), standardizing them (StandardScaler
semantics), and running BCD over the same block partition.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.images import (
    Convolver,
    FusedConvFeaturizer,
    Pooler,
    SymmetricRectifier,
)
from keystone_tpu.ops.learning.conv_block import ConvBlockLeastSquaresEstimator
from keystone_tpu.parallel import linalg
from keystone_tpu.parallel.mesh import make_mesh, use_mesh


def _featurizer(num_filters=12, seed=0):
    rng = np.random.default_rng(seed)
    filters = rng.normal(size=(num_filters, 6 * 6 * 3)).astype(np.float32) * 0.1
    return FusedConvFeaturizer(
        Convolver(filters, 3, normalize_patches=True),
        SymmetricRectifier(alpha=0.25),
        Pooler(13, 14, None, "sum"),
        filter_block=4,
    )


@pytest.mark.parametrize("num_filters,block_filters", [(12, 4), (10, 4)])
def test_conv_block_solver_matches_explicit(num_filters, block_filters):
    fz = _featurizer(num_filters)
    rng = np.random.default_rng(1)
    n, k = 48, 3
    images = rng.random((n, 32, 32, 3)).astype(np.float32)
    y = rng.normal(size=(n, k)).astype(np.float32)
    fpf = 2 * 2 * 2  # pool 2x2, symmetric rectifier doubles channels
    bs = fpf * block_filters

    mesh = make_mesh(devices=jax.devices()[:8])
    with use_mesh(mesh):
        est = ConvBlockLeastSquaresEstimator(
            fz, block_size=bs, num_iter=1, reg=0.1, image_chunk=6
        )
        model = est.fit(ArrayDataset(images), ArrayDataset(y))

        # Explicit path: featurize, standardize, permute columns into the
        # estimator's block-major order, BCD with the same block size.
        feats = np.asarray(fz.apply_arrays(jnp.asarray(images)))
        mu = feats.mean(axis=0)
        sd = feats.std(axis=0, ddof=1)
        inv_sd = np.where(sd < 1e-8, 1.0, 1.0 / sd)
        feats_std = (feats - mu) * inv_sd

        nb = -(-num_filters // block_filters)
        perm = est._standard_permutation(2, 2, block_filters, nb)
        f_pad = nb * block_filters
        d_std = 2 * 2 * 2 * f_pad
        # Embed real features into the padded-standard layout, then select
        # block-major order (padded-filter columns are zero).
        fi = np.arange(d_std) % (2 * f_pad) % f_pad
        keep = fi < num_filters
        padded = np.zeros((n, d_std), np.float32)
        padded[:, keep] = feats_std
        feats_bm = padded[:, perm]

        yc = y - y.mean(axis=0)
        w_bm = linalg.block_coordinate_descent(
            linalg.prepare_row_sharded(jnp.asarray(feats_bm), mesh),
            linalg.prepare_row_sharded(jnp.asarray(yc), mesh),
            reg=0.1, num_epochs=1, block_size=bs, mesh=mesh,
        )
        ref_pred = feats_bm @ np.asarray(w_bm) + y.mean(axis=0)

        got = np.asarray(model.apply_arrays(jnp.asarray(images)))
    np.testing.assert_allclose(got, ref_pred, rtol=1e-3, atol=1e-4)


def test_conv_block_solver_learns():
    """End-to-end sanity: with enough filters the solver fits random
    labels on the training set far better than chance."""
    fz = _featurizer(16, seed=2)
    rng = np.random.default_rng(3)
    n = 48
    images = rng.random((n, 32, 32, 3)).astype(np.float32)
    labels = -np.ones((n, 4), np.float32)
    cls = rng.integers(0, 4, n)
    labels[np.arange(n), cls] = 1.0

    mesh = make_mesh(devices=jax.devices()[:8])
    with use_mesh(mesh):
        est = ConvBlockLeastSquaresEstimator(
            fz, block_size=32, num_iter=3, reg=1e-4, image_chunk=6
        )
        model = est.fit(ArrayDataset(images), ArrayDataset(labels))
        pred = np.asarray(model.apply_arrays(jnp.asarray(images)))
    acc = (pred.argmax(axis=1) == cls).mean()
    assert acc > 0.8, acc


@pytest.mark.parametrize("standardize", [True, False])
def test_conv_block_solver_reg0_rank_deficient_stays_finite(standardize):
    """reg=0 with more features per block than examples: the scale-aware
    λ floor (standardize→n; else probe featurization) must keep the
    rank-deficient block Cholesky finite — the absolute 1e-6 floor
    silently emitted NaNs here."""
    fz = _featurizer(16, seed=4)
    rng = np.random.default_rng(5)
    n = 8  # features per block (32) > examples
    images = rng.random((n, 32, 32, 3)).astype(np.float32)
    y = rng.normal(size=(n, 2)).astype(np.float32)

    mesh = make_mesh(devices=jax.devices()[:8])
    with use_mesh(mesh):
        est = ConvBlockLeastSquaresEstimator(
            fz, block_size=32, num_iter=2, reg=0.0,
            standardize=standardize, image_chunk=4,
        )
        model = est.fit(ArrayDataset(images), ArrayDataset(y))
        pred = np.asarray(model.apply_arrays(jnp.asarray(images)))
    assert np.isfinite(pred).all()
    rel = np.linalg.norm(pred - y) / np.linalg.norm(y)
    assert rel < 0.2, rel  # interpolating regime: fits train closely
