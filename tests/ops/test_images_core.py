"""Image-core operator tests.

Mirrors the reference's test strategy of comparing operator output against
straight-line reference implementations / golden conv values
(reference: nodes/images/ConvolverSuite.scala, PoolerSuite.scala).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.images import (
    CenterCornerPatcher,
    Convolver,
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
    Pooler,
    RandomPatcher,
    SymmetricRectifier,
    Windower,
    pack_filters,
)
from keystone_tpu.ops.learning.zca import ZCAWhitenerEstimator
from keystone_tpu.utils import image as imutil


def reference_convolve(img, packed_filters, channels, normalize, whitener_means, var_constant=10.0):
    """Direct im2col transliteration of Convolver.scala:128-204 semantics."""
    s = int(np.sqrt(packed_filters.shape[1] // channels))
    rx = img.shape[0] - s + 1
    ry = img.shape[1] - s + 1
    patches = np.zeros((rx * ry, s * s * channels))
    for y in range(ry):
        for x in range(rx):
            for poy in range(s):
                for pox in range(s):
                    for c in range(channels):
                        px = c + pox * channels + poy * channels * s
                        patches[x + y * rx, px] = img[x + pox, y + poy, c]
    if normalize:
        means = patches.mean(axis=1, keepdims=True)
        var = ((patches - means) ** 2).sum(axis=1, keepdims=True) / (patches.shape[1] - 1)
        patches = (patches - means) / np.sqrt(var + var_constant)
    if whitener_means is not None:
        patches = patches - whitener_means
    res = patches @ packed_filters.T  # (rx*ry, F)
    out = np.zeros((rx, ry, packed_filters.shape[0]))
    for y in range(ry):
        for x in range(rx):
            out[x, y, :] = res[x + y * rx]
    return out


@pytest.mark.parametrize("normalize", [False, True])
def test_convolver_matches_im2col_reference(normalize):
    rng = np.random.default_rng(0)
    imgs = rng.normal(size=(3, 10, 9, 3)).astype(np.float32)
    filters = rng.normal(size=(4, 3, 3, 3)).astype(np.float32)
    packed = pack_filters(filters)

    conv = Convolver(packed, img_channels=3, normalize_patches=normalize)
    out = np.asarray(conv.apply_batch(ArrayDataset(imgs)).data)

    for i in range(imgs.shape[0]):
        want = reference_convolve(imgs[i], packed, 3, normalize, None)
        np.testing.assert_allclose(out[i], want, rtol=2e-4, atol=2e-4)


def test_convolver_with_whitener_matches_reference():
    rng = np.random.default_rng(1)
    imgs = rng.normal(size=(2, 8, 8, 1)).astype(np.float32)
    filters = rng.normal(size=(5, 3, 3, 1)).astype(np.float32)
    patch_samples = rng.normal(size=(200, 9)).astype(np.float32)
    whitener = ZCAWhitenerEstimator(eps=0.1).fit_single(patch_samples)

    conv = Convolver.create(filters, whitener=whitener, normalize_patches=True)
    out = np.asarray(conv.apply_batch(ArrayDataset(imgs)).data)

    w = np.asarray(whitener.whitener)
    mu = np.asarray(whitener.means)
    packed_whitened = (pack_filters(filters) - mu) @ w @ w.T
    for i in range(imgs.shape[0]):
        want = reference_convolve(imgs[i], packed_whitened, 1, True, mu)
        np.testing.assert_allclose(out[i], want, rtol=3e-3, atol=3e-3)


def reference_pool(img, stride, pool_size, pixel_fn, pool_fn=np.sum):
    """Transliteration of Pooler.scala:29-68."""
    x_dim, y_dim, channels = img.shape
    start = pool_size // 2
    nx = int(np.ceil((x_dim - start) / stride))
    ny = int(np.ceil((y_dim - start) / stride))
    out = np.zeros((nx, ny, channels))
    for x in range(start, x_dim, stride):
        for y in range(start, y_dim, stride):
            sx, ex = x - pool_size // 2, min(x + pool_size // 2, x_dim)
            sy, ey = y - pool_size // 2, min(y + pool_size // 2, y_dim)
            for c in range(channels):
                pool = np.zeros(pool_size * pool_size)
                idx = 0
                for yy in range(sy, ey):
                    for xx in range(sx, ex):
                        pool[idx] = pixel_fn(img[xx, yy, c])
                        idx += 1
                out[(x - start) // stride, (y - start) // stride, c] = pool_fn(pool)
    return out


@pytest.mark.parametrize("shape,stride,pool", [((12, 12, 2), 4, 4), ((13, 11, 1), 3, 6)])
def test_pooler_matches_reference(shape, stride, pool):
    rng = np.random.default_rng(2)
    img = rng.normal(size=shape)
    pooler = Pooler(stride, pool, pixel_function=abs)
    out = np.asarray(pooler.apply_batch(ArrayDataset(img[None].astype(np.float32))).data[0])
    want = reference_pool(img, stride, pool, abs)
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)


def test_symmetric_rectifier():
    img = np.array([[[1.0, -2.0]]])[None]  # (1,1,1,2)
    out = np.asarray(SymmetricRectifier(alpha=0.5).apply_batch(ArrayDataset(img)).data)
    np.testing.assert_allclose(out[0, 0, 0], [0.5, 0.0, 0.0, 1.5])


def test_grayscale_bgr_weights():
    img = np.zeros((1, 2, 2, 3))
    img[..., 2] = 100.0  # R channel (BGR order)
    out = np.asarray(GrayScaler().apply_batch(ArrayDataset(img)).data)
    np.testing.assert_allclose(out, np.full((1, 2, 2, 1), 29.89), rtol=1e-5)


def test_pixel_scaler_and_vectorizer_layout():
    img = np.arange(2 * 3 * 2, dtype=np.float64).reshape(1, 2, 3, 2)
    vec = np.asarray(ImageVectorizer().apply_batch(ArrayDataset(img)).data)[0]
    # out[c + x*C + y*C*X] == img[x, y, c]
    X, C = 2, 2
    for x in range(2):
        for y in range(3):
            for c in range(2):
                assert vec[c + x * C + y * C * X] == img[0, x, y, c]
    scaled = np.asarray(PixelScaler().apply_batch(ArrayDataset(img)).data)
    np.testing.assert_allclose(scaled, img / 255.0)


def test_windower_counts_and_content():
    rng = np.random.default_rng(3)
    imgs = rng.normal(size=(2, 8, 6, 3)).astype(np.float32)
    out = Windower(stride=2, window_size=4).apply_batch(ArrayDataset(imgs))
    # per image: ((8-4)/2+1) * ((6-4)/2+1) = 3*2 = 6 windows
    assert out.physical_rows == 12
    first = np.asarray(out.data)[0]
    np.testing.assert_allclose(first, imgs[0, 0:4, 0:4, :])
    # x-major ordering: second window advances y first
    second = np.asarray(out.data)[1]
    np.testing.assert_allclose(second, imgs[0, 0:4, 2:6, :])


def test_random_patcher_shapes():
    rng = np.random.default_rng(4)
    imgs = rng.normal(size=(3, 10, 10, 2)).astype(np.float32)
    out = RandomPatcher(5, 4, 4).apply_batch(ArrayDataset(imgs))
    assert np.asarray(out.data).shape == (15, 4, 4, 2)


def test_center_corner_patcher():
    img = np.arange(5 * 5, dtype=np.float64).reshape(1, 5, 5, 1)
    out = CenterCornerPatcher(3, 3, horizontal_flips=True).apply_batch(ArrayDataset(img))
    arr = np.asarray(out.data)
    assert arr.shape == (10, 3, 3, 1)
    np.testing.assert_allclose(arr[0], img[0, 0:3, 0:3, :])  # top-left corner
    np.testing.assert_allclose(arr[1], imutil.flip_horizontal(img[0, 0:3, 0:3, :]))
    np.testing.assert_allclose(arr[8], img[0, 1:4, 1:4, :])  # center


def test_conv2d_separable_same_shape():
    rng = np.random.default_rng(5)
    img = rng.normal(size=(9, 7, 2))
    out = imutil.conv2d_separable(img, np.array([1.0, 2.0, 1.0]), np.array([1.0, 1.0]))
    assert out.shape == img.shape


def test_vectorize_roundtrip():
    rng = np.random.default_rng(6)
    img = rng.normal(size=(4, 5, 3))
    meta = imutil.ImageMetadata.of(img)
    vec = imutil.vectorize(img)
    np.testing.assert_allclose(imutil.unvectorize(vec, meta), img)


# ------------------------------------------------------- fused featurizer


def _cifar_ops(num_filters=37, alpha=0.25, whitener=None, normalize=True):
    from keystone_tpu.ops.images import (
        Convolver,
        FusedConvFeaturizer,
        ImageVectorizer,
        Pooler,
        SymmetricRectifier,
    )

    rng = np.random.default_rng(0)
    filters = rng.normal(size=(num_filters, 6 * 6 * 3)).astype(np.float32) * 0.1
    conv = Convolver(filters, 3, whitener=whitener, normalize_patches=normalize)
    rect = SymmetricRectifier(alpha=alpha)
    pool = Pooler(13, 14, None, "sum")
    return conv, rect, pool, ImageVectorizer()


@pytest.mark.parametrize("filter_block", [8, 16, 37, 64])
def test_fused_conv_featurizer_matches_unfused(filter_block):
    from keystone_tpu.ops.images import FusedConvFeaturizer

    conv, rect, pool, vec = _cifar_ops()
    rng = np.random.default_rng(1)
    imgs = jnp.asarray(rng.random((5, 32, 32, 3), dtype=np.float32))
    ref = vec.apply_arrays(pool.apply_arrays(rect.apply_arrays(conv.apply_arrays(imgs))))
    fused = FusedConvFeaturizer(conv, rect, pool, filter_block=filter_block).apply_arrays(imgs)
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(ref), rtol=1e-4, atol=1e-6
    )


def test_fused_conv_featurizer_with_whitener_and_no_normalize():
    from keystone_tpu.ops.learning.zca import ZCAWhitenerEstimator
    from keystone_tpu.ops.images import FusedConvFeaturizer

    rng = np.random.default_rng(2)
    whitener = ZCAWhitenerEstimator(eps=0.1).fit_single(
        rng.normal(size=(200, 6 * 6 * 3)).astype(np.float32)
    )
    for normalize in (True, False):
        conv, rect, pool, vec = _cifar_ops(
            num_filters=20, whitener=whitener, normalize=normalize
        )
        imgs = jnp.asarray(rng.random((3, 32, 32, 3), dtype=np.float32))
        ref = vec.apply_arrays(
            pool.apply_arrays(rect.apply_arrays(conv.apply_arrays(imgs)))
        )
        fused = FusedConvFeaturizer(conv, rect, pool, filter_block=7).apply_arrays(imgs)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_fused_conv_featurizer_in_pipeline():
    """The fused featurizer slots into the Pipeline API like the ops it
    replaces (build_random_patch uses it at numFilters=10000 scale)."""
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.images import FusedConvFeaturizer

    conv, rect, pool, _ = _cifar_ops(num_filters=12)
    rng = np.random.default_rng(3)
    imgs = ArrayDataset(rng.random((4, 32, 32, 3)).astype(np.float32))
    pipe = FusedConvFeaturizer(conv, rect, pool, filter_block=5).to_pipeline()
    out = pipe(imgs).get()
    assert np.asarray(out.data).shape == (4, 2 * 2 * 24)
