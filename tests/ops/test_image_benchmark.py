"""Microbenchmark-as-test suite over the image operators.

Analog of the reference's ImageBenchMarkSuite
(reference: src/test/scala/keystoneml/nodes/images/ImageBenchMarkSuite.scala):
the same conv/pool parameter grid (CIFAR at three filter counts, an
ImageNet-shaped config, a multi-channel "SolarFlares" config), run as
timed correctness tests — each asserts output geometry and prints the
measured throughput, so the suite doubles as a regression harness for
featurizer performance on whatever backend runs the tests.
"""

import time
from dataclasses import dataclass

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.ops.images import (
    Convolver,
    FusedConvFeaturizer,
    Pooler,
    SymmetricRectifier,
)


@dataclass(frozen=True)
class BenchParam:
    """reference: ImageBenchMarkSuite.scala TestParam (pool args there are
    (poolSize, poolStride) reversed in the array literal; sizes below
    mirror the reference's intent of a 2x2-ish pooled grid)."""

    name: str
    size: tuple
    kernel_size: int
    num_kernels: int
    pool_stride: int
    pool_size: int


# The reference's grid, scaled where a config would thrash a CI CPU
# (filter counts capped at 1000; the 100-channel conv input trimmed).
PARAMS = [
    BenchParam("Cifar100", (32, 32, 3), 6, 100, 13, 14),
    BenchParam("Cifar1000", (32, 32, 3), 6, 1000, 13, 14),
    BenchParam("ImageNet", (128, 128, 3), 6, 100, (128 - 5) // 2, (128 - 5) // 2),
    BenchParam("SolarFlares", (96, 96, 12), 6, 64, (96 - 5) // 12, (96 - 5) // 12),
]


def _throughput(fn, arg, iters=3):
    jax.block_until_ready(fn(arg))  # compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(arg))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


@pytest.mark.parametrize("p", PARAMS, ids=[p.name for p in PARAMS])
def test_conv_featurizer_benchmark(p):
    rng = np.random.default_rng(0)
    x_dim, y_dim, channels = p.size
    filters = rng.normal(
        size=(p.num_kernels, p.kernel_size**2 * channels)
    ).astype(np.float32) * 0.1
    fz = FusedConvFeaturizer(
        Convolver(filters, channels, normalize_patches=True),
        SymmetricRectifier(alpha=0.25),
        Pooler(p.pool_stride, p.pool_size, None, "sum"),
        filter_block=min(256, p.num_kernels),
    )
    n = 16
    imgs = jnp.asarray(rng.random((n, x_dim, y_dim, channels), dtype=np.float32))
    fn = jax.jit(fz.apply_arrays)
    sec = _throughput(fn, imgs)

    rx, ry = x_dim - p.kernel_size + 1, y_dim - p.kernel_size + 1
    pooled = Pooler(p.pool_stride, p.pool_size, None, "sum").apply_arrays(
        jnp.zeros((1, rx, ry, 1))
    )
    expect_d = int(pooled.shape[1]) * int(pooled.shape[2]) * 2 * p.num_kernels
    out = fn(imgs)
    assert out.shape == (n, expect_d)
    conv_flops = 2.0 * n * rx * ry * p.kernel_size**2 * channels * p.num_kernels
    print(
        f"\n[bench:{p.name}] {n / sec:8.1f} img/s  "
        f"{conv_flops / sec / 1e9:8.1f} conv GFLOP/s  d={expect_d}"
    )


@pytest.mark.parametrize("p", PARAMS[:2], ids=[p.name for p in PARAMS[:2]])
def test_pooler_benchmark(p):
    rng = np.random.default_rng(1)
    x_dim, y_dim, _ = p.size
    rx, ry = x_dim - p.kernel_size + 1, y_dim - p.kernel_size + 1
    x = jnp.asarray(rng.random((32, rx, ry, p.num_kernels), dtype=np.float32))
    pool = Pooler(p.pool_stride, p.pool_size, None, "sum")
    fn = jax.jit(pool.apply_arrays)
    sec = _throughput(fn, x)
    out = fn(x)
    assert out.shape[0] == 32 and out.shape[-1] == p.num_kernels
    print(f"\n[bench:pool:{p.name}] {32 / sec:9.1f} img/s {tuple(out.shape)}")


def test_sift_benchmark():
    from keystone_tpu.ops.images.sift import SIFTExtractor

    rng = np.random.default_rng(2)
    imgs = jnp.asarray(rng.random((4, 128, 128), dtype=np.float32))
    ext = SIFTExtractor(scale_step=1)
    fn = jax.jit(ext.apply_arrays)
    sec = _throughput(fn, imgs)
    out = fn(imgs)
    assert out.shape[0] == 4 and out.shape[2] == 128
    print(f"\n[bench:sift] {4 / sec:6.1f} img/s  descriptors/img={out.shape[1]}")


def test_hog_benchmark():
    from keystone_tpu.ops.images.hog import HogExtractor

    rng = np.random.default_rng(3)
    imgs = jnp.asarray(rng.random((4, 64, 64, 3), dtype=np.float32))
    ext = HogExtractor()
    fn = jax.jit(ext.apply_arrays)
    sec = _throughput(fn, imgs)
    out = fn(imgs)
    assert out.shape[0] == 4
    print(f"\n[bench:hog] {4 / sec:6.1f} img/s  dim={out.shape[1:]}")
