"""Cost-constant plumbing: measured-on-chip weights must actually steer
the meta-solver (round-2 verdict item 4's test leg).

The reference fit its constants on its cluster and shipped them
(reference: nodes/learning/LeastSquaresEstimator.scala:17-31,
scripts/solver-comparisons-final.csv); here the analogous artifact is
keystone_tpu/ops/learning/tpu_cost_constants.json written by
scripts/solver_comparison.py --fit-constants on the chip.
"""

import json

import numpy as np
import pytest

from keystone_tpu.ops.learning import cost as cost_mod
from keystone_tpu.ops.learning.cost import CostWeights
from keystone_tpu.ops.learning.least_squares import LeastSquaresEstimator
from keystone_tpu.workflow.optimize import DataStats


def _choice(weights, n, d, k, sparsity=1.0, machines=1):
    """The meta-solver's pick for given stats/weights, via the same cost
    comparison optimize() runs (shape stats supplied directly)."""
    from keystone_tpu.data.dataset import ArrayDataset

    est = LeastSquaresEstimator(weights=weights, num_machines=machines)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, d)).astype(np.float32)
    if sparsity < 1.0:
        mask = rng.random((32, d)) < sparsity
        x = x * mask
    y = rng.normal(size=(32, k)).astype(np.float32)
    picked = est.optimize(
        [ArrayDataset(x), ArrayDataset(y)],
        DataStats(n_total=n, num_shards=1, n_per_shard=[n]),
    )
    return type(picked).__name__


def test_measured_constants_file_preferred(tmp_path, monkeypatch):
    path = tmp_path / "tpu_cost_constants.json"
    path.write_text(json.dumps({"cpu": 1e-11, "mem": 2e-9, "network": 3e-8}))
    monkeypatch.setattr(cost_mod, "MEASURED_CONSTANTS_PATH", str(path))
    w = cost_mod.default_cost_weights(backend="tpu")
    assert w == CostWeights(cpu=1e-11, mem=2e-9, network=3e-8)


def test_missing_or_corrupt_measured_file_falls_back(tmp_path, monkeypatch):
    monkeypatch.setattr(
        cost_mod, "MEASURED_CONSTANTS_PATH", str(tmp_path / "nope.json")
    )
    assert cost_mod.default_cost_weights(backend="tpu") == cost_mod.tpu_weights()
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    monkeypatch.setattr(cost_mod, "MEASURED_CONSTANTS_PATH", str(bad))
    assert cost_mod.default_cost_weights(backend="tpu") == cost_mod.tpu_weights()


def test_cpu_backend_keeps_reference_constants():
    assert (
        cost_mod.default_cost_weights(backend="cpu")
        == cost_mod.DEFAULT_COST_WEIGHTS
    )


def test_weights_are_load_bearing():
    """optimize() must actually consume the weights: a compute-dominated
    and a network-dominated weight set must disagree somewhere on a shape
    grid — guards against the weights being plumbed but ignored."""
    cpu_heavy = CostWeights(cpu=1e-6, mem=1e-15, network=1e-15)
    net_heavy = CostWeights(cpu=1e-15, mem=1e-15, network=1e-3)
    grid = [
        (n, d, k)
        for n in (10_000, 1_000_000)
        for d in (128, 1024, 4096)
        for k in (2, 138)
    ]
    flips = [
        (n, d, k)
        for (n, d, k) in grid
        if _choice(cpu_heavy, n, d, k, machines=8)
        != _choice(net_heavy, n, d, k, machines=8)
    ]
    assert flips, "no shape flips the solver choice between weight sets"


def test_meta_solver_prediction_matches_measured_sweep():
    """With the committed on-chip constants, the meta-solver's pick at
    each measured dense shape must be (near-)fastest among what the sweep
    actually measured — the end-to-end check that the refit makes
    auto-selection reflect this machine (reference analog:
    LeastSquaresEstimator's constants reproducing
    solver-comparisons-final.csv's winners)."""
    import csv
    import os

    csv_path = os.path.join(
        os.path.dirname(__file__), "..", "..", "scripts",
        "solver-comparisons-tpu.csv",
    )
    w = cost_mod.measured_tpu_weights()
    if w is None or not os.path.exists(csv_path):
        pytest.skip("on-chip sweep/constants not committed yet")

    by_shape = {}
    with open(csv_path) as f:
        for row in csv.DictReader(f):
            key = (int(row["n"]), int(row["d"]), int(row["k"]), float(row["sparsity"]))
            by_shape.setdefault(key, {})[row["solver"]] = float(row["ms"])

    name_map = {
        "LinearMapEstimator": "exact",
        "BlockLeastSquaresEstimator": "block",
        "DenseLBFGSEstimator": "lbfgs",
        "SparseLBFGSEstimator": "sparse_lbfgs",
    }
    for (n, d, k, sparsity), times in by_shape.items():
        if len(times) < 2:
            continue  # single-candidate shapes can't mis-rank
        picked = name_map[_choice(w, n, d, k, sparsity=sparsity)]
        if picked not in times:
            continue  # picked solver wasn't measured at this shape
        fastest = min(times.values())
        argmin = min(times, key=times.get)
        # r3 verdict item 7: model-argmin must equal measured-argmin on
        # every sweep row (a 5% band absorbs measurement noise on ties).
        assert picked == argmin or times[picked] <= 1.05 * fastest, (
            f"at (n={n}, d={d}, k={k}, sp={sparsity}) picked {picked} "
            f"({times[picked]:.0f} ms) vs fastest {argmin} "
            f"({fastest:.0f} ms): {times}"
        )


def test_sparse_data_picks_sparse_solver():
    """The Amazon asymmetry (reference csv: sparse d=16384 inverts the
    winner, solver-comparisons-final.csv:11-12) must survive any weights:
    very sparse wide data routes to the sparse LBFGS path."""
    for w in (cost_mod.DEFAULT_COST_WEIGHTS, cost_mod.tpu_weights()):
        picked = _choice(w, 50_000_000, 16384, 2, sparsity=0.005)
        assert picked == "SparseLBFGSEstimator", (w, picked)


def test_measured_constants_committed_and_sane():
    """Once the on-chip refit has run, the committed JSON must load and
    carry positive weights fitted on a TPU device kind."""
    w = cost_mod.measured_tpu_weights()
    if w is None:
        pytest.skip("tpu_cost_constants.json not committed yet")
    assert w.cpu > 0 and w.mem > 0 and w.network > 0
    with open(cost_mod.MEASURED_CONSTANTS_PATH) as f:
        payload = json.load(f)
    assert "fitted_on" in payload


def test_measured_constants_physically_plausible():
    """r3 verdict item 7: the fitted weights may not imply a machine
    faster than first principles (r3's unbounded fit implied 2e16 flop/s
    — 100x v5e peak), and the committed per-row residuals must be under
    the 25% band the fit model claims."""
    w = cost_mod.measured_tpu_weights()
    if w is None:
        pytest.skip("tpu_cost_constants.json not committed yet")
    fp = cost_mod.tpu_weights()
    assert w.cpu >= fp.cpu, (w.cpu, fp.cpu)
    assert w.mem >= fp.mem, (w.mem, fp.mem)
    assert w.network >= fp.network, (w.network, fp.network)
    with open(cost_mod.MEASURED_CONSTANTS_PATH) as f:
        payload = json.load(f)
    per_row = payload.get("per_row_rel_residual", {})
    assert per_row, "refit must report per-row residuals"
    worst = max(per_row.values())
    assert worst < 0.25, per_row
