"""Dense SIFT vs a committed OpenCV fixture — the external oracle.

The reference validated its native SIFT against MATLAB vl_phow output
with a committed fixture and a tolerance test
(reference: src/test/scala/keystoneml/utils/external/VLFeatSuite.scala:34-52).
Here the oracle is OpenCV's SIFT evaluated at our dense grid's keypoints
(generated once by scripts/make_sift_fixture.py; OpenCV is not needed to
run the test). Exact equality is not expected — OpenCV uses a Gaussian
spatial window, vl_dsift semantics use a flat window — so the assertion
is cosine similarity of the quantized descriptors under the fixed
convention map, which still breaks loudly on any axis-order,
orientation-binning, normalization, or quantization bug.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.ops.images.sift import SIFTExtractor

FIXTURE_DIR = os.path.join(
    os.path.dirname(__file__), "..", "fixtures", "sift_opencv"
)
BIN_SIZE = 4
STEP = 4
IMG_SIZE = 80

# Convention map from our (xbin, ybin, orient) layout to OpenCV's,
# probed over sizes/shifts (see scripts/make_sift_fixture.py docstring):
# swap the spatial bin axes, roll orientation by 6.
SWAP_XY = True
ORIENT_ROLL = 6


def _make_image(seed: int) -> np.ndarray:
    from scipy.ndimage import gaussian_filter

    rng = np.random.default_rng(seed)
    base = rng.random((IMG_SIZE, IMG_SIZE)).astype(np.float32)
    img = gaussian_filter(base, 3.0, mode="nearest")
    return (img - img.min()) / (img.max() - img.min())


def _to_opencv_layout(desc: np.ndarray) -> np.ndarray:
    d = desc.reshape(-1, 4, 4, 8)
    if SWAP_XY:
        d = np.transpose(d, (0, 2, 1, 3))
    return np.roll(d, ORIENT_ROLL, axis=-1).reshape(-1, 128)


def _load_fixture(seed: int) -> np.ndarray:
    return np.loadtxt(
        os.path.join(FIXTURE_DIR, f"opencv_dsift_seed{seed}.csv"), delimiter=","
    ).astype(np.float32)


def _cosines_vs_fixture(desc: np.ndarray, fixture: np.ndarray) -> np.ndarray:
    mapped = _to_opencv_layout(desc)
    na = np.linalg.norm(mapped, axis=1) + 1e-9
    nb = np.linalg.norm(fixture, axis=1) + 1e-9
    return (mapped * fixture).sum(axis=1) / (na * nb)


@pytest.mark.parametrize("seed", [42, 7])
def test_sift_matches_opencv_fixture(seed):
    fixture = _load_fixture(seed)

    img = _make_image(seed)
    # The fixture image is [0,1]·255-quantized before OpenCV sees it;
    # match that exactly so the comparison is apples-to-apples.
    img_q = (img * 255).astype(np.uint8).astype(np.float32) / 255.0
    ext = SIFTExtractor(step_size=STEP, bin_size=BIN_SIZE, scales=1, scale_step=1)
    ours = np.asarray(ext.apply_arrays(jnp.asarray(img_q[None])))[0]
    assert ours.shape == fixture.shape

    cos = _cosines_vs_fixture(ours, fixture)

    # A wrong axis order / orientation roll drops mean cosine below ~0.75
    # (probed); correct implementation sits near 0.98.
    assert cos.mean() > 0.95, f"mean cosine {cos.mean():.3f}"
    assert np.quantile(cos, 0.1) > 0.9, f"p10 cosine {np.quantile(cos, 0.1):.3f}"


def test_convention_map_is_the_best_one():
    """The committed (swap, roll) convention must be the argmax over all
    candidate maps — guards against the map silently compensating for a
    future axis bug in the extractor."""
    seed = 42
    fixture = _load_fixture(seed)
    img = _make_image(seed)
    img_q = (img * 255).astype(np.uint8).astype(np.float32) / 255.0
    ext = SIFTExtractor(step_size=STEP, bin_size=BIN_SIZE, scales=1, scale_step=1)
    ours = np.asarray(ext.apply_arrays(jnp.asarray(img_q[None])))[0]

    def mean_cos(cand):
        na = np.linalg.norm(cand, axis=1) + 1e-9
        nb = np.linalg.norm(fixture, axis=1) + 1e-9
        return float(((cand * fixture).sum(axis=1) / (na * nb)).mean())

    o = ours.reshape(-1, 4, 4, 8)
    scores = {}
    for swap in (False, True):
        base = np.transpose(o, (0, 2, 1, 3)) if swap else o
        for rev in (False, True):
            ob = base[..., ::-1] if rev else base
            for shift in range(8):
                scores[(swap, rev, shift)] = mean_cos(
                    np.roll(ob, shift, axis=-1).reshape(-1, 128)
                )
    best = max(scores, key=scores.get)
    assert best == (SWAP_XY, False, ORIENT_ROLL), (
        f"best map {best} (cos {scores[best]:.3f}) != committed "
        f"({SWAP_XY}, False, {ORIENT_ROLL}) (cos {scores[(SWAP_XY, False, ORIENT_ROLL)]:.3f})"
    )


def test_bf16_binning_passes_the_reference_tolerance():
    """bf16 spatial binning (docs/NEXT_LEVERS.md item 3) must hold the
    reference's own acceptance gate vs the fp32 build: 99.5% of
    x512-quantized entries within 1 (VLFeatSuite.scala:47-52), plus the
    OpenCV-fixture cosine gate. (Full-pyramid bf16 was measured FAILING
    this gate at 97.5% — the smoother feeds a gradient stencil that
    amplifies rounding — which is why only the binning conv has a dtype
    knob.)"""
    img = _make_image(42)
    img_q = (img * 255).astype(np.uint8).astype(np.float32) / 255.0
    batch = jnp.asarray(img_q[None])

    f32 = np.asarray(
        SIFTExtractor(step_size=STEP, bin_size=BIN_SIZE, scales=1).apply_arrays(batch)
    )[0]
    b16 = np.asarray(
        SIFTExtractor(
            step_size=STEP, bin_size=BIN_SIZE, scales=1,
            binning_dtype=jnp.bfloat16,
        ).apply_arrays(batch)
    )[0]
    close = np.abs(b16.astype(np.float64) - f32.astype(np.float64)) <= 1.0
    assert close.mean() > 0.995, f"within-1 fraction {close.mean():.4f}"

    cos = _cosines_vs_fixture(b16, _load_fixture(42))
    assert cos.mean() > 0.95, f"mean cosine {cos.mean():.3f}"


def test_bf16_binning_masked_path_matches_native():
    """The production native-resolution path (apply_arrays_masked) under
    bf16 binning: padded-bucket descriptors must stay within-1 of the
    SAME extractor's native-size run — the parity the imagenet_native
    workload relies on if the default ever flips."""
    ext = SIFTExtractor(scale_step=1, binning_dtype=jnp.bfloat16)
    rng = np.random.default_rng(3)
    small, big = 40, 64
    img = rng.random((small, small)).astype(np.float32)
    padded = np.pad(img, ((0, big - small), (0, big - small)), mode="edge")
    desc, valid = ext.apply_arrays_masked(
        jnp.asarray(padded[None]), jnp.asarray([[small, small]], jnp.int32)
    )
    native = np.asarray(ext.apply_arrays(jnp.asarray(img[None])))
    got = np.asarray(desc)[0][np.asarray(valid)[0]]
    assert got.shape == native[0].shape
    frac = (np.abs(got - native[0]) <= 1.0).mean()
    assert frac > 0.995, frac
