"""Stats ops vs numpy golden values."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.stats.core import (
    CosineRandomFeatures,
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
    SignedHellingerMapper,
    StandardScaler,
    Sampler,
)


def test_random_sign_node():
    node = RandomSignNode.create(16, seed=0)
    signs = np.asarray(node.signs)
    assert set(np.unique(signs)) <= {-1.0, 1.0}
    x = np.random.default_rng(1).normal(size=(4, 16)).astype(np.float32)
    out = np.asarray(node.apply_batch(ArrayDataset(x)).data)
    np.testing.assert_allclose(out, x * signs, rtol=1e-6)


def test_padded_fft_matches_numpy():
    x = np.random.default_rng(0).normal(size=(3, 20)).astype(np.float32)
    out = np.asarray(PaddedFFT().apply_batch(ArrayDataset(x)).data)
    # pad 20 -> 32, full fft, real part of first 16
    padded = np.pad(x, ((0, 0), (0, 12)))
    expected = np.fft.fft(padded, axis=-1).real[:, :16]
    assert out.shape == (3, 16)
    np.testing.assert_allclose(out, expected, rtol=1e-3, atol=1e-4)


def test_padded_fft_power_of_two_input():
    x = np.random.default_rng(0).normal(size=(2, 16)).astype(np.float32)
    out = np.asarray(PaddedFFT().apply_batch(ArrayDataset(x)).data)
    assert out.shape == (2, 8)


def test_linear_rectifier():
    x = np.array([[-1.0, 0.5, 2.0]], dtype=np.float32)
    out = np.asarray(LinearRectifier(0.0, 1.0).apply_batch(ArrayDataset(x)).data)
    np.testing.assert_allclose(out, [[0.0, 0.0, 1.0]])


def test_normalize_rows():
    x = np.array([[3.0, 4.0], [0.0, 0.0]], dtype=np.float32)
    out = np.asarray(NormalizeRows().apply_batch(ArrayDataset(x)).data)
    np.testing.assert_allclose(out, [[0.6, 0.8], [0.0, 0.0]], rtol=1e-6)


def test_signed_hellinger():
    x = np.array([[-4.0, 9.0]], dtype=np.float32)
    out = np.asarray(SignedHellingerMapper().apply_batch(ArrayDataset(x)).data)
    np.testing.assert_allclose(out, [[-2.0, 3.0]], rtol=1e-6)


def test_standard_scaler_mean_and_std():
    rng = np.random.default_rng(0)
    x = (rng.normal(size=(200, 5)) * [1, 2, 3, 4, 5] + [10, 0, -5, 1, 2]).astype(np.float32)
    model = StandardScaler().fit(ArrayDataset(x))
    out = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_allclose(out.std(axis=0, ddof=1), 1.0, atol=1e-3)


def test_standard_scaler_mean_only():
    x = np.random.default_rng(0).normal(size=(50, 3)).astype(np.float32)
    model = StandardScaler(normalize_std_dev=False).fit(ArrayDataset(x))
    assert model.std is None
    out = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-5)


def test_standard_scaler_constant_column_guard():
    x = np.ones((10, 2), dtype=np.float32)
    model = StandardScaler().fit(ArrayDataset(x))
    np.testing.assert_allclose(np.asarray(model.std), 1.0)


def test_standard_scaler_respects_padding_mask():
    x = np.random.default_rng(0).normal(size=(10, 3)).astype(np.float32)
    padded = np.concatenate([x, np.zeros((6, 3), dtype=np.float32)])
    model_pad = StandardScaler().fit(ArrayDataset(padded, num_examples=10))
    model_raw = StandardScaler().fit(ArrayDataset(x))
    np.testing.assert_allclose(np.asarray(model_pad.mean), np.asarray(model_raw.mean), atol=1e-5)
    np.testing.assert_allclose(np.asarray(model_pad.std), np.asarray(model_raw.std), atol=1e-5)


def test_sampler():
    x = np.arange(100, dtype=np.float32).reshape(100, 1)
    out = Sampler(10, seed=0).apply_batch(ArrayDataset(x))
    assert len(out) == 10


def test_cosine_random_features_matches_numpy():
    """cos(xWᵀ + b) vs numpy golden values
    (reference: nodes/stats/CosineRandomFeaturesSuite)."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 5)).astype(np.float32)
    w = rng.normal(size=(7, 5))
    b = rng.uniform(0, 2 * np.pi, size=7)
    out = CosineRandomFeatures(w, b).apply_batch(ArrayDataset(x))
    expected = np.cos(x @ w.T.astype(np.float32) + b.astype(np.float32))
    np.testing.assert_allclose(np.asarray(out.data), expected, atol=1e-5)


def test_cosine_random_features_create_shapes_and_dists():
    t = CosineRandomFeatures.create(5, 16, gamma=0.5, dist="gaussian", seed=1)
    assert t.w.shape == (16, 5) and t.b.shape == (16,)
    c = CosineRandomFeatures.create(5, 16, gamma=0.5, dist="cauchy", seed=1)
    assert c.w.shape == (16, 5)
    # Cauchy tails are heavier: max |w| should exceed the gaussian's
    assert float(abs(np.asarray(c.w)).max()) > float(abs(np.asarray(t.w)).max())
    with pytest.raises(ValueError):
        CosineRandomFeatures.create(5, 16, 0.5, dist="laplace")


def test_cosine_random_features_mismatched_b():
    with pytest.raises(ValueError):
        CosineRandomFeatures(np.ones((4, 3)), np.ones(5))
