"""Linear & block solvers vs closed-form solutions (reference:
LinearMapperSuite, BlockLinearMapperSuite, LocalLeastSquaresSuite)."""

import numpy as np

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.ops.learning.linear import (
    LinearMapEstimator,
    LinearMapper,
    LocalLeastSquaresEstimator,
)


def make_problem(n=256, d=16, k=4, seed=0, noise=0.0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, k)).astype(np.float32)
    b = rng.normal(size=(k,)).astype(np.float32)
    y = x @ w + b + noise * rng.normal(size=(n, k)).astype(np.float32)
    return x, y, w, b


def closed_form(x, y, reg=0.0):
    mu_a, mu_b = x.mean(0), y.mean(0)
    xc, yc = x - mu_a, y - mu_b
    w = np.linalg.solve(xc.T @ xc + reg * np.eye(x.shape[1]), xc.T @ yc)
    return w, mu_a, mu_b


def test_linear_map_estimator_recovers_model():
    x, y, w_true, b_true = make_problem()
    model = LinearMapEstimator().fit(ArrayDataset(x), ArrayDataset(y))
    pred = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    np.testing.assert_allclose(pred, y, rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(model.weights), w_true, rtol=1e-2, atol=1e-2)


def test_linear_map_estimator_ridge_matches_closed_form():
    x, y, _, _ = make_problem(noise=0.5)
    reg = 2.0
    w_exp, mu_a, mu_b = closed_form(x, y, reg)
    model = LinearMapEstimator(reg=reg).fit(ArrayDataset(x), ArrayDataset(y))
    np.testing.assert_allclose(np.asarray(model.weights), w_exp, rtol=1e-2, atol=1e-3)
    np.testing.assert_allclose(np.asarray(model.intercept), mu_b, atol=1e-4)


def test_linear_map_single_datum():
    x, y, _, _ = make_problem()
    model = LinearMapEstimator().fit(ArrayDataset(x), ArrayDataset(y))
    single = model.apply(x[0])
    np.testing.assert_allclose(np.asarray(single), y[0], rtol=5e-2, atol=5e-2)


def test_local_least_squares_matches_distributed():
    x, y, _, _ = make_problem(noise=0.3)
    reg = 1.0
    dist = LinearMapEstimator(reg=reg).fit(ArrayDataset(x), ArrayDataset(y))
    local = LocalLeastSquaresEstimator(reg=reg).fit(ArrayDataset(x), ArrayDataset(y))
    np.testing.assert_allclose(
        np.asarray(dist.weights), np.asarray(local.weights), rtol=1e-2, atol=1e-3
    )


def test_block_least_squares_converges():
    x, y, _, _ = make_problem(n=512, d=24, k=3, noise=0.1)
    reg = 0.5
    w_exp, mu_a, mu_b = closed_form(x, y, reg)
    est = BlockLeastSquaresEstimator(block_size=8, num_iter=40, reg=reg)
    model = est.fit(ArrayDataset(x), ArrayDataset(y))
    np.testing.assert_allclose(np.asarray(model.weights)[:24], w_exp, rtol=5e-2, atol=5e-3)
    pred = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    expected_pred = (x - mu_a) @ w_exp + mu_b
    np.testing.assert_allclose(pred, expected_pred, rtol=5e-2, atol=5e-2)


def test_block_least_squares_with_feature_padding():
    # d=10 not divisible by block 4 → internal zero-padding must be harmless
    x, y, _, _ = make_problem(n=128, d=10, k=2)
    est = BlockLeastSquaresEstimator(block_size=4, num_iter=30, reg=0.1)
    model = est.fit(ArrayDataset(x), ArrayDataset(y))
    w_exp, _, _ = closed_form(x, y, 0.1)
    np.testing.assert_allclose(np.asarray(model.weights)[:10], w_exp, rtol=5e-2, atol=1e-2)


def test_estimator_weight_for_cache_planner():
    est = BlockLeastSquaresEstimator(block_size=4, num_iter=5)
    assert est.weight == 16


def test_block_mapper_apply_and_evaluate_streams_per_block():
    """Streaming per-block evaluation: evaluator sees one cumulative
    prediction per feature block and the final one equals apply()
    (reference: BlockLinearMapper.scala:89-135)."""
    import numpy as np

    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
    from keystone_tpu.utils.testing import assert_about_eq

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 12)).astype(np.float32)
    y = rng.normal(size=(32, 3)).astype(np.float32)
    model = BlockLeastSquaresEstimator(block_size=4, num_iter=3, reg=0.1).fit(
        ArrayDataset(x), ArrayDataset(y)
    )

    seen = []
    model.apply_and_evaluate(x, lambda p: seen.append(np.asarray(p)))
    assert len(seen) == 3  # d=12 / block_size=4
    full = np.asarray(model.apply_arrays(x))
    assert_about_eq(seen[-1], full, thresh=1e-4)
    # intermediate partials differ from the final (blocks genuinely stream)
    assert not np.allclose(seen[0], full)


def test_linear_map_estimator_refine_mode(monkeypatch):
    """KEYSTONE_SOLVER_PRECISION=refine routes through the fused
    fast-Gram + iterative-refinement solver and still matches the
    closed-form ridge solution (mode is read at fit time, not import)."""
    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "refine")
    x, y, _, _ = make_problem(noise=0.3, seed=7)
    reg = 1.0
    w_exp, _, mu_b = closed_form(x, y, reg)
    model = LinearMapEstimator(reg=reg).fit(ArrayDataset(x), ArrayDataset(y))
    np.testing.assert_allclose(np.asarray(model.weights), w_exp, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(model.intercept), mu_b, atol=1e-4)


def test_solver_mode_rejects_typos(monkeypatch):
    import pytest

    from keystone_tpu.parallel import linalg

    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "fastest")
    with pytest.raises(ValueError, match="KEYSTONE_SOLVER_PRECISION"):
        linalg.solver_mode()


def test_block_solver_underdetermined_without_reg_still_learns():
    """More features than examples with reg=0: the scale-aware λ floor
    must keep the rank-deficient block solve finite (an absolute 1e-6
    floor left fp32 Cholesky emitting silent NaNs → chance-level error,
    the round-3 synthetic-TIMIT bug)."""
    rng = np.random.default_rng(11)
    n, d, k = 128, 512, 4  # d > n → every 256-wide block is singular
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    y = x @ w_true
    model = BlockLeastSquaresEstimator(256, num_iter=3, reg=0.0).fit(
        ArrayDataset(x), ArrayDataset(y)
    )
    pred = np.asarray(model.apply_arrays(x))
    assert np.isfinite(pred).all()
    # interpolating regime: the minimum-norm-ish solution fits train well
    rel = np.linalg.norm(pred - y) / np.linalg.norm(y)
    assert rel < 0.05, rel


def test_exact_solver_singular_without_reg_raises():
    """reg=None on a singular system must fail loudly (the reference's
    Breeze Cholesky threw), not silently return NaN weights."""
    import pytest

    rng = np.random.default_rng(12)
    x = rng.normal(size=(32, 64)).astype(np.float32)  # rank < d
    y = rng.normal(size=(32, 3)).astype(np.float32)
    with pytest.raises(FloatingPointError, match="singular"):
        LinearMapEstimator().fit(ArrayDataset(x), ArrayDataset(y))
