"""Golden parity against the REFERENCE'S OWN committed test fixtures.

Every other golden test in this repo validates against scipy/sklearn/
OpenCV oracles or generated archives; this module reads the fixtures the
reference itself ships and validates against them, with tolerances no
looser than the reference's own suites — the only way to catch spec-level
divergence (channel order, conv anchoring, GMM floors, label-map
conventions) that an independently generated oracle could share.

Fixture → reference suite map:
  images/gantrycrane.png + convolved.gantrycrane.csv
      → ConvolverSuite.scala "convolutions should match scipy"
        (CSV produced by src/test/python/images/pyconv.py:
        scipy.signal.convolve(img, arange(27).reshape(3,3,3), 'valid'))
  gmm_data.txt → GaussianMixtureModelSuite.scala "GMM Two Centers
        dataset 3" (centers 0, variances {(1,25),(25,1)}, weights .5)
  images/voc_codebook/{means,variances}.csv + priors
      → utils/external/EncEvalSuite.scala (GaussianMixtureModel.load)
  aMat.csv / bMat.csv (+ aMat-1class) → BlockWeightedLeastSquaresSuite
        (zero-gradient checks at tol 1e-2 / 1e-1)
  images/imagenet/n15075141.tar + imagenet-test-labels
      → loaders/ImageNetLoaderSuite.scala
  images/voc/voctest.tar + voclabels.csv → loaders/VOCLoaderSuite.scala

Skips cleanly if the reference tree is absent (public CI).
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

REF = "/root/reference/src/test/resources"
pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference fixtures not available"
)


def _ref(*parts: str) -> str:
    return os.path.join(REF, *parts)


# ------------------------------------------------------------- convolver


def test_convolver_matches_reference_scipy_golden():
    """reference: ConvolverSuite.scala:104-140 — our Convolver must
    reproduce the committed scipy convolution of gantrycrane.png
    exactly (the reference asserts image equality, not approximate)."""
    from PIL import Image

    from keystone_tpu.ops.images.core import Convolver, pack_filters

    img = np.array(Image.open(_ref("images", "gantrycrane.png")))
    assert img.shape == (264, 400, 3)

    rows = np.loadtxt(_ref("images", "convolved.gantrycrane.csv"), delimiter=",")
    h = int(rows[:, 0].max()) + 1
    w = int(rows[:, 1].max()) + 1
    golden = np.zeros((h, w))
    golden[rows[:, 0].astype(int), rows[:, 1].astype(int)] = rows[:, 2]

    # pyconv.py computes a TRUE convolution (flip in x, y, AND channel):
    # our Convolver correlates, so hand it the fully flipped kernel.
    k1 = np.arange(27, dtype=np.float32).reshape(3, 3, 3)
    filt = k1[::-1, ::-1, ::-1][None]
    conv = Convolver(pack_filters(filt), 3, normalize_patches=False)
    out = np.asarray(conv.apply_arrays(jnp.asarray(img[None], jnp.float32)))[
        0, :, :, 0
    ]

    assert out.shape == golden.shape
    # All quantities are integer-valued and < 2^24, exactly representable
    # in float32 — match to rounding noise, like the reference's equals().
    np.testing.assert_allclose(out, golden, rtol=0, atol=1e-2)


# ------------------------------------------------------------------ gmm


def test_gmm_fit_matches_mllib_dataset3_expectations():
    """reference: GaussianMixtureModelSuite.scala:64-119 'dataset 3' —
    fit k=2 on gmm_data.txt, same tolerances (0.5 / 2.0 / 0.05)."""
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.learning.gmm import GaussianMixtureModelEstimator

    data = np.loadtxt(_ref("gmm_data.txt"))
    assert data.shape[1] == 2
    est = GaussianMixtureModelEstimator(
        2, min_cluster_size=1, seed=0, stop_tolerance=0.0, max_iterations=30
    )
    gmm = est.fit(ArrayDataset(data.astype(np.float32)))

    means = np.asarray(gmm.means, np.float64)        # (d, k)
    variances = np.asarray(gmm.variances, np.float64)
    weights = np.asarray(gmm.weights, np.float64)

    np.testing.assert_allclose(means, 0.0, atol=0.5)
    # Components in either order: variance columns {(1,25), (25,1)}.
    v = variances.T  # (k, d)
    order1 = np.allclose(v, [[1.0, 25.0], [25.0, 1.0]], atol=2.0)
    order2 = np.allclose(v, [[25.0, 1.0], [1.0, 25.0]], atol=2.0)
    assert order1 or order2, v
    np.testing.assert_allclose(weights, 0.5, atol=0.05)


def test_voc_codebook_loads_and_encodes():
    """reference: EncEvalSuite.scala:15-41 — the committed VOC GMM
    codebook must load (reference layout: (dim, centers) columns) and
    drive a Fisher encoding to finite values. (The suite's exact FV-sum
    check needs images/feats.csv, which the reference does not ship.)"""
    from keystone_tpu.ops.images.fisher import FisherVector
    from keystone_tpu.ops.learning.gmm import GaussianMixtureModel

    gmm = GaussianMixtureModel.load(
        _ref("images", "voc_codebook", "means.csv"),
        _ref("images", "voc_codebook", "variances.csv"),
        _ref("images", "voc_codebook", "priors"),
    )
    d, k = gmm.means.shape
    assert gmm.variances.shape == (d, k)
    assert gmm.weights.shape == (k,)
    np.testing.assert_allclose(float(jnp.sum(gmm.weights)), 1.0, atol=1e-3)
    assert float(jnp.min(gmm.variances)) > 0.0

    rng = np.random.default_rng(0)
    descs = rng.normal(size=(2, 7, d)).astype(np.float32) * np.sqrt(
        np.asarray(gmm.variances).mean()
    )
    fv = np.asarray(FisherVector(gmm).apply_arrays(jnp.asarray(descs)))
    assert fv.shape == (2, d, 2 * k)
    assert np.isfinite(fv).all()


# ------------------------------------------------------- weighted solver


def _load_ab(a_name: str, b_name: str):
    a = np.loadtxt(_ref(a_name), delimiter=",").astype(np.float32)
    b = np.loadtxt(_ref(b_name), delimiter=",").astype(np.float32)
    return a, b.reshape(a.shape[0], -1)


def _weighted_gradient(a, y, lam, mw, w, b):
    """reference: BlockWeightedLeastSquaresSuite.scala:19-61
    computeGradient — per-example weights are negWt=(1-mw)/n everywhere
    except posWt=negWt+mw/n_c in the example's own class column;
    gradient = Aᵀ(Wts ⊙ (A·x + b − y)) + λ·x."""
    a = a.astype(np.float64)
    y = y.astype(np.float64)
    n, k = y.shape
    cls = np.argmax(y, axis=1)
    counts = np.bincount(cls, minlength=k)
    neg = (1.0 - mw) / n
    wts = np.full((n, k), neg)
    pos = neg + mw / np.maximum(counts[cls], 1)
    wts[np.arange(n), cls] = pos
    resid = (a @ w + b - y) * wts
    return a.T @ resid + lam * w


@pytest.mark.parametrize("block_size,tol", [(4, 1e-2), (5, 1e-1)])
def test_block_weighted_solver_zero_gradient_on_reference_fixture(
    block_size, tol
):
    """reference: BlockWeightedLeastSquaresSuite.scala:142-166 (bs=4,
    tol 1e-2) and :188-223 (features not divisible by blockSize, tol
    1e-1), on the reference's own aMat/bMat."""
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.learning.weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    a, y = _load_ab("aMat.csv", "bMat.csv")
    lam, mw = 0.1, 0.3
    est = BlockWeightedLeastSquaresEstimator(
        block_size, num_iter=10, reg=lam, mixture_weight=mw
    )
    model = est.fit(ArrayDataset(a), ArrayDataset(y))

    d = a.shape[1]
    w = np.asarray(model.weights, np.float64)[:d]
    if model.feature_mean is not None:
        # Model predicts (x − μ)·W + b; fold μ into the intercept to
        # match the reference's x·W + b form.
        b = np.asarray(model.intercept, np.float64) - (
            np.asarray(model.feature_mean, np.float64) @ w
        )
    else:
        b = np.asarray(model.intercept, np.float64)

    g = _weighted_gradient(a, y, lam, mw, w, b)
    assert np.linalg.norm(g.ravel()) == pytest.approx(0.0, abs=tol)


def test_block_weighted_solver_single_class_fixture():
    """reference: BlockWeightedLeastSquaresSuite.scala:168-186 — the
    1-class fixture must fit without error and produce finite weights."""
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.learning.weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    a, y = _load_ab("aMat-1class.csv", "bMat-1class.csv")
    est = BlockWeightedLeastSquaresEstimator(4, num_iter=3, reg=0.1,
                                             mixture_weight=0.3)
    model = est.fit(ArrayDataset(a), ArrayDataset(y))
    assert np.isfinite(np.asarray(model.weights)).all()


def test_exact_solver_closed_form_on_reference_fixture():
    """VERDICT r3 item 3: the exact solver on aMat/bMat vs the float64
    closed-form centered ridge solution."""
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.learning.linear import LinearMapEstimator

    a, y = _load_ab("aMat.csv", "bMat.csv")
    lam = 0.1
    model = LinearMapEstimator(reg=lam).fit(ArrayDataset(a), ArrayDataset(y))

    a64, y64 = a.astype(np.float64), y.astype(np.float64)
    ac = a64 - a64.mean(axis=0)
    yc = y64 - y64.mean(axis=0)
    expect = np.linalg.solve(
        ac.T @ ac + lam * np.eye(a.shape[1]), ac.T @ yc
    )
    np.testing.assert_allclose(
        np.asarray(model.weights, np.float64), expect, rtol=1e-4, atol=1e-5
    )


def _gantrycrane_bgr() -> np.ndarray:
    """The reference loads images in BGR channel order
    (utils/images/Image.scala:23-30); flip PIL's RGB to match."""
    from PIL import Image

    rgb = np.array(Image.open(_ref("images", "gantrycrane.png")))
    return rgb[..., ::-1].astype(np.float32)


def test_lcs_matches_matlab_golden_sums():
    """reference: LCSExtractorSuite.scala:10-28 — MATLAB golden sums on
    gantrycrane.png. The reference (double pipeline) asserts 1e-8; this
    float32 pipeline lands at ~5e-6 relative — pure f32 accumulation
    distance on a 3e7 sum, asserted at 1e-5."""
    import jax.numpy as jnp

    from keystone_tpu.ops.images.lcs import LCSExtractor

    lcs = LCSExtractor(stride=4, stride_start=16, sub_patch_size=6)
    d = np.asarray(
        lcs.apply_arrays(jnp.asarray(_gantrycrane_bgr()[None]))
    )[0].astype(np.float64)
    first = d[0].sum()  # our rows = the reference's keypoint columns
    full = d.sum()
    assert abs(first - 3.786557667540610e3) / 3.786557667540610e3 < 1e-5
    assert abs(full - 3.171963632855949e7) / 3.171963632855949e7 < 1e-5


def test_hog_matches_matlab_golden_sums():
    """reference: HogExtractorSuite.scala:10-38 — voc-release5 MATLAB
    sums at binSize 50 (their tol 1e-8; f32 here → 1e-5) and binSize 8
    (their own tol is already 1e-4 'error a bit higher'; f32 → 5e-4)."""
    import jax.numpy as jnp

    from keystone_tpu.ops.images.hog import HogExtractor

    scaled = jnp.asarray((_gantrycrane_bgr() / 255.0)[None])
    s50 = float(np.asarray(HogExtractor(bin_size=50).apply_arrays(scaled)).sum())
    assert abs(s50 - 59.2162514) / 59.2162514 < 1e-5
    s8 = float(np.asarray(HogExtractor(bin_size=8).apply_arrays(scaled)).sum())
    assert abs(s8 - 4.5775269e3) / 4.5775269e3 < 5e-4


def test_daisy_matches_matlab_golden_sums():
    """reference: DaisyExtractorSuite.scala:11-31 — MATLAB golden sums;
    this implementation meets the reference's own tolerances (1e-7 full
    sum, 1e-5 first keypoint) despite the ground-up cascaded-blur
    redesign."""
    import jax.numpy as jnp

    from keystone_tpu.ops.images.core import GrayScaler
    from keystone_tpu.ops.images.daisy import DaisyExtractor

    gray = GrayScaler().apply_arrays(jnp.asarray(_gantrycrane_bgr()[None]))
    d = np.asarray(DaisyExtractor().apply_arrays(gray))[0].astype(np.float64)
    first = d[0].sum()
    full = d.sum()
    assert abs(first - 55.127217737738533) / 55.127217737738533 < 1e-5
    assert abs(full - 3.240635661296463e5) / 3.240635661296463e5 < 1e-7


def test_sift_scale_step_descriptor_counts_on_reference_jpeg():
    """reference: nodes/images/external/SIFTExtractorSuite.scala — on its
    000012.jpg, scaleStep=0 must produce more descriptors than
    scaleStep=1 (finer scale sampling → more valid keypoints)."""
    import jax.numpy as jnp
    from PIL import Image

    from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
    from keystone_tpu.ops.images.sift import SIFTExtractor

    rgb = np.array(Image.open(_ref("images", "000012.jpg")))
    bgr = jnp.asarray(rgb[..., ::-1].astype(np.float32)[None])
    gray = GrayScaler().apply_arrays(PixelScaler().apply_arrays(bgr))

    n1 = np.asarray(SIFTExtractor(scale_step=1).apply_arrays(gray)).shape[1]
    n0 = np.asarray(SIFTExtractor(scale_step=0).apply_arrays(gray)).shape[1]
    assert n1 < n0, (n1, n0)


def test_lda_on_iris_matches_published_eigenvectors():
    """reference: LinearDiscriminantAnalysisSuite.scala:13-38 — LDA(2)
    on standardized iris.data must reproduce the published discriminant
    directions (±sign) at the reference's 1e-4 tolerance."""
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.learning.lda import LinearDiscriminantAnalysis
    from keystone_tpu.ops.stats.core import StandardScaler

    rows = []
    labels = []
    name_to_label = {"Iris-setosa": 1, "Iris-versicolor": 2, "Iris-virginica": 3}
    with open(_ref("iris.data")) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            parts = line.split(",")
            rows.append([float(v) for v in parts[:-1]])
            labels.append(name_to_label[parts[-1]])
    x = np.asarray(rows, np.float32)
    y = np.asarray(labels)
    assert x.shape == (150, 4)

    scaled = StandardScaler().fit(ArrayDataset(x)).apply_batch(ArrayDataset(x))
    model = LinearDiscriminantAnalysis(2).fit(scaled, ArrayDataset(y))
    w = np.asarray(model.weights, np.float64)  # (4, 2), unit columns

    major = np.array([-0.1498, -0.1482, 0.8511, 0.4808])
    minor = np.array([0.0095, 0.3272, -0.5748, 0.75])
    for col, expect in ((w[:, 0], major), (w[:, 1], minor)):
        ok = np.allclose(col, expect, atol=1e-4) or np.allclose(
            -col, expect, atol=1e-4
        )
        assert ok, (col, expect)


# ---------------------------------------------------------------- loaders


def test_imagenet_loader_on_reference_tar():
    """reference: loaders/ImageNetLoaderSuite.scala — 5 images, all
    label 12, filenames starting n15075141, from the real archive +
    label map."""
    from keystone_tpu.data.loaders.imagenet import load_imagenet

    ds = load_imagenet(
        _ref("images", "imagenet"), _ref("images", "imagenet-test-labels")
    )
    recs = ds.collect()
    assert len(recs) == 5
    assert {r["label"] for r in recs} == {12}
    assert all(
        os.path.basename(r["filename"]).startswith("n15075141") for r in recs
    )
    shapes = {np.asarray(r["image"]).shape for r in recs}
    assert all(len(s) == 3 and s[2] == 3 for s in shapes)


def test_voc_loader_on_reference_tar():
    """reference: loaders/VOCLoaderSuite.scala — 10 images; 000104.jpg
    is multi-label {14, 19}; 13 labels total, 9 distinct."""
    from keystone_tpu.data.loaders.voc import load_voc

    ds = load_voc(
        _ref("images", "voc"), _ref("images", "voclabels.csv")
    )
    recs = ds.collect()
    assert len(recs) == 10

    monitor = [r for r in recs if r["filename"].endswith("000104.jpg")]
    assert len(monitor) == 1
    assert set(monitor[0]["labels"]) == {14, 19}

    all_labels = [l for r in recs for l in r["labels"]]
    assert len(all_labels) == 13
    assert len(set(all_labels)) == 9
