"""Kernel solver tests (reference: nodes/learning/KernelModelSuite.scala —
including the learns-XOR-exactly property)."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.learning.kernel import (
    GaussianKernelGenerator,
    KernelRidgeRegression,
    gaussian_kernel_block,
)


def np_gaussian_kernel(a, b, gamma):
    sq = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
    return np.exp(-gamma * sq)


def test_kernel_block_matches_numpy():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    a = rng.normal(size=(10, 4)).astype(np.float32)
    b = rng.normal(size=(7, 4)).astype(np.float32)
    out = np.asarray(gaussian_kernel_block(jnp.asarray(a), jnp.asarray(b), 0.3))
    np.testing.assert_allclose(out, np_gaussian_kernel(a, b, 0.3), rtol=1e-4, atol=1e-5)


def test_krr_learns_xor():
    """reference: KernelModelSuite.scala:14-38"""
    x = np.array([[-1, -1], [-1, 1], [1, -1], [1, 1]], dtype=np.float32)
    y = np.array([[1, -1], [-1, 1], [-1, 1], [1, -1]], dtype=np.float32)
    est = KernelRidgeRegression(GaussianKernelGenerator(1.0), reg=0.01,
                                block_size=2, num_epochs=40)
    model = est.fit(ArrayDataset(x), ArrayDataset(y))
    pred = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    assert (np.sign(pred) == np.sign(y)).all()
    assert (pred.argmax(1) == y.argmax(1)).all()


def test_krr_converges_to_exact_dual():
    rng = np.random.default_rng(1)
    n, d, k = 60, 3, 2
    gamma, lam = 0.5, 0.1
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, k)).astype(np.float32)
    kmat = np_gaussian_kernel(x, x, gamma)
    alpha_exact = np.linalg.solve(kmat + lam * np.eye(n), y)

    est = KernelRidgeRegression(GaussianKernelGenerator(gamma), reg=lam,
                                block_size=16, num_epochs=300, block_permuter=7)
    model = est.fit(ArrayDataset(x), ArrayDataset(y))
    duals = np.asarray(model.duals)[:n]
    np.testing.assert_allclose(duals, alpha_exact, rtol=5e-2, atol=5e-3)

    # held-out application through the ring path
    xt = rng.normal(size=(13, d)).astype(np.float32)
    pred = np.asarray(model.apply_batch(ArrayDataset(xt)).data)
    expected = np_gaussian_kernel(xt, x, gamma) @ alpha_exact
    np.testing.assert_allclose(pred, expected, rtol=5e-2, atol=5e-3)


def test_krr_with_row_padding():
    """n=50 not divisible by 8 devices × block 16: padding must be inert."""
    rng = np.random.default_rng(2)
    n = 50
    x = rng.normal(size=(n, 2)).astype(np.float32)
    y = rng.normal(size=(n, 1)).astype(np.float32)
    gamma, lam = 1.0, 0.5
    est = KernelRidgeRegression(GaussianKernelGenerator(gamma), reg=lam,
                                block_size=16, num_epochs=50)
    model = est.fit(ArrayDataset(x), ArrayDataset(y))
    kmat = np_gaussian_kernel(x, x, gamma)
    alpha_exact = np.linalg.solve(kmat + lam * np.eye(n), y)
    np.testing.assert_allclose(np.asarray(model.duals)[:n], alpha_exact,
                               rtol=5e-2, atol=5e-3)
    # padded dual rows are exactly zero
    assert np.abs(np.asarray(model.duals)[n:]).max() == 0.0


def test_krr_on_hybrid_replica_mesh():
    """KRR training + ring apply on a (replica, data) hybrid mesh: the
    two-level ring (ICI ring per cycle, DCN hop between) must visit every
    shard (SURVEY §2.10 hierarchical backend)."""
    import jax
    import numpy as np

    from keystone_tpu.ops.learning.kernel import (
        GaussianKernelGenerator,
        KernelRidgeRegression,
    )
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.parallel.mesh import make_hybrid_mesh, use_mesh

    mesh = make_hybrid_mesh(num_replicas=2, devices=jax.devices()[:8])
    rng = np.random.default_rng(0)
    n = 48
    x = rng.standard_normal((n, 3)).astype(np.float32)
    y = (x[:, :1] * x[:, 1:2] > 0).astype(np.float32) * 2 - 1

    with use_mesh(mesh):
        krr = KernelRidgeRegression(
            GaussianKernelGenerator(gamma=1.0), reg=1e-4,
            block_size=8, num_epochs=12,
        )
        model = krr.fit(ArrayDataset(x), ArrayDataset(y))
        preds = np.asarray(model.apply_arrays(x))
    # same check as the single-axis XOR test: training data fits exactly
    assert (np.sign(preds) == y).mean() > 0.95
