"""Block-sparse kernels: BSR container round-trips, lax-vs-Pallas
(interpret) parity at ≤1e-5, and the estimator fast path dispatching on
the tuned density threshold (docs/AUTOTUNING.md)."""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data.dataset import ArrayDataset, ObjectDataset
from keystone_tpu.obs import names as _names
from keystone_tpu.ops.pallas import blocksparse as bs
from keystone_tpu.utils.sparse import BlockSparseMatrix, is_sparse_rows

BM, BN = 8, 16


def _block_sparse_dense(rng, m, d, density):
    """Dense (m, d) matrix whose nonzero structure is block-sparse."""
    nbr = (m + BM - 1) // BM
    nbc = (d + BN - 1) // BN
    keep = rng.rand(nbr, nbc) < density
    keep[0, 0] = True
    vals = rng.randn(nbr, BM, nbc, BN).astype(np.float32)
    return (vals * keep[:, None, :, None]).reshape(nbr * BM, nbc * BN)[:m, :d]


# ----------------------------------------------------------- the container


def test_from_dense_round_trip_and_counts():
    rng = np.random.RandomState(0)
    a = _block_sparse_dense(rng, 50, 70, 0.3)  # ragged: padding exercised
    bsr = BlockSparseMatrix.from_dense(a, (BM, BN))
    assert bsr.shape == (50, 70)
    assert np.allclose(bsr.to_dense(), a)
    total = bsr.n_block_rows * bsr.n_block_cols
    assert bsr.nnz_blocks + bsr.blocks_skipped() == total
    assert bsr.density() == pytest.approx(bsr.nnz_blocks / total)


def test_density_probes_agree_with_container():
    from keystone_tpu.utils.sparse import block_density, block_density_exceeds

    rng = np.random.RandomState(12)
    for m, d, density in ((50, 70, 0.3), (128, 64, 0.05), (64, 64, 1.0)):
        a = _block_sparse_dense(rng, m, d, density)
        bsr = BlockSparseMatrix.from_dense(a, (BM, BN))
        exact = block_density(a, (BM, BN))
        assert exact == pytest.approx(bsr.density())
        for threshold in (0.01, exact, 0.99):
            # the banded early-exit probe must agree with the exact
            # density at every threshold (incl. bands smaller than nbr)
            assert block_density_exceeds(
                a, (BM, BN), threshold, band_rows=2
            ) == (exact > threshold)


def test_from_csr_rows_matches_from_dense():
    scipy_sparse = pytest.importorskip("scipy.sparse")
    rng = np.random.RandomState(1)
    a = _block_sparse_dense(rng, 40, 64, 0.2)
    rows = [scipy_sparse.csr_matrix(a[i : i + 1]) for i in range(40)]
    assert is_sparse_rows(rows)
    bsr = BlockSparseMatrix.from_csr_rows(rows, (BM, BN))
    assert np.allclose(bsr.to_dense(), a)
    # no dense detour: stored blocks match the dense-tiled construction
    ref = BlockSparseMatrix.from_dense(a, (BM, BN))
    assert bsr.nnz_blocks == ref.nnz_blocks


def test_transpose_and_ell():
    rng = np.random.RandomState(2)
    a = _block_sparse_dense(rng, 32, 48, 0.25)
    bsr = BlockSparseMatrix.from_dense(a, (BM, BN))
    assert np.allclose(bsr.transpose().to_dense(), a.T)
    idx, blocks = bsr.to_ell()
    assert idx.shape[0] == bsr.n_block_rows
    assert blocks.shape[1:] == (idx.shape[1], BM, BN)
    # rebuild from ELL: padded slots are zero blocks at column 0 — inert
    rebuilt = np.zeros((bsr.padded_shape[1] // BN, BN, idx.shape[0] * BM))
    dense = np.zeros(bsr.padded_shape, np.float32)
    for i in range(idx.shape[0]):
        for k in range(idx.shape[1]):
            j = idx[i, k]
            dense[i * BM:(i + 1) * BM, j * BN:(j + 1) * BN] += blocks[i, k]
    assert np.allclose(dense[:32, :48], a)


# -------------------------------------------------------------- the kernels


def test_matmul_parity_lax_vs_numpy_vs_interpret():
    rng = np.random.RandomState(3)
    a = _block_sparse_dense(rng, 48, 64, 0.3)
    bsr = BlockSparseMatrix.from_dense(a, (BM, BN))
    b = rng.randn(64, 5).astype(np.float32)
    ref = a @ b
    scale = np.abs(ref).max()
    out_lax = np.asarray(bs.bsr_matmul(bsr, b, impl="lax"))
    out_int = np.asarray(bs.bsr_matmul(bsr, b, impl="pallas", interpret=True))
    assert np.abs(out_lax - ref).max() / scale <= 1e-5
    # the CI parity gate's bound: interpret-vs-fallback ≤ 1e-5
    assert np.abs(out_int - out_lax).max() / scale <= 1e-5


def test_gram_totals_match_dense_reference_and_interpret():
    rng = np.random.RandomState(4)
    a = _block_sparse_dense(rng, 56, 48, 0.25)
    bsr = BlockSparseMatrix.from_dense(a, (BM, BN))
    y = rng.randn(56, 3).astype(np.float32)
    g, c, sa, sb = [np.asarray(v) for v in bs.bsr_gram_totals(bsr, y, impl="lax")]
    assert np.abs(g - a.T @ a).max() / np.abs(a.T @ a).max() <= 1e-5
    assert np.abs(c - a.T @ y).max() / np.abs(a.T @ y).max() <= 1e-5
    assert np.allclose(sa, a.sum(axis=0), atol=1e-4)
    assert np.allclose(sb, y.sum(axis=0), atol=1e-4)
    gi, ci, *_ = [
        np.asarray(v)
        for v in bs.bsr_gram_totals(bsr, y, impl="pallas", interpret=True)
    ]
    assert np.abs(gi - g).max() / np.abs(g).max() <= 1e-5
    assert np.abs(ci - c).max() / max(np.abs(c).max(), 1e-9) <= 1e-5


def test_duplicate_blocks_accumulate():
    blocks = np.ones((2, BM, BN), np.float32)
    bsr = BlockSparseMatrix(
        (BM, BN), (BM, BN), np.array([0, 2]), np.array([0, 0]), blocks
    )
    assert np.allclose(bsr.to_dense(), 2.0)
    out = np.asarray(bs.bsr_matmul(bsr, np.ones((BN, 2), np.float32)))
    assert np.allclose(out, 2.0 * BN)


# ---------------------------------------------------------------- dispatch


def test_density_threshold_resolution(tmp_path, monkeypatch):
    from keystone_tpu.obs.store import ProfileStore, set_store, shape_class

    monkeypatch.setenv("KEYSTONE_BLOCKSPARSE_THRESHOLD", "0.42")
    assert bs.density_threshold() == pytest.approx(0.42)
    monkeypatch.delenv("KEYSTONE_BLOCKSPARSE_THRESHOLD")
    # tuned store entry wins over the shipped default
    monkeypatch.setenv("KEYSTONE_PROFILE_STORE", str(tmp_path / "ps.jsonl"))
    st = ProfileStore(str(tmp_path / "ps.jsonl"))
    set_store(st)
    try:
        shape = shape_class(4096, (512,), "float32")
        st.record("blocksparse:threshold", shape, threshold=0.11,
                  speedup=3.0, source="tune")
        assert bs.density_threshold(rows="n2^12") == pytest.approx(0.11)
        # no matching bucket: the shipped default
        assert bs.density_threshold(rows="n2^20") == pytest.approx(
            bs.DEFAULT_DENSITY_THRESHOLD
        )
    finally:
        set_store(None)


def test_default_block_shape_env_and_shrink(monkeypatch):
    monkeypatch.setenv("KEYSTONE_BLOCKSPARSE_BLOCK", "16x64")
    assert bs.default_block_shape() == (16, 64)
    monkeypatch.delenv("KEYSTONE_BLOCKSPARSE_BLOCK")
    bm, bn = bs.default_block_shape(64)  # tiny d: lane dim shrinks
    assert bn <= 64


# ------------------------------------------------------ estimator fast path


def _sparse_problem(rng, n=512, d=256, k=2, density=0.08):
    a = _block_sparse_dense(rng, n, d, density)
    y = rng.randn(n, k).astype(np.float32)
    return a, y


def test_fast_path_parity_and_metrics(monkeypatch):
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator

    monkeypatch.setenv("KEYSTONE_BLOCKSPARSE_BLOCK", f"{BM}x{BN}")
    monkeypatch.setenv("KEYSTONE_BLOCKSPARSE_THRESHOLD", "0.3")
    rng = np.random.RandomState(5)
    a, y = _sparse_problem(rng)
    est = BlockLeastSquaresEstimator(64, num_iter=2, reg=1e-3)
    fits = _names.metric(_names.BLOCKSPARSE_FITS)
    skipped = _names.metric(_names.BLOCKSPARSE_BLOCKS_SKIPPED)
    before, skipped_before = fits.value(impl="lax"), skipped.value()
    sparse_model = est.fit(ArrayDataset(a), ArrayDataset(y))
    assert fits.value(impl="lax") == before + 1
    assert skipped.value() > skipped_before
    monkeypatch.setenv("KEYSTONE_BLOCKSPARSE", "off")
    dense_model = est.fit(ArrayDataset(a), ArrayDataset(y))
    p_sparse = np.asarray(sparse_model.apply_arrays(jnp.asarray(a[:64])))
    p_dense = np.asarray(dense_model.apply_arrays(jnp.asarray(a[:64])))
    rel = np.abs(p_sparse - p_dense).max() / np.abs(p_dense).max()
    assert rel <= 1e-4  # same math as fit_stream; BCD-order differences only


def test_fast_path_consumes_csr_row_datasets(monkeypatch):
    scipy_sparse = pytest.importorskip("scipy.sparse")
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator

    monkeypatch.setenv("KEYSTONE_BLOCKSPARSE_BLOCK", f"{BM}x{BN}")
    monkeypatch.setenv("KEYSTONE_BLOCKSPARSE_THRESHOLD", "0.3")
    rng = np.random.RandomState(6)
    a, y = _sparse_problem(rng)
    rows = [scipy_sparse.csr_matrix(a[i : i + 1]) for i in range(len(a))]
    est = BlockLeastSquaresEstimator(64, num_iter=1, reg=1e-3)
    m_rows = est.fit(ObjectDataset(rows), ArrayDataset(y))
    m_dense = est.fit(ArrayDataset(a), ArrayDataset(y))
    p1 = np.asarray(m_rows.apply_arrays(jnp.asarray(a[:32])))
    p2 = np.asarray(m_dense.apply_arrays(jnp.asarray(a[:32])))
    assert np.abs(p1 - p2).max() / np.abs(p2).max() <= 1e-5


def test_dense_input_above_threshold_keeps_legacy_path(monkeypatch):
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator

    monkeypatch.setenv("KEYSTONE_BLOCKSPARSE_THRESHOLD", "0.01")
    rng = np.random.RandomState(7)
    a = rng.randn(256, 64).astype(np.float32)  # fully dense
    y = rng.randn(256, 2).astype(np.float32)
    est = BlockLeastSquaresEstimator(32, num_iter=1, reg=1e-3)
    fits = _names.metric(_names.BLOCKSPARSE_FITS)
    before = fits.total()
    est.fit(ArrayDataset(a), ArrayDataset(y))
    assert fits.total() == before  # never dispatched sparse


def test_csr_rows_above_threshold_densify_through_bsr(monkeypatch):
    scipy_sparse = pytest.importorskip("scipy.sparse")
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator

    monkeypatch.setenv("KEYSTONE_BLOCKSPARSE_THRESHOLD", "0.001")
    rng = np.random.RandomState(8)
    a = rng.randn(128, 64).astype(np.float32)
    y = rng.randn(128, 2).astype(np.float32)
    rows = [scipy_sparse.csr_matrix(a[i : i + 1]) for i in range(len(a))]
    est = BlockLeastSquaresEstimator(32, num_iter=1, reg=1e-3)
    m = est.fit(ObjectDataset(rows), ArrayDataset(y))  # must not crash
    ref = est.fit(ArrayDataset(a), ArrayDataset(y))
    p1 = np.asarray(m.apply_arrays(jnp.asarray(a[:16])))
    p2 = np.asarray(ref.apply_arrays(jnp.asarray(a[:16])))
    assert np.abs(p1 - p2).max() / np.abs(p2).max() <= 1e-5


def test_fast_path_oom_degrades_through_ladder(monkeypatch):
    """The sparse dispatch keeps the estimator's OOM contract: a first-
    attempt OOM halves the block through the DegradationLadder instead
    of raising (the dense paths' behavior, preserved)."""
    from keystone_tpu import reliability
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
    from keystone_tpu.reliability import FaultSpec

    monkeypatch.setenv("KEYSTONE_BLOCKSPARSE_BLOCK", f"{BM}x{BN}")
    monkeypatch.setenv("KEYSTONE_BLOCKSPARSE_THRESHOLD", "0.3")
    rng = np.random.RandomState(10)
    a, y = _sparse_problem(rng, n=256, d=256)
    est = BlockLeastSquaresEstimator(64, num_iter=1, reg=1e-3)
    with reliability.injected(
        FaultSpec(
            match="BlockLeastSquaresEstimator.solve", kind="oom", first_n=1
        )
    ):
        m = est.fit(ArrayDataset(a), ArrayDataset(y))
    assert m.degradation["reduced"] and m.block_size == 32


def test_hashing_tf_block_sparse_features():
    pytest.importorskip("scipy.sparse")
    from keystone_tpu.ops.nlp.text import HashingTF, block_sparse_features

    tf = HashingTF(512)
    docs = [["alpha", "beta", "alpha"], ["gamma"], ["beta", "delta"]]
    rows = [tf.apply(doc) for doc in docs]
    bsr = block_sparse_features(rows, block_shape=(BM, BN))
    assert bsr.shape == (3, 512)
    assert bsr.density() < 0.5
    stacked = np.vstack([r.toarray() for r in rows])
    assert np.allclose(bsr.to_dense(), stacked)


def test_linalg_gram_accepts_bsr():
    from keystone_tpu.parallel import linalg

    rng = np.random.RandomState(9)
    a = _block_sparse_dense(rng, 64, 48, 0.2)
    bsr = BlockSparseMatrix.from_dense(a, (BM, BN))
    g, _ = linalg.gram(bsr)
    assert np.abs(np.asarray(g) - a.T @ a).max() / np.abs(a.T @ a).max() <= 1e-5
    b = rng.randn(64, 3).astype(np.float32)
    g2, atb = linalg.gram(bsr, b)
    assert np.abs(np.asarray(atb) - a.T @ b).max() / np.abs(a.T @ b).max() <= 1e-5
