"""PCA / ZCA / KMeans / GMM / NaiveBayes / LDA vs golden references
(reference suites: PCASuite, ZCAWhitenerSuite, KMeansPlusPlusSuite,
GaussianMixtureModelSuite, NaiveBayesSuite, LinearDiscriminantAnalysisSuite)."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset, ObjectDataset
from keystone_tpu.ops.learning.gmm import GaussianMixtureModelEstimator, GaussianMixtureModel
from keystone_tpu.ops.learning.kmeans import KMeansModel, KMeansPlusPlusEstimator
from keystone_tpu.ops.learning.lda import LinearDiscriminantAnalysis
from keystone_tpu.ops.learning.naive_bayes import NaiveBayesEstimator
from keystone_tpu.ops.learning.pca import (
    ApproximatePCAEstimator,
    ColumnPCAEstimator,
    DistributedPCAEstimator,
    PCAEstimator,
)
from keystone_tpu.ops.learning.zca import ZCAWhitenerEstimator


def numpy_pca(x, dims):
    xc = x - x.mean(0)
    _, _, vt = np.linalg.svd(xc, full_matrices=False)
    v = vt.T
    col_max, col_absmax = v.max(0), np.abs(v).max(0)
    signs = np.where(col_max == col_absmax, 1.0, -1.0)
    return (v * signs)[:, :dims]


@pytest.fixture
def x():
    rng = np.random.default_rng(0)
    base = rng.normal(size=(300, 4)) @ np.diag([5.0, 2.0, 1.0, 0.1])
    return (base @ rng.normal(size=(4, 8))).astype(np.float32)


def test_local_pca_matches_numpy(x):
    model = PCAEstimator(3).fit(ArrayDataset(x))
    expected = numpy_pca(x, 3)
    np.testing.assert_allclose(np.asarray(model.components), expected, atol=2e-3)


def test_distributed_pca_matches_local(x):
    local = PCAEstimator(3).fit(ArrayDataset(x))
    dist = DistributedPCAEstimator(3).fit(ArrayDataset(x))
    # compare up to sign per column (eigh vs svd sign conventions are fixed
    # by the shared convention, but tiny eigenvalues can flip)
    a, b = np.asarray(local.components), np.asarray(dist.components)
    for i in range(3):
        assert min(np.linalg.norm(a[:, i] - b[:, i]), np.linalg.norm(a[:, i] + b[:, i])) < 5e-2


def test_approximate_pca_spans_top_subspace(x):
    exact = numpy_pca(x, 2)
    approx = np.asarray(ApproximatePCAEstimator(2, q=5).fit(ArrayDataset(x)).components)
    # subspace comparison: projection matrices should agree
    p_exact = exact @ exact.T
    p_approx = approx @ approx.T
    assert np.linalg.norm(p_exact - p_approx) < 0.1


def test_pca_transformer_projects(x):
    model = PCAEstimator(3).fit(ArrayDataset(x))
    out = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    assert out.shape == (300, 3)


def test_column_pca_on_descriptor_matrices():
    rng = np.random.default_rng(1)
    mats = [rng.normal(size=(20, 6)).astype(np.float32) for _ in range(10)]
    est = ColumnPCAEstimator(dims=2)
    model = est.fit(ObjectDataset(mats))
    out = model.apply(mats[0])
    assert out.shape == (20, 2)


def test_column_pca_optimize_accepts_vector_items():
    # Regression: plain (d,) feature-vector datasets (one row per item,
    # e.g. pooled features feeding PCA inside a Pipeline) used to raise
    # IndexError in optimize(), silently skipping the cost-model choice.
    from keystone_tpu.workflow.optimize import DataStats

    rng = np.random.default_rng(4)
    vecs = ArrayDataset(rng.normal(size=(50, 8)).astype(np.float32))
    est = ColumnPCAEstimator(dims=2)
    stats = DataStats(n_total=50, num_shards=1, n_per_shard=[50])
    chosen = est.optimize([vecs], stats)
    assert chosen in (est.local, est.distributed)


def test_zca_whitens_covariance():
    rng = np.random.default_rng(2)
    x = (rng.normal(size=(500, 6)) @ rng.normal(size=(6, 6))).astype(np.float32)
    model = ZCAWhitenerEstimator(eps=1e-6).fit_single(x)
    out = (x - np.asarray(model.means)) @ np.asarray(model.whitener)
    cov = out.T @ out / (len(x) - 1)
    np.testing.assert_allclose(cov, np.eye(6), atol=0.05)


def test_kmeans_recovers_separated_clusters():
    rng = np.random.default_rng(3)
    centers = np.array([[0, 0], [10, 10], [-10, 10]], dtype=np.float32)
    x = np.concatenate([c + 0.5 * rng.normal(size=(100, 2)) for c in centers]).astype(np.float32)
    model = KMeansPlusPlusEstimator(3, 20, seed=0).fit(ArrayDataset(x))
    fitted = np.asarray(model.means)
    # every true center has a fitted center nearby
    for c in centers:
        assert np.min(np.linalg.norm(fitted - c, axis=1)) < 1.0
    # one-hot assignment output
    assign = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    assert assign.shape == (300, 3)
    np.testing.assert_allclose(assign.sum(axis=1), 1.0)
    # points from the same true cluster agree
    assert (assign[:100].argmax(1) == assign[0].argmax()).all()


def test_gmm_recovers_separated_clusters():
    rng = np.random.default_rng(4)
    x = np.concatenate([
        rng.normal(loc=0.0, scale=1.0, size=(300, 3)),
        rng.normal(loc=8.0, scale=2.0, size=(300, 3)),
    ]).astype(np.float32)
    est = GaussianMixtureModelEstimator(k=2, max_iterations=50, min_cluster_size=10, seed=0)
    model = est.fit(ArrayDataset(x))
    means = np.asarray(model.means)  # (d, k)
    m0, m1 = means[:, 0], means[:, 1]
    lo, hi = sorted([np.mean(m0), np.mean(m1)])
    assert abs(lo - 0.0) < 1.0 and abs(hi - 8.0) < 1.0
    post = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    assert post.shape == (600, 2)
    np.testing.assert_allclose(post.sum(axis=1), 1.0, atol=1e-5)
    # posteriors nearly hard for well-separated clusters
    assert (post[:300].argmax(1) == post[0].argmax()).mean() > 0.99


def test_gmm_csv_roundtrip(tmp_path):
    means = np.array([[0.0, 1.0], [2.0, 3.0]])
    variances = np.array([[1.0, 1.0], [2.0, 2.0]])
    weights = np.array([0.4, 0.6])
    np.savetxt(tmp_path / "m.csv", means, delimiter=",")
    np.savetxt(tmp_path / "v.csv", variances, delimiter=",")
    np.savetxt(tmp_path / "w.csv", weights, delimiter=",")
    model = GaussianMixtureModel.load(
        str(tmp_path / "m.csv"), str(tmp_path / "v.csv"), str(tmp_path / "w.csv")
    )
    assert model.k == 2 and model.dim == 2


def test_naive_bayes_separates():
    rng = np.random.default_rng(5)
    # word-count-ish data: class 0 favors features 0-4, class 1 favors 5-9
    n = 400
    y = rng.integers(0, 2, size=n)
    rates = np.where(y[:, None] == 0,
                     np.array([[5.0] * 5 + [0.5] * 5]),
                     np.array([[0.5] * 5 + [5.0] * 5]))
    x = rng.poisson(rates).astype(np.float32)
    model = NaiveBayesEstimator(2).fit(ArrayDataset(x), ArrayDataset(y.astype(np.int32)))
    scores = np.asarray(model.apply_batch(ArrayDataset(x)).data)
    acc = (scores.argmax(1) == y).mean()
    assert acc > 0.95
    assert scores.shape == (n, 2)


def test_lda_separates_classes():
    rng = np.random.default_rng(6)
    x = np.concatenate([
        rng.normal(loc=[0, 0, 0], size=(100, 3)),
        rng.normal(loc=[5, 5, 0], size=(100, 3)),
    ]).astype(np.float32)
    y = np.array([0] * 100 + [1] * 100, dtype=np.int32)
    model = LinearDiscriminantAnalysis(1).fit(ArrayDataset(x), ArrayDataset(y))
    proj = np.asarray(model.apply_batch(ArrayDataset(x)).data).ravel()
    # 1-D projection separates the classes
    t = (proj[:100].mean() + proj[100:].mean()) / 2
    acc = ((proj < t) == (y == (0 if proj[:100].mean() < t else 1))).mean()
    assert acc > 0.95
