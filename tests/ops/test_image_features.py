"""Image feature extractors: dense SIFT, Fisher Vector, LCS.

Mirrors the reference's tolerance-based golden testing strategy
(reference: utils/external/VLFeatSuite.scala, EncEvalSuite.scala,
nodes/images/FisherVectorSuite) with numpy-golden checks and structural
invariants instead of MATLAB fixtures.
"""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.images.fisher import FisherVector, GMMFisherVectorEstimator
from keystone_tpu.ops.images.lcs import LCSExtractor
from keystone_tpu.ops.images.sift import SIFTExtractor
from keystone_tpu.ops.learning.gmm import GaussianMixtureModel


# ------------------------------------------------------------------- SIFT


def test_sift_shapes_match_grid_counts():
    ext = SIFTExtractor(step_size=4, bin_size=4, scales=2, scale_step=1)
    x = np.random.default_rng(0).uniform(size=(2, 48, 40)).astype(np.float32)
    out = np.asarray(ext.apply_arrays(x))
    assert out.shape == (2, sum(ext.grid_counts(48, 40)), 128)


def test_sift_quantized_range():
    ext = SIFTExtractor(step_size=4, bin_size=4, scales=2)
    x = np.random.default_rng(1).uniform(size=(1, 48, 48)).astype(np.float32)
    out = np.asarray(ext.apply_arrays(x))
    assert out.min() >= 0 and out.max() <= 255
    np.testing.assert_array_equal(out, np.floor(out))  # integer quantization
    assert out.max() > 0  # random texture → real descriptors


def test_sift_flat_image_zeroed_by_contrast_threshold():
    ext = SIFTExtractor(step_size=4, bin_size=4, scales=1)
    x = np.full((1, 40, 40), 0.5, dtype=np.float32)
    out = np.asarray(ext.apply_arrays(x))
    np.testing.assert_array_equal(out, 0.0)


def test_sift_translation_equivariance():
    """Shifting the image by one step moves descriptors one grid cell."""
    step = 4
    ext = SIFTExtractor(step_size=step, bin_size=4, scales=1)
    rng = np.random.default_rng(2)
    base = rng.uniform(size=(56, 48)).astype(np.float32)
    shifted = np.roll(base, -step, axis=0)
    d0 = np.asarray(ext.apply_arrays(base[None]))[0]
    d1 = np.asarray(ext.apply_arrays(shifted[None]))[0]
    off = 1 + 2 * ext.scales
    span = 3 * ext.bin_size
    nx = (56 - 1 - off - span) // step + 1
    ny = (48 - 1 - off - span) // step + 1
    g0 = d0.reshape(nx, ny, 128)
    g1 = d1.reshape(nx, ny, 128)
    # interior rows (away from roll wraparound and border padding)
    a, b = g0[2:-1], g1[1:-2]
    match = np.mean(np.abs(a - b) <= 1.0)
    assert match > 0.95, f"only {match:.2%} of entries within 1"


def test_sift_gray_channel_axis_accepted():
    ext = SIFTExtractor(scales=1)
    x = np.random.default_rng(3).uniform(size=(1, 40, 40, 1)).astype(np.float32)
    out = np.asarray(ext.apply_arrays(x))
    assert out.ndim == 3 and out.shape[-1] == 128


# ---------------------------------------------------------------- FisherVector


def _toy_gmm(d=4, k=3, seed=0):
    rng = np.random.default_rng(seed)
    means = rng.normal(size=(d, k))
    variances = rng.uniform(0.5, 1.5, size=(d, k))
    weights = rng.uniform(0.2, 1.0, size=k)
    weights /= weights.sum()
    return GaussianMixtureModel(means, variances, weights)


def test_fisher_vector_matches_reference_formulas():
    """FV algebra vs direct numpy evaluation of the Sanchez et al. formulas
    (reference: FisherVector.scala:38-52)."""
    gmm = _toy_gmm()
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2, 10, 4)).astype(np.float32)
    fv = np.asarray(FisherVector(gmm).apply_arrays(x))

    means = np.asarray(gmm.means, dtype=np.float64)
    variances = np.asarray(gmm.variances, dtype=np.float64)
    weights = np.asarray(gmm.weights, dtype=np.float64)
    for i in range(2):
        q = np.asarray(gmm.apply_arrays(x[i]))  # (n, K) posteriors
        n = x.shape[1]
        s0 = q.mean(axis=0)
        s1 = x[i].T.astype(np.float64) @ q / n
        s2 = (x[i].T.astype(np.float64) ** 2) @ q / n
        fv1 = (s1 - means * s0) / (np.sqrt(variances) * np.sqrt(weights))
        fv2 = (s2 - 2 * means * s1 + (means**2 - variances) * s0) / (
            variances * np.sqrt(2 * weights)
        )
        expected = np.concatenate([fv1, fv2], axis=1)
        np.testing.assert_allclose(fv[i], expected, rtol=1e-4, atol=1e-4)


def test_fisher_vector_shape():
    gmm = _toy_gmm(d=5, k=4)
    x = np.random.default_rng(2).normal(size=(3, 7, 5)).astype(np.float32)
    assert np.asarray(FisherVector(gmm).apply_arrays(x)).shape == (3, 5, 8)


def test_gmm_fisher_vector_estimator_end_to_end():
    rng = np.random.default_rng(3)
    # two well-separated descriptor clusters
    a = rng.normal(size=(4, 20, 3)) + 5.0
    b = rng.normal(size=(4, 20, 3)) - 5.0
    data = ArrayDataset(np.concatenate([a, b]).astype(np.float32))
    est = GMMFisherVectorEstimator(k=2)
    fv = est.fit(data)
    assert isinstance(fv, FisherVector)
    out = np.asarray(fv.apply_arrays(np.asarray(data.data)))
    assert out.shape == (8, 3, 4)
    assert np.isfinite(out).all()


# ----------------------------------------------------------------------- LCS


def test_lcs_shape_and_values_vs_numpy():
    """Box means/stds + grid reads vs a direct numpy evaluation
    (reference: LCSExtractorSuite checks dims on a real image)."""
    ext = LCSExtractor(stride=4, stride_start=16, sub_patch_size=6)
    rng = np.random.default_rng(0)
    x = rng.uniform(size=(1, 48, 48, 3)).astype(np.float32)
    out = np.asarray(ext.apply_arrays(x))
    kx = np.arange(16, 48 - 16, 4)
    assert out.shape == (1, len(kx) ** 2, 4 * 4 * 3 * 2)

    # numpy golden for one keypoint / channel / neighbor
    s = 6
    pad_lo = (s - 1) // 2
    padded = np.zeros((48 + s - 1, 48 + s - 1))
    padded[pad_lo : pad_lo + 48, pad_lo : pad_lo + 48] = x[0, :, :, 0]
    win = np.lib.stride_tricks.sliding_window_view(padded, (s, s))
    mean_img = win.mean(axis=(2, 3))
    sq_img = (win**2).mean(axis=(2, 3))
    std_img = np.sqrt(np.maximum(sq_img - mean_img**2, 0))

    offs = ext._neighbor_offsets()
    kp = (16, 16)  # first keypoint
    expected_first_pair = (
        mean_img[kp[0] + offs[0], kp[1] + offs[0]],
        std_img[kp[0] + offs[0], kp[1] + offs[0]],
    )
    np.testing.assert_allclose(out[0, 0, 0], expected_first_pair[0], atol=1e-4)
    np.testing.assert_allclose(out[0, 0, 1], expected_first_pair[1], atol=1e-4)


def test_lcs_out_of_bounds_raises():
    ext = LCSExtractor(stride=4, stride_start=4, sub_patch_size=6)
    x = np.zeros((1, 32, 32, 3), dtype=np.float32)
    with pytest.raises(ValueError):
        ext.apply_arrays(x)


# ----------------------------------------------------------------------- HOG


def test_hog_shape_and_layout():
    from keystone_tpu.ops.images.hog import HogExtractor

    ext = HogExtractor(bin_size=8)
    x = np.random.default_rng(0).uniform(size=(2, 64, 48, 3)).astype(np.float32)
    out = np.asarray(ext.apply_arrays(x))
    nxc, nyc = 8, 6
    assert out.shape == (2, (nxc - 2) * (nyc - 2), 32)
    np.testing.assert_array_equal(out[..., 31], 0.0)  # truncation feature
    assert (out >= 0).all()
    assert out.max() > 0


def test_hog_flat_image_is_zero():
    from keystone_tpu.ops.images.hog import HogExtractor

    x = np.full((1, 32, 32, 3), 0.7, dtype=np.float32)
    out = np.asarray(HogExtractor(bin_size=8).apply_arrays(x))
    np.testing.assert_allclose(out, 0.0)


def test_hog_interp_matrix_partition_of_unity():
    from keystone_tpu.ops.images.hog import _interp_matrix

    m = _interp_matrix(30, 4, 8)
    sums = m.sum(axis=1)
    # interior pixels distribute all their mass; border pixels lose the
    # out-of-bounds share exactly as the reference's bounds checks do
    assert (sums <= 1.0 + 1e-6).all()
    assert (sums[4:-4] > 0.999).all()


def test_hog_gradient_orientation_selective():
    """A pure vertical edge puts its mass in a different orientation bin
    than a horizontal edge."""
    from keystone_tpu.ops.images.hog import HogExtractor

    ext = HogExtractor(bin_size=4)
    v = np.zeros((1, 32, 32, 1), dtype=np.float32)
    v[:, 16:, :, :] = 1.0  # edge along y (gradient in x)
    h = np.transpose(v, (0, 2, 1, 3))
    fv = np.asarray(ext.apply_arrays(v)).sum(axis=(0, 1))
    fh = np.asarray(ext.apply_arrays(h)).sum(axis=(0, 1))
    assert np.argmax(fv[:18]) != np.argmax(fh[:18])


# --------------------------------------------------------------------- DAISY


def test_daisy_shape_and_normalized_histograms():
    from keystone_tpu.ops.images.daisy import DaisyExtractor

    ext = DaisyExtractor()
    x = np.random.default_rng(1).uniform(size=(1, 48, 48)).astype(np.float32)
    out = np.asarray(ext.apply_arrays(x))
    kx = np.arange(16, 48 - 16, 4)
    assert out.shape == (1, len(kx) ** 2, ext.feature_size)
    # every H-bin block is L2-normalized (or zeroed)
    blocks = out.reshape(out.shape[0], out.shape[1], -1, ext.daisy_h)
    norms = np.linalg.norm(blocks, axis=-1)
    assert np.all((np.abs(norms - 1.0) < 1e-4) | (norms < 1e-6))


def test_daisy_flat_image_interior_zero():
    """A constant image has zero gradients, so interior keypoints (outside
    the reach of the zero-padding border artifact the reference's conv2D
    shares) produce zero histograms."""
    from keystone_tpu.ops.images.daisy import DaisyExtractor

    ext = DaisyExtractor()
    x = np.full((1, 96, 96), 0.25, dtype=np.float32)
    out = np.asarray(ext.apply_arrays(x))
    kx = np.arange(16, 96 - 16, 4)
    nk = len(kx)
    grid = out.reshape(nk, nk, -1)
    interior = (kx >= 40) & (kx <= 55)
    sub = grid[np.ix_(interior, interior)]
    np.testing.assert_allclose(sub, 0.0, atol=1e-6)


def test_daisy_border_guard():
    from keystone_tpu.ops.images.daisy import DaisyExtractor

    x = np.zeros((1, 48, 48), dtype=np.float32)
    with pytest.raises(ValueError):
        DaisyExtractor(pixel_border=4).apply_arrays(x)
