"""Golden tests against external implementations (scipy / sklearn).

The reference validated its ops against implementations it did not write:
MATLAB vl_phow (VLFeatSuite.scala:34-52), a SciPy convolve dump
(src/test/python/images/pyconv.py:10-14 feeding ConvolverSuite), R's LDA
(LinearDiscriminantAnalysisSuite) and enceval fixtures (EncEvalSuite).
This suite is the same strategy with in-env externals: every major op
family gets at least one assertion against scipy or scikit-learn, so
common-mode errors between our XLA and native paths can't hide.
"""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.utils.testing import assert_about_eq


def rand(shape, seed=0, scale=1.0):
    return (np.random.default_rng(seed).normal(size=shape) * scale).astype(np.float32)


# ------------------------------------------------------------------ convolver


def test_convolver_matches_scipy_correlate():
    """Unnormalized Convolver == scipy valid cross-correlation summed over
    channels — the reference's own validation method (pyconv.py:10-14)."""
    from scipy.signal import correlate2d

    from keystone_tpu.ops.images.core import Convolver

    rng = np.random.default_rng(0)
    images = rng.random((3, 12, 12, 3)).astype(np.float32)
    filter_images = rng.random((4, 5, 5, 3)).astype(np.float32)

    conv = Convolver.create(filter_images, whitener=None, normalize_patches=False)
    got = np.asarray(conv.apply_arrays(images))  # (3, 8, 8, 4)

    expected = np.zeros_like(got)
    for n in range(3):
        for f in range(4):
            acc = np.zeros((8, 8), dtype=np.float64)
            for c in range(3):
                acc += correlate2d(
                    images[n, :, :, c], filter_images[f, :, :, c], mode="valid"
                )
            expected[n, :, :, f] = acc
    assert_about_eq(got, expected, thresh=1e-2)


# ------------------------------------------------------------------------ fft


def test_padded_fft_matches_scipy():
    from scipy.fft import rfft

    from keystone_tpu.ops.stats.core import PaddedFFT

    x = rand((5, 100), seed=1)
    got = np.asarray(PaddedFFT().apply_arrays(x))
    padded = np.pad(x, ((0, 0), (0, 28)))  # next pow2 = 128
    expected = rfft(padded, axis=-1).real[:, :64]
    assert got.shape == (5, 64)
    assert_about_eq(got, expected, thresh=1e-3)


# ------------------------------------------------------------------------ pca


def test_pca_matches_sklearn_up_to_sign():
    from sklearn.decomposition import PCA as SkPCA

    from keystone_tpu.ops.learning.pca import PCAEstimator

    x = rand((200, 10), seed=2)
    ours = np.asarray(PCAEstimator(4).fit(ArrayDataset(x)).components)  # (d, k)
    theirs = SkPCA(n_components=4).fit(np.asarray(x, np.float64)).components_.T
    for j in range(4):
        a, b = ours[:, j], theirs[:, j]
        assert min(np.abs(a - b).max(), np.abs(a + b).max()) < 1e-3, f"component {j}"


# ------------------------------------------------------------------- k-means


def test_kmeans_recovers_sklearn_centers_on_blobs():
    from sklearn.cluster import KMeans as SkKMeans
    from sklearn.datasets import make_blobs

    from keystone_tpu.ops.learning.kmeans import KMeansPlusPlusEstimator

    x, _ = make_blobs(
        n_samples=300, centers=4, cluster_std=0.3, random_state=0, n_features=5
    )
    x = x.astype(np.float32)
    ours = np.asarray(KMeansPlusPlusEstimator(4, 20, seed=0).fit(ArrayDataset(x)).means)
    theirs = SkKMeans(4, n_init=5, random_state=0).fit(x).cluster_centers_
    # match centers greedily: every sklearn center has one of ours nearby
    for t in theirs:
        assert np.min(np.linalg.norm(ours - t, axis=1)) < 0.15


# --------------------------------------------------------------------- logreg


def test_logistic_regression_agrees_with_sklearn():
    from sklearn.datasets import make_classification
    from sklearn.linear_model import LogisticRegression as SkLogReg

    from keystone_tpu.ops.learning.logistic import LogisticRegressionEstimator

    x, y = make_classification(
        n_samples=400, n_features=8, n_informative=5, n_classes=3, random_state=1
    )
    x = x.astype(np.float32)
    model = LogisticRegressionEstimator(num_classes=3, reg=1e-6, num_iterations=300).fit(
        ArrayDataset(x), ArrayDataset(y.astype(np.int32))
    )
    ours = np.asarray(model.apply_arrays(x)).argmax(axis=1)
    # Align formulations: no intercept (ours has none), near-zero L2.
    theirs = SkLogReg(max_iter=2000, C=1e4, fit_intercept=False).fit(x, y).predict(x)
    assert (ours == theirs).mean() > 0.97


# ------------------------------------------------------------------------ lda


def test_lda_projection_spans_sklearn_subspace():
    """Discriminant subspaces agree (principal angles ≈ 0) with sklearn's
    eigen-solver LDA — the R-fixture check of the reference's
    LinearDiscriminantAnalysisSuite, with an in-env external."""
    from scipy.linalg import subspace_angles
    from sklearn.datasets import make_blobs
    from sklearn.discriminant_analysis import LinearDiscriminantAnalysis as SkLDA

    from keystone_tpu.ops.learning.lda import LinearDiscriminantAnalysis

    x, y = make_blobs(n_samples=300, centers=3, cluster_std=1.0, random_state=3,
                      n_features=6)
    x = x.astype(np.float32)
    ours = LinearDiscriminantAnalysis(2).fit(
        ArrayDataset(x), ArrayDataset(y.astype(np.int32))
    )
    w_ours = np.asarray(ours.weights)[:, :2]  # (d, 2) projection
    sk = SkLDA(solver="eigen", n_components=2).fit(np.asarray(x, np.float64), y)
    w_sk = sk.scalings_[:, :2]
    angles = subspace_angles(w_ours, w_sk)
    assert np.max(angles) < 0.05, f"principal angles {angles}"


# ------------------------------------------------------------------------ gmm


def test_gmm_recovers_sklearn_means_on_blobs():
    from sklearn.datasets import make_blobs
    from sklearn.mixture import GaussianMixture as SkGMM

    from keystone_tpu.ops.learning.gmm import GaussianMixtureModelEstimator

    x, _ = make_blobs(
        n_samples=400, centers=3, cluster_std=0.4, random_state=4, n_features=4
    )
    x = x.astype(np.float32)
    ours = GaussianMixtureModelEstimator(3, max_iterations=50, seed=0).fit(
        ArrayDataset(x)
    )
    our_means = np.asarray(ours.means).T  # (k, d)
    their_means = SkGMM(3, covariance_type="diag", random_state=0).fit(x).means_
    for t in their_means:
        assert np.min(np.linalg.norm(our_means - t, axis=1)) < 0.2


# ----------------------------------------------------------------------- sift


def test_sift_gradient_invariants():
    """External-anchor substitutes for the vlfeat fixture (no vlfeat in
    this environment): brightness-shift invariance (gradient-based
    descriptors ignore constant offsets) and the published vl_dsift grid
    geometry from grid_counts."""
    from keystone_tpu.ops.images.sift import SIFTExtractor

    sift = SIFTExtractor()
    rng = np.random.default_rng(5)
    img = rng.random((2, 48, 48)).astype(np.float32)

    base = np.asarray(sift.apply_arrays(img))
    shifted = np.asarray(sift.apply_arrays(img + 37.0))
    assert_about_eq(base, shifted, thresh=2.0)  # descriptors are uint8-scale

    counts = sift.grid_counts(48, 48)
    assert base.shape == (2, sum(counts), 128)
    assert np.isfinite(base).all()
