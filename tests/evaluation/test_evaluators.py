"""Evaluator tests (reference: evaluation/*Suite.scala)."""

import numpy as np

from keystone_tpu.evaluation import (
    AugmentedExamplesEvaluator,
    BinaryClassifierEvaluator,
    MeanAveragePrecisionEvaluator,
)


def test_binary_metrics():
    pred = [True, True, False, False, True]
    act = [True, False, False, True, True]
    m = BinaryClassifierEvaluator().evaluate(pred, act)
    assert (m.tp, m.fp, m.tn, m.fn) == (2.0, 1.0, 1.0, 1.0)
    assert m.accuracy == 3 / 5
    assert m.precision == 2 / 3
    assert m.recall == 2 / 3
    assert m.specificity == 1 / 2
    np.testing.assert_allclose(m.f_score(), 2 / 3)


def test_map_perfect_ranking_is_one():
    # class 0 scores rank all its positives first -> AP = 1
    scores = np.array([[0.9, 0.1], [0.8, 0.6], [0.2, 0.9], [0.1, 0.7]])
    labels = [[0], [0], [1], [1]]
    aps = MeanAveragePrecisionEvaluator(2).evaluate(scores, labels)
    np.testing.assert_allclose(aps, [1.0, 1.0])


def test_map_matches_hand_computation():
    # one class, ranking: pos, neg, pos  -> precisions 1, 1/2, 2/3 at
    # recalls 1/2, 1/2, 1. 11-point AP: levels <=0.5 take max prec at
    # recall>=t which is 1.0 (6 levels), levels >0.5 take 2/3 (5 levels).
    scores = np.array([[0.9], [0.8], [0.7]])
    labels = [[0], [], [0]]
    aps = MeanAveragePrecisionEvaluator(1).evaluate(scores, labels)
    want = (6 * 1.0 + 5 * (2 / 3)) / 11.0
    np.testing.assert_allclose(aps, [want])


def test_augmented_average_policy():
    # two examples, three copies each; average of copies decides
    names = ["a", "a", "a", "b", "b", "b"]
    scores = np.array(
        [[0.9, 0.1], [0.0, 0.4], [0.2, 0.3],  # a: avg (0.367, 0.267) -> 0
         [0.1, 0.2], [0.3, 0.25], [0.1, 0.5]]  # b: avg (0.167, 0.317) -> 1
    )
    labels = np.array([0, 0, 0, 1, 1, 1])
    m = AugmentedExamplesEvaluator(names, 2).evaluate(scores, labels)
    assert m.total_error == 0.0


def test_augmented_borda_policy():
    names = ["a", "a"]
    # borda: ranks per copy — copy1 favors class2, copy2 favors class2
    scores = np.array([[0.1, 0.5, 0.9], [0.3, 0.2, 0.8]])
    labels = np.array([2, 2])
    m = AugmentedExamplesEvaluator(names, 3, policy="borda").evaluate(scores, labels)
    assert m.total_error == 0.0


# --------------------------------------------------- sklearn golden tests


def test_multiclass_metrics_match_sklearn():
    """Confusion matrix + macro/micro precision/recall/F1 vs sklearn —
    an oracle this repo's authors didn't write (the reference validated
    its evaluator arithmetic by hand, MulticlassClassifierEvaluatorSuite)."""
    from sklearn.metrics import (
        confusion_matrix,
        f1_score,
        precision_score,
        recall_score,
    )

    from keystone_tpu.evaluation import MulticlassClassifierEvaluator

    rng = np.random.default_rng(0)
    k, n = 5, 400
    actual = rng.integers(0, k, n)
    predicted = np.where(rng.random(n) < 0.6, actual, rng.integers(0, k, n))

    m = MulticlassClassifierEvaluator(k).evaluate(predicted, actual)

    # Our convention: matrix[i, j] counts actual i predicted j (transpose
    # if the internal layout differs — total/diagonal agreement pins it).
    sk = confusion_matrix(actual, predicted, labels=np.arange(k))
    np.testing.assert_array_equal(np.asarray(m.confusion_matrix), sk)

    np.testing.assert_allclose(
        m.macro_precision,
        precision_score(actual, predicted, average="macro", zero_division=0),
        atol=1e-12,
    )
    np.testing.assert_allclose(
        m.macro_recall,
        recall_score(actual, predicted, average="macro", zero_division=0),
        atol=1e-12,
    )
    np.testing.assert_allclose(
        m.micro_f1,
        f1_score(actual, predicted, average="micro", zero_division=0),
        atol=1e-12,
    )
    np.testing.assert_allclose(
        m.total_accuracy, float((actual == predicted).mean()), atol=1e-12
    )


def test_macro_f1_is_mean_of_class_f1():
    from keystone_tpu.evaluation import MulticlassClassifierEvaluator

    rng = np.random.default_rng(1)
    actual = rng.integers(0, 3, 100)
    predicted = rng.integers(0, 3, 100)
    m = MulticlassClassifierEvaluator(3).evaluate(predicted, actual)
    np.testing.assert_allclose(m.macro_f1, m.class_f1().mean())


def test_binary_metrics_match_sklearn():
    from sklearn.metrics import f1_score, precision_score, recall_score

    from keystone_tpu.evaluation import BinaryClassifierEvaluator

    rng = np.random.default_rng(2)
    actual = rng.random(300) < 0.4
    predicted = rng.random(300) < 0.5
    m = BinaryClassifierEvaluator().evaluate(predicted, actual)
    np.testing.assert_allclose(
        m.precision, precision_score(actual, predicted, zero_division=0), atol=1e-12
    )
    np.testing.assert_allclose(
        m.recall, recall_score(actual, predicted, zero_division=0), atol=1e-12
    )
    np.testing.assert_allclose(
        m.f_score(), f1_score(actual, predicted, zero_division=0), atol=1e-12
    )


def test_map_matches_direct_recomputation():
    """Verify the evaluator's vectorized per-class argsort AP against a
    straight-line scalar recomputation of VOC2007 11-point AP from the
    same ranking (independent arithmetic path)."""
    from keystone_tpu.evaluation import MeanAveragePrecisionEvaluator

    rng = np.random.default_rng(3)
    n, k = 200, 3
    scores = rng.random((n, k))
    labels = [
        [c for c in range(k) if rng.random() < 0.3] for _ in range(n)
    ]
    aps = MeanAveragePrecisionEvaluator(k).evaluate(scores, labels)

    for c in range(k):
        y = np.array([1 if c in lab else 0 for lab in labels])
        order = np.argsort(-scores[:, c], kind="stable")
        ys = y[order]
        tp = np.cumsum(ys)
        prec = tp / (np.arange(n) + 1)
        rec = tp / max(ys.sum(), 1)
        ap = 0.0
        for t in np.linspace(0.0, 1.0, 11):
            mask = rec >= t - 1e-12
            ap += prec[mask].max() if mask.any() else 0.0
        np.testing.assert_allclose(aps[c], ap / 11.0, atol=1e-9)


def test_multiclass_summary_renders():
    from keystone_tpu.evaluation import MulticlassClassifierEvaluator

    m = MulticlassClassifierEvaluator(3).evaluate([0, 1, 2, 1], [0, 1, 1, 1])
    s = m.summary()
    assert "Accuracy" in s or "accuracy" in s
