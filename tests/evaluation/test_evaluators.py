"""Evaluator tests (reference: evaluation/*Suite.scala)."""

import numpy as np

from keystone_tpu.evaluation import (
    AugmentedExamplesEvaluator,
    BinaryClassifierEvaluator,
    MeanAveragePrecisionEvaluator,
)


def test_binary_metrics():
    pred = [True, True, False, False, True]
    act = [True, False, False, True, True]
    m = BinaryClassifierEvaluator().evaluate(pred, act)
    assert (m.tp, m.fp, m.tn, m.fn) == (2.0, 1.0, 1.0, 1.0)
    assert m.accuracy == 3 / 5
    assert m.precision == 2 / 3
    assert m.recall == 2 / 3
    assert m.specificity == 1 / 2
    np.testing.assert_allclose(m.f_score(), 2 / 3)


def test_map_perfect_ranking_is_one():
    # class 0 scores rank all its positives first -> AP = 1
    scores = np.array([[0.9, 0.1], [0.8, 0.6], [0.2, 0.9], [0.1, 0.7]])
    labels = [[0], [0], [1], [1]]
    aps = MeanAveragePrecisionEvaluator(2).evaluate(scores, labels)
    np.testing.assert_allclose(aps, [1.0, 1.0])


def test_map_matches_hand_computation():
    # one class, ranking: pos, neg, pos  -> precisions 1, 1/2, 2/3 at
    # recalls 1/2, 1/2, 1. 11-point AP: levels <=0.5 take max prec at
    # recall>=t which is 1.0 (6 levels), levels >0.5 take 2/3 (5 levels).
    scores = np.array([[0.9], [0.8], [0.7]])
    labels = [[0], [], [0]]
    aps = MeanAveragePrecisionEvaluator(1).evaluate(scores, labels)
    want = (6 * 1.0 + 5 * (2 / 3)) / 11.0
    np.testing.assert_allclose(aps, [want])


def test_augmented_average_policy():
    # two examples, three copies each; average of copies decides
    names = ["a", "a", "a", "b", "b", "b"]
    scores = np.array(
        [[0.9, 0.1], [0.0, 0.4], [0.2, 0.3],  # a: avg (0.367, 0.267) -> 0
         [0.1, 0.2], [0.3, 0.25], [0.1, 0.5]]  # b: avg (0.167, 0.317) -> 1
    )
    labels = np.array([0, 0, 0, 1, 1, 1])
    m = AugmentedExamplesEvaluator(names, 2).evaluate(scores, labels)
    assert m.total_error == 0.0


def test_augmented_borda_policy():
    names = ["a", "a"]
    # borda: ranks per copy — copy1 favors class2, copy2 favors class2
    scores = np.array([[0.1, 0.5, 0.9], [0.3, 0.2, 0.8]])
    labels = np.array([2, 2])
    m = AugmentedExamplesEvaluator(names, 3, policy="borda").evaluate(scores, labels)
    assert m.total_error == 0.0
