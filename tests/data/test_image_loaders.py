"""ImageNet / VOC tar loader tests.

Mirrors the reference's loader integration suites, which read small real
tars from test resources (reference: loaders/ImageNetLoaderSuite.scala,
loaders/VOCLoaderSuite.scala). Here the fixtures are generated: tiny JPEG
tars with known directory/label structure.
"""

import io
import os
import tarfile

import numpy as np
import pytest

from keystone_tpu.data.loaders.imagenet import load_imagenet, read_label_map
from keystone_tpu.data.loaders.voc import load_voc, read_voc_labels

PIL = pytest.importorskip("PIL")
from PIL import Image as PILImage  # noqa: E402


def _jpeg_bytes(rgb, size=(24, 18)):
    img = PILImage.new("RGB", size, rgb)  # size = (width, height)
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=95)
    return buf.getvalue()


def _add_entry(tar, name, payload):
    info = tarfile.TarInfo(name)
    info.size = len(payload)
    tar.addfile(info, io.BytesIO(payload))


@pytest.fixture
def imagenet_tar(tmp_path):
    tar_path = tmp_path / "shard0.tar"
    with tarfile.open(tar_path, "w") as tar:
        _add_entry(tar, "n01/img0.jpg", _jpeg_bytes((255, 0, 0)))
        _add_entry(tar, "n01/img1.jpg", _jpeg_bytes((0, 255, 0)))
        _add_entry(tar, "n02/img2.jpg", _jpeg_bytes((0, 0, 255)))
        _add_entry(tar, "n03/skipped.jpg", _jpeg_bytes((9, 9, 9)))  # not in label map
        _add_entry(tar, "n01/broken.jpg", b"not a jpeg")
    labels_path = tmp_path / "labels.txt"
    labels_path.write_text("n01 0\nn02 1\n")
    return str(tar_path), str(labels_path)


def test_read_label_map(imagenet_tar):
    _, labels_path = imagenet_tar
    assert read_label_map(labels_path) == {"n01": 0, "n02": 1}


def test_load_imagenet(imagenet_tar):
    tar_path, labels_path = imagenet_tar
    ds = load_imagenet(tar_path, labels_path)
    records = ds.collect()
    # unmapped class + undecodable jpeg are skipped
    assert len(records) == 3
    labels = sorted(r["label"] for r in records)
    assert labels == [0, 0, 1]
    rec = next(r for r in records if r["filename"] == "n01/img0.jpg")
    # (X, Y, C) with X = height rows, Y = width cols, BGR channel order
    assert rec["image"].shape == (18, 24, 3)
    # solid red in BGR: channel 2 is large, channels 0/1 small (JPEG lossy)
    assert rec["image"][..., 2].mean() > 200
    assert rec["image"][..., 0].mean() < 60


def test_load_imagenet_directory_of_tars(imagenet_tar, tmp_path):
    tar_path, labels_path = imagenet_tar
    ds = load_imagenet(os.path.dirname(tar_path), labels_path)
    assert len(ds) == 3
    assert ds.num_shards == 1


def test_load_imagenet_resize(imagenet_tar):
    tar_path, labels_path = imagenet_tar
    ds = load_imagenet(tar_path, labels_path, resize=(16, 16))
    arrays = ds.to_arrays()
    assert arrays.data["image"].shape == (3, 16, 16, 3)
    assert arrays.data["label"].shape == (3,)


@pytest.fixture
def voc_tar(tmp_path):
    prefix = "VOCdevkit/VOC2007/JPEGImages/"
    tar_path = tmp_path / "voc.tar"
    with tarfile.open(tar_path, "w") as tar:
        _add_entry(tar, prefix + "000001.jpg", _jpeg_bytes((10, 200, 30)))
        _add_entry(tar, prefix + "000002.jpg", _jpeg_bytes((200, 10, 30)))
        _add_entry(tar, "VOCdevkit/VOC2007/Annotations/000001.xml", b"<xml/>")
    labels_path = tmp_path / "labels.csv"
    labels_path.write_text(
        "id,class,a,b,filename\n"
        '1,1,x,y,"000001.jpg"\n'
        '2,7,x,y,"000001.jpg"\n'
        '3,7,x,y,"000001.jpg"\n'
        '4,20,x,y,"000002.jpg"\n'
    )
    return str(tar_path), str(labels_path)


def test_read_voc_labels(voc_tar):
    _, labels_path = voc_tar
    labels = read_voc_labels(labels_path)
    assert labels == {"000001.jpg": [0, 6], "000002.jpg": [19]}


def test_load_voc(voc_tar):
    tar_path, labels_path = voc_tar
    ds = load_voc(tar_path, labels_path)
    records = sorted(ds.collect(), key=lambda r: r["filename"])
    # the Annotations/ entry is excluded by the name prefix
    assert len(records) == 2
    assert records[0]["labels"] == [0, 6]
    assert records[1]["labels"] == [19]
    assert records[0]["image"].ndim == 3
