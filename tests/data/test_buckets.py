"""BucketedDataset / bucketize unit tests (the native-resolution
substrate: data/buckets.py, data/dataset.py BucketedDataset)."""

import numpy as np
import pytest

from keystone_tpu.data.buckets import (
    bucket_labels,
    bucketize_images,
    to_bucketed_dataset,
)
from keystone_tpu.data.dataset import ArrayDataset, BucketedDataset


def _recs(sizes, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {"image": rng.random((x, y, 3)).astype(np.float32), "label": i}
        for i, (x, y) in enumerate(sizes)
    ]


def test_max_rows_splits_groups_into_same_shape_buckets():
    recs = _recs([(30, 30)] * 7 + [(60, 60)] * 2)
    buckets = bucketize_images(recs, granularity=32, max_rows=3)
    shapes = [b.bucket_shape for b in buckets]
    counts = [len(b) for b in buckets]
    assert shapes == [(32, 32), (32, 32), (32, 32), (64, 64)]
    assert counts == [3, 3, 1, 2]
    # labels survive the split in order
    assert bucket_labels(buckets).tolist() == [0, 1, 2, 3, 4, 5, 6, 7, 8]


def test_edge_padding_replicates_border():
    recs = _recs([(30, 31)])
    (b,) = bucketize_images(recs, granularity=32)
    img = recs[0]["image"]
    padded = b.images[0]
    np.testing.assert_array_equal(padded[:30, :31], img)
    np.testing.assert_array_equal(padded[30, :31], img[29])  # replicated row
    np.testing.assert_array_equal(padded[:30, 31], img[:, 30])  # replicated col
    assert b.dims[0].tolist() == [30, 31]


def test_bucketed_dataset_protocol():
    recs = _recs([(20, 20), (20, 20), (50, 40)])
    bd = to_bucketed_dataset(bucketize_images(recs, granularity=32))
    assert len(bd) == 3
    assert bd.num_shards == 2
    assert bd.per_shard_counts() == [2, 1]
    items = bd.collect()
    assert len(items) == 3 and "image" in items[0]


def test_bucketed_map_batched_and_concat():
    recs = _recs([(20, 20), (20, 20), (50, 40)])
    bd = to_bucketed_dataset(bucketize_images(recs, granularity=32))
    # per-bucket batched op producing fixed-width rows → concat works
    summed = bd.map_datasets(
        lambda b: ArrayDataset(
            np.asarray(b.data["image"]).sum(axis=(1, 2)), b.num_examples
        )
    )
    dense = summed.concat()
    assert np.asarray(dense.data).shape == (3, 3)
    # bucket-major order matches bucket_labels order
    buckets = bucketize_images(recs, granularity=32)
    direct = np.concatenate(
        [np.asarray(b.images).sum(axis=(1, 2)) for b in buckets]
    )
    np.testing.assert_allclose(np.asarray(dense.data), direct, rtol=1e-6)


def test_empty_bucket_list_rejected():
    with pytest.raises(ValueError):
        BucketedDataset([])
