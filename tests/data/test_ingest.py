"""Host-ingest utility: fixture builder + native decode measurement."""

import os

import numpy as np
import pytest

from keystone_tpu.data.ingest import build_jpeg_tar_fixture, measure_ingest
from keystone_tpu import native


def test_fixture_build_is_cached(tmp_path):
    p = str(tmp_path / "fix.tar")
    build_jpeg_tar_fixture(p, 8, size=64)
    mtime = os.path.getmtime(p)
    build_jpeg_tar_fixture(p, 8, size=64)  # second call must reuse
    assert os.path.getmtime(p) == mtime


@pytest.mark.skipif(native.load() is None, reason="native lib not built")
def test_measure_ingest_decodes_all(tmp_path):
    p = str(tmp_path / "fix.tar")
    build_jpeg_tar_fixture(p, 12, size=64)
    out = measure_ingest(p, resize=(64, 64), batch=5)
    assert out["images"] == 12
    assert out["images_per_sec_decode"] > 0


@pytest.mark.skipif(native.load() is None, reason="native lib not built")
def test_measure_ingest_overlap_path(tmp_path):
    p = str(tmp_path / "fix.tar")
    build_jpeg_tar_fixture(p, 10, size=64)
    seen = []

    def featurize(images):
        seen.append(np.asarray(images).shape)
        return None

    out = measure_ingest(p, resize=(64, 64), batch=4, featurize=featurize)
    assert out["images"] == 10
    assert sum(s[0] for s in seen) == 10
    assert "images_per_sec_overlapped" in out
