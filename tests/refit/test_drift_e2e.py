"""Drifting-refit, end to end through the serving fleet and the HTTP
front door.

The closed loop the refit daemon automates, exercised by hand against
REAL infrastructure: a workload's truth drifts, a candidate refit on
fresh data is published through :class:`SupervisorPublisher`, every
worker re-warms and acks WITH the version it warmed, and the next HTTP
request is answered by the new weights — zero dropped requests, the
publish visible in ``GET /stats`` provenance.

The real-process version pays two jax worker boots and is slow-marked;
the tier-1 twin drives the SAME publisher/supervisor/front-end surfaces
over jax-free stub workers, so the ack/ledger/HTTP contract is covered
on every run.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from keystone_tpu.reliability.recovery import get_recovery_log
from keystone_tpu.refit.publish import SupervisorPublisher
from keystone_tpu.serving.frontend import ServingFrontend
from keystone_tpu.serving.supervisor import SupervisorConfig, WorkerSupervisor

pytestmark = [pytest.mark.refit, pytest.mark.serving]


def _post(front, path, obj, timeout=120):
    request = urllib.request.Request(
        f"http://{front.host}:{front.port}{path}",
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(request, timeout=timeout) as response:
            return response.status, json.loads(response.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def _get(front, path, timeout=30):
    with urllib.request.urlopen(
        f"http://{front.host}:{front.port}{path}", timeout=timeout
    ) as response:
        return response.status, json.loads(response.read())


def _get_text(front, path, timeout=30):
    with urllib.request.urlopen(
        f"http://{front.host}:{front.port}{path}", timeout=timeout
    ) as response:
        return response.status, response.read().decode()


# ------------------------------------------------------- tier-1 stub twin


def test_stub_fleet_publish_acks_per_worker_through_the_front_door(tmp_path):
    """The publish contract without jax: every stub worker acks the swap
    with the version it moved to, the restart spec repoints at the
    published digest, the ledger counts the acks, and HTTP traffic flows
    un-dropped before, during, and after."""
    sup = WorkerSupervisor(
        {"stub": {"delay_ms": 5}},
        SupervisorConfig(
            workers=2, heartbeat_s=0.05, hang_timeout_s=5.0,
            ready_timeout_s=15.0, monitor_interval_s=0.02,
        ),
    ).start()
    front = None
    try:
        sup.wait_ready()
        front = ServingFrontend(sup, "127.0.0.1", 0).start()
        pub = SupervisorPublisher(
            sup, str(tmp_path / "store"), incumbent={"weights": [1.0]}
        )

        code, before = _post(front, "/v1/apply", {"x": [3.0], "deadline_ms": 15000})
        assert (code, before["y"]) == (200, [6.0])

        # Drift "detected" → candidate refit on fresh rows → publish.
        t1 = pub.publish({"weights": [2.0]}, round_index=1)
        assert set(t1.acks) == {"0", "1"}
        for ack in t1.acks.values():
            # Stub workers boot at version 1; the first swap warms v2.
            assert (ack["kind"], ack["version"]) == ("swapped", 2)
        assert sup.spec == {
            "checkpoint_dir": str(tmp_path / "store"), "digest": t1.digest,
        }

        t2 = pub.publish({"weights": [3.0]}, round_index=2)
        assert all(a["version"] == 3 for a in t2.acks.values())
        assert t2.prev_digest == t1.digest

        published = get_recovery_log().events("refit_publish")
        assert [e.detail["acked"] for e in published] == [2, 2]

        code, after = _post(front, "/v1/apply", {"x": [3.0], "deadline_ms": 15000})
        assert (code, after["y"]) == (200, [6.0])  # stubs echo 2x regardless
        code, health = _get(front, "/healthz")
        assert (code, health["status"], health["alive"]) == (200, "ok", 2)
        assert sup.stats()["failures"] == 0

        # Quality plane, fleet-wide: each stub worker sketched its served
        # payloads locally and shipped the delta on a heartbeat; the
        # supervisor merged them, so /stats carries the fleet sketch and
        # the /metrics scrape exports the keystone_quality_* family.
        deadline = time.monotonic() + 10
        while True:
            quality = sup.stats().get("quality")
            rows = (
                (quality or {}).get("models", {})
                .get("default", {}).get("sketch") or {}
            ).get("rows", 0)
            if rows >= 2:  # both served requests reached the fleet view
                break
            assert time.monotonic() < deadline, quality
            time.sleep(0.05)
        score_channel = quality["models"]["default"]["sketch"]["channels"]["score"]
        assert score_channel["count"] >= 2  # per-request prediction scores
        code, exposition = _get_text(front, "/metrics")
        assert code == 200
        assert "keystone_quality_sketch_rows" in exposition
        assert 'model="default"' in exposition
    finally:
        if front is not None:
            front.stop()
        sup.stop()


# ------------------------------------------------- real fleet (slow, jax)

D, K = 6, 2


def _fit(x, y):
    """The refit a daemon round performs, in one line: least squares on
    the rows the tap retained."""
    from keystone_tpu.ops.learning.linear import LinearMapper

    w, *_ = np.linalg.lstsq(x, y, rcond=None)
    return LinearMapper(w.astype(np.float32))


@pytest.mark.slow
def test_drifting_refit_reaches_real_workers_through_http(tmp_path):
    rng = np.random.default_rng(0)
    w_true = rng.standard_normal((D, K)).astype(np.float32)

    env = {"KEYSTONE_COMPILATION_CACHE": str(tmp_path / "shared-xla-cache")}
    sup = WorkerSupervisor(
        {"synthetic": {"d": D, "seed": 0}},
        SupervisorConfig(
            workers=2, heartbeat_s=0.2, hang_timeout_s=5.0,
            ready_timeout_s=180.0, max_batch=4,
        ),
        env=env,
    ).start()
    front = None
    try:
        sup.wait_ready()  # BOTH workers: acks below must cover the fleet
        front = ServingFrontend(sup, "127.0.0.1", 0).start()
        pub = SupervisorPublisher(sup, str(tmp_path / "store"))

        # Round 1: fit the pre-drift workload, publish to the fleet.
        x1 = rng.standard_normal((256, D)).astype(np.float32)
        v1 = _fit(x1, x1 @ w_true)
        t1 = pub.publish(v1, round_index=1)
        assert set(t1.acks) == {"0", "1"}
        for ack in t1.acks.values():
            # Synthetic boot model is v1 in each worker's registry; the
            # published candidate warms as v2 — the ack carries it.
            assert (ack["kind"], ack["version"]) == ("swapped", 2)

        probe = [1.0] * D
        code, out = _post(front, "/v1/apply", {"x": probe, "deadline_ms": 90000})
        assert code == 200
        np.testing.assert_allclose(
            out["y"], np.asarray(probe) @ np.asarray(v1.weights),
            rtol=1e-4, atol=1e-5,
        )

        # The workload drifts; a fresh fit goes out as round 2.
        w_drifted = w_true + 0.5 * rng.standard_normal((D, K)).astype(np.float32)
        x2 = rng.standard_normal((256, D)).astype(np.float32)
        v2 = _fit(x2, x2 @ w_drifted)
        t2 = pub.publish(v2, round_index=2)
        for ack in t2.acks.values():
            assert (ack["kind"], ack["version"]) == ("swapped", 3)

        code, out2 = _post(front, "/v1/apply", {"x": probe, "deadline_ms": 90000})
        assert code == 200
        np.testing.assert_allclose(
            out2["y"], np.asarray(probe) @ np.asarray(v2.weights),
            rtol=1e-4, atol=1e-5,
        )
        assert not np.allclose(out["y"], out2["y"]), (
            "drifted refit never reached served traffic"
        )

        # Publish provenance through the front door: the fleet agrees on
        # v3 from the checkpoint store, and nothing was dropped. Model
        # stats ride heartbeats, so give the snapshot a beat to catch up.
        deadline = time.monotonic() + 10
        while True:
            code, stats = _get(front, "/stats")
            assert code == 200
            if stats["models"]["default"]["current"] == 3:
                break
            assert time.monotonic() < deadline, stats["models"]
            time.sleep(0.1)
        assert stats["models"]["default"]["source"].startswith("checkpoint:")
        assert stats["failures"] == 0 and stats["timeouts"] == 0
        assert stats["supervisor"]["requeued"] == 0
        ledgered = get_recovery_log().events("refit_publish")
        assert [e.detail["acked"] for e in ledgered] == [2, 2]
    finally:
        if front is not None:
            front.stop()
        sup.stop()
