"""The stream-state contract: export → checkpoint → merge/resume →
finish ≡ one-shot fit, for every ``supports_fit_stream`` estimator,
single-device and sharded (docs/REFIT.md)."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.ops.learning.least_squares import LeastSquaresEstimator
from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.refit.state import (
    StateMismatch,
    StreamState,
    load_stream_state,
    merge_stream_states,
    save_stream_state,
)
from keystone_tpu.reliability.checkpoint import CheckpointStore
from keystone_tpu.workflow.streaming import ChunkStream

pytestmark = pytest.mark.refit

N, D, K, CHUNK = 384, 10, 3, 64


def _problem(seed=0, n=N):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, D)).astype(np.float32)
    w = rng.normal(size=(D, K)).astype(np.float32)
    y = (x @ w + 0.01 * rng.normal(size=(n, K))).astype(np.float32)
    return x, y


def _stream(x, y, chunk=CHUNK, partition=None):
    return ChunkStream(
        ArrayDataset(x), ArrayDataset(y), (), chunk_rows=chunk,
        partition=partition,
    )


def _rel(a, b):
    return float(np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-30))


ESTIMATORS = [
    ("linear_map", lambda: LinearMapEstimator(reg=1e-3)),
    ("block_ls", lambda: BlockLeastSquaresEstimator(8, num_iter=2, reg=1e-3)),
    ("least_squares_meta", lambda: LeastSquaresEstimator(reg=1e-3, block_size=8)),
]


@pytest.mark.parametrize("name,make", ESTIMATORS, ids=[e[0] for e in ESTIMATORS])
def test_roundtrip_export_checkpoint_merge_finish(name, make, tmp_path):
    """Split fit → export both halves → persist through the checkpoint
    store → load → merge → finish_from_state ≡ the one-shot streamed fit
    (parity ≤ 1e-6), for all three fit_stream estimators."""
    x, y = _problem()
    reference = make().fit_stream(_stream(x, y))
    ref_out = np.asarray(reference.apply_arrays(x))

    store = CheckpointStore(str(tmp_path))
    half = N // 2
    for i, sl in enumerate((slice(None, half), slice(half, None))):
        est = make()
        est.fit_stream(_stream(x[sl], y[sl]))
        assert save_stream_state(store, f"part{i}", est.export_stream_state())

    a = load_stream_state(store, "part0")
    b = load_stream_state(store, "part1")
    assert a is not None and b is not None
    assert a.num_examples + b.num_examples == N
    merged = merge_stream_states(a, b)
    fitted = make().finish_from_state(merged)
    assert _rel(np.asarray(fitted.apply_arrays(x)), ref_out) <= 1e-6


@pytest.mark.parametrize("name,make", ESTIMATORS, ids=[e[0] for e in ESTIMATORS])
def test_resume_fold_extends_state(name, make):
    """fit_stream(state=…) seeds the carry: first-half fit + resumed
    second-half fold ≡ one fit over everything (parity ≤ 1e-6)."""
    x, y = _problem(seed=1)
    reference = make().fit_stream(_stream(x, y))
    ref_out = np.asarray(reference.apply_arrays(x))

    first = make()
    first.fit_stream(_stream(x[: N // 2], y[: N // 2]))
    resumed_est = make()
    resumed = resumed_est.fit_stream(
        _stream(x[N // 2 :], y[N // 2 :]), state=first.export_stream_state()
    )
    assert _rel(np.asarray(resumed.apply_arrays(x)), ref_out) <= 1e-6
    # The re-exported state covers the union.
    assert resumed_est.export_stream_state().num_examples == N


@pytest.mark.parametrize("name,make", ESTIMATORS, ids=[e[0] for e in ESTIMATORS])
def test_sharded_fold_state_parity(name, make):
    """The same contract through the PARTITIONED chunk plan: a sharded
    resumed fold matches the 1-device one-shot fit ≤ 1e-6 (per-device
    partial stats, one reduce at finish — docs/PARTITIONING.md)."""
    import jax

    from keystone_tpu.parallel.partitioner import Partitioner

    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    x, y = _problem(seed=2)
    reference = make().fit_stream(_stream(x, y))
    ref_out = np.asarray(reference.apply_arrays(x))

    decision = Partitioner().decide_stream("refit-test", CHUNK, record=False)
    assert decision.eligible
    first = make()
    first.fit_stream(_stream(x[: N // 2], y[: N // 2], partition=decision))
    est = make()
    resumed = est.fit_stream(
        _stream(x[N // 2 :], y[N // 2 :], partition=decision),
        state=first.export_stream_state(),
    )
    assert _rel(np.asarray(resumed.apply_arrays(x)), ref_out) <= 1e-6


def test_state_decay_scales_statistics():
    x, y = _problem(seed=3, n=128)
    est = LinearMapEstimator(reg=1e-3)
    est.fit_stream(_stream(x, y))
    state = est.export_stream_state()
    assert state.scaled(1.0) is state
    half = state.scaled(0.5)
    assert half.num_examples == state.num_examples // 2
    assert np.allclose(half.carry[0], state.carry[0] * 0.5)
    # The decayed state still finishes to the SAME model (every
    # statistic and the count scale together — the centering identity
    # is homogeneous).
    a = np.asarray(est.finish_from_state(state).apply_arrays(x))
    b = np.asarray(est.finish_from_state(half).apply_arrays(x))
    assert _rel(b, a) <= 1e-5
    with pytest.raises(StateMismatch):
        state.scaled(0.0)


def test_mismatched_states_fail_loudly():
    x, y = _problem(seed=4, n=128)
    est = LinearMapEstimator(reg=1e-3)
    est.fit_stream(_stream(x, y))
    state = est.export_stream_state()
    wrong_kind = StreamState(
        kind="sketch", estimator="x", num_examples=1, carry=state.carry
    )
    with pytest.raises(StateMismatch):
        merge_stream_states(state, wrong_kind)
    narrow = LinearMapEstimator(reg=1e-3)
    narrow.fit_stream(_stream(x[:, :4], y, chunk=32))
    with pytest.raises(StateMismatch):
        merge_stream_states(state, narrow.export_stream_state())
    # Seeding a stream of the wrong width refuses before any chunk flows.
    with pytest.raises(StateMismatch):
        LinearMapEstimator(reg=1e-3).fit_stream(
            _stream(x[:, :4], y, chunk=32), state=state
        )


def test_unknown_format_version_is_a_miss(tmp_path):
    x, y = _problem(seed=5, n=128)
    est = LinearMapEstimator(reg=1e-3)
    est.fit_stream(_stream(x, y))
    state = est.export_stream_state()
    state.format_version = 99
    store = CheckpointStore(str(tmp_path))
    save_stream_state(store, "future", state)
    assert load_stream_state(store, "future") is None


def test_seeded_fold_correct_under_warm_cache(tmp_path):
    """The donation gate (linalg.donation_safe): with a persistent
    compilation cache configured on the CPU backend, the streaming step
    jit must NOT donate its carry — jax 0.4.37 CPU executables
    deserialized from the cache misapply input→output aliasing, and a
    donated seeded carry silently accumulates garbage across folds
    (minimal repro: jit(f, donate_argnums=(0,)) + persistent cache →
    second process's results drift by hundreds). Asserted structurally:
    carry buffers survive the step when the cache is active, and are
    donated (deleted) when it is not."""
    import jax
    import jax.numpy as jnp

    from keystone_tpu.parallel.linalg import donation_safe
    from keystone_tpu.workflow import streaming as streaming_mod

    saved = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        assert donation_safe()
        jax.config.update("jax_compilation_cache_dir", str(tmp_path))
        assert not donation_safe()

        def step(carry, x_feat, y_b):  # fresh fn: bypass the step cache
            (g,) = carry
            return (g + x_feat.T @ x_feat,)

        jitted, _ = streaming_mod._shared_step_jit((), step)
        carry = (jnp.zeros((D, D)),)
        x_b = jnp.ones((8, D))
        y_b = jnp.ones((8, K))
        mask = jnp.ones((8, 1))
        out, _probe = jitted(carry, x_b, y_b, mask)
        jax.block_until_ready(out)
        assert not carry[0].is_deleted(), (
            "carry was donated under an active persistent cache — the "
            "deserialized-executable aliasing hazard is live again"
        )
    finally:
        jax.config.update("jax_compilation_cache_dir", saved)
