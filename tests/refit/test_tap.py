"""Traffic tap: bounded drop-oldest backpressure that never touches the
serve path (docs/REFIT.md)."""

import time

import numpy as np
import pytest

from keystone_tpu.refit.tap import TrafficTap

pytestmark = pytest.mark.refit


def test_feed_drain_roundtrip_oldest_first():
    tap = TrafficTap(capacity_rows=100)
    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    y = np.arange(6, dtype=np.float32).reshape(6, 1)
    assert tap.feed(x[:4], y[:4]) == 4
    assert tap.feed(x[4:], y[4:]) == 2
    got_x, got_y = tap.drain(4)
    np.testing.assert_array_equal(got_x, x[:4])
    np.testing.assert_array_equal(got_y, y[:4])
    assert tap.depth() == 2
    got_x, _ = tap.drain()
    np.testing.assert_array_equal(got_x, x[4:])
    assert tap.drain() is None


def test_bound_drops_oldest_and_counts():
    tap = TrafficTap(capacity_rows=8)
    x = np.arange(24, dtype=np.float32).reshape(12, 2)
    y = np.zeros((12, 1), np.float32)
    retained = tap.feed(x, y)
    assert retained == 8 and tap.dropped == 4
    got_x, _ = tap.drain()
    # Drop-OLDEST: the freshest 8 rows survive (drift keeps them relevant).
    np.testing.assert_array_equal(got_x, x[4:])
    assert tap.stats()["dropped"] == 4


def test_feed_1d_class_labels_keeps_every_row():
    """1-D integer class labels (the shadow-eval-supported label form)
    are one label PER ROW — every row must survive the feed, as (n, 1)."""
    tap = TrafficTap(capacity_rows=32)
    x = np.arange(10, dtype=np.float32).reshape(5, 2)
    labels = np.array([0, 1, 2, 1, 0], np.float32)
    assert tap.feed(x, labels) == 5
    got_x, got_y = tap.drain()
    np.testing.assert_array_equal(got_x, x)
    np.testing.assert_array_equal(got_y, labels[:, None])
    # Misaligned batches are refused whole, never truncated.
    assert tap.feed(x, np.zeros((3,), np.float32)) == 0
    assert tap.depth() == 0


def test_drain_drops_minority_shapes_instead_of_requeueing():
    """A shape-anomalous row must not become the NEXT drain's reference
    shape (that would starve the daemon down to the minority); misfits
    are dropped and counted."""
    tap = TrafficTap(capacity_rows=32)
    tap.feed(np.zeros((4, 3), np.float32), np.zeros((4, 1), np.float32))
    tap.feed(np.zeros((1, 5), np.float32), np.zeros((1, 1), np.float32))
    tap.feed(np.zeros((2, 3), np.float32), np.zeros((2, 1), np.float32))
    got_x, _ = tap.drain()
    assert got_x.shape == (6, 3)  # the majority shape, both batches
    assert tap.dropped == 1  # the odd (5,)-wide row was dropped, loudly
    assert tap.drain() is None  # nothing requeued


def test_single_row_feed_and_mirror_sampling():
    tap = TrafficTap(capacity_rows=16, mirror_rows=4, sample_every=2)
    tap.feed([1.0, 2.0], [0.0, 1.0])
    got_x, got_y = tap.drain()
    assert got_x.shape == (1, 2) and got_y.shape == (1, 2)
    for i in range(10):
        tap.observe(np.full((3,), float(i), np.float32))
    mirror = tap.mirror()
    assert mirror.shape == (4, 3)  # bounded, freshest kept
    assert tap.mirrored == 5  # 1-in-2 sampling


def test_slow_daemon_never_stalls_or_drops_serving():
    """The backpressure satellite: serving through a full, never-drained
    tap answers EVERY request — a slow (dead) refit daemon costs tap
    rows, never serving traffic."""
    from keystone_tpu.serving.config import ServingConfig
    from keystone_tpu.serving.server import PipelineServer
    from keystone_tpu.serving.synthetic import synthetic_fitted_pipeline

    d, n = 8, 64
    tap = TrafficTap(capacity_rows=4, mirror_rows=4)
    # Pre-fill the labeled buffer to its bound: the daemon is "slow" —
    # nothing ever drains it while traffic flows.
    tap.feed(np.zeros((4, d), np.float32), np.zeros((4, 1), np.float32))
    server = PipelineServer(
        model=synthetic_fitted_pipeline(d=d, seed=0),
        config=ServingConfig(max_batch=8, queue_depth=n + 16),
        tap=tap,
    ).start()
    try:
        server.warmup(np.zeros((d,), np.float32))
        t0 = time.monotonic()
        futures = server.submit_many(
            [np.full((d,), float(i % 5), np.float32) for i in range(n)],
            deadline_s=60.0,
        )
        results = [f.result(timeout=60.0) for f in futures]
        wall = time.monotonic() - t0
    finally:
        server.stop(drain=True)
    assert len(results) == n  # zero dropped
    assert wall < 30.0  # never parked behind the tap
    # The tap stayed at its bound; overflow was ITS loss, not serving's.
    stats = tap.stats()
    assert stats["labeled_depth"] <= 4
    assert stats["mirror_depth"] <= 4
    # Served payloads were sampled into the mirror without blocking.
    assert tap.mirrored > 0
