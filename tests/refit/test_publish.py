"""Publish/rollback controller + the KV305 publish verifier + bounded
registry history under live traffic (docs/REFIT.md)."""

import numpy as np
import pytest

from keystone_tpu.ops.learning.linear import LinearMapper
from keystone_tpu.refit.publish import InProcessPublisher, SupervisorPublisher
from keystone_tpu.serving.config import ServingConfig
from keystone_tpu.serving.server import PipelineServer

pytestmark = pytest.mark.refit

D, K = 6, 2


def _mapper(scale=1.0):
    rng = np.random.default_rng(0)
    return LinearMapper((scale * rng.normal(size=(D, K))).astype(np.float32))


def _server(tap=None):
    server = PipelineServer(
        model=_mapper(),
        config=ServingConfig(max_batch=4, queue_depth=128),
        name="m",
        tap=tap,
    ).start()
    server.warmup(np.zeros((D,), np.float32))
    return server


def test_publish_then_rollback_is_o1_and_ledgered():
    from keystone_tpu.reliability.recovery import get_recovery_log

    server = _server()
    try:
        pub = InProcessPublisher(
            server, name="m", example=np.zeros((D,), np.float32)
        )
        ticket = pub.publish(_mapper(scale=2.0), round_index=1)
        assert server.registry.resolve("m").version == ticket.version == 2
        assert ticket.acks["in-process"]["version"] == 2
        entry = pub.rollback(ticket, reason="test")
        assert entry.version == 1
        assert server.registry.resolve("m").version == 1
        info = server.registry.last_rollback("m")
        assert info["from_version"] == 2 and info["to_version"] == 1
        kinds = {e.kind for e in get_recovery_log().events()}
        assert {"refit_publish", "refit_rollback"} <= kinds
        # Provenance rides stats (satellite contract).
        models = server.stats()["models"]["m"]
        assert models["current"] == 1
        assert models["last_rollback"]["from_version"] == 2
        assert models["published_at"]
    finally:
        server.stop(drain=True)


def test_hot_swap_then_rollback_zero_dropped_inflight():
    """The bounded-history satellite pin: publish a new version and roll
    back WHILE requests are in flight — every request answers (entries
    are immutable; in-flight batches finish on the version they
    resolved), and rollback never re-loads from disk."""
    server = _server()
    try:
        pub = InProcessPublisher(
            server, name="m", example=np.zeros((D,), np.float32)
        )
        payloads = [np.full((D,), float(i % 3), np.float32) for i in range(48)]
        futures = server.submit_many(payloads[:24], deadline_s=60.0)
        ticket = pub.publish(_mapper(scale=3.0), round_index=1)
        futures += server.submit_many(payloads[24:36], deadline_s=60.0)
        pub.rollback(ticket, reason="mid-traffic rollback")
        futures += server.submit_many(payloads[36:], deadline_s=60.0)
        results = [f.result(timeout=60.0) for f in futures]
        assert len(results) == 48  # zero dropped through swap AND rollback
        assert server.registry.resolve("m").version == 1
    finally:
        server.stop(drain=True)


def test_registry_history_is_bounded_with_o1_rollback():
    from keystone_tpu.serving.registry import ModelRegistry

    # history_limit floors at 1: zero retained previous versions would
    # make the watch window's auto-rollback impossible.
    assert ModelRegistry(history_limit=0).history_limit == 1

    r = ModelRegistry(history_limit=2)
    for i in range(6):
        r.publish("m", f"model-{i}")
    # current (6) + previous 2 retained; older evicted.
    assert r.versions("m") == [4, 5, 6]
    assert r.evicted == 3
    entry = r.rollback("m")  # default: the retained previous version
    assert entry.version == 5
    # A rollback-pinned current survives later evictions.
    for i in range(3):
        r.publish("m", f"model-late-{i}")
    assert r.resolve("m").version == 9
    from keystone_tpu.serving.config import UnknownModel

    with pytest.raises(UnknownModel):
        r.resolve("m", version=1)  # evicted long ago


def test_kv305_bucket_and_spec_mismatch():
    import jax

    from keystone_tpu.workflow.verify import verify_refit_publish

    incumbent = _mapper()
    candidate = _mapper(scale=2.0)
    # Bucket drift: candidate plan wants a bucket the fleet never warmed.
    report = verify_refit_publish(
        candidate, incumbent, buckets=[1, 2, 4, 16], warmed_buckets=[1, 2, 4]
    )
    assert [d.code for d in report.errors()] == ["KV305"]
    assert report.errors()[0].details["missing"] == [16]
    # Matching warm set: clean.
    ok = verify_refit_publish(
        candidate, incumbent, buckets=[1, 2], warmed_buckets=[1, 2, 4]
    )
    assert ok.ok
    # Apply-spec drift: a candidate with a different output width than
    # the incumbent cannot serve through the warmed executables.
    wide = LinearMapper(np.zeros((D, K + 2), np.float32))
    report = verify_refit_publish(
        wide, incumbent, example=np.zeros((D,), np.float32)
    )
    assert [d.code for d in report.errors()] == ["KV305"]
    same = verify_refit_publish(
        candidate, incumbent, example=np.zeros((D,), np.float32)
    )
    assert same.ok


def test_kv305_strict_mode_refuses_publish(monkeypatch):
    from keystone_tpu.workflow.verify import VerificationError

    server = _server()
    try:
        pub = InProcessPublisher(
            server, name="m", example=np.zeros((D,), np.float32)
        )
        monkeypatch.setenv("KEYSTONE_VERIFY", "strict")
        wide = LinearMapper(np.zeros((D, K + 2), np.float32))
        with pytest.raises(VerificationError):
            pub.publish(wide, round_index=1)
        assert server.registry.resolve("m").version == 1  # nothing landed
    finally:
        server.stop(drain=True)


def test_supervisor_stats_surface_model_provenance():
    """GET /stats (supervisor.stats()) carries the fleet's active model
    versions from the first ready worker that reports them — without
    spawning processes here (the heartbeat path is exercised by the
    multiworker e2e)."""
    from keystone_tpu.serving.supervisor import WorkerSupervisor

    sup = WorkerSupervisor({"stub": {}})
    worker = sup._workers["0"]
    worker.state = "ready"
    worker.stats = {
        "served": 3,
        "models": {"m": {"current": 7, "published_at": 123.0,
                         "last_rollback": None}},
    }
    stats = sup.stats()
    assert stats["models"]["m"]["current"] == 7
    assert stats["models"]["m"]["published_at"] == 123.0


class _FakeSupervisor:
    """Just the swap/stats surface SupervisorPublisher drives."""

    def __init__(self):
        self.spec = {"synthetic": {"d": D}}
        self.swapped_to = []

    def swap(self, spec, name=None, timeout_s=120.0):
        self.swapped_to.append(spec)
        return {"0": {"kind": "swapped", "version": len(self.swapped_to)},
                "1": {"kind": "swapped", "version": len(self.swapped_to)}}

    def stats(self):
        return {"p99_ms": 1.0}


def test_supervisor_publisher_swaps_digests_and_repoints_restart_spec(tmp_path):
    sup = _FakeSupervisor()
    pub = SupervisorPublisher(
        sup, str(tmp_path), name="m", incumbent=_mapper()
    )
    t1 = pub.publish(_mapper(scale=2.0), round_index=1)
    assert all(a["kind"] == "swapped" for a in t1.acks.values())
    assert sup.spec == {"checkpoint_dir": str(tmp_path), "digest": t1.digest}
    # Content-addressed: a different candidate at the SAME round tag
    # (e.g. after a daemon restart) must not overwrite t1's entry —
    # that would silently re-install the bad model at rollback time.
    pub2 = SupervisorPublisher(
        _FakeSupervisor(), str(tmp_path), name="m", incumbent=_mapper()
    )
    t1b = pub2.publish(_mapper(scale=9.0), round_index=1)
    assert t1b.digest != t1.digest
    t2 = pub.publish(_mapper(scale=3.0), round_index=2)
    assert t2.prev_digest == t1.digest
    pub.rollback(t2, reason="test")
    # The fleet (and any future restart) is back on the previous digest.
    assert sup.spec["digest"] == t1.digest
    import pickle

    with open(tmp_path / f"{t1.digest}.pkl", "rb") as f:
        assert isinstance(pickle.load(f), LinearMapper)
