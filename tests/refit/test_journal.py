"""Durable refit rounds (docs/REFIT.md "Durable rounds"): the round
journal makes a drained-but-unfolded batch survive a daemon kill, makes
re-folds exactly-once, and carries label-delayed rows across restarts.
"""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.refit.daemon import RefitConfig, RefitDaemon
from keystone_tpu.refit.shadow import ShadowEvaluator
from keystone_tpu.refit.tap import TrafficTap
from keystone_tpu.reliability import faultinject
from keystone_tpu.reliability.checkpoint import CheckpointStore
from keystone_tpu.reliability.faultinject import FaultSpec
from keystone_tpu.reliability.recovery import get_recovery_log
from keystone_tpu.workflow.streaming import ChunkStream

D, K = 8, 3
_rng = np.random.default_rng(3)
W_TRUE = _rng.standard_normal((D, K)).astype(np.float32)


def make_rows(n, rng=None):
    rng = rng or _rng
    x = rng.standard_normal((n, D)).astype(np.float32)
    y = np.eye(K, dtype=np.float32)[np.argmax(x @ W_TRUE, axis=1)]
    return x, y


class StubPublisher:
    """In-process publisher stub: enough surface for run_once."""

    def __init__(self, model):
        self.model = model
        self.published = 0

    def current_model(self):
        return self.model

    def publish(self, candidate, round_index=0):
        # Mirror the real publishers' chaos surface (refit/publish.py):
        # the journal's retry-the-publish path needs the probe to fire.
        faultinject.probe("refit.publish")
        self.model = candidate
        self.published += 1

        class Ticket:
            version = f"v{round_index}"

        return Ticket()

    def apply_live(self, x):
        return np.asarray(self.model.apply_arrays(x))

    def rollback(self, ticket, reason=""):
        pass

    def settle(self):
        pass


def make_daemon(store, tap, est=None, name="journal"):
    """A daemon the way a restarted process builds one: the v1 state is
    PERSISTED (first construction seeds the store), and every daemon —
    first or restarted — loads its state from the store, so restarts see
    whatever the last committed fold left."""
    from keystone_tpu.refit.state import load_stream_state, save_stream_state

    est = est or LinearMapEstimator(reg=1e-2)
    x0, y0 = make_rows(512, np.random.default_rng(0))
    model = est.fit_stream(
        ChunkStream(ArrayDataset(x0), ArrayDataset(y0), (), chunk_rows=128)
    )
    if load_stream_state(store, "refit-state") is None:
        save_stream_state(store, "refit-state", est.export_stream_state())
    return RefitDaemon(
        est,
        tap,
        StubPublisher(model),
        store=store,
        shadow=ShadowEvaluator(margin=0.5),
        config=RefitConfig(name=name, min_rows=64, chunk_rows=128),
    )


def test_kill_mid_fold_resumes_from_journal_not_the_tap(tmp_path):
    store = CheckpointStore(str(tmp_path))
    tap = TrafficTap(capacity_rows=8192)
    daemon = make_daemon(store, tap)
    base_rows = daemon.state_rows()

    x, y = make_rows(512, np.random.default_rng(1))
    tap.feed(x, y)
    with pytest.raises(ConnectionError):
        with faultinject.injected(
            FaultSpec(match="refit.fold", kind="transient", calls=(1,))
        ):
            daemon.run_once()
    # The rows left the tap with the drain; only the journal has them.
    assert tap.depth() == 0
    assert daemon._load_journal() is not None

    # "Restart": a fresh daemon over the same store (no in-memory state).
    daemon2 = make_daemon(store, tap, name="journal")
    out = daemon2.run_once()
    assert out == "published"
    assert daemon2._load_journal() is None
    # 512 fed − 128 eval holdout = 384 trained rows, exactly once.
    assert daemon2.state_rows() == base_rows + 384
    kinds = {e.kind for e in get_recovery_log().events()}
    assert "refit_journal_resume" in kinds


def test_refold_after_partial_commit_is_exactly_once(tmp_path):
    # Kill window: state saved post-fold but journal still says
    # "drained". The resume must rewind to the journaled pre-fold
    # snapshot — re-folding on top of the extended state would count
    # the same rows twice.
    store = CheckpointStore(str(tmp_path))
    tap = TrafficTap(capacity_rows=8192)
    daemon = make_daemon(store, tap)
    base_rows = daemon.state_rows()
    pre_fold_state = daemon.state

    x, y = make_rows(512, np.random.default_rng(2))
    tap.feed(x, y)
    assert daemon.run_once() == "published"
    folded_rows = daemon.state_rows()
    assert folded_rows == base_rows + 384

    # Reconstruct the torn-kill journal by hand.
    daemon._save_journal(
        {
            "phase": "drained",
            "round": 1,
            "x": x,
            "y": y,
            "state_before": pre_fold_state,
        }
    )
    daemon2 = make_daemon(store, tap, name="journal")
    assert daemon2.run_once() == "published"
    assert daemon2.state_rows() == folded_rows  # once, not twice


def test_folded_phase_skips_refold_and_republishes(tmp_path):
    # Kill between the folded-state commit and the publish: the resume
    # must NOT re-fold (phase "folded") — it rebuilds the candidate from
    # statistics alone and retries the publish.
    store = CheckpointStore(str(tmp_path))
    tap = TrafficTap(capacity_rows=8192)
    daemon = make_daemon(store, tap)
    base_rows = daemon.state_rows()
    x, y = make_rows(512, np.random.default_rng(4))
    tap.feed(x, y)
    with pytest.raises(ConnectionError):
        with faultinject.injected(
            FaultSpec(match="refit.publish", kind="transient", calls=(1,))
        ):
            daemon.run_once()
    journal = daemon._load_journal()
    assert journal is not None and journal["phase"] == "folded"
    folded_rows = daemon.state_rows()

    daemon2 = make_daemon(store, tap, name="journal")
    assert daemon2.run_once() == "published"
    assert daemon2.state_rows() == folded_rows == base_rows + 384


def test_poisoned_journal_discarded_after_replay_budget(tmp_path):
    # A journaled batch whose replay fails deterministically must cost
    # ONE batch, not wedge every future round (and restarted process)
    # forever: after max_journal_replays failed replays the journal is
    # discarded with ledger evidence and fresh rounds proceed.
    store = CheckpointStore(str(tmp_path))
    tap = TrafficTap(capacity_rows=8192)
    daemon = make_daemon(store, tap)
    daemon.config.max_journal_replays = 2
    x, y = make_rows(512, np.random.default_rng(9))
    tap.feed(x, y)
    with faultinject.injected(
        FaultSpec(match="refit.fold", kind="transient", first_n=10)
    ):
        for _ in range(3):  # drain+fail, replay 1, replay 2 — all poisoned
            with pytest.raises(ConnectionError):
                daemon.run_once()
    # Budget exhausted: the journal is dropped and the daemon absorbs
    # fresh traffic again.
    rows_before = daemon.state_rows()
    x2, y2 = make_rows(512, np.random.default_rng(10))
    tap.feed(x2, y2)
    assert daemon.run_once() == "published"
    assert daemon._load_journal() is None
    assert daemon.state_rows() == rows_before + 384
    kinds = {e.kind for e in get_recovery_log().events()}
    assert "refit_journal_discard" in kinds


def test_label_delayed_rows_survive_daemon_restart(tmp_path):
    # Label-delay realism (ROADMAP refit item d): payloads observed at
    # round r get labels at round r+DELAY. The tap retains what has not
    # been drained; the journal carries what HAS been drained through a
    # mid-sequence kill+restart — no labeled row is ever lost.
    DELAY, ROUNDS, PER_ROUND = 2, 6, 256
    store = CheckpointStore(str(tmp_path))
    tap = TrafficTap(capacity_rows=65536)
    daemon = make_daemon(store, tap)
    base_rows = daemon.state_rows()

    pending = []  # rows whose labels have not arrived yet
    fed = 0
    outcomes = []
    for r in range(1, ROUNDS + 1):
        pending.append(make_rows(PER_ROUND, np.random.default_rng(100 + r)))
        if len(pending) > DELAY:
            x, y = pending.pop(0)  # labels arrive DELAY rounds late
            tap.feed(x, y)
            fed += PER_ROUND
        if r == 4:
            # Kill mid-fold, then restart the daemon mid-sequence.
            try:
                with faultinject.injected(
                    FaultSpec(match="refit.fold", kind="transient", calls=(1,))
                ):
                    daemon.run_once()
            except ConnectionError:
                pass
            daemon = make_daemon(store, tap, name="journal")
        outcomes.append(daemon.run_once())

    # Drain whatever the last rounds left behind (delayed tail labels
    # arrive after the loop in this schedule).
    while pending:
        x, y = pending.pop(0)
        tap.feed(x, y)
        fed += PER_ROUND
        outcomes.append(daemon.run_once())

    assert tap.stats()["dropped"] == 0
    # Every fed row was absorbed exactly once: 3/4 of each drain trains,
    # 1/4 holds out for eval — and nothing was double-folded through the
    # kill/restart at round 4.
    assert daemon.state_rows() - base_rows == int(fed * 0.75)
    # Rounds before the first delayed labels arrive legitimately skip;
    # once labels flow, every round trains.
    assert "skipped_nodata" not in outcomes[DELAY:]
