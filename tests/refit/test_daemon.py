"""The refit daemon: round outcomes, the watch-window auto-rollback,
state persistence across daemons, and the supervised loop."""

import numpy as np
import pytest

from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.refit.daemon import RefitConfig, RefitDaemon
from keystone_tpu.refit.publish import InProcessPublisher
from keystone_tpu.refit.shadow import ShadowEvaluator
from keystone_tpu.refit.tap import TrafficTap
from keystone_tpu.reliability import faultinject
from keystone_tpu.reliability.checkpoint import CheckpointStore
from keystone_tpu.serving.config import ServingConfig
from keystone_tpu.serving.server import PipelineServer

pytestmark = pytest.mark.refit

D, K, N = 8, 3, 256
RNG = np.random.default_rng(7)
W_TRUE = RNG.normal(size=(D, K)).astype(np.float32)


def _rows(n=N, seed=1):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, D)).astype(np.float32)
    y = (x @ W_TRUE).astype(np.float32)
    return x, y


def _fitted(x, y):
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.workflow.streaming import ChunkStream

    est = LinearMapEstimator(reg=1e-3)
    model = est.fit_stream(
        ChunkStream(ArrayDataset(x), ArrayDataset(y), (), chunk_rows=64)
    )
    return est, model


def _loop(tmp_path, min_rows=64, **config):
    x0, y0 = _rows(seed=0)
    est, model = _fitted(x0, y0)
    server = PipelineServer(
        model=model, config=ServingConfig(max_batch=4, queue_depth=64), name="m"
    ).start()
    server.warmup(np.zeros((D,), np.float32))
    tap = TrafficTap(capacity_rows=4096)
    daemon = RefitDaemon(
        est,
        tap,
        InProcessPublisher(server, name="m", example=np.zeros((D,), np.float32)),
        store=CheckpointStore(str(tmp_path)),
        shadow=ShadowEvaluator(margin=0.05),
        config=RefitConfig(name="m", min_rows=min_rows, chunk_rows=64, **config),
        state=est.export_stream_state(),
    )
    return server, tap, daemon


def test_run_once_outcomes(tmp_path):
    server, tap, daemon = _loop(tmp_path)
    try:
        assert daemon.run_once() == "skipped_nodata"  # empty tap
        x, y = _rows(seed=2)
        tap.feed(x, y)
        assert daemon.run_once() == "published"
        assert server.registry.resolve("m").version == 2
        assert daemon.state_rows() > N  # state extended past the seed fit
        # Persisted: a FRESH daemon over the same store resumes the state.
        _, _, daemon2 = _loop(tmp_path)
        daemon2._state = None
        from keystone_tpu.refit.state import load_stream_state

        resumed = load_stream_state(daemon2.store, "refit-state")
        assert resumed is not None
        assert resumed.num_examples == daemon.state_rows()
    finally:
        server.stop(drain=True)


def test_watch_window_rolls_back_corrupted_candidate(tmp_path):
    """The auto-rollback e2e in miniature: a candidate corrupted AFTER
    shadow eval (its blind spot) is published, caught by the live-score
    watch window, and rolled back — with ledger evidence."""
    from keystone_tpu.ops.learning.linear import LinearMapper
    from keystone_tpu.reliability.recovery import get_recovery_log

    server, tap, daemon = _loop(tmp_path)
    try:
        def negate(model):
            return LinearMapper(
                -np.asarray(model.weights),
                intercept=model.intercept,
                feature_mean=model.feature_mean,
            )

        x, y = _rows(seed=3)
        tap.feed(x, y)
        with faultinject.injected(
            faultinject.FaultSpec(
                match="refit.candidate", kind="corrupt", calls=(1,),
                corrupt=negate,
            )
        ):
            assert daemon.run_once() == "rolled_back"
        assert server.registry.resolve("m").version == 1  # incumbent back
        events = get_recovery_log().events("refit_rollback")
        assert events and "live score" in events[-1].detail["reason"]
        # And the loop recovers: the next clean round publishes.
        x, y = _rows(seed=4)
        tap.feed(x, y)
        assert daemon.run_once() == "published"
        assert server.registry.resolve("m").version == 3
    finally:
        server.stop(drain=True)


def test_shadow_gate_skips_worse_candidate(tmp_path):
    """A candidate that scores below incumbent - margin is never
    published (refit_skip in the ledger, registry untouched)."""
    from keystone_tpu.reliability.recovery import get_recovery_log

    server, tap, daemon = _loop(tmp_path, state_decay=0.1)
    try:
        # A deterministic score_fn ranks the candidate below the
        # incumbent: the gate logic is under test, not the evaluator.
        scores = iter([0.2, 0.9])  # candidate, then incumbent
        daemon.shadow = ShadowEvaluator(
            margin=0.05, score_fn=lambda pred, y: next(scores)
        )
        x, y = _rows(seed=5)
        tap.feed(x, y)
        assert daemon.run_once() == "skipped_eval"
        assert server.registry.resolve("m").version == 1
        skips = get_recovery_log().events("refit_skip")
        assert any(e.detail.get("reason") == "shadow_eval" for e in skips)
    finally:
        server.stop(drain=True)


def test_supervised_loop_runs_rounds_and_stops(tmp_path):
    server, tap, daemon = _loop(tmp_path)
    daemon.config.interval_s = 0.05
    try:
        x, y = _rows(seed=6)
        tap.feed(x, y)
        import time

        with daemon:
            deadline = time.monotonic() + 20.0
            while not daemon.outcomes and time.monotonic() < deadline:
                time.sleep(0.05)
        assert daemon.outcomes, "supervised loop never ran a round"
        assert daemon.outcomes[0]["outcome"] == "published"
    finally:
        server.stop(drain=True)


def test_supervised_loop_survives_errors_within_budget(tmp_path):
    from keystone_tpu.reliability.recovery import get_recovery_log

    server, tap, daemon = _loop(tmp_path)
    daemon.config.interval_s = 0.02
    daemon.config.max_consecutive_failures = 2
    calls = {"n": 0}

    def boom():
        calls["n"] += 1
        raise RuntimeError("poisoned round")

    daemon.run_once = boom
    try:
        import time

        with daemon:
            deadline = time.monotonic() + 20.0
            while calls["n"] < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            time.sleep(0.1)  # let the loop observe the budget and exit
        assert calls["n"] == 2  # stopped AT the budget, not spinning
        kinds = [e.kind for e in get_recovery_log().events()]
        assert kinds.count("refit_round_error") >= 2
        assert "refit_daemon_failed" in kinds
    finally:
        server.stop(drain=True)


def test_watch_window_thread_inherits_round_trace_context(tmp_path):
    """Satellite contract: the watch window runs on its OWN thread but
    must inherit the round's trace context via attach()/current_context()
    — refit:watch nests under refit:round in the same trace, and the
    whole tap→fold→shadow→publish→watch round is one span tree."""
    from keystone_tpu.obs import spans

    server, tap, daemon = _loop(tmp_path)
    try:
        x, y = _rows(seed=11)
        with spans.tracing_session("refit-trace", sync_timings=False) as session:
            tap.feed(x, y)
            assert daemon.run_once() == "published"
        by_name = {}
        for s in session.spans():
            by_name.setdefault(s.name, []).append(s)
        for name in ("refit:round", "refit:fold", "refit:shadow",
                     "refit:publish", "refit:watch"):
            assert name in by_name, (name, sorted(by_name))
        round_span = by_name["refit:round"][0]
        watch = by_name["refit:watch"][0]
        # one trace id ties the whole round together...
        assert {s.trace_id for spans_ in by_name.values() for s in spans_} == {
            session.trace_id
        }
        # ...the phase spans nest under the round...
        for name in ("refit:fold", "refit:shadow", "refit:publish"):
            assert by_name[name][0].parent_id == round_span.span_id, name
        # ...and the watch span does too, from ANOTHER thread (the
        # attach() handoff, not stack nesting).
        assert watch.parent_id == round_span.span_id
        assert watch.thread_name == "keystone-refit-watch"
        assert watch.thread_id != round_span.thread_id
        assert watch.attributes.get("outcome") == "published"
        assert round_span.attributes.get("outcome") == "published"
    finally:
        server.stop(drain=True)


def test_sequential_watch_gate_rolls_back_with_archived_evidence(tmp_path):
    """watch_gate="sequential": the anytime-valid mSPRT replaces the
    fixed margin floor. A corrupted candidate's per-row live scores
    separate from the incumbent's, the gate decides rollback with
    archived evidence, and a clean next round closes promote."""
    from keystone_tpu.obs.quality import get_quality_plane, reset_quality_plane
    from keystone_tpu.ops.learning.linear import LinearMapper
    from keystone_tpu.reliability.recovery import get_recovery_log

    reset_quality_plane()
    server, tap, daemon = _loop(tmp_path, watch_gate="sequential")
    try:
        def negate(model):
            return LinearMapper(
                -np.asarray(model.weights),
                intercept=model.intercept,
                feature_mean=model.feature_mean,
            )

        tap.feed(*_rows(seed=21))
        with faultinject.injected(
            faultinject.FaultSpec(
                match="refit.candidate", kind="corrupt", calls=(1,),
                corrupt=negate,
            )
        ):
            assert daemon.run_once() == "rolled_back"
        assert server.registry.resolve("m").version == 1
        events = get_recovery_log().events("refit_rollback")
        assert "sequential gate" in events[-1].detail["reason"]
        plane = get_quality_plane()
        decision = list(plane.decisions)[-1]
        assert decision["kind"] == "refit_watch"
        assert decision["decision"] == "rollback"
        assert decision["alpha"] == daemon.config.gate_alpha
        # The watch window's scores were label-joined into the plane.
        assert plane.stream("m", "labeled").count > 0
        # A clean round decides promote (by evidence or on budget) and
        # the publish sticks — the gate does not cry wolf.
        tap.feed(*_rows(seed=22))
        assert daemon.run_once() == "published"
        assert list(plane.decisions)[-1]["decision"] == "promote"
        assert not plane.open_gates(), "every round's gate is closed"
    finally:
        server.stop(drain=True)


def test_adaptive_decay_shrinks_fold_decay_under_drift(tmp_path):
    """adaptive_decay=True: a drifting live-score stream (quality-plane
    drift detector over threshold) shrinks the decay the fold actually
    applies below the configured state_decay."""
    from keystone_tpu.obs.quality import get_quality_plane, reset_quality_plane

    reset_quality_plane()
    server, tap, daemon = _loop(
        tmp_path, adaptive_decay=True, state_decay=1.0
    )
    try:
        plane = get_quality_plane()
        rng = np.random.default_rng(23)
        det = plane.drift("m")
        for s in rng.normal(1.0, 0.1, size=128):
            det.observe(float(s))
        det.freeze_baseline()
        for s in rng.normal(0.2, 0.1, size=128):  # 8-sigma regression
            det.observe(float(s))
        assert plane.check_drift("m") is not None
        tap.feed(*_rows(seed=24))
        assert daemon.run_once() == "published"
        assert daemon.applied_decay < 1.0, (
            "detected drift must shrink the applied state decay"
        )
        assert daemon.outcomes[-1]["state_decay"] == round(
            daemon.applied_decay, 4
        )
    finally:
        server.stop(drain=True)


def test_daemon_kill_mid_label_join_replays_exactly_once(tmp_path):
    """Exactly-once label joins across the journal, both kill windows:
    (1) a crash AFTER the in-memory join but BEFORE the quality state
    persisted loses the join with the process — the journal replay
    re-joins it, once; (2) a crash AFTER the quality state persisted but
    BEFORE the journal cleared replays the round, but the persisted join
    token makes the replay skip the re-join — never double-counted."""
    from keystone_tpu.obs.quality import get_quality_plane, reset_quality_plane

    reset_quality_plane()
    eval_rows = N // 4  # eval_fraction 0.25 of the drained batch

    # -- window 1: die between the join and the quality-state persist.
    server, tap, daemon = _loop(tmp_path)
    try:
        tap.feed(*_rows(seed=25))

        def die(*a, **k):
            raise RuntimeError("killed before quality persist")

        daemon._persist_quality = die
        with pytest.raises(RuntimeError, match="killed before"):
            daemon.run_once()
        assert get_quality_plane().stream("m", "labeled").count == eval_rows
    finally:
        server.stop(drain=True)

    reset_quality_plane()  # the process died: in-memory joins are gone
    server, tap, daemon2 = _loop(tmp_path)
    try:
        assert get_quality_plane().stream("m", "labeled").count == 0
        assert daemon2.run_once() in ("published", "rolled_back")
        plane = get_quality_plane()
        assert plane.stream("m", "labeled").count == eval_rows, (
            "journal replay joins the lost batch exactly once"
        )
        assert plane.report()["models"]["m"]["label_joins"] == eval_rows

        # -- window 2: die between the quality persist and journal clear.
        tap.feed(*_rows(seed=26))
        real_clear = daemon2._clear_journal
        daemon2._clear_journal = lambda: (_ for _ in ()).throw(
            RuntimeError("killed before journal clear")
        )
        with pytest.raises(RuntimeError, match="journal clear"):
            daemon2.run_once()
        daemon2._clear_journal = real_clear
        assert plane.stream("m", "labeled").count == 2 * eval_rows
    finally:
        server.stop(drain=True)

    reset_quality_plane()
    server, tap, daemon3 = _loop(tmp_path)
    try:
        # Restored from the persisted quality state: both joins present.
        plane = get_quality_plane()
        assert plane.stream("m", "labeled").count == 2 * eval_rows
        assert daemon3.run_once() in ("published", "rolled_back")
        assert plane.stream("m", "labeled").count == 2 * eval_rows, (
            "replayed batch whose join persisted must NOT join again"
        )
    finally:
        server.stop(drain=True)


def test_watch_window_thread_exception_propagates_to_round(tmp_path):
    """An exception inside the watch thread must re-raise on the round
    thread (the supervised loop owns the error ledger) — never vanish
    into a dead thread."""
    server, tap, daemon = _loop(tmp_path)
    try:
        x, y = _rows(seed=12)
        tap.feed(x, y)

        def boom(*a, **k):
            raise RuntimeError("watch exploded")

        daemon._watch_inner = boom
        with pytest.raises(RuntimeError, match="watch exploded"):
            daemon.run_once()
    finally:
        server.stop(drain=True)
