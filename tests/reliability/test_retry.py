"""Retry engine: the classification table, backoff determinism, deadline
watchdogs, and the retry loop's give-up semantics."""

import time

import pytest

from keystone_tpu.reliability import (
    CorruptRecordError,
    Deadline,
    DeadlineExceeded,
    ErrorClass,
    RetryPolicy,
    classify_error,
    get_recovery_log,
    run_with_deadline,
    wait_until,
)


# ------------------------------------------------------------ classification


@pytest.mark.parametrize(
    "exc,expected",
    [
        (RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating 1.2G"), ErrorClass.OOM),
        (ValueError("XLA allocation failure: Out of memory"), ErrorClass.OOM),
        (MemoryError(), ErrorClass.OOM),
        (RuntimeError("UNAVAILABLE: socket closed"), ErrorClass.TRANSIENT),
        (RuntimeError("coordinator heartbeat missed"), ErrorClass.TRANSIENT),
        (RuntimeError("worker preempted by scheduler"), ErrorClass.TRANSIENT),
        (ConnectionResetError("peer reset"), ErrorClass.TRANSIENT),
        (TimeoutError("no response"), ErrorClass.TRANSIENT),
        (DeadlineExceeded("node: deadline"), ErrorClass.DEADLINE),
        (RuntimeError("DEADLINE_EXCEEDED: rpc"), ErrorClass.DEADLINE),
        (CorruptRecordError("bad jpeg"), ErrorClass.CORRUPT_DATA),
        (RuntimeError("DATA_LOSS: truncated record"), ErrorClass.CORRUPT_DATA),
        (ValueError("block size 12 not divisible"), ErrorClass.PERMANENT),
        (TypeError("estimator dependencies must be datasets"), ErrorClass.PERMANENT),
        (FileNotFoundError("no archive(s) at /x"), ErrorClass.PERMANENT),
        (OSError("stale NFS file handle"), ErrorClass.TRANSIENT),
        (KeyError("label"), ErrorClass.PERMANENT),
    ],
)
def test_classification_table(exc, expected):
    assert classify_error(exc) is expected


def test_message_pattern_wins_over_type():
    # An OOM surfaced through a ValueError path must still walk the
    # degradation ladder, not be treated as a user error.
    assert classify_error(ValueError("RESOURCE_EXHAUSTED while compiling")) is ErrorClass.OOM


# ------------------------------------------------------------------- backoff


def test_backoff_schedule_is_deterministic_per_seed():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.1, multiplier=2.0, seed=42)
    assert p.backoff_schedule() == p.backoff_schedule()
    assert p.backoff_schedule() != RetryPolicy(
        max_attempts=5, base_delay_s=0.1, multiplier=2.0, seed=43
    ).backoff_schedule()
    # exponential envelope: each delay within jitter of base * mult^i
    for i, d in enumerate(p.backoff_schedule()):
        nominal = 0.1 * 2.0**i
        assert nominal * (1 - p.jitter) <= d <= nominal * (1 + p.jitter)


def test_backoff_respects_max_delay():
    p = RetryPolicy(max_attempts=10, base_delay_s=1.0, multiplier=10.0,
                    max_delay_s=3.0, jitter=0.0, seed=0)
    assert p.backoff_schedule() == [1.0, 3.0, 3.0, 3.0, 3.0, 3.0, 3.0, 3.0, 3.0]


def test_call_sleeps_the_published_schedule(no_sleep_policy):
    policy, slept = no_sleep_policy
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("UNAVAILABLE: relay hiccup")
        return "ok"

    assert policy.call(flaky, label="flaky") == "ok"
    assert slept == policy.backoff_schedule()[: len(slept)]
    assert len(calls) == 3
    retries = get_recovery_log().events("retry")
    assert len(retries) >= 2
    assert retries[-1].detail["error_class"] == "transient"


def test_call_never_retries_permanent(no_sleep_policy):
    policy, slept = no_sleep_policy
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("bad shape")

    with pytest.raises(ValueError):
        policy.call(broken)
    assert len(calls) == 1 and slept == []


def test_call_never_retries_oom_by_default(no_sleep_policy):
    # OOM is the ladder's job: retrying the same shape re-OOMs.
    policy, slept = no_sleep_policy
    with pytest.raises(RuntimeError):
        policy.call(lambda: (_ for _ in ()).throw(
            RuntimeError("RESOURCE_EXHAUSTED")))
    assert slept == []


def test_call_gives_up_after_max_attempts(no_sleep_policy):
    policy, slept = no_sleep_policy
    calls = []

    def always_down():
        calls.append(1)
        raise ConnectionError("UNAVAILABLE")

    with pytest.raises(ConnectionError):
        policy.call(always_down)
    assert len(calls) == policy.max_attempts
    assert len(slept) == policy.max_attempts - 1


# ----------------------------------------------------------------- deadlines


def test_run_with_deadline_passes_result_and_errors():
    assert run_with_deadline(lambda: 7, 5.0) == 7
    with pytest.raises(ValueError, match="inner"):
        run_with_deadline(lambda: (_ for _ in ()).throw(ValueError("inner")), 5.0)


def test_run_with_deadline_times_out():
    with pytest.raises(DeadlineExceeded, match="hung-node"):
        run_with_deadline(lambda: time.sleep(5.0), 0.1, label="hung-node")


def test_policy_deadline_recovers_hang():
    attempts = []

    def hangs_once():
        attempts.append(1)
        if len(attempts) == 1:
            time.sleep(5.0)
        return "late but fine"

    policy = RetryPolicy(max_attempts=2, deadline_s=0.2, sleep=lambda s: None)
    assert policy.call(hangs_once, label="hang") == "late but fine"
    assert len(attempts) == 2


def test_wait_until_polls_then_deadline():
    state = {"n": 0}

    def pred():
        state["n"] += 1
        return state["n"] >= 3

    assert wait_until(pred, Deadline.after(5.0), interval=0.0, sleep=lambda s: None)
    with pytest.raises(DeadlineExceeded, match="coordinator"):
        wait_until(lambda: False, Deadline.after(0.05), interval=0.01,
                   label="coordinator")


# --------------------------------------------- retry bounded by a deadline


def test_call_stops_retrying_past_the_deadline():
    """The retry clock and the request deadline are ONE clock: when the
    next backoff would sleep past the caller's remaining budget, the
    last error surfaces instead of a retry the deadline has already
    disowned (the serving _apply_group contract)."""
    fake_now = [100.0]
    slept = []
    policy = RetryPolicy(
        max_attempts=5, base_delay_s=1.0, multiplier=1.0, jitter=0.0, seed=0,
        sleep=lambda s: (slept.append(s), fake_now.__setitem__(0, fake_now[0] + s)),
    )
    deadline = Deadline(2.5, clock=lambda: fake_now[0])

    def always_transient():
        raise ConnectionError("UNAVAILABLE: flaky")

    with pytest.raises(ConnectionError):
        policy.call(always_transient, label="bounded", deadline=deadline)
    # budget 2.5s, 1s backoffs: attempt, sleep, attempt, sleep, attempt,
    # then the third backoff (0.5s left < 1s delay) abandons.
    assert slept == [1.0, 1.0]
    abandoned = get_recovery_log().events("retry_abandoned")
    assert abandoned and abandoned[-1].detail["attempt"] == 3


def test_call_with_roomy_deadline_retries_normally():
    policy = RetryPolicy(
        max_attempts=3, base_delay_s=0.001, jitter=0.0, seed=0
    )
    attempts = {"n": 0}

    def flaky():
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise ConnectionError("UNAVAILABLE: flaky")
        return "ok"

    assert policy.call(flaky, deadline=Deadline(30.0)) == "ok"
    assert attempts["n"] == 3
