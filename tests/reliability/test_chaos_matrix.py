"""The chaos-matrix sweep: every registered probe site, faulted.

``KNOWN_PROBE_SITES`` (reliability/faultinject.py) is the package's
whole chaos surface — and before this sweep only hand-picked sites were
exercised, so a new ``probe()`` call could land with no test ever aiming
a fault at it. This matrix closes the gap structurally:

- every site carries a deterministic driver (workload + FaultSpec +
  recovery assertions); ``test_matrix_covers_every_probe_site`` fails
  the moment a site is registered without one;
- every driver asserts the site's recovery CONTRACT — the ledger kinds
  that prove the fault was absorbed, plus the site-specific invariant
  (zero dropped requests on serving sites, parity on the recoverable
  fit sites, a completed fit on degradable solver sites);
- the shared harness asserts the cross-cutting invariant: no keystone
  thread outlives its driver (a faulted path must join what it spawned).

Marked ``slow`` (multi-process serving drivers, several fits):
scripts/chaos_sweep_smoke.sh is the CI face; tier-1 excludes it.
"""

import json
import threading
import time

import numpy as np
import pytest

from keystone_tpu.reliability import faultinject
from keystone_tpu.reliability.faultinject import (
    KNOWN_PROBE_SITES,
    FaultSpec,
    injected,
)
from keystone_tpu.reliability.recovery import get_recovery_log

pytestmark = pytest.mark.slow

D, K = 8, 3
_rng = np.random.default_rng(11)
X = _rng.normal(size=(512, D)).astype(np.float32)
W = _rng.normal(size=(D, K)).astype(np.float32)
Y = (X @ W + 0.01 * _rng.normal(size=(512, K))).astype(np.float32)


def _keystone_threads():
    return sorted(
        t.name
        for t in threading.enumerate()
        if t.is_alive() and t.name.startswith("keystone-")
    )


def _ledger_has(kind, label=None):
    return any(
        e.kind == kind and (label is None or label in e.label)
        for e in get_recovery_log().events()
    )


def _stream_fit(**fit_kwargs):
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.learning.linear import LinearMapEstimator
    from keystone_tpu.workflow.pipeline import BatchTransformer

    class Scale(BatchTransformer):
        def __init__(self, c):
            self.c = float(c)

        def apply_arrays(self, a):
            return a * self.c

    pipeline = Scale(2.0).to_pipeline().then_label_estimator(
        LinearMapEstimator(reg=1e-3), ArrayDataset(X), ArrayDataset(Y)
    )
    return pipeline.fit(**fit_kwargs)


def _preds(fitted):
    from keystone_tpu.data.dataset import ArrayDataset

    return np.asarray(fitted.apply_batch(ArrayDataset(X[:32])).data)


# ------------------------------------------------------------- the drivers


def drive_streaming_chunk():
    """A fault inside the chunk dispatch aborts the fold loudly; the
    invariant is hygiene: the abandoned fold joins its prefetch workers
    and a clean re-run succeeds."""
    import os

    os.environ["KEYSTONE_STREAM_CHUNK_ROWS"] = "64"
    with injected(
        FaultSpec(match="streaming.chunk", kind="transient", calls=(2,))
    ):
        with pytest.raises(ConnectionError):
            _stream_fit()
    assert _ledger_has("fault", "streaming.chunk")
    assert not [n for n in _keystone_threads() if "prefetch" in n]
    from keystone_tpu.workflow.executor import PipelineEnv

    PipelineEnv.reset()
    assert _preds(_stream_fit()).shape == (32, K)


def drive_shard_loss():
    """A device lost mid-stream is ABSORBED: the fit completes on the
    surviving shards with parity vs the single-device reference."""
    import os

    from keystone_tpu.parallel.partitioner import partition_disabled
    from keystone_tpu.workflow.executor import PipelineEnv
    from keystone_tpu.workflow.streaming import last_stream_report

    os.environ["KEYSTONE_STREAM_CHUNK_ROWS"] = "64"
    PipelineEnv.reset()
    with partition_disabled():
        ref = _preds(_stream_fit())
    PipelineEnv.reset()
    with injected(
        FaultSpec(match="parallel.shard_loss", kind="transient", calls=(3,))
    ):
        out = _preds(_stream_fit())
    report = last_stream_report()
    assert report.shard_losses == 1 and report.shards == 7
    err = float(np.linalg.norm(out - ref) / np.linalg.norm(ref))
    assert err <= 1e-5, err
    assert _ledger_has("shard_loss") and _ledger_has("shard_resume")


def drive_ingest_decode():
    """A transient inside the decode pool surfaces loudly; the same
    archive then loads cleanly (fault did not poison the loader)."""
    PIL = pytest.importorskip("PIL.Image")
    import io
    import tarfile
    import tempfile

    from keystone_tpu.data.loaders.archive import load_image_archives

    path = tempfile.mktemp(suffix=".tar")
    with tarfile.open(path, "w") as tar:
        for i in range(4):
            img = np.full((16, 16, 3), i * 40, np.uint8)
            buf = io.BytesIO()
            PIL.fromarray(img).save(buf, format="JPEG")
            info = tarfile.TarInfo(name=f"cls{i % 2}/img{i}.JPEG")
            info.size = len(buf.getvalue())
            tar.addfile(info, io.BytesIO(buf.getvalue()))
    with injected(
        FaultSpec(match="ingest.decode_batch", kind="transient", calls=(1,))
    ):
        with pytest.raises(ConnectionError):
            load_image_archives(path, label_fn=lambda n: n.split("/")[0])
    assert _ledger_has("fault", "ingest.decode_batch")
    ds = load_image_archives(path, label_fn=lambda n: n.split("/")[0])
    assert len(ds) == 4


def drive_serving_apply():
    """A transient under a live batch is retried per policy: every
    request answers, zero failures — the 0-dropped-requests invariant."""
    from keystone_tpu.reliability.retry import RetryPolicy
    from keystone_tpu.serving.config import ServingConfig
    from keystone_tpu.serving.server import PipelineServer
    from keystone_tpu.serving.synthetic import (
        synthetic_fitted_pipeline,
        synthetic_requests,
    )

    fp = synthetic_fitted_pipeline(d=D)
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.01, max_delay_s=0.02)
    with injected(
        FaultSpec(match="serving.apply", kind="transient", calls=(1,))
    ):
        with PipelineServer(
            fp,
            config=ServingConfig(
                max_batch=8, max_wait_ms=10.0, queue_depth=64,
                retry_policy=policy,
            ),
        ) as server:
            futures = server.submit_many(synthetic_requests(8, d=D))
            results = [f.result(timeout=60) for f in futures]
            stats = server.stats()
    assert len(results) == 8
    assert stats["failures"] == 0 and stats["retries"] >= 1
    assert _ledger_has("fault", "serving.apply")


def _stub_supervisor(chaos):
    from keystone_tpu.serving.supervisor import (
        SupervisorConfig,
        WorkerSupervisor,
    )

    env = {
        f"KEYSTONE_FAULT_SPECS_WORKER_{wid}": json.dumps(specs)
        for wid, specs in chaos.items()
    }
    return WorkerSupervisor(
        {"stub": {"delay_ms": 2}},
        SupervisorConfig(
            workers=2,
            heartbeat_s=0.05,
            hang_timeout_s=0.8,
            ready_timeout_s=30.0,
            monitor_interval_s=0.02,
        ),
        env=env,
    )


def drive_worker_request_kill():
    """SIGKILL inside a worker's request path: in-flight work requeues
    onto the healthy sibling — zero dropped requests."""
    sup = _stub_supervisor(
        {"0": [{"match": "serving.worker.request", "kind": "kill", "calls": [4]}]}
    ).start()
    try:
        sup.wait_ready()
        futures = [sup.submit([float(i)], deadline_s=60) for i in range(32)]
        results = [f.result(timeout=60) for f in futures]
        assert [r[0] for r in results] == [2.0 * i for i in range(32)]
        assert _ledger_has("worker_crash")
    finally:
        sup.stop()


def drive_worker_heartbeat_corrupt():
    """Garbled heartbeats must read as a dead worker: hang-detected,
    recycled, and the fleet serves again."""
    sup = _stub_supervisor(
        {"0": [{"match": "serving.worker.heartbeat", "kind": "corrupt",
                "first_n": 10000}]}
    ).start()
    try:
        deadline = time.monotonic() + 30
        while not get_recovery_log().events("worker_crash"):
            assert time.monotonic() < deadline, "corrupt channel undetected"
            time.sleep(0.05)
        sup.wait_ready(timeout_s=30)
        assert sup.submit([2.0], deadline_s=60).result(timeout=60) == [4.0]
    finally:
        sup.stop()


def _refit_rig(tmp_store):
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.learning.linear import LinearMapEstimator
    from keystone_tpu.refit.daemon import RefitConfig, RefitDaemon
    from keystone_tpu.refit.shadow import ShadowEvaluator
    from keystone_tpu.refit.tap import TrafficTap
    from keystone_tpu.reliability.checkpoint import CheckpointStore
    from keystone_tpu.workflow.streaming import ChunkStream

    class StubPublisher:
        def __init__(self, model):
            self.model = model
            self.rollbacks = 0

        def current_model(self):
            return self.model

        def publish(self, candidate, round_index=0):
            faultinject.probe("refit.publish")
            self.model = candidate

            class Ticket:
                version = f"v{round_index}"

            return Ticket()

        def apply_live(self, x):
            return np.asarray(self.model.apply_arrays(x))

        def rollback(self, ticket, reason=""):
            self.rollbacks += 1
            get_recovery_log().record("refit_rollback", "chaos", reason=reason)

        def settle(self):
            pass

    est = LinearMapEstimator(reg=1e-2)
    x0 = _rng.normal(size=(512, D)).astype(np.float32)
    y0 = np.eye(K, dtype=np.float32)[np.argmax(x0 @ W, axis=1)]
    model = est.fit_stream(
        ChunkStream(ArrayDataset(x0), ArrayDataset(y0), (), chunk_rows=128)
    )
    store = CheckpointStore(str(tmp_store))
    tap = TrafficTap(capacity_rows=8192)
    daemon = RefitDaemon(
        est,
        tap,
        StubPublisher(model),
        store=store,
        shadow=ShadowEvaluator(margin=0.5),
        config=RefitConfig(name="chaos", min_rows=64, chunk_rows=128),
        state=est.export_stream_state(),
    )
    x1 = _rng.normal(size=(512, D)).astype(np.float32)
    y1 = np.eye(K, dtype=np.float32)[np.argmax(x1 @ W, axis=1)]
    tap.feed(x1, y1)
    return daemon, tap


def drive_refit_fold(tmp_path):
    """A fault inside the fold loses nothing: the drained rows resume
    from the round journal on the next round."""
    daemon, tap = _refit_rig(tmp_path / "fold")
    before = daemon.state_rows()
    with injected(FaultSpec(match="refit.fold", kind="transient", calls=(1,))):
        with pytest.raises(ConnectionError):
            daemon.run_once()
    assert tap.depth() == 0  # rows left the tap with the drain...
    assert daemon.run_once() == "published"  # ...and the journal has them
    assert daemon.state_rows() == before + 384
    assert _ledger_has("fault", "refit.fold")
    assert _ledger_has("refit_journal_resume")


def drive_refit_candidate(tmp_path):
    """A candidate corrupted AFTER shadow eval (the eval blind spot) is
    caught by the watch window and rolled back."""

    def negate(model):
        from keystone_tpu.ops.learning.linear import LinearMapper

        return LinearMapper(
            -np.asarray(model.weights),
            intercept=model.intercept,
            feature_mean=model.feature_mean,
        )

    daemon, _ = _refit_rig(tmp_path / "candidate")
    with injected(
        FaultSpec(
            match="refit.candidate", kind="corrupt", calls=(1,), corrupt=negate
        )
    ):
        outcome = daemon.run_once()
    assert outcome == "rolled_back"
    assert daemon.publisher.rollbacks == 1
    assert _ledger_has("fault", "refit.candidate")


def drive_refit_publish(tmp_path):
    """A fault inside the swap itself retries from the journal's folded
    phase: no re-fold (exactly once), publish lands on round 2."""
    daemon, _ = _refit_rig(tmp_path / "publish")
    before = daemon.state_rows()
    with injected(
        FaultSpec(match="refit.publish", kind="transient", calls=(1,))
    ):
        with pytest.raises(ConnectionError):
            daemon.run_once()
    folded = daemon.state_rows()
    assert folded == before + 384
    assert daemon.run_once() == "published"
    assert daemon.state_rows() == folded  # journal skipped the re-fold
    assert _ledger_has("fault", "refit.publish")


def _solver_data(n=96, d=24):
    from keystone_tpu.data.dataset import ArrayDataset

    rng = np.random.default_rng(5)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, K)).astype(np.float32)
    y = (x @ w).astype(np.float32)
    return ArrayDataset(x), ArrayDataset(y)


def drive_least_squares_oom():
    """OOM in the preferred rung falls down the degradation ladder; the
    fit still completes."""
    from keystone_tpu.ops.learning.least_squares import LeastSquaresEstimator

    data, labels = _solver_data()
    with injected(
        FaultSpec(match="LeastSquaresEstimator.solve", kind="oom", calls=(1,))
    ):
        model = LeastSquaresEstimator(reg=1e-3).fit(data, labels)
    assert model.degradation["rung"] == "block"
    assert _ledger_has("degrade")


def drive_block_solver_oom():
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator

    data, labels = _solver_data()
    with injected(
        FaultSpec(
            match="BlockLeastSquaresEstimator.solve", kind="oom", calls=(1,)
        )
    ):
        model = BlockLeastSquaresEstimator(16, num_iter=1, reg=1e-3).fit(
            data, labels
        )
    assert model.degradation is not None
    assert _ledger_has("degrade")


def drive_krr_oom():
    from keystone_tpu.ops.learning.kernel import (
        GaussianKernelGenerator,
        KernelRidgeRegression,
    )

    data, labels = _solver_data(n=64, d=8)
    with injected(
        FaultSpec(match="KernelRidgeRegression.solve", kind="oom", calls=(1,))
    ):
        model = KernelRidgeRegression(
            GaussianKernelGenerator(0.1), reg=1e-2, block_size=32,
            num_epochs=1,
        ).fit(data, labels)
    assert model.degradation is not None
    assert _ledger_has("degrade")


def drive_sketch_finish_oom():
    """OOM in the sketched finish's dual ridge falls to the lstsq rung;
    the streamed fit still completes and predicts."""
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.sketch import SketchedLeastSquaresEstimator
    from keystone_tpu.workflow.streaming import ChunkStream

    data, labels = _solver_data(n=256, d=24)
    stream = ChunkStream(data, labels, (), chunk_rows=64)
    with injected(
        FaultSpec(match="sketch.finish", kind="oom", calls=(1,))
    ):
        model = SketchedLeastSquaresEstimator(
            reg=1e-3, sketch_size=128
        ).fit_stream(stream)
    assert model.degradation["rung"] == "lstsq"
    assert _ledger_has("degrade")
    preds = np.asarray(model.apply_arrays(np.asarray(data.data)[:16]))
    assert preds.shape == (16, K)


#: site → driver. The sweep fails when KNOWN_PROBE_SITES grows past it.
MATRIX = {
    "streaming.chunk": drive_streaming_chunk,
    "parallel.shard_loss": drive_shard_loss,
    "ingest.decode_batch": drive_ingest_decode,
    "serving.apply": drive_serving_apply,
    "serving.worker.request": drive_worker_request_kill,
    "serving.worker.heartbeat": drive_worker_heartbeat_corrupt,
    "refit.fold": drive_refit_fold,
    "refit.candidate": drive_refit_candidate,
    "refit.publish": drive_refit_publish,
    "LeastSquaresEstimator.solve": drive_least_squares_oom,
    "BlockLeastSquaresEstimator.solve": drive_block_solver_oom,
    "KernelRidgeRegression.solve": drive_krr_oom,
    "sketch.finish": drive_sketch_finish_oom,
}

#: drivers that accept a tmp_path for a checkpoint store
_NEEDS_TMP = {"refit.fold", "refit.candidate", "refit.publish"}


def test_matrix_covers_every_probe_site():
    missing = set(KNOWN_PROBE_SITES) - set(MATRIX)
    stale = set(MATRIX) - set(KNOWN_PROBE_SITES)
    assert not missing, (
        f"probe sites with no chaos-matrix driver: {sorted(missing)} — "
        "register a driver in tests/reliability/test_chaos_matrix.py"
    )
    assert not stale, f"matrix entries for unregistered sites: {sorted(stale)}"


@pytest.mark.parametrize("site", sorted(KNOWN_PROBE_SITES))
def test_fault_at_site_is_recovered(site, tmp_path):
    driver = MATRIX[site]
    before = _keystone_threads()
    if site in _NEEDS_TMP:
        driver(tmp_path)
    else:
        driver()
    # Cross-cutting invariant: the driver (and the faulted machinery it
    # exercised) joined everything it spawned.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        leaked = [n for n in _keystone_threads() if n not in before]
        if not leaked:
            break
        time.sleep(0.1)
    assert not leaked, f"threads leaked by the {site} driver: {leaked}"
