"""Checkpoint/restore of fitted pipeline state: digest stability, torn-file
tolerance, in-process resume, and the killed-then-resumed subprocess run
(the lineage-recovery replacement, ISSUE acceptance criterion)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.reliability import (
    CheckpointStore,
    enable_checkpointing,
    get_recovery_log,
    prefix_digest,
)
from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.operators import DatasetOperator
from keystone_tpu.workflow.pipeline import Estimator, Transformer
from keystone_tpu.workflow.prefix import Prefix

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class _Scale(Transformer):
    def __init__(self, s):
        self.s = s

    def apply(self, datum):
        return datum * self.s

    def apply_batch(self, ds):
        return ArrayDataset(np.asarray(ds.data) * self.s, ds.num_examples)


class _CountingEstimator(Estimator):
    fits = []

    def __init__(self, tag):
        self.tag = tag

    def fit(self, data):
        _CountingEstimator.fits.append(self.tag)
        return _Scale(float(np.mean(np.asarray(data.data))))


@pytest.fixture(autouse=True)
def _clear_counts():
    _CountingEstimator.fits = []
    yield


# ------------------------------------------------------------------ digests


def _prefix_for(est, arr):
    data_op = DatasetOperator(ArrayDataset(arr))
    return Prefix(((est, ((data_op, ()),))))


def test_prefix_digest_stable_across_fresh_objects():
    # Identity-hashed operators, content-equal state: equal digests —
    # the property that makes resume work in a NEW process.
    a = _prefix_for(_CountingEstimator("A"), np.arange(12.0))
    b = _prefix_for(_CountingEstimator("A"), np.arange(12.0))
    assert a.tree[0] is not b.tree[0]
    assert prefix_digest(a) == prefix_digest(b)


def test_prefix_digest_stable_for_set_attributes():
    # Set iteration order follows PYTHONHASHSEED; the digest must not.
    class SetEst(_CountingEstimator):
        def __init__(self, names):
            self.names = names

    arr = np.arange(4.0)
    a = _prefix_for(SetEst({"zebra", "apple", "mango"}), arr)
    b = _prefix_for(SetEst({"mango", "zebra", "apple"}), arr)
    c = _prefix_for(SetEst({"zebra", "apple"}), arr)
    assert prefix_digest(a) == prefix_digest(b)
    assert prefix_digest(a) != prefix_digest(c)


def test_prefix_digest_sensitive_to_config_and_data():
    base = _prefix_for(_CountingEstimator("A"), np.arange(12.0))
    other_cfg = _prefix_for(_CountingEstimator("B"), np.arange(12.0))
    other_data = _prefix_for(_CountingEstimator("A"), np.arange(12.0) + 1)
    assert prefix_digest(base) != prefix_digest(other_cfg)
    assert prefix_digest(base) != prefix_digest(other_data)


# -------------------------------------------------------------------- store


def test_store_round_trip_and_torn_file(tmp_path):
    store = CheckpointStore(str(tmp_path))
    prefix = _prefix_for(_CountingEstimator("A"), np.arange(4.0))
    model = _Scale(3.5)
    assert store.save(prefix, model)
    restored = store.lookup(prefix)
    assert isinstance(restored, _Scale) and restored.s == 3.5
    # torn entry (killed mid-write after rename... simulated corruption):
    # must read as a miss, not crash the resume
    entry = os.path.join(str(tmp_path), prefix_digest(prefix) + ".pkl")
    with open(entry, "wb") as f:
        f.write(b"\x80truncated garbage")
    from keystone_tpu.reliability.checkpoint import _MISS

    assert store.lookup(prefix) is _MISS
    assert store.stats()["writes"] == 1


def test_unpicklable_fit_is_skipped_not_fatal(tmp_path):
    store = CheckpointStore(str(tmp_path))
    prefix = _prefix_for(_CountingEstimator("A"), np.arange(4.0))
    assert store.save(prefix, lambda x: x) is False  # lambdas don't pickle
    assert os.listdir(str(tmp_path)) == []


# ------------------------------------------------------------------- resume


def test_in_process_resume_skips_refit(tmp_path):
    ck = str(tmp_path / "ck")
    enable_checkpointing(ck)
    data = ArrayDataset(np.arange(8.0).reshape(8, 1))
    out1 = _CountingEstimator("A").with_data(data).apply(data).get()
    assert _CountingEstimator.fits == ["A"]

    # "new process": fresh env, fresh operator objects, same data content
    PipelineEnv.reset()
    enable_checkpointing(ck)
    data2 = ArrayDataset(np.arange(8.0).reshape(8, 1))
    out2 = _CountingEstimator("A").with_data(data2).apply(data2).get()
    assert _CountingEstimator.fits == ["A"]  # NOT refit
    assert get_recovery_log().summary()["checkpoint_hits"] == 1
    np.testing.assert_allclose(np.asarray(out1.data), np.asarray(out2.data))


def test_changed_estimator_config_refits(tmp_path):
    ck = str(tmp_path / "ck")
    enable_checkpointing(ck)
    data = ArrayDataset(np.arange(8.0).reshape(8, 1))
    _CountingEstimator("A").with_data(data).apply(data).get()
    PipelineEnv.reset()
    enable_checkpointing(ck)
    _CountingEstimator("B").with_data(data).apply(data).get()
    assert _CountingEstimator.fits == ["A", "B"]  # different digest → refit


_RESUME_SCRIPT = """
import os, sys
import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.workflow.pipeline import Estimator, Transformer
from keystone_tpu import reliability as R

ckdir, countfile, mode = sys.argv[1], sys.argv[2], sys.argv[3]


class Scale(Transformer):
    def __init__(self, s):
        self.s = s

    def apply(self, d):
        return d * self.s

    def apply_batch(self, ds):
        return ArrayDataset(np.asarray(ds.data) * self.s, ds.num_examples)


class CountingEst(Estimator):
    def __init__(self, tag):
        self.tag = tag

    def fit(self, data):
        with open(countfile, "a") as f:
            f.write(self.tag + "\\n")
        return Scale(float(np.mean(np.asarray(data.data))) + 1.0)


R.enable_checkpointing(ckdir)
data = ArrayDataset(np.arange(8.0).reshape(8, 1))

# stage 1: fit estimator A (write-through to the checkpoint)
out_a = CountingEst("A").with_data(data).apply(data).get()
if mode == "kill":
    os._exit(137)  # simulated preemption AFTER A's fit, before the run ends

# stage 2 (resumed run only): A again — must restore, not refit — plus B
out_a2 = CountingEst("A").with_data(data).apply(data).get()
out_b = CountingEst("B").with_data(data).apply(data).get()
hits = R.get_recovery_log().summary()["checkpoint_hits"]
print("RESUME_OK hits=%d" % hits)
assert hits >= 1, hits
"""


def test_killed_then_resumed_run_reuses_fitted_prefixes(tmp_path):
    """ISSUE acceptance: kill a run after an estimator fit; the resumed
    run (fresh process) must reuse the checkpointed fit without refitting."""
    ck = str(tmp_path / "ck")
    countfile = str(tmp_path / "fits.txt")
    script = str(tmp_path / "resume_script.py")
    with open(script, "w") as f:
        f.write(_RESUME_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("PALLAS_AXON_POOL_IPS", None)

    run1 = subprocess.run(
        [sys.executable, script, ck, countfile, "kill"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
    )
    assert run1.returncode == 137, run1.stderr[-2000:]
    assert open(countfile).read().splitlines() == ["A"]

    run2 = subprocess.run(
        [sys.executable, script, ck, countfile, "resume"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
    )
    assert run2.returncode == 0, (run2.stdout + run2.stderr)[-2000:]
    assert "RESUME_OK" in run2.stdout
    # A fit exactly once ACROSS BOTH PROCESSES; B fit once in run 2.
    assert sorted(open(countfile).read().splitlines()) == ["A", "B"]


def test_token_memo_hashes_shared_values_once(monkeypatch):
    """Digesting N prefixes of one plan re-tokenizes the same training
    array N times; inside token_memo() the content hash is paid once and
    the digests are unchanged."""
    import numpy as np

    from keystone_tpu.reliability import checkpoint as cp

    arr = np.arange(64, dtype=np.float32)
    cold = cp._value_token(arr)

    calls = {"n": 0}
    real_sha1 = cp.hashlib.sha1

    def counting_sha1(*a, **kw):
        calls["n"] += 1
        return real_sha1(*a, **kw)

    monkeypatch.setattr(cp.hashlib, "sha1", counting_sha1)
    with cp.token_memo():
        tokens = [cp._value_token(arr) for _ in range(5)]
    assert calls["n"] == 1
    assert all(t == cold for t in tokens)
    # the memo dies with the scope: a later call re-hashes
    assert cp._value_token(arr) == cold
    assert calls["n"] == 2
