"""End-to-end recovery (ISSUE acceptance criterion): a pipeline run with an
injected OOM (first N solver calls) AND an injected transient fault
completes successfully and reports retry/degradation metadata."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.reliability import (
    FaultSpec,
    RetryPolicy,
    enable_checkpointing,
    get_recovery_log,
)
from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.pipeline import Transformer


class _Center(Transformer):
    """A stand-in featurize stage with a distinctive label to target."""

    def __init__(self, shift):
        self.shift = shift

    def apply(self, datum):
        return datum - self.shift

    def apply_batch(self, ds):
        return ArrayDataset(np.asarray(ds.data) - self.shift, ds.num_examples)


def _problem(n=64, d=16, k=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, k)).astype(np.float32)
    return x, x @ w


def test_pipeline_completes_through_oom_and_transient(injector):
    x, y = _problem()
    env = PipelineEnv.get_or_create()
    env.retry_policy = RetryPolicy(max_attempts=3, sleep=lambda s: None)

    injector(
        # OOM on the first solver attempt → the estimator's internal
        # DegradationLadder halves the block and retries.
        FaultSpec(match="BlockLeastSquaresEstimator.solve", kind="oom", first_n=1),
        # One transient fault on the featurize node → executor-level retry.
        FaultSpec(match="_Center", kind="transient", calls=(1,)),
    )

    pipe = _Center(0.5).to_pipeline().then_label_estimator(
        BlockLeastSquaresEstimator(block_size=8, reg=1e-3),
        ArrayDataset(x), ArrayDataset(y),
    )
    out = np.asarray(pipe.apply(ArrayDataset(x)).get().data)

    assert out.shape == y.shape and np.isfinite(out).all()
    summary = get_recovery_log().summary()
    # Retry metadata: the transient fault was retried at least once.
    assert summary["retries"] >= 1, summary
    # Degradation metadata: the solver gave up one block-size rung.
    assert summary["degradations"] == 1, summary
    degrade = get_recovery_log().events("degrade")[0]
    assert degrade.detail["first_rung"] == 8 and degrade.detail["rung"] == 4
    assert "RESOURCE_EXHAUSTED" in degrade.detail["reason"]


def test_recovered_run_matches_clean_run_with_checkpoint(tmp_path, injector):
    """The full story in one test: a faulted run completes AND its
    checkpointed fits are reused by a later clean run (no refit), with
    identical outputs."""
    x, y = _problem()

    def build():
        return _Center(0.5).to_pipeline().then_label_estimator(
            BlockLeastSquaresEstimator(block_size=8, reg=1e-3),
            ArrayDataset(x), ArrayDataset(y),
        )

    env = PipelineEnv.get_or_create()
    env.retry_policy = RetryPolicy(max_attempts=3, sleep=lambda s: None)
    store = enable_checkpointing(str(tmp_path / "ck"))
    injector(
        FaultSpec(match="BlockLeastSquaresEstimator.solve", kind="oom", first_n=1),
    )
    out_faulted = np.asarray(build().apply(ArrayDataset(x)).get().data)
    assert store.writes >= 1

    PipelineEnv.reset()
    store2 = enable_checkpointing(str(tmp_path / "ck"))
    out_resumed = np.asarray(build().apply(ArrayDataset(x)).get().data)
    assert store2.hits >= 1  # fit restored, not recomputed
    np.testing.assert_allclose(out_faulted, out_resumed)


def test_meta_solver_fallback_nests_inner_degradation(injector):
    """When the meta-solver falls to the block solver AND the block solver
    itself halves its block on OOM, both reductions must survive in the
    model's degradation record (outer solver switch + nested block rung)."""
    from keystone_tpu.data.dataset import ArrayDataset as ADS
    from keystone_tpu.ops.learning.least_squares import LeastSquaresEstimator

    x, y = _problem()
    injector(
        FaultSpec(match="LeastSquaresEstimator.solve", kind="oom", first_n=1),
        FaultSpec(match="BlockLeastSquaresEstimator.solve", kind="oom", first_n=1),
    )
    model = LeastSquaresEstimator(reg=1e-3, block_size=8).fit(ADS(x), ADS(y))
    record = model.degradation
    assert record["first_rung"] == "dense_lbfgs" and record["rung"] == "block"
    assert record["inner"]["first_rung"] == 8 and record["inner"]["rung"] == 4


def test_corrupt_node_output_is_caught_by_consumer(injector):
    """Corrupt-data injection: NaN-poisoned node output flows to the
    consumer, which is exactly what a validation layer must catch — the
    harness makes that failure mode constructible on demand."""
    x, _ = _problem()
    injector(FaultSpec(match="_Center", kind="corrupt", calls=(1,)))
    out = _Center(0.0).to_pipeline().apply(ArrayDataset(x)).get()
    assert np.isnan(np.asarray(out.data)).all()
