"""Fault injector mechanics: spec matching, call counting, fault kinds."""

import numpy as np
import pytest

from keystone_tpu.reliability import (
    FaultSpec,
    InjectedOOM,
    InjectedTransient,
    classify_error,
    ErrorClass,
    injected,
    probe,
)
from keystone_tpu.reliability import faultinject


def test_injected_errors_classify_correctly():
    assert classify_error(InjectedOOM("x")) is ErrorClass.OOM
    assert classify_error(InjectedTransient("x")) is ErrorClass.TRANSIENT


def test_probe_is_noop_without_injector():
    assert faultinject.current() is None
    probe("anything")  # must not raise


def test_oom_on_exact_calls(injector):
    inj = injector(FaultSpec(match="site", kind="oom", calls=(2,)))
    probe("site")  # call 1: clean
    with pytest.raises(InjectedOOM):
        probe("site")  # call 2: faulted
    probe("site")  # call 3: clean again
    assert inj.calls("site") == 3


def test_first_n_prefix_faulting(injector):
    injector(FaultSpec(match="s", kind="transient", first_n=2))
    for _ in range(2):
        with pytest.raises(InjectedTransient):
            probe("s")
    probe("s")  # third call clean


def test_match_is_substring_and_star(injector):
    injector(FaultSpec(match="Solver", kind="oom", calls=(1,)))
    probe("unrelated-site")  # no match, no fault
    with pytest.raises(InjectedOOM):
        probe("BlockSolver.fit")


def test_hang_uses_injector_sleep(injector):
    slept = []
    injector(FaultSpec(match="h", kind="hang", hang_s=9.0, calls=(1,)),
             sleep=slept.append)
    probe("h")  # hangs (recorded, not real)
    assert slept == [9.0]


def test_corrupt_nan_fills_wrapped_value(injector):
    inj = injector(FaultSpec(match="node", kind="corrupt", calls=(1,)))
    wrapped = inj.wrap("node", lambda: np.ones((2, 2), np.float32))
    out = wrapped()
    assert np.isnan(np.asarray(out)).all()
    # next call returns clean data
    assert np.asarray(wrapped()).sum() == 4.0


def test_no_nested_injectors():
    with injected(FaultSpec(match="a")):
        with pytest.raises(RuntimeError, match="already active"):
            with injected(FaultSpec(match="b")):
                pass
