"""Fault injector mechanics: spec matching, call counting, fault kinds."""

import numpy as np
import pytest

from keystone_tpu.reliability import (
    FaultSpec,
    InjectedOOM,
    InjectedTransient,
    classify_error,
    ErrorClass,
    injected,
    probe,
)
from keystone_tpu.reliability import faultinject


def test_injected_errors_classify_correctly():
    assert classify_error(InjectedOOM("x")) is ErrorClass.OOM
    assert classify_error(InjectedTransient("x")) is ErrorClass.TRANSIENT


def test_probe_is_noop_without_injector():
    assert faultinject.current() is None
    probe("anything")  # must not raise


def test_oom_on_exact_calls(injector):
    inj = injector(FaultSpec(match="site", kind="oom", calls=(2,)))
    probe("site")  # call 1: clean
    with pytest.raises(InjectedOOM):
        probe("site")  # call 2: faulted
    probe("site")  # call 3: clean again
    assert inj.calls("site") == 3


def test_first_n_prefix_faulting(injector):
    injector(FaultSpec(match="s", kind="transient", first_n=2))
    for _ in range(2):
        with pytest.raises(InjectedTransient):
            probe("s")
    probe("s")  # third call clean


def test_match_is_substring_and_star(injector):
    injector(FaultSpec(match="Solver", kind="oom", calls=(1,)))
    probe("unrelated-site")  # no match, no fault
    with pytest.raises(InjectedOOM):
        probe("BlockSolver.fit")


def test_hang_uses_injector_sleep(injector):
    slept = []
    injector(FaultSpec(match="h", kind="hang", hang_s=9.0, calls=(1,)),
             sleep=slept.append)
    probe("h")  # hangs (recorded, not real)
    assert slept == [9.0]


def test_corrupt_nan_fills_wrapped_value(injector):
    inj = injector(FaultSpec(match="node", kind="corrupt", calls=(1,)))
    wrapped = inj.wrap("node", lambda: np.ones((2, 2), np.float32))
    out = wrapped()
    assert np.isnan(np.asarray(out)).all()
    # next call returns clean data
    assert np.asarray(wrapped()).sum() == 4.0


def test_no_nested_injectors():
    with injected(FaultSpec(match="a")):
        with pytest.raises(RuntimeError, match="already active"):
            with injected(FaultSpec(match="b")):
                pass


# ------------------------------------------------- process-level chaos (PR 7)


def test_specs_round_trip_through_env():
    specs = (
        FaultSpec(match="serving.worker.request", kind="kill", calls=(7,)),
        FaultSpec(match="serving.worker.heartbeat", kind="corrupt", first_n=3),
        FaultSpec(match="apply", kind="hang", hang_s=2.5, calls=(1, 4)),
    )
    decoded = faultinject.specs_from_env(faultinject.specs_to_env(specs))
    assert [
        (s.match, s.kind, s.calls, s.first_n, s.hang_s) for s in decoded
    ] == [(s.match, s.kind, s.calls, s.first_n, s.hang_s) for s in specs]


def test_install_from_env_is_process_lifetime(monkeypatch):
    monkeypatch.setenv(
        "KEYSTONE_FAULT_SPECS",
        faultinject.specs_to_env((FaultSpec(match="site", kind="oom", calls=(1,)),)),
    )
    injector = faultinject.install_from_env()
    try:
        assert injector is not None and faultinject.current() is injector
        with pytest.raises(InjectedOOM):
            probe("site")
        # idempotent while active
        assert faultinject.install_from_env() is None
    finally:
        faultinject._current = None


def test_install_from_env_noop_when_unset(monkeypatch):
    monkeypatch.delenv("KEYSTONE_FAULT_SPECS", raising=False)
    assert faultinject.install_from_env() is None
    assert faultinject.current() is None


def test_corrupt_garbles_strings_into_non_json(injector):
    inj = injector(FaultSpec(match="hb", kind="corrupt", calls=(1,)))
    import json

    garbled = inj.wrap("hb", lambda: '{"kind": "heartbeat", "seq": 1}')()
    with pytest.raises(json.JSONDecodeError):
        json.loads(garbled)
    # call 2 passes through intact
    assert json.loads(inj.wrap("hb", lambda: '{"seq": 2}')()) == {"seq": 2}


def test_kill_spec_sigkills_the_process():
    import signal
    import subprocess
    import sys

    code = (
        "from keystone_tpu.reliability import faultinject\n"
        "faultinject.install_from_env()\n"
        "from keystone_tpu.reliability.faultinject import probe\n"
        "probe('safe')\n"
        "print('before', flush=True)\n"
        "probe('serving.worker.request')\n"
        "print('after', flush=True)\n"
    )
    import os

    env = dict(
        os.environ,
        KEYSTONE_FAULT_SPECS=faultinject.specs_to_env(
            (FaultSpec(match="serving.worker.request", kind="kill", calls=(1,)),)
        ),
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=60, env=env,
    )
    assert proc.returncode == -signal.SIGKILL
    assert "before" in proc.stdout and "after" not in proc.stdout
