"""Ingest graceful degradation: corrupt records are skipped-and-quarantined
with counts surfaced, instead of aborting the load."""

import io
import os
import tarfile

import numpy as np
import pytest

from keystone_tpu.reliability import get_recovery_log


def _make_tar(path, entries):
    with tarfile.open(path, "w") as tar:
        for name, payload in entries:
            info = tarfile.TarInfo(name=name)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))


def _jpeg_bytes(seed=0, size=24):
    from PIL import Image

    rng = np.random.default_rng(seed)
    buf = io.BytesIO()
    Image.fromarray(
        rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
    ).save(buf, format="JPEG")
    return buf.getvalue()


def test_archive_loader_quarantines_corrupt_and_unlabeled(tmp_path):
    from keystone_tpu.data.loaders.archive import load_image_archives

    tar = str(tmp_path / "data.tar")
    _make_tar(tar, [
        ("cls0/good_a.jpg", _jpeg_bytes(0)),
        ("cls0/corrupt.jpg", b"\xff\xd8 this is not a real jpeg"),
        ("cls1/good_b.jpg", _jpeg_bytes(1)),
        ("unknown/no_label.jpg", _jpeg_bytes(2)),
    ])

    def label_fn(name):
        return {"cls0": 0, "cls1": 1}[name.split("/")[0]]  # KeyError on unknown

    ds = load_image_archives(tar, label_fn, use_native=False)
    assert len(ds) == 2  # both good records survived
    assert ds.quarantine["decode_failed"] == 1
    assert ds.quarantine["label_missing"] == 1
    assert ds.quarantine["quarantined"] == 2
    assert any("corrupt" in e or "no_label" in e for e in ds.quarantine["examples"])
    assert get_recovery_log().summary()["quarantined_records"] == 2


def test_archive_loader_clean_tar_reports_zero(tmp_path):
    from keystone_tpu.data.loaders.archive import load_image_archives

    tar = str(tmp_path / "clean.tar")
    _make_tar(tar, [("c/a.jpg", _jpeg_bytes(0))])
    ds = load_image_archives(tar, lambda name: 0, use_native=False)
    assert len(ds) == 1 and ds.quarantine["quarantined"] == 0
    assert get_recovery_log().summary()["quarantined_records"] == 0


def test_csv_loader_quarantines_malformed_rows(tmp_path):
    from keystone_tpu.data.loaders.csv import load_csv

    p = str(tmp_path / "rows.csv")
    with open(p, "w") as f:
        f.write("1,2,3\n4,notanumber,6\n7,8,9\n1,2\n10,11,12\n")
    ds = load_csv(p)
    np.testing.assert_allclose(
        np.asarray(ds.data), [[1, 2, 3], [7, 8, 9], [10, 11, 12]]
    )
    assert ds.quarantine["quarantined"] == 2
    assert len(ds.quarantine["examples"]) == 2
    assert get_recovery_log().summary()["quarantined_records"] == 2


def test_csv_loader_truncated_first_row_does_not_redefine_width(tmp_path):
    # The majority width wins: a truncated FIRST row is the quarantined
    # one, not every good row after it.
    from keystone_tpu.data.loaders.csv import load_csv

    p = str(tmp_path / "truncated_head.csv")
    with open(p, "w") as f:
        f.write("1,2\n" + "".join(f"{i},{i},{i}\n" for i in range(10)))
    ds = load_csv(p)
    assert np.asarray(ds.data).shape == (10, 3)
    assert ds.quarantine["quarantined"] == 1
    assert ds.quarantine["wrong_width"] == 1


def test_csv_loader_fallback_skips_comments_like_loadtxt(tmp_path):
    # '#' lines are loadtxt-skippable, so the tolerant fallback must not
    # count them as quarantined just because another row was bad.
    from keystone_tpu.data.loaders.csv import load_csv

    p = str(tmp_path / "commented.csv")
    with open(p, "w") as f:
        f.write("# header comment\n1,2,3\nbad,row,here\n4,5,6\n")
    ds = load_csv(p)
    assert np.asarray(ds.data).shape == (2, 3)
    assert ds.quarantine["quarantined"] == 1  # only the bad row


def test_csv_loader_all_garbage_still_raises(tmp_path):
    from keystone_tpu.data.loaders.csv import load_csv

    p = str(tmp_path / "garbage.csv")
    with open(p, "w") as f:
        f.write("not,a\nnumber,anywhere\n")
    with pytest.raises(ValueError, match="no parsable"):
        load_csv(p)


def test_measure_ingest_counts_corrupt_entries(tmp_path):
    from keystone_tpu import native
    from keystone_tpu.data.ingest import build_jpeg_tar_fixture, measure_ingest

    if native.load() is None:
        pytest.skip("native lib not built")
    fix = str(tmp_path / "fix.tar")
    build_jpeg_tar_fixture(fix, 6, size=48)
    # append a corrupt member
    with tarfile.open(fix, "a") as tar:
        payload = b"not a jpeg at all"
        info = tarfile.TarInfo(name="synset0000/corrupt.JPEG")
        info.size = len(payload)
        tar.addfile(info, io.BytesIO(payload))
    out = measure_ingest(fix, resize=(48, 48), batch=4)
    assert out["images"] == 6
    assert out["corrupt_skipped"] == 1
    assert get_recovery_log().summary()["quarantined_records"] == 1
