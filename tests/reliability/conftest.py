"""Reliability-suite fixtures: fault injection + a clean recovery ledger.

The ``injector`` fixture is the harness ISSUE/docs/RELIABILITY.md promise:
activate deterministic faults (OOM / transient / hang / corrupt on the
Nth call of a matched node or probe site) for the remainder of a test,
with automatic deactivation."""

import contextlib

import pytest

from keystone_tpu.reliability import faultinject


@pytest.fixture
def injector():
    """Factory fixture: ``injector(FaultSpec(...), ...)`` activates a
    FaultInjector (returned for call-count assertions) until test end."""
    with contextlib.ExitStack() as stack:

        def activate(*specs, **kwargs):
            return stack.enter_context(faultinject.injected(*specs, **kwargs))

        yield activate


@pytest.fixture
def no_sleep_policy():
    """A RetryPolicy that never really sleeps but records what it would
    have slept — keeps backoff assertions exact and tests instant."""
    from keystone_tpu.reliability import RetryPolicy

    slept = []
    policy = RetryPolicy(max_attempts=3, seed=0, sleep=slept.append)
    return policy, slept
