"""DegradationLadder semantics + the exact rung sequences the bench
ladders walked before the extraction (they must not drift)."""

import pytest

from keystone_tpu.reliability import (
    DegradationLadder,
    LadderExhausted,
    get_recovery_log,
    halving_rungs,
)


def _oom():
    raise RuntimeError("RESOURCE_EXHAUSTED: fake OOM")


# ---------------------------------------------------------------- sequences


def test_halving_rungs_match_bench_timit_exact():
    # bench timit_exact: start n aligned to ndev, halve with alignment,
    # last attemptable rung is the first value <= full_n // 16.
    full_n, ndev = 2_200_000, 8
    rungs = halving_rungs(full_n - full_n % ndev, full_n // 16, align=ndev)
    assert rungs[0] == 2_200_000
    for v in rungs:
        assert v % ndev == 0
    assert rungs[-1] <= full_n // 16 < rungs[-2]
    # exactly the old loop: n = (n // 2) - ((n // 2) % ndev)
    expect, n = [n0 := full_n - full_n % ndev], n0
    while n > full_n // 16:
        n = (n // 2) - ((n // 2) % ndev)
        expect.append(n)
    assert rungs == expect


def test_halving_rungs_match_bench_cifar_and_wide_block():
    assert halving_rungs(50_000, 50_000 // 4) == [50_000, 25_000, 12_500]
    wide = halving_rungs(2_200_000, 8_192)
    assert wide[0] == 2_200_000 and wide[-1] <= 8_192 < wide[-2]
    assert halving_rungs(8_192, 8_192) == [8_192]  # small mode: one rung


# ----------------------------------------------------------------- behavior


def test_ladder_degrades_on_oom_and_annotates():
    ladder = DegradationLadder([64, 32, 16], label="t")
    tried = []

    def attempt(b):
        tried.append(b)
        if b > 16:
            _oom()
        return {"block": b}

    out = ladder.annotate(ladder.run(attempt))
    assert tried == [64, 32, 16]
    assert ladder.reduced
    assert out["extrapolated"] is True
    assert out["reduced_from"] == 64
    assert "RESOURCE_EXHAUSTED" in out["reduction_reason"]
    ev = get_recovery_log().events("degrade")
    assert len(ev) == 1 and ev[0].detail["rung"] == 16


def test_ladder_success_on_first_rung_adds_no_fields():
    ladder = DegradationLadder([64, 32], label="t")
    out = ladder.annotate(ladder.run(lambda b: {"block": b}))
    assert not ladder.reduced
    assert "extrapolated" not in out and "reduced_from" not in out
    assert get_recovery_log().events("degrade") == []


def test_ladder_reraises_non_oom_immediately():
    ladder = DegradationLadder([64, 32], label="t")
    tried = []

    def attempt(b):
        tried.append(b)
        raise ValueError("not an OOM")

    with pytest.raises(ValueError):
        ladder.run(attempt)
    assert tried == [64]


def test_ladder_exhaustion_keeps_last_error():
    ladder = DegradationLadder([8, 4], label="solver")
    with pytest.raises(LadderExhausted, match="RESOURCE_EXHAUSTED"):
        ladder.run(lambda b: _oom())
    assert isinstance(LadderExhausted("x"), RuntimeError)  # bench contract


def test_ladder_on_degrade_hook_and_last_error():
    seen = []
    ladder = DegradationLadder(
        [2, 1], label="t", on_degrade=lambda rung, err: seen.append((rung, err))
    )

    def attempt(b):
        if b == 2:
            _oom()
        assert "RESOURCE_EXHAUSTED" in ladder.last_error  # visible mid-run
        return b

    assert ladder.run(attempt) == 1
    assert seen == [(2, "RuntimeError: RESOURCE_EXHAUSTED: fake OOM")]


def test_ladder_rejects_empty_rungs():
    with pytest.raises(ValueError, match="empty rung"):
        DegradationLadder([], label="t")
