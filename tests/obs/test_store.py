"""Profile-store contract tests: persistence, merge-on-write concurrency,
fingerprint invalidation, eviction, and torn-write tolerance
(docs/OBSERVABILITY.md)."""

import json
import os
import subprocess
import sys

from keystone_tpu.obs.store import (
    ProfileStore,
    dataset_shape_class,
    default_store_path,
    get_store,
    rows_bucket,
    shape_class,
    store_enabled,
)

FP = {"jax": "test", "backend": "cpu", "device_kind": "virtual"}


def make_store(tmp_path, name="ps.jsonl", fp=FP, **kw):
    return ProfileStore(str(tmp_path / name), fingerprint=dict(fp), **kw)


# ------------------------------------------------------------- shape classes


def test_shape_class_buckets_rows_keeps_dims_exact():
    assert shape_class(100_000, (768,), "float32") == "n2^17|768|float32"
    assert shape_class(131_072, (768,)) == "n2^17|768"
    # same bucket across a 2x band, different beyond it
    assert shape_class(65_537) == shape_class(131_072)
    assert shape_class(65_536) != shape_class(65_537)
    assert rows_bucket("n2^17|768|float32") == "n2^17"


def test_dataset_shape_class_uses_transfer_dtype():
    import numpy as np

    from keystone_tpu.data.dataset import ArrayDataset

    ds = ArrayDataset(np.zeros((100, 16), dtype=np.float64))
    # float64 narrows to float32 at transfer width
    assert dataset_shape_class(ds) == "n2^7|16|float32"


# ----------------------------------------------------------------- round trip


def test_record_lookup_round_trip_and_newest_wins(tmp_path):
    s = make_store(tmp_path)
    s.record("k", "n2^4", wall_s=1.0)
    s.record("k", "n2^4", wall_s=2.5)
    m = s.lookup("k", "n2^4")
    assert m == {"wall_s": 2.5, "source": "observed"}
    # a FRESH instance over the same file sees the same merged view
    s2 = make_store(tmp_path)
    assert s2.lookup("k", "n2^4") == {"wall_s": 2.5, "source": "observed"}
    assert s2._entries[("k", "n2^4", "cpu")]["obs"] == 2


def test_lookup_miss_and_backend_isolation(tmp_path):
    s = make_store(tmp_path)
    s.record("k", "n2^4", backend="tpu", wall_s=1.0)
    assert s.lookup("k", "n2^4") is None  # default backend is cpu
    assert s.lookup("k", "n2^4", backend="tpu") == {"wall_s": 1.0, "source": "observed"}
    assert s.misses == 1 and s.hits == 1


def test_fingerprint_invalidation_on_environment_change(tmp_path):
    s = make_store(tmp_path)
    s.record("k", "n2^4", wall_s=1.0)
    # same backend key, different device kind: a v5e profile must not
    # drive decisions on a v6
    changed = ProfileStore(
        str(tmp_path / "ps.jsonl"),
        fingerprint={**FP, "device_kind": "other-chip"},
    )
    assert changed.lookup("k", "n2^4") is None
    assert changed.invalidations == 1
    # the original environment still reads it
    assert make_store(tmp_path).lookup("k", "n2^4") == {"wall_s": 1.0, "source": "observed"}


def test_torn_lines_are_skipped_not_fatal(tmp_path):
    s = make_store(tmp_path)
    s.record("good", "n2^4", wall_s=1.0)
    with open(s.path, "a") as f:
        f.write('{"k": "torn", "s": "n2^4"')  # no newline, no close brace
    s2 = make_store(tmp_path)
    assert s2.lookup("good", "n2^4") == {"wall_s": 1.0, "source": "observed"}
    assert s2.lookup("torn", "n2^4") is None


def test_eviction_keeps_newest_within_bound(tmp_path):
    s = make_store(tmp_path, max_entries=4)
    for i in range(12):
        s.record(f"k{i}", "n2^4", v=i)
    s.compact()
    assert len(s) == 4
    kept = {k for k, _, _ in s.entries()}
    assert kept == {"k8", "k9", "k10", "k11"}
    # file is bounded too
    assert sum(1 for _ in open(s.path)) == 4


def test_entries_query_by_prefix_and_rows(tmp_path):
    s = make_store(tmp_path)
    s.record("stream:abc:cr64", "n2^10|8|float32", chunk_rows=64)
    s.record("stream:abc:cr128", "n2^10|8|float32", chunk_rows=128)
    s.record("solver:block_ls:bs4:precrefine", "n2^10|16|float32", wall_s=0.5)
    assert len(list(s.entries(key_prefix="stream:abc:"))) == 2
    assert len(list(s.entries(rows="n2^10"))) == 3
    assert len(list(s.entries(key_prefix="solver:", rows="n2^10"))) == 1


# ---------------------------------------------------------------- concurrency

_WRITER = r"""
import json, sys
sys.path.insert(0, {repo!r})
from keystone_tpu.obs.store import ProfileStore
fp = {fp!r}
s = ProfileStore({path!r}, fingerprint=fp)
who = sys.argv[1]
for i in range(40):
    s.record(f"shared", "n2^4", writer=who, i=i)
    s.record(f"{{who}}:{{i}}", "n2^4", v=i)
print("WROTE", who)
"""


def test_concurrent_writers_merge_without_loss(tmp_path):
    """Two PROCESSES profiling the same digest concurrently: every
    distinct key survives, the shared key holds exactly one (whole,
    parseable) winning observation — no torn or lost lines."""
    path = str(tmp_path / "ps.jsonl")
    script = _WRITER.format(
        repo=os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        fp=FP, path=path,
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, who],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for who in ("a", "b")
    ]
    for p in procs:
        out, err = p.communicate(timeout=60)
        assert p.returncode == 0, err
        assert "WROTE" in out
    # every line in the file is whole JSON
    with open(path) as f:
        for line in f:
            json.loads(line)
    s = ProfileStore(path, fingerprint=dict(FP))
    keys = {k for k, _, _ in s.entries()}
    assert {f"a:{i}" for i in range(40)} <= keys
    assert {f"b:{i}" for i in range(40)} <= keys
    shared = s.lookup("shared", "n2^4")
    assert shared is not None and shared["writer"] in ("a", "b")


def test_concurrent_writer_and_compaction(tmp_path):
    """Compaction in one process must merge (not clobber) lines another
    process appended meanwhile — the merge-on-write contract."""
    path = str(tmp_path / "ps.jsonl")
    a = ProfileStore(path, fingerprint=dict(FP))
    a.record("a-entry", "n2^4", v=1)
    # second process appends AFTER a's snapshot was loaded
    script = _WRITER.format(
        repo=os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        fp=FP, path=path,
    )
    subprocess.run(
        [sys.executable, "-c", script, "c"], check=True, capture_output=True,
        timeout=60,
    )
    a.compact()  # re-reads under the lock: c's appends must survive
    keys = {k for k, _, _ in ProfileStore(path, fingerprint=dict(FP)).entries()}
    assert "a-entry" in keys
    assert {f"c:{i}" for i in range(40)} <= keys


# ------------------------------------------------------------------ singleton


def test_get_store_honors_off_switch(monkeypatch):
    monkeypatch.setenv("KEYSTONE_PROFILE_STORE", "off")
    assert not store_enabled()
    assert get_store() is None


def test_get_store_reresolves_on_env_change(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_PROFILE_STORE", str(tmp_path / "a.jsonl"))
    s1 = get_store()
    assert s1 is not None and s1.path.endswith("a.jsonl")
    monkeypatch.setenv("KEYSTONE_PROFILE_STORE", str(tmp_path / "b.jsonl"))
    s2 = get_store()
    assert s2 is not None and s2.path.endswith("b.jsonl")


def test_default_path_rides_next_to_compilation_cache(monkeypatch):
    monkeypatch.delenv("KEYSTONE_PROFILE_STORE", raising=False)
    monkeypatch.setenv("KEYSTONE_COMPILATION_CACHE", "/some/root/xla-cache")
    assert default_store_path() == "/some/root/profile-store.jsonl"


def test_broken_store_never_raises(tmp_path):
    s = make_store(tmp_path)
    s.path = str(tmp_path / "no-such-dir" / "ps.jsonl")
    s.record("k", "n2^4", v=1)  # must not raise


def test_compaction_fires_at_slack_not_max_entries(tmp_path):
    """Re-recording the same keys must compact the file at the documented
    ~256-line slack, not at max_entries appends: with the default 4096
    cap a duplicate-heavy workload would otherwise grow the file to ~16x
    its merged size before the first rewrite."""
    from keystone_tpu.obs.store import _COMPACT_SLACK

    st = make_store(tmp_path)  # default max_entries (4096)
    for i in range(_COMPACT_SLACK + 40):
        st.record("solver:block_ls:bs512", "n2^12|8|float32", wall_s=0.1 + i)
    with open(st.path) as f:
        lines = sum(1 for _ in f)
    # one merged entry + at most the post-compaction append slack
    assert lines <= 41
    _, _, m = next(iter(st.entries(key_prefix="solver:")))
    assert m["wall_s"] == 0.1 + _COMPACT_SLACK + 39  # newest survived


# ------------------------------------------------------------ stale marking


def test_mark_stale_round_trip(tmp_path):
    """The drift sentinel's provenance contract (obs/cost.py): a stale:
    mark turns lookups into misses (consumers re-measure), survives for
    inspection via include_stale, and is cleared by the next fresh
    measurement."""
    st = make_store(tmp_path)
    st.record("stream:abc:cr512", "n2^12|8|float32",
              chunk_rows=512, rows_per_s=1e5)
    assert st.lookup("stream:abc:cr512", "n2^12|8|float32") is not None

    assert st.mark_stale("stream:abc:cr512", "n2^12|8|float32") is True
    # marking twice is a no-op (one drift = one mark)
    assert st.mark_stale("stream:abc:cr512", "n2^12|8|float32") is False
    # absent entries can't be marked
    assert st.mark_stale("stream:gone", "n2^12|8|float32") is False

    misses_before = st.stats()["misses"]
    assert st.lookup("stream:abc:cr512", "n2^12|8|float32") is None
    assert st.stats()["misses"] == misses_before + 1

    m = st.lookup("stream:abc:cr512", "n2^12|8|float32", include_stale=True)
    from keystone_tpu.obs.store import is_stale

    assert is_stale(m)
    assert m["source"] == "stale:observed"
    assert m["stale_reason"] == "cost_drift"
    # original measurements survive for post-hoc inspection
    assert m["rows_per_s"] == 1e5

    # entries() skips stale by default (the knob rule's query surface)
    assert list(st.entries(key_prefix="stream:")) == []
    assert len(list(st.entries(key_prefix="stream:", include_stale=True))) == 1
    # by_source surfaces the mark for check --store
    assert st.by_source().get("stale:observed") == 1

    # a fresh measurement overwrites the mark entirely
    st.record("stream:abc:cr512", "n2^12|8|float32",
              chunk_rows=512, rows_per_s=2e5)
    fresh = st.lookup("stream:abc:cr512", "n2^12|8|float32")
    assert fresh is not None and fresh["rows_per_s"] == 2e5
    assert not is_stale(fresh)


def test_stale_mark_persists_across_processes(tmp_path):
    """A drift mark written by one process must gate a FRESH process's
    lookups — the mark is provenance in the file, not process state."""
    st = make_store(tmp_path)
    st.record("autocache:abc", "n2^12", t0=0.1, t1=1e-5)
    assert st.mark_stale("autocache:abc", "n2^12") is True

    code = """
import json, sys
from keystone_tpu.obs.store import ProfileStore
fp = {"jax": "test", "backend": "cpu", "device_kind": "virtual"}
st = ProfileStore(sys.argv[1], fingerprint=fp)
print(json.dumps({
    "lookup": st.lookup("autocache:abc", "n2^12"),
    "raw": st.lookup("autocache:abc", "n2^12", include_stale=True),
}))
"""
    out = subprocess.run(
        [sys.executable, "-c", code, st.path],
        capture_output=True, text=True, check=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    assert payload["lookup"] is None
    assert payload["raw"]["source"] == "stale:observed"
