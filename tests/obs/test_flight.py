"""Flight recorder (obs/flight.py): ring bounds, the recovery-ledger
hook, trigger-driven dumps, rate limiting, and the artifact schema."""

import json
import os

import pytest

from keystone_tpu.obs import flight, spans
from keystone_tpu.obs.flight import (
    FlightRecorder,
    get_flight_recorder,
    install_flight_recorder,
    reset_flight_recorder,
)
from keystone_tpu.reliability.recovery import get_recovery_log


@pytest.fixture(autouse=True)
def _fresh_recorder():
    reset_flight_recorder()
    yield
    reset_flight_recorder()


def test_ledger_hook_rings_and_bounds(tmp_path):
    recorder = install_flight_recorder(
        "test", capacity=8, out_dir=str(tmp_path)
    )
    assert get_flight_recorder() is recorder
    # install is idempotent: the first role wins
    assert install_flight_recorder("other") is recorder
    # every recovery-ledger record lands in the ring via the hook...
    for i in range(20):
        get_recovery_log().record("retry", f"op-{i}", attempt=i)
    with recorder._lock:
        ring = list(recorder._ledger)
    # ...bounded drop-oldest
    assert len(ring) == 8
    assert ring[-1]["label"] == "op-19"
    assert ring[0]["label"] == "op-12"
    assert all(e["kind"] == "retry" for e in ring)
    # a benign kind does not dump
    assert not list(tmp_path.glob("flightrec-*.json"))


def test_fault_probe_trigger_dumps_artifact(tmp_path):
    """An armed fault probe firing IS a trigger: the `fault` ledger event
    (recorded BEFORE a kill spec SIGKILLs) dumps the post-mortem — this
    is how a killed worker leaves evidence behind."""
    from keystone_tpu.reliability import faultinject

    install_flight_recorder("w0", out_dir=str(tmp_path))
    with spans.tracing_session("t") as session:
        with spans.span("serving-ish"):
            pass
        with faultinject.injected(
            faultinject.FaultSpec(
                match="serving.apply", kind="transient", calls=(1,)
            )
        ):
            with pytest.raises(ConnectionError):
                faultinject.probe("serving.apply")
    dumps = sorted(tmp_path.glob("flightrec-w0-*.json"))
    assert len(dumps) == 1
    artifact = json.loads(dumps[0].read_text())
    assert artifact["flightrec"] == 1
    assert artifact["role"] == "w0"
    assert artifact["pid"] == os.getpid()
    assert artifact["trigger"] == "fault_probe"
    assert any(e["kind"] == "fault" for e in artifact["ledger"])
    # the active session's span tail rides along, fragment-shaped
    names = {f["n"] for f in artifact["spans"]}
    assert "serving-ish" in names
    assert all({"n", "t", "s", "a", "b"} <= set(f) for f in artifact["spans"])
    # and the registry snapshot is attached
    assert isinstance(artifact["metrics"], dict)


def test_refit_rollback_and_slo_degrade_trigger(tmp_path):
    recorder = install_flight_recorder(
        "refit", out_dir=str(tmp_path), min_dump_interval_s=0.0
    )
    get_recovery_log().record("refit_rollback", "m", reason="live score")
    get_recovery_log().record(
        "slo", "serving-slo", direction="degrade", p99_ms=50.0
    )
    # recover direction is NOT a trigger
    get_recovery_log().record(
        "slo", "serving-slo", direction="recover", p99_ms=1.0
    )
    triggers = [d["trigger"] for d in recorder.dumps]
    assert triggers == ["refit_rollback", "slo_degrade"]


def test_dump_rate_limit_and_force(tmp_path):
    recorder = FlightRecorder(
        "r", out_dir=str(tmp_path), min_dump_interval_s=60.0
    )
    assert recorder.dump("fault_probe") is not None
    assert recorder.dump("fault_probe") is None  # inside the interval
    assert recorder.dump("worker_crash") is not None  # per-trigger limits
    assert recorder.dump("fault_probe", force=True) is not None
    assert [d["trigger"] for d in recorder.dumps] == [
        "fault_probe", "worker_crash", "fault_probe",
    ]


def test_marks_and_metric_snapshots_are_bounded_and_rate_limited(tmp_path):
    recorder = FlightRecorder(
        "r", out_dir=str(tmp_path), metrics_interval_s=60.0
    )
    for i in range(100):
        recorder.mark("beat", seq=i)
    assert recorder.observe_metrics() is True
    assert recorder.observe_metrics() is False  # rate-limited
    path = recorder.dump("fault_probe", force=True)
    artifact = json.loads(open(path).read())
    assert len(artifact["marks"]) == 64  # mark ring bound
    assert artifact["marks"][-1]["seq"] == 99
    assert len(artifact["metric_snapshots"]) == 1


def test_hook_is_noop_without_recorder():
    # No recorder installed: the module hook is a single global read and
    # the ledger write always succeeds.
    flight.observe_ledger("fault", "x", {"a": 1})
    get_recovery_log().record("fault", "y")


def test_dump_carries_perf_ledger_tail(tmp_path):
    """A crash snapshot carries the cost observatory's perf picture:
    the last perf-ledger entries ride every flightrec dump
    (docs/OBSERVABILITY.md "Cost observatory")."""
    from keystone_tpu.obs import cost

    cost.reset_cost_observatory()
    try:
        ledger = cost.get_ledger()
        for i in range(40):
            ledger.record(
                cost.PerfLedgerEntry(
                    node=f"node-{i}", seconds=0.01 * i, synced=True,
                    t_s=0.0, t_unix=0.0, flops=float(i),
                    roofline="compute-bound",
                    predicted_model="autocache", predicted_s=0.01,
                )
            )
        recorder = install_flight_recorder("w1", out_dir=str(tmp_path))
        path = recorder.dump("fault_probe", force=True)
        artifact = json.loads(open(path).read())
        perf = artifact["perf_ledger"]
        # bounded tail (32), newest last, full entry schema
        assert len(perf) == 32
        assert perf[-1]["node"] == "node-39"
        assert perf[0]["node"] == "node-8"
        assert perf[-1]["roofline"] == "compute-bound"
        assert perf[-1]["predicted_model"] == "autocache"
        assert perf[-1]["flops"] == 39.0
    finally:
        cost.reset_cost_observatory()
