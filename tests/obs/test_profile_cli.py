"""``keystone-tpu profile``: flag surface (fast) and the full instrumented
run (slow — covered in CI by scripts/profile_smoke.sh as well)."""

import argparse
import json
import os

import pytest

from keystone_tpu.obs.profile import add_profile_arguments


def test_profile_flags_parse_jax_free():
    parser = argparse.ArgumentParser()
    add_profile_arguments(parser)
    args = parser.parse_args(
        ["--rows", "64", "--num-ffts", "1", "--out", "/tmp/x", "--no-serve"]
    )
    assert args.rows == 64 and args.num_ffts == 1 and args.no_serve


def test_profile_subcommand_listed_in_cli():
    from keystone_tpu.cli import main

    import io
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert main(["--list"]) == 0
    assert "profile" in buf.getvalue()


@pytest.mark.slow
def test_profile_cli_end_to_end(tmp_path):
    """Acceptance: a Perfetto-loadable Chrome trace with nested
    pipeline → node → solver-iteration spans plus a Prometheus snapshot
    spanning executor, autocache, reliability, and serving metrics."""
    from keystone_tpu.cli import main

    rc = main([
        "profile", "--rows", "64", "--num-ffts", "1", "--block-size", "32",
        "--serve-requests", "4", "--out", str(tmp_path),
    ])
    assert rc == 0

    trace = json.loads((tmp_path / "profile_trace.json").read_text())
    events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert events, "empty chrome trace"
    by_id = {e["args"]["span_id"]: e for e in events}

    def chain(event):
        out = [event["name"]]
        while event["args"].get("parent_id") in by_id:
            event = by_id[event["args"]["parent_id"]]
            out.append(event["name"])
        return list(reversed(out))

    iteration_chains = [
        chain(e) for e in events if e["name"] == "solver:iteration"
    ]
    assert any(
        "profile" in c and any(n.startswith("node:") for n in c)
        for c in iteration_chains
    ), f"no pipeline → node → solver-iteration chain: {iteration_chains}"
    assert any(e["name"].startswith("serve:request") for e in events)

    prom = (tmp_path / "profile_metrics.prom").read_text()
    assert prom.strip()
    for family in (
        "keystone_executor_nodes_executed_total",
        "keystone_autocache_cached_nodes_total",
        "keystone_reliability_events_total",
        "keystone_serving_requests_total",
    ):
        assert family in prom, f"missing {family} in prometheus export"
    assert 'keystone_serving_requests_total' in prom
