"""Span semantics: nesting, ids, cross-thread handoff, no-op fast path."""

import threading

from keystone_tpu.obs import spans


def test_nesting_parents_and_trace_ids():
    with spans.tracing_session("t") as session:
        with spans.span("outer", kind="test") as outer:
            with spans.span("inner") as inner:
                assert inner.parent_id == outer.span_id
                assert inner.trace_id == outer.trace_id == session.trace_id
            with spans.span("sibling") as sib:
                assert sib.parent_id == outer.span_id
    finished = session.spans()
    assert [s.name for s in finished] == ["inner", "sibling", "outer"]
    assert finished[-1].parent_id is None
    assert all(s.end_s >= s.start_s for s in finished)


def test_no_session_is_noop():
    assert spans.active_session() is None
    with spans.span("anything") as sp:
        assert sp is spans.NOOP_SPAN
        sp.set_attribute("k", "v")  # must not raise
        sp.add_event("e")
    assert spans.current_context() is None
    spans.add_span_event("nothing")  # must not raise


def test_attributes_and_events():
    with spans.tracing_session() as session:
        with spans.span("op", x=1) as sp:
            sp.set_attribute("y", 2)
            spans.add_span_event("milestone", stage="mid")
    (finished,) = session.spans()
    assert finished.attributes == {"x": 1, "y": 2}
    assert finished.events[0].name == "milestone"
    assert finished.events[0].attributes == {"stage": "mid"}


def test_error_status_and_exception_event():
    with spans.tracing_session() as session:
        try:
            with spans.span("boom"):
                raise ValueError("bad")
        except ValueError:
            pass
    (finished,) = session.spans()
    assert finished.status == "error"
    assert finished.events[0].attributes["type"] == "ValueError"


def test_cross_thread_attach_parents_under_submitter():
    captured = {}
    with spans.tracing_session() as session:
        with spans.span("request") as req:
            ctx = spans.current_context()
            assert ctx == (req.trace_id, req.span_id)

        def worker():
            with spans.attach(ctx):
                with spans.span("batch") as sp:
                    captured["parent"] = sp.parent_id
                    captured["trace"] = sp.trace_id

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert captured["parent"] == req.span_id
    assert captured["trace"] == req.trace_id


def test_record_span_synthesizes_finished_span():
    with spans.tracing_session() as session:
        with spans.span("submit") as sub:
            ctx = sub.context()
        rec = spans.record_span("later", 1.0, 2.5, parent=ctx, k="v")
        assert rec.parent_id == sub.span_id
        assert abs(rec.duration_s - 1.5) < 1e-9
    assert "later" in [s.name for s in session.spans()]
    # without a session it degrades to None, never an error
    assert spans.record_span("nope", 0.0, 1.0) is None


def test_session_cap_drops_and_counts():
    with spans.tracing_session(max_spans=2) as session:
        for i in range(4):
            with spans.span(f"s{i}"):
                pass
    assert len(session) == 2
    assert session.dropped == 2


def test_nested_sessions_reuse_outer():
    with spans.tracing_session("outer") as a:
        with spans.tracing_session("inner") as b:
            assert a is b
            with spans.span("x"):
                pass
        assert spans.active_session() is a  # inner exit keeps outer installed
    assert spans.active_session() is None
    assert [s.name for s in a.spans()] == ["x"]


def test_wire_context_round_trip_and_garbage_tolerance():
    """The fleet wire format: a context survives to_wire/from_wire, and a
    malformed wire field degrades to None (drops the link) instead of
    failing the request that carried it."""
    assert spans.to_wire(None) is None
    ctx = ("abcd1234abcd1234", "ffff0000ffff0000")
    assert spans.from_wire(spans.to_wire(ctx)) == ctx
    # session-root context (empty span id) survives too
    root = ("abcd1234abcd1234", "")
    assert spans.from_wire(spans.to_wire(root)) == root
    for garbage in (None, 17, "", "no-separator", ":missing-trace", {"t": 1}):
        assert spans.from_wire(garbage) is None


def test_current_context_falls_back_to_attached():
    """A thread with no open span but an attached remote context hands
    off the ATTACHED context — the second hop of a cross-process chain
    (worker pipe thread → server worker thread) must keep the
    originating trace id, not restart at the local session root."""
    with spans.tracing_session("t") as session:
        remote = ("feedface00000000", "0123456789abcdef")
        with spans.attach(remote):
            assert spans.current_context() == remote
            # an OPEN span still wins over the attached context
            with spans.span("inner") as inner:
                assert spans.current_context() == (remote[0], inner.span_id)
        # detached again: back to the session root handoff
        assert spans.current_context() == (session.trace_id, "")


def test_install_session_is_process_lifetime_and_idempotent():
    session = spans.install_session("proc", sync_timings=False)
    try:
        assert spans.active_session() is session
        assert spans.install_session("other") is session  # idempotent
        with spans.span("s"):
            pass
        assert [s.name for s in session.spans()] == ["s"]
        # nested context-manager sessions reuse it rather than replacing
        with spans.tracing_session("nested") as inner:
            assert inner is session
        assert spans.active_session() is session
    finally:
        # install_session has no uninstall by design (process scope);
        # tests clear the module global directly.
        spans._session = None


def test_ring_session_evicts_oldest_and_counts():
    """Process-lifetime (ring) sessions keep the most RECENT spans: a
    worker hours into its life must ship fresh spans and dump the crash
    window, not freeze on its first max_spans and go dark."""
    session = spans.TraceSession("w", max_spans=4, ring=True)
    spans._session = session
    try:
        for i in range(10):
            with spans.span(f"s{i}"):
                pass
    finally:
        spans._session = None
    assert [s.name for s in session.spans()] == ["s6", "s7", "s8", "s9"]
    assert session.added == 10 and session.evicted == 6
    assert session.dropped == 0  # ring evicts, never drops new spans
    buffer, total = session.tail()
    assert total - len(buffer) == 6  # absolute index of buffer[0]


def test_unentered_span_context_leaves_no_phantom_on_stack():
    """span() has no side effects until __enter__: constructing a
    context manager and never entering it must not corrupt later spans'
    parentage on this thread."""
    with spans.tracing_session("t") as session:
        spans.span("never-entered", a=1)  # constructed, not entered
        with spans.span("real") as real:
            assert real.parent_id is None  # roots at the session
    assert [s.name for s in session.spans()] == ["real"]
