"""Span semantics: nesting, ids, cross-thread handoff, no-op fast path."""

import threading

from keystone_tpu.obs import spans


def test_nesting_parents_and_trace_ids():
    with spans.tracing_session("t") as session:
        with spans.span("outer", kind="test") as outer:
            with spans.span("inner") as inner:
                assert inner.parent_id == outer.span_id
                assert inner.trace_id == outer.trace_id == session.trace_id
            with spans.span("sibling") as sib:
                assert sib.parent_id == outer.span_id
    finished = session.spans()
    assert [s.name for s in finished] == ["inner", "sibling", "outer"]
    assert finished[-1].parent_id is None
    assert all(s.end_s >= s.start_s for s in finished)


def test_no_session_is_noop():
    assert spans.active_session() is None
    with spans.span("anything") as sp:
        assert sp is spans.NOOP_SPAN
        sp.set_attribute("k", "v")  # must not raise
        sp.add_event("e")
    assert spans.current_context() is None
    spans.add_span_event("nothing")  # must not raise


def test_attributes_and_events():
    with spans.tracing_session() as session:
        with spans.span("op", x=1) as sp:
            sp.set_attribute("y", 2)
            spans.add_span_event("milestone", stage="mid")
    (finished,) = session.spans()
    assert finished.attributes == {"x": 1, "y": 2}
    assert finished.events[0].name == "milestone"
    assert finished.events[0].attributes == {"stage": "mid"}


def test_error_status_and_exception_event():
    with spans.tracing_session() as session:
        try:
            with spans.span("boom"):
                raise ValueError("bad")
        except ValueError:
            pass
    (finished,) = session.spans()
    assert finished.status == "error"
    assert finished.events[0].attributes["type"] == "ValueError"


def test_cross_thread_attach_parents_under_submitter():
    captured = {}
    with spans.tracing_session() as session:
        with spans.span("request") as req:
            ctx = spans.current_context()
            assert ctx == (req.trace_id, req.span_id)

        def worker():
            with spans.attach(ctx):
                with spans.span("batch") as sp:
                    captured["parent"] = sp.parent_id
                    captured["trace"] = sp.trace_id

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert captured["parent"] == req.span_id
    assert captured["trace"] == req.trace_id


def test_record_span_synthesizes_finished_span():
    with spans.tracing_session() as session:
        with spans.span("submit") as sub:
            ctx = sub.context()
        rec = spans.record_span("later", 1.0, 2.5, parent=ctx, k="v")
        assert rec.parent_id == sub.span_id
        assert abs(rec.duration_s - 1.5) < 1e-9
    assert "later" in [s.name for s in session.spans()]
    # without a session it degrades to None, never an error
    assert spans.record_span("nope", 0.0, 1.0) is None


def test_session_cap_drops_and_counts():
    with spans.tracing_session(max_spans=2) as session:
        for i in range(4):
            with spans.span(f"s{i}"):
                pass
    assert len(session) == 2
    assert session.dropped == 2


def test_nested_sessions_reuse_outer():
    with spans.tracing_session("outer") as a:
        with spans.tracing_session("inner") as b:
            assert a is b
            with spans.span("x"):
                pass
        assert spans.active_session() is a  # inner exit keeps outer installed
    assert spans.active_session() is None
    assert [s.name for s in a.spans()] == ["x"]
