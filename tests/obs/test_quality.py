"""The quality plane (obs/quality.py): accumulator exactness, sketch
mergeability across heartbeat shards, anytime-valid gate behaviour, the
edge-triggered drift detector, plane state persistence, and the
``keystone-tpu quality`` CLI scenario (all jax-free — the plane is
stdlib-only by contract)."""

import math
import random

import pytest

from keystone_tpu.obs import names
from keystone_tpu.obs.metrics import get_registry
from keystone_tpu.obs.quality import (
    DriftDetector,
    Moments,
    P2Quantile,
    PayloadSketch,
    QualityPlane,
    QuantileSketch,
    ScoreStream,
    SequentialGate,
    get_quality_plane,
    reset_quality_plane,
)


@pytest.fixture(autouse=True)
def _fresh_plane():
    reset_quality_plane()
    yield
    reset_quality_plane()


def _gauss(n, mean=0.0, std=1.0, seed=0):
    rng = random.Random(seed)
    return [rng.gauss(mean, std) for _ in range(n)]


# ------------------------------------------------------------ accumulators


def test_moments_merge_is_exact_for_any_split():
    """Chan's parallel update: merging per-shard moments equals one
    single-process pass, to float rounding — the EXACT half of the
    sketch-mergeability contract."""
    values = _gauss(997, mean=3.0, std=2.0, seed=1)
    single = Moments()
    for v in values:
        single.observe(v)
    for split in (1, 7, 100, 996):
        shards = []
        for start in range(0, len(values), split):
            m = Moments()
            for v in values[start:start + split]:
                m.observe(v)
            shards.append(m)
        merged = Moments()
        for m in shards:
            merged.merge(m)
        assert merged.count == single.count == len(values)
        assert math.isclose(merged.mean, single.mean, rel_tol=1e-9)
        assert math.isclose(merged.m2, single.m2, rel_tol=1e-9)
        assert merged.min == single.min and merged.max == single.max


def test_moments_wire_roundtrip():
    m = Moments()
    for v in (1.0, 2.0, 4.0):
        m.observe(v)
    back = Moments.from_wire(m.to_wire())
    assert back.count == 3
    assert math.isclose(back.mean, m.mean)
    assert math.isclose(back.variance, m.variance)
    empty = Moments.from_wire(Moments().to_wire())
    assert empty.count == 0 and empty.min == math.inf


def test_p2_quantile_tracks_gaussian_median():
    est = P2Quantile(0.5)
    for v in _gauss(4000, mean=10.0, std=2.0, seed=2):
        est.observe(v)
    assert abs(est.value() - 10.0) < 0.25
    # small-sample path (buffered, exact) and wire round trip
    small = P2Quantile(0.5)
    for v in (3.0, 1.0, 2.0):
        small.observe(v)
    assert small.value() == 2.0
    assert P2Quantile.from_wire(small.to_wire()).value() == 2.0
    assert abs(P2Quantile.from_wire(est.to_wire()).value() - est.value()) < 1e-12


def test_quantile_sketch_merge_bounded_error():
    """Ben-Haim/Tom-Tov: heartbeat-sharded inserts then merge must agree
    with single-process inserts to within a few percent of the spread —
    the BOUNDED half of the mergeability contract."""
    values = _gauss(3000, mean=0.0, std=1.0, seed=3)
    single = QuantileSketch(64)
    for v in values:
        single.add(v)
    merged = QuantileSketch(64)
    for start in range(0, len(values), 250):  # 12 heartbeat deltas
        shard = QuantileSketch(64)
        for v in values[start:start + 250]:
            shard.add(v)
        merged.merge(shard)
    srt = sorted(values)
    for q in (0.1, 0.5, 0.9):
        exact = srt[int(q * (len(srt) - 1))]
        assert abs(single.quantile(q) - exact) < 0.15, q
        assert abs(merged.quantile(q) - exact) < 0.15, q
        assert abs(merged.quantile(q) - single.quantile(q)) < 0.2, q


def test_payload_sketch_heartbeat_merge_matches_single_process():
    """The fleet contract end to end: N worker deltas shipped over the
    wire and merged in the supervisor == one process observing all rows.
    Moments exact, quantiles bounded."""
    rng = random.Random(4)
    rows = [[rng.gauss(0, 1), rng.gauss(5, 2)] for _ in range(1200)]
    scores = [rng.gauss(0.8, 0.1) for _ in range(1200)]

    single = PayloadSketch(max_features=4, bins=64)
    for row, score in zip(rows, scores):
        single.observe_row(row)
        single.observe_score(score)

    fleet = PayloadSketch(max_features=4, bins=64)
    for start in range(0, len(rows), 100):  # 12 worker heartbeats
        delta = PayloadSketch(max_features=4, bins=64)
        for row, score in zip(rows[start:start + 100],
                              scores[start:start + 100]):
            delta.observe_row(row)
            delta.observe_score(score)
        # over the wire, like a heartbeat payload
        fleet.merge(PayloadSketch.from_wire(delta.to_wire()))

    assert fleet.rows == single.rows == 1200
    for key in ("f0", "f1", "score"):
        a = fleet.channels[key].moments
        b = single.channels[key].moments
        assert a.count == b.count
        assert math.isclose(a.mean, b.mean, rel_tol=1e-9, abs_tol=1e-9)
        assert math.isclose(a.m2, b.m2, rel_tol=1e-6)
        spread = b.max - b.min
        for q in (0.5, 0.9):
            qa = fleet.channels[key].quantiles.quantile(q)
            qb = single.channels[key].quantiles.quantile(q)
            assert abs(qa - qb) < 0.05 * spread, (key, q)
    assert fleet.wire_bytes() > 0
    summary = fleet.summary()
    assert summary["rows"] == 1200 and "score" in summary["channels"]


def test_score_stream_state_roundtrip_resumes_quantiles():
    stream = ScoreStream()
    stream.observe_many(_gauss(500, mean=1.0, std=0.1, seed=5))
    resumed = ScoreStream.from_state(stream.to_state())
    rest = _gauss(500, mean=1.0, std=0.1, seed=6)
    stream.observe_many(rest)
    resumed.observe_many(rest)
    assert resumed.count == stream.count == 1000
    assert math.isclose(resumed.mean, stream.mean, rel_tol=1e-12)
    for q in ScoreStream.QUANTILES:
        assert math.isclose(resumed.quantile(q), stream.quantile(q))
    summary = stream.summary()
    assert summary["count"] == 1000 and abs(summary["p50"] - 1.0) < 0.02


# -------------------------------------------------------- sequential gate


def test_gate_same_distribution_stays_open_within_budget():
    rng = random.Random(7)
    gate = SequentialGate("m", alpha=0.05, max_samples=10_000)
    for _ in range(400):
        verdict = gate.observe(
            candidate=rng.gauss(1.0, 0.1), baseline=rng.gauss(1.0, 0.1)
        )
        assert verdict == "continue"
    assert gate.decision is None


def test_gate_detects_regression_and_is_sticky():
    rng = random.Random(8)
    gate = SequentialGate("m", alpha=0.05)
    verdict = "continue"
    n = 0
    while verdict == "continue":
        n += 1
        verdict = gate.observe(
            candidate=rng.gauss(0.7, 0.1), baseline=rng.gauss(1.0, 0.1)
        )
    assert verdict == "rollback"
    assert n < 200, "a 3-sigma shift should decide fast"
    # sticky: further (clean) evidence cannot reopen a closed gate
    for _ in range(50):
        assert gate.observe(candidate=2.0, baseline=0.0) == "rollback"
    evidence = gate.evidence()
    assert evidence["decision"] == "rollback"
    assert evidence["lr"] >= 1.0 / 0.05
    assert evidence["candidate"]["n"] >= 2


def test_gate_budget_exhaustion_promotes_without_evidence_of_harm():
    rng = random.Random(9)
    gate = SequentialGate("m", alpha=0.05, min_samples=8, max_samples=40)
    verdict = "continue"
    while verdict == "continue":
        verdict = gate.observe(
            candidate=rng.gauss(1.0, 0.1), baseline=rng.gauss(1.0, 0.1)
        )
    assert verdict == "promote"
    assert gate.budget_exhausted
    assert gate.samples <= 42


def test_gate_false_positive_rate_under_alpha_on_seeded_runs():
    """20 clean A/A comparisons at alpha=0.05 on pinned seeds: zero
    spurious decisions inside a realistic budget (the smoke's
    clean-traffic criterion in miniature)."""
    for seed in range(20):
        rng = random.Random(1000 + seed)
        gate = SequentialGate("m", alpha=0.05, max_samples=10_000)
        for _ in range(256):
            gate.observe(
                candidate=rng.gauss(1.0, 0.1), baseline=rng.gauss(1.0, 0.1)
            )
        assert gate.decision is None, seed


# --------------------------------------------------------- drift detector


def test_drift_detector_edge_triggered_and_rearms():
    det = DriftDetector(threshold=0.5, min_count=32, floor=0.5)
    for v in _gauss(64, mean=1.0, std=0.1, seed=10):
        det.observe(v)
    det.freeze_baseline()
    assert det.drift_score() == 0.0  # empty current window
    for v in _gauss(64, mean=0.7, std=0.1, seed=11):  # 3-sigma shift
        det.observe(v)
    event = det.check()
    assert event is not None and event["kind"] == "drift"
    assert event["score"] > 0.5
    assert det.check() is None, "edge-triggered: one event per crossing"
    # decay suggestion shrinks toward the floor under drift
    assert det.suggested_decay(1.0) < 1.0
    assert det.suggested_decay(1.0) >= 0.5
    # falling back under threshold re-arms
    det.current = type(det.current)()
    for v in _gauss(64, mean=1.0, std=0.1, seed=12):
        det.observe(v)
    assert det.check() is None  # quiet again
    assert det.suggested_decay(1.0) == 1.0
    for v in _gauss(200, mean=0.5, std=0.1, seed=13):
        det.observe(v)
    assert det.check() is not None, "re-armed detector fires again"
    assert det.events == 2


def test_drift_detector_needs_min_count():
    det = DriftDetector(threshold=0.5, min_count=64, floor=0.5)
    for v in _gauss(64, mean=1.0, std=0.1, seed=14):
        det.observe(v)
    det.freeze_baseline()
    for v in _gauss(10, mean=0.0, std=0.1, seed=15):
        det.observe(v)
    assert det.drift_score() == 0.0, "too few current samples to call drift"


# ------------------------------------------------------------- the plane


def test_plane_worker_delta_merge_and_report():
    worker = QualityPlane()
    rng = random.Random(16)
    for _ in range(200):
        worker.observe_served(
            "m", [rng.gauss(0, 1) for _ in range(3)], rng.gauss(0.9, 0.05)
        )
    assert worker.stream("m", "live").count == 200
    delta = worker.drain_delta()
    assert delta is not None and "m" in delta
    assert worker.drain_delta() is None, "drain resets the pending delta"

    supervisor = QualityPlane()
    supervisor.merge_delta(delta, role="worker")
    sketch = supervisor.sketch("m")
    assert sketch is not None and sketch.rows == 200
    report = supervisor.report()
    assert report["models"]["m"]["sketch"]["rows"] == 200
    assert report["sketch_merges"] == 1


def test_plane_label_join_and_state_restore():
    plane = get_quality_plane()
    scores = _gauss(128, mean=0.95, std=0.02, seed=17)
    assert plane.join_labels("m", scores) == 128
    for s in scores:
        plane.observe_score("m", s, role="live")
    plane.drift("m").freeze_baseline()
    state = plane.state("m")

    reset_quality_plane()
    fresh = get_quality_plane()
    fresh.restore("m", state)
    assert fresh.stream("m", "labeled").count == 128
    assert fresh.report()["models"]["m"]["label_joins"] == 128
    det = fresh.drift("m")
    assert det.baseline is not None and det.baseline.count == 128


def test_plane_decision_recording_bumps_metric_and_archive():
    plane = get_quality_plane()
    registry = get_registry()
    counter = names.metric(names.QUALITY_GATE_DECISIONS)
    before = counter.value(model="m", decision="rollback")
    gate = plane.open_gate("m", kind="canary", alpha=0.05, min_samples=8)
    assert len(plane.open_gates()) == 1
    rng = random.Random(18)
    while gate.observe(candidate=rng.gauss(0.5, 0.1),
                       baseline=rng.gauss(1.0, 0.1)) == "continue":
        pass
    evidence = plane.record_decision(gate)
    assert evidence["decision"] == "rollback"
    assert not plane.open_gates(), "recording a decision closes the gate"
    assert list(plane.decisions)[-1]["kind"] == "canary"
    assert counter.value(model="m", decision="rollback") == before + 1
    plane.publish_metrics(registry)


def test_plane_disabled_env_is_noop(monkeypatch):
    monkeypatch.setenv("KEYSTONE_QUALITY", "off")
    plane = QualityPlane()
    plane.observe_served("m", [1.0, 2.0], 0.5)
    assert plane.join_labels("m", [1.0, 2.0]) == 0
    assert plane.stream("m", "live").count == 0
    assert plane.drain_delta() is None
    assert plane.check_drift("m") is None
    assert plane.suggested_decay("m", base=0.7) == 0.7


def test_plane_payload_sampling(monkeypatch):
    monkeypatch.setenv("KEYSTONE_QUALITY_SAMPLE", "4")
    plane = QualityPlane()
    for _ in range(40):
        plane.observe_payload("m", [1.0, 2.0])
    delta = plane.drain_delta()
    assert delta["m"]["rows"] == 10, "1-in-4 sampling sketches 10 of 40"


# ------------------------------------------------------------------- CLI


def _cli_args(**over):
    import argparse

    ns = argparse.Namespace(
        rows=256, shift=0.0, seed=0, model="default", features=4,
        alpha=None, max_samples=None, labels=64, as_json=True,
    )
    for key, value in over.items():
        setattr(ns, key, value)
    return ns


def test_quality_cli_clean_traffic_is_quiet(capsys):
    from keystone_tpu.obs.quality_cli import quality_from_args

    rc = quality_from_args(_cli_args())
    out = capsys.readouterr().out
    assert rc == 0
    import json as _json

    stats = _json.loads(out.split("QUALITY_STATS:", 1)[1])
    assert stats["drift_events"] == 0
    assert stats["decisions"] == []
    assert stats["report"]["open_gates"], "clean run ends with gate OPEN"


def test_quality_cli_shift_fires_drift_and_rollback(capsys):
    from keystone_tpu.obs.quality_cli import quality_from_args

    rc = quality_from_args(_cli_args(shift=3.0))
    out = capsys.readouterr().out
    assert rc == 2
    import json as _json

    stats = _json.loads(out.split("QUALITY_STATS:", 1)[1])
    assert stats["drift_events"] == 1
    assert stats["rollbacks"] == 1
    assert stats["state_decay"]["default"] < 1.0, (
        "drift must move the suggested state_decay"
    )
