"""bench-diff verdict tests: improved / regressed / noisy synthetic
inputs, exact count asserts, platform guards, and artifact-format
parsing (docs/OBSERVABILITY.md)."""

import json

from keystone_tpu.obs.benchdiff import (
    compare_leg,
    diff_reports,
    load_bench_report,
    main,
    report_legs,
)


def leg(**kw):
    base = {
        "n": 1024, "d": 64,
        "fit_ms": 100.0, "wall_s": 5.0,
        "fused_dispatches_per_apply": 1.0,
        "parity_rel_err": 1e-6,
    }
    base.update(kw)
    return base


def report(platform="cpu", **legs):
    return {"platform": platform, **legs}


def diff(base_leg, cur_leg, **kw):
    return diff_reports(
        report(timit=base_leg), report(timit=cur_leg), **kw
    )


# ----------------------------------------------------------------- verdicts


def test_unchanged_rerun_passes():
    v = diff(leg(), leg())
    assert v["ok"] and v["legs"]["timit"]["status"] == "ok"


def test_synthetic_2x_slowdown_is_flagged():
    v = diff(leg(), leg(fit_ms=200.0))
    assert not v["ok"]
    assert v["regressions"] == ["timit"]
    bad = [c for c in v["legs"]["timit"]["checks"] if c["verdict"] == "regression"]
    assert bad and bad[0]["key"] == "fit_ms" and bad[0]["ratio"] == 2.0


def test_noise_within_tolerance_passes():
    # +30% on a 100 ms leg is CI noise at the default 50% tolerance
    v = diff(leg(), leg(fit_ms=130.0))
    assert v["ok"]


def test_small_absolute_deltas_never_regress():
    # 3 ms -> 9 ms is a 3x ratio but below the 50 ms floor: jitter
    v = diff(leg(fit_ms=3.0), leg(fit_ms=9.0))
    assert v["ok"]


def test_improvement_is_reported_not_failed():
    v = diff(leg(), leg(fit_ms=40.0))
    assert v["ok"] and v["legs"]["timit"]["status"] == "improved"


def test_dispatch_count_compared_exactly():
    v = diff(leg(), leg(fused_dispatches_per_apply=2.0))
    assert not v["ok"]
    bad = [c for c in v["legs"]["timit"]["checks"] if c["verdict"] == "regression"]
    assert bad[0]["kind"] == "exact"


def test_compile_counts_compared_exactly():
    b = leg(streaming_report={"compiles_first_chunk": 1, "compiles_steady_state": 0})
    c = leg(streaming_report={"compiles_first_chunk": 1, "compiles_steady_state": 2})
    v = diff(b, c)
    assert not v["ok"]
    bad = [x for x in v["legs"]["timit"]["checks"] if x["verdict"] == "regression"]
    assert bad[0]["key"] == "streaming_report.compiles_steady_state"


def test_parity_blowup_is_flagged_and_jitter_is_not():
    assert diff(leg(), leg(parity_rel_err=5e-6))["ok"]  # fp jitter
    assert not diff(leg(parity_rel_err=1e-4), leg(parity_rel_err=0.5))["ok"]


def test_overlap_flag_regression():
    b = leg(streaming_report={"overlap_ok": True})
    c = leg(streaming_report={"overlap_ok": False})
    assert not diff(b, c)["ok"]


def test_config_mismatch_is_incomparable_not_regression():
    v = diff(leg(n=1024), leg(n=2048, fit_ms=500.0))
    assert v["ok"]
    assert v["legs"]["timit"]["status"] == "incomparable"


def test_platform_mismatch_skips_timings_keeps_counts():
    base = report(platform="tpu", timit=leg())
    cur = report(platform="cpu", timit=leg(fit_ms=5000.0))
    v = diff_reports(base, cur)
    assert v["ok"] and not v["timings_comparable"]
    # but a count delta still fails across platforms
    cur_bad = report(platform="cpu", timit=leg(fused_dispatches_per_apply=3.0))
    assert not diff_reports(base, cur_bad)["ok"]


def test_leg_now_failing_is_a_regression():
    v = diff(leg(), {"error": "RESOURCE_EXHAUSTED"})
    assert not v["ok"]
    assert "failure" in v["legs"]["timit"]["note"]


def test_errored_baseline_and_missing_legs_are_skipped():
    base = report(timit={"error": "died"}, other=leg())
    cur = report(timit=leg())
    v = diff_reports(base, cur)
    assert v["ok"]
    assert v["legs"]["timit"]["status"] == "skipped"
    assert v["legs"]["other"]["status"] == "skipped"


def test_wall_s_and_environment_keys_are_ignored():
    b = leg(wall_s=5.0, obs={"xla_compiles": 3}, peak_host_rss_mb=1000.0)
    c = leg(wall_s=50.0, obs={"xla_compiles": 40}, peak_host_rss_mb=9000.0)
    assert diff(b, c)["ok"]


# ----------------------------------------------------------- artifact formats


def test_load_raw_child_report(tmp_path):
    p = tmp_path / "r.json"
    p.write_text(json.dumps(report(timit=leg())))
    r = load_bench_report(str(p))
    assert report_legs(r) == ["timit"]


def test_load_driver_wrapper_with_embedded_report(tmp_path):
    inner = report(timit=leg())
    wrapper = {"n": 5, "cmd": "python bench.py", "rc": 0,
               "tail": "noise\nBENCH_CHILD_JSON:" + json.dumps(inner) + "\n",
               "parsed": None}
    p = tmp_path / "w.json"
    p.write_text(json.dumps(wrapper))
    r = load_bench_report(str(p))
    assert r["timit"]["fit_ms"] == 100.0


def test_load_truncated_tail_recovers_whole_legs(tmp_path):
    # the committed driver artifacts keep only the last N bytes: the
    # outer object is beheaded but whole legs survive
    inner = report(timit=leg(), gram=leg(fit_ms=8.0))
    tail = json.dumps(inner)
    wrapper = {"n": 5, "cmd": "x", "rc": 0, "tail": tail[len(tail) // 2:],
               "parsed": None}
    p = tmp_path / "t.json"
    p.write_text(json.dumps(wrapper))
    r = load_bench_report(str(p))
    assert "gram" in r or "timit" in r  # at least the unbeheaded legs


def test_committed_artifacts_parse():
    import os

    root = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    for name in ("BENCH_CI_BASELINE.json", "BENCH_r05.json"):
        r = load_bench_report(os.path.join(root, name))
        assert report_legs(r), name


def test_main_exit_codes(tmp_path):
    good = tmp_path / "good.json"
    bad = tmp_path / "bad.json"
    good.write_text(json.dumps(report(timit=leg())))
    bad.write_text(json.dumps(report(timit=leg(fit_ms=300.0))))
    assert main(["--baseline", str(good), "--current", str(good)]) == 0
    assert main(["--baseline", str(good), "--current", str(bad)]) == 1
    out = tmp_path / "verdict.json"
    main(["--baseline", str(good), "--current", str(bad), "--out", str(out)])
    verdict = json.loads(out.read_text())
    assert verdict["regressions"] == ["timit"]


def test_unknown_platform_skips_timings():
    """A truncated driver wrapper loses its platform key; its recovered
    legs may carry TPU walls — never ratio them against CPU walls."""
    base = {"timit": leg()}  # no platform key at all
    cur = report(platform="cpu", timit=leg(fit_ms=5000.0))
    v = diff_reports(base, cur)
    assert v["ok"] and not v["timings_comparable"]
    # counts still exact across the unknown boundary
    cur_bad = report(platform="cpu", timit=leg(fused_dispatches_per_apply=9.0))
    assert not diff_reports(base, cur_bad)["ok"]


def test_truncated_current_leg_is_a_regression():
    """A leg that used to finish and now blows its child deadline is the
    gate's reason to exist — partial surviving keys must not read ok."""
    v = diff(leg(), dict(leg(), truncated="child deadline (150s)"))
    assert not v["ok"]
    assert "failure" in v["legs"]["timit"]["note"]


def test_obs_registry_deltas_are_never_exact_compared():
    """obs.* metric deltas span warmups/incidental applies — a benign
    warmup change must not fail the gate even when the key mentions
    dispatches."""
    b = leg(obs={"metrics_delta": {"keystone_fusion_batch_dispatches_total{fused=0}": 168.0}})
    c = leg(obs={"metrics_delta": {"keystone_fusion_batch_dispatches_total{fused=0}": 170.0}})
    assert diff(b, c)["ok"]


def test_toplevel_chunks_is_config_nested_chunks_is_exact():
    # reconfigured leg (different chunking plan) → incomparable, not failed
    v = diff(leg(chunks=8), leg(chunks=4))
    assert v["ok"] and v["legs"]["timit"]["status"] == "incomparable"
    # but the ENGINE dispatching fewer chunks than planned is a regression
    b = leg(streaming_report={"chunks": 8})
    c = leg(streaming_report={"chunks": 6})
    assert not diff(b, c)["ok"]


def test_explicitly_requested_missing_leg_fails_the_gate():
    """A leg named via --legs that is absent from either artifact must be
    a regression, not a silent skip — a typo'd CI leg list or a renamed
    bench leg would otherwise leave the gate green forever."""
    base = report(fusion=leg())
    cur = report(fusion=leg())
    verdict = diff_reports(base, cur, legs=["fusion", "streamin"])
    assert not verdict["ok"]
    assert verdict["legs"]["streamin"]["status"] == "regression"
    assert "required leg missing" in verdict["legs"]["streamin"]["note"]
    # missing only from the baseline is equally fatal for a required leg
    verdict = diff_reports(report(), report(fusion=leg()), legs=["fusion"])
    assert not verdict["ok"]


def test_auto_discovered_one_sided_leg_still_skips():
    """Without an explicit --legs list, artifacts may legitimately differ
    in coverage: one-sided legs skip instead of failing."""
    verdict = diff_reports(report(fusion=leg()), report(serving=leg()))
    assert verdict["ok"]
    assert verdict["legs"]["fusion"]["status"] == "skipped"
    assert verdict["legs"]["serving"]["status"] == "skipped"


# ------------------------------------------------- serving_multiworker leg


def mw_leg(**kw):
    base = {
        "d": 8, "requests": 96, "kill_at_request": 10,
        "one_worker_rps": 2700.0, "one_worker_p99_ms": 25.0,
        "one_worker_dropped": 0,
        "two_worker_kill_rps": 1000.0, "two_worker_p99_ms": 12.0,
        "dropped": 0, "requeued": 48, "worker_restarts": 1,
        "compiles_steady_state": 0, "throughput_vs_one_worker": 0.37,
    }
    base.update(kw)
    return base


def test_dropped_request_counts_compared_exactly():
    """The chaos invariant: ONE dropped request under the mid-sweep kill
    is a regression no tolerance forgives — on either sweep."""
    for key in ("dropped", "one_worker_dropped"):
        v = diff(mw_leg(), mw_leg(**{key: 1}))
        assert not v["ok"], key
        bad = [c for c in v["legs"]["timit"]["checks"]
               if c["verdict"] == "regression"]
        assert bad and bad[0]["key"] == key and bad[0]["kind"] == "exact"
    # a steady-state compile appearing after the restart is equally exact
    assert not diff(mw_leg(), mw_leg(compiles_steady_state=2))["ok"]


def test_partition_counters_compared_exactly():
    """The sharded leg's invariants (docs/PARTITIONING.md): shard counts
    and the finish-reduce collective payload are pure functions of the
    pinned plan — any drift is a plan change, not noise."""
    def sharded_leg(**kw):
        base = {
            "stream": {"shards_chosen": 8, "collective_bytes": 271392},
        }
        base["stream"].update(kw)
        return base

    for key, bad_value in (
        ("shards_chosen", 4),
        ("collective_bytes", 271392 * 2),
    ):
        v = diff(sharded_leg(), sharded_leg(**{key: bad_value}))
        assert not v["ok"], key
        bad = [c for c in v["legs"]["timit"]["checks"]
               if c["verdict"] == "regression"]
        assert bad and bad[0]["key"] == f"stream.{key}"
        assert bad[0]["kind"] == "exact"
    assert diff(sharded_leg(), sharded_leg())["ok"]


def test_exact_key_degrading_to_none_is_a_regression_not_a_skip():
    """compiles_steady_state=None happens precisely when the measured
    path is broken (no worker stats flowed) — the exact gate must fire,
    not silently evaporate."""
    v = diff(mw_leg(), mw_leg(compiles_steady_state=None))
    assert not v["ok"]
    bad = [c for c in v["legs"]["timit"]["checks"]
           if c["verdict"] == "regression"]
    assert bad and bad[0]["key"] == "compiles_steady_state"


def test_exact_key_missing_from_current_is_a_regression():
    """A renamed / no-longer-measured exact invariant fails loudly; a
    missing non-exact key (timing, info) is still just skipped."""
    cur = mw_leg()
    del cur["dropped"]
    v = diff(mw_leg(), cur)
    assert not v["ok"]
    bad = [c for c in v["legs"]["timit"]["checks"]
           if c["verdict"] == "regression"]
    assert [c["key"] for c in bad] == ["dropped"] and bad[0]["current"] is None
    cur = mw_leg()
    del cur["one_worker_p99_ms"], cur["requeued"]
    assert diff(mw_leg(), cur)["ok"]


def test_true_bool_invariant_missing_from_current_is_a_regression():
    """A bool invariant that held (overlap_ok=True) and then vanished
    un-gates itself exactly like a renamed exact key — regression. A
    False baseline bool vanishing gates nothing (there was no invariant
    to lose)."""
    v = diff(mw_leg(overlap_ok=True), mw_leg())
    assert not v["ok"]
    bad = [c for c in v["legs"]["timit"]["checks"]
           if c["verdict"] == "regression"]
    assert bad and bad[0]["key"] == "overlap_ok" and bad[0]["kind"] == "bool"
    assert diff(mw_leg(overlap_ok=False), mw_leg())["ok"]


def test_requeued_and_restart_variance_is_not_gated():
    """How MUCH work was in flight at kill time (requeued) and whether a
    CI flake cost an extra restart are scheduler timing, not pinned
    invariants — runs differing only there must pass."""
    v = diff(mw_leg(), mw_leg(requeued=7, worker_restarts=2,
                              throughput_vs_one_worker=0.9))
    assert v["ok"]


def test_committed_baseline_gates_the_multiworker_leg():
    """The committed CI baseline must carry the leg (tier1 names it via
    --legs, so losing it fails the gate) with the invariants at zero."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    r = load_bench_report(os.path.join(root, "BENCH_CI_BASELINE.json"))
    assert "serving_multiworker" in report_legs(r)
    mw = r["serving_multiworker"]
    assert mw["dropped"] == 0 and mw["one_worker_dropped"] == 0
    assert mw["compiles_steady_state"] == 0 and mw["worker_restarts"] >= 1
