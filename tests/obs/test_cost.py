"""Cost observatory (obs/cost.py): guarded harvest, roofline
calibration + store warm-start, the perf ledger, harvest frames, and
the drift sentinel's baseline/stale contract
(docs/OBSERVABILITY.md "Cost observatory")."""

import numpy as np
import pytest

from keystone_tpu.obs import cost, names
from keystone_tpu.obs.metrics import get_registry
from keystone_tpu.obs.store import ProfileStore, is_stale, set_store

FP = {"jax": "test", "backend": "cpu", "device_kind": "virtual"}


@pytest.fixture(autouse=True)
def _fresh_observatory():
    import os

    env_before = os.environ.get("KEYSTONE_PROFILE_STORE")
    cost.reset_cost_observatory()
    cost.set_cost_observatory(True)
    yield
    if env_before is not None:
        os.environ["KEYSTONE_PROFILE_STORE"] = env_before
    else:
        os.environ.pop("KEYSTONE_PROFILE_STORE", None)
    cost.set_cost_observatory(None)
    cost.reset_cost_observatory()
    set_store(None)


def _own_store(tmp_path, monkeypatch=None):
    """Point the process store at a per-test file. ``get_store()``
    re-resolves from KEYSTONE_PROFILE_STORE, so the env is the only
    reliable isolation door."""
    import os

    path = str(tmp_path / "cost.jsonl")
    if monkeypatch is not None:
        monkeypatch.setenv("KEYSTONE_PROFILE_STORE", path)
    else:
        os.environ["KEYSTONE_PROFILE_STORE"] = path
    from keystone_tpu.obs.store import get_store

    return get_store()


# ------------------------------------------------------------------- harvest


def test_harvest_cost_facts_from_jitted_fn_zero_compiles():
    import jax
    import jax.numpy as jnp

    from keystone_tpu.utils.compilation_cache import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((32, 32), jnp.float32)
    f(x)  # the signature has executed: lower() rides the trace cache
    before = compile_count()
    facts = cost.harvest_cost_facts(f, (x,))
    assert compile_count() == before, "harvest must not compile"
    assert facts is not None
    assert facts.flops and facts.flops > 2 * 32**3 * 0.5
    assert facts.bytes_accessed and facts.bytes_accessed > 0
    assert facts.intensity == facts.flops / facts.bytes_accessed
    assert len(facts.lowering_digest) == 16


def test_harvest_guarded_against_broken_backends():
    class Broken:
        def cost_analysis(self):
            raise RuntimeError("backend says no")

    assert cost.harvest_cost_facts(Broken()) is None

    class Lowered:
        def cost_analysis(self):
            return None

        def as_text(self):
            return "module {}"

    facts = cost.harvest_cost_facts(Lowered())
    assert facts is not None
    assert facts.flops is None and facts.bytes_accessed is None
    assert facts.intensity is None


def test_normalize_cost_analysis_shapes():
    norm = cost._normalize_cost_analysis
    assert norm(None) == (None, None)
    assert norm({"flops": 10.0, "bytes accessed": 4.0}) == (10.0, 4.0)
    # list-of-dicts sums; missing/negative fields degrade to None
    assert norm([{"flops": 1.0}, {"flops": 2.0}]) == (3.0, None)
    assert norm([{"flops": -1.0}]) == (None, None)
    assert norm("garbage") == (None, None)


def test_facts_cache_hits_per_signature():
    import jax
    import jax.numpy as jnp

    calls = []
    real = cost.harvest_cost_facts

    f = jax.jit(lambda x: x * 2.0)
    x = jnp.ones((8,), jnp.float32)
    f(x)
    try:
        cost.harvest_cost_facts = lambda fn, a=None: calls.append(1) or real(
            fn, a
        )
        assert cost._cached_facts(f, (x,)) is not None
        assert cost._cached_facts(f, (x,)) is not None
        assert len(calls) == 1, "second lookup must hit the facts cache"
    finally:
        cost.harvest_cost_facts = real


# ------------------------------------------------------------------ roofline


def test_roofline_probe_and_store_warm_start(tmp_path):
    store = _own_store(tmp_path)
    roofline = cost.get_roofline()
    assert roofline is not None
    assert roofline.source == "probe"
    assert roofline.peak_flops_per_s > 0 and roofline.peak_bytes_per_s > 0
    # persisted: a fresh in-process resolve warm-starts from the store
    cost.set_roofline(None)
    again = cost.get_roofline()
    assert again.source == "store"
    assert again.peak_flops_per_s == pytest.approx(
        roofline.peak_flops_per_s
    )
    assert store.lookup(
        f"roofline:{roofline.backend}", cost.ROOFLINE_SHAPE
    )


def test_roofline_classify_and_predict():
    r = cost.Roofline(peak_flops_per_s=1e9, peak_bytes_per_s=1e8)
    assert r.ridge_intensity == 10.0
    assert r.classify(20.0) == "compute-bound"
    assert r.classify(5.0) == "memory-bound"
    assert r.classify(None) is None
    # roofline time = max(compute floor, memory floor)
    assert r.predicted_seconds(1e9, 1e7) == pytest.approx(1.0)
    assert r.predicted_seconds(1e7, 1e8) == pytest.approx(1.0)
    assert r.predicted_seconds(None, None) is None


# -------------------------------------------------------------------- ledger


def test_ledger_ring_bounds_and_cursor():
    ledger = cost.PerfLedger(capacity=4)
    for i in range(10):
        ledger.record(
            cost.PerfLedgerEntry(
                node=f"n{i}", seconds=0.1, synced=True, t_s=0.0, t_unix=0.0
            )
        )
    assert ledger.cursor() == 10
    assert [e.node for e in ledger.tail(2)] == ["n8", "n9"]
    # entries(since) is ring-bounded: only the last 4 survive
    assert [e.node for e in ledger.entries(5)] == ["n6", "n7", "n8", "n9"]
    assert ledger.entries(10) == []
    summary = ledger.summary(since=6)
    assert summary["nodes"] == 4


# -------------------------------------------------------- frames + finalize


def test_note_jit_call_requires_frame():
    cost.note_jit_call("x", object(), (1,))  # no frame: silently dropped
    frame = cost.push_frame("node")
    try:
        cost.note_jit_call("x", object(), (1,))
        assert len(frame.notes) == 1
    finally:
        cost.pop_frame(frame)
    assert cost.current_frame() is None


def test_finalize_node_joins_facts_prediction_and_span(tmp_path):
    import jax
    import jax.numpy as jnp

    _own_store(tmp_path)
    cost.set_roofline(
        cost.Roofline(peak_flops_per_s=1e12, peak_bytes_per_s=1e11)
    )
    f = jax.jit(lambda x: x @ x)
    x = jnp.ones((16, 16), jnp.float32)
    f(x)

    class Op:
        predicted_cost = cost.Prediction(
            model="solver_ladder", key="solver:ladder:X", seconds=0.5
        )

    class Span:
        attrs = {}

        def set_attribute(self, k, v):
            self.attrs[k] = v

    frame = cost.push_frame("node:test")
    cost.note_jit_call("matmul", f, (x,))
    cost.pop_frame(frame)
    span = Span()
    entry = cost.finalize_node(
        "node:test", 1.0, True, op=Op(), span=span, frame=frame
    )
    assert entry is not None
    assert entry.flops and entry.bytes_accessed
    assert entry.roofline in ("compute-bound", "memory-bound")
    assert entry.flops_per_s == pytest.approx(entry.flops / 1.0)
    assert entry.predicted_model == "solver_ladder"
    assert entry.predicted_s == 0.5
    assert entry.ratio == pytest.approx(2.0)  # 1.0 measured vs 0.5 claimed
    assert entry.lowering_digest
    # the span carries the join surface, lowering digest included
    assert span.attrs["lowering_digest"] == entry.lowering_digest
    assert span.attrs["roofline"] == entry.roofline
    assert span.attrs["predicted_model"] == "solver_ladder"


def test_finalize_skips_unclaimed_nodes_unless_record_all():
    frame = cost.push_frame("host-node")
    cost.pop_frame(frame)
    assert (
        cost.finalize_node("host-node", 0.1, True, frame=frame) is None
    )
    cost.record_all_nodes(True)
    frame = cost.push_frame("host-node")
    cost.pop_frame(frame)
    entry = cost.finalize_node("host-node", 0.1, True, frame=frame)
    assert entry is not None and entry.flops is None


def test_resolve_prediction_sums_fused_members():
    cost.note_plan_prediction(
        "A", cost.Prediction("autocache", key="autocache:a", shape="s",
                             seconds=0.2, calibrated=True)
    )
    cost.note_plan_prediction(
        "B", cost.Prediction("autocache", key="autocache:b", shape="s",
                             seconds=0.3, calibrated=True)
    )

    class Fused:
        member_labels = ("A", "B")

    resolved = cost._resolve_prediction(Fused(), "Fused[A+B]")
    assert resolved.seconds == pytest.approx(0.5)
    assert resolved.key == "autocache:a,autocache:b"
    assert resolved.calibrated


# ------------------------------------------------------------------ sentinel


def _calibrated(key="autocache:k1", shape="n2^10", seconds=0.01):
    return cost.Prediction(
        model="autocache", key=key, shape=shape, seconds=seconds,
        calibrated=True,
    )


def test_sentinel_baselines_then_fires_and_marks_stale(tmp_path):
    store = _own_store(tmp_path)
    store.record("autocache:k1", "n2^10", t0=0.0, t1=1e-5)
    sentinel = cost.get_drift_sentinel()
    pred = _calibrated()
    # 1st warm observation: baseline written, no judgment
    assert sentinel.observe("node", pred, measured_s=0.1) is None
    m = store.lookup("autocache:k1", "n2^10")
    assert m[cost.DriftSentinel.BASELINE_FIELD] == pytest.approx(0.1)
    # in-band: quiet (and the EMA nudges the baseline toward reality)
    assert sentinel.observe("node", pred, measured_s=0.12) is None
    # sustained 10x: first out-of-band is noise, second fires
    assert sentinel.observe("node", pred, measured_s=1.1) is None
    reg_before = get_registry().snapshot()
    event = sentinel.observe("node", pred, measured_s=1.1)
    assert event is not None
    assert event["stale_marked"] is True
    assert event["ratio"] > cost.drift_ratio_tolerance()
    # the entry is stale: consumers re-measure instead of replaying
    assert store.lookup("autocache:k1", "n2^10") is None
    stale = store.lookup("autocache:k1", "n2^10", include_stale=True)
    assert is_stale(stale) and stale["stale_reason"] == "cost_drift"
    # metric + recovery-ledger event landed
    moved = {
        k: v - reg_before.get(k, 0)
        for k, v in get_registry().snapshot().items()
        if k.startswith("keystone_cost_drift_events")
    }
    assert any(v == 1 for v in moved.values()), moved
    from keystone_tpu.reliability.recovery import get_recovery_log

    kinds = [e.kind for e in get_recovery_log().events()]
    assert "cost_drift" in kinds
    # already stale: the sentinel goes quiet until a re-measure
    assert sentinel.observe("node", pred, measured_s=1.1) is None
    # fresh measurement re-records the entry → baseline restarts
    store.record("autocache:k1", "n2^10", t0=0.0, t1=1e-5)
    assert sentinel.observe("node", pred, measured_s=1.1) is None  # baseline
    assert sentinel.observe("node", pred, measured_s=1.15) is None  # in band


def test_sentinel_scores_rate_predictions_directly(tmp_path):
    store = _own_store(tmp_path)
    store.record("stream:c:cr512", "n2^12", chunk_rows=512, rows_per_s=1e5)
    sentinel = cost.get_drift_sentinel()
    pred = cost.Prediction(
        model="measured_knob", key="stream:c:cr512", shape="n2^12",
        rows_per_s=1e5, calibrated=True,
    )
    # achieved ~= claimed: quiet
    assert sentinel.observe("s", pred, measured_rate=9e4) is None
    # sustained 10x slower than the stored claim: fires on the 2nd
    assert sentinel.observe("s", pred, measured_rate=1e4) is None
    event = sentinel.observe("s", pred, measured_rate=1e4)
    assert event is not None and event["stale_marked"]
    assert store.lookup("stream:c:cr512", "n2^12") is None


def test_sentinel_ignores_uncalibrated_compound_and_missing(tmp_path):
    store = _own_store(tmp_path)
    sentinel = cost.get_drift_sentinel()
    relative = cost.Prediction("solver_ladder", key="solver:ladder:X",
                               seconds=1e-6, calibrated=False)
    for _ in range(4):
        assert sentinel.observe("n", relative, measured_s=10.0) is None
    compound = cost.Prediction(
        "autocache", key="autocache:a,autocache:b", shape="s",
        seconds=0.01, calibrated=True,
    )
    for _ in range(4):
        assert sentinel.observe("n", compound, measured_s=10.0) is None
    # no store entry behind the key: nothing to govern
    missing = _calibrated(key="autocache:gone")
    for _ in range(4):
        assert sentinel.observe("n", missing, measured_s=10.0) is None
    assert sentinel.events == []


def test_observatory_disabled_is_inert(tmp_path):
    cost.set_cost_observatory(False)
    cost.note_plan_prediction("X", _calibrated())
    assert cost.plan_prediction("X") is None
    frame = cost.current_frame()
    assert frame is None


# ------------------------------------------------------- ledger-only tracing


def test_timed_execute_ledger_only_records_entries(tmp_path):
    """Observatory on, no span session: timed_execute still lands ledger
    entries (unsynced) without touching the node-seconds histogram."""
    import jax.numpy as jnp

    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.serving.synthetic import SyntheticDense
    from keystone_tpu.workflow.operators import DatasetOperator
    from keystone_tpu.workflow.tracing import timed_execute

    _own_store(tmp_path)
    cost.record_all_nodes(True)
    w = np.eye(4, dtype=np.float32)
    op = SyntheticDense([w])
    data = DatasetOperator(
        ArrayDataset(jnp.ones((8, 4), jnp.float32))
    ).execute([])
    cursor = cost.get_ledger().cursor()
    before = get_registry().snapshot()
    from keystone_tpu.workflow.pipeline import BatchTransformer

    class Wrap(BatchTransformer):
        label = "wrap"

        def apply_arrays(self, x):
            return op.apply_arrays(x)

    timed_execute(Wrap(), [data]).get()
    entries = cost.get_ledger().entries(cursor)
    assert [e.node for e in entries] == ["wrap"]
    assert entries[0].synced is False
    moved = {
        k: v
        for k, v in get_registry().snapshot().items()
        if k.startswith(names.NODE_SECONDS) and v != before.get(k, 0)
    }
    assert not moved, "ledger-only runs must not feed the traced histogram"


def test_sentinel_rebases_stored_baseline_on_first_process_sight(tmp_path):
    """A baseline written by ANOTHER process is load noise at ms scale:
    the first observation of a key in this process re-bases it to local
    reality instead of scoring it — cross-process wall jumps never
    false-fire; in-process drift still does."""
    store = _own_store(tmp_path)
    base = cost.DriftSentinel.BASELINE_FIELD
    # "another process" recorded a 6x-slower baseline
    store.record("autocache:k2", "n2^10", t0=0.0, t1=1e-5, **{base: 0.6})
    sentinel = cost.get_drift_sentinel()
    pred = _calibrated(key="autocache:k2")
    # 6x faster than the stored baseline — rebased, not scored
    for _ in range(3):
        assert sentinel.observe("n", pred, measured_s=0.1) is None
    assert store.lookup("autocache:k2", "n2^10")[base] == pytest.approx(
        0.1, rel=0.2
    )
    # ...but in-process drift on the rebased baseline still fires
    assert sentinel.observe("n", pred, measured_s=1.0) is None
    assert sentinel.observe("n", pred, measured_s=1.0) is not None


def test_partial_fused_coverage_is_never_calibrated():
    """A fused chain with only SOME members in the plan book must not
    produce a calibrated prediction: a partial sum understates the
    chain's claim, and a single covered member would slip past the
    sentinel's compound-key guard and score the whole chain's wall
    against that one entry."""
    cost.note_plan_prediction(
        "A", cost.Prediction("autocache", key="autocache:a", shape="s",
                             seconds=0.2, calibrated=True)
    )

    class Fused:
        member_labels = ("A", "B")  # B never profiled

    resolved = cost._resolve_prediction(Fused(), "Fused[A+B]")
    assert resolved is not None
    assert resolved.seconds == pytest.approx(0.2)
    assert resolved.calibrated is False  # partial coverage: display only
