"""Cross-layer observability: executor node spans, reliability events as
span events + counters, serving trace propagation and registry parity."""

import numpy as np

from keystone_tpu.obs import metrics, names, spans


def _counter_value(name, **labels):
    metric = metrics.get_registry().get(name)
    if metric is None:
        return 0.0
    return metric.value(**labels)


def test_trace_shim_produces_nested_node_spans_and_metrics():
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.stats.core import LinearRectifier, NormalizeRows
    from keystone_tpu.workflow.tracing import trace

    executed_before = _counter_value(names.NODES_EXECUTED)
    ds = ArrayDataset(
        np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
    )
    pipeline = LinearRectifier(0.0).to_pipeline() >> NormalizeRows()
    with trace() as t:
        pipeline(ds).get()
    # legacy flat view still works; the two-transformer chain executes as
    # ONE fused node whose label carries the member names
    (timing,) = [x for x in t.timings if "NormalizeRows" in x.label]
    assert timing.label.startswith("Fused[")
    assert "TOTAL" in t.report()
    # hierarchy: node spans parented under the pipeline root
    roots = [s for s in t.session.spans() if s.parent_id is None]
    assert [s.name for s in roots] == ["pipeline"]
    node_spans = t.session.find("node:")
    assert {s.parent_id for s in node_spans} == {roots[0].span_id}
    fused_spans = t.session.find("node:Fused[")
    assert fused_spans and "NormalizeRows" in fused_spans[0].attributes["fused_members"]
    # node wall-time histogram populated for the traced (fused) op
    hist = metrics.get_registry().get(names.NODE_SECONDS)
    assert hist.count(op=timing.label) >= 1
    # executor counters moved
    assert _counter_value(names.NODES_EXECUTED) > executed_before


def test_reliability_events_publish_counters_and_span_events():
    from keystone_tpu.reliability.retry import RetryPolicy

    before = _counter_value(names.RELIABILITY_EVENTS, kind="retry")
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("UNAVAILABLE: transient")
        return "ok"

    with spans.tracing_session() as session:
        with spans.span("work"):
            policy = RetryPolicy(max_attempts=3, base_delay_s=0.0, sleep=lambda s: None)
            assert policy.call(flaky, label="probe") == "ok"
    assert _counter_value(names.RELIABILITY_EVENTS, kind="retry") == before + 2
    (work,) = session.find("work")
    retry_events = [e for e in work.events if e.name == "reliability:retry"]
    assert len(retry_events) == 2
    assert retry_events[0].attributes["label"] == "probe"


def test_degradation_ladder_rungs_surface_as_events():
    from keystone_tpu.reliability.degrade import DegradationLadder

    before = _counter_value(names.RELIABILITY_EVENTS, kind="degrade")

    def attempt(rung):
        if rung > 1:
            raise MemoryError("RESOURCE_EXHAUSTED: oom")
        return rung

    with spans.tracing_session() as session:
        with spans.span("solve"):
            ladder = DegradationLadder([4, 2, 1], label="test-ladder")
            assert ladder.run(attempt) == 1
    assert _counter_value(names.RELIABILITY_EVENTS, kind="degrade") == before + 1
    (solve,) = session.find("solve")
    assert any(e.name == "reliability:degrade" for e in solve.events)


def test_checkpoint_store_counters(tmp_path):
    from keystone_tpu.reliability.checkpoint import CheckpointStore
    from keystone_tpu.workflow.pipeline import Transformer

    class Tagged(Transformer):
        def __init__(self, tag):
            self.tag = tag

        def apply(self, x):
            return x

    from keystone_tpu.workflow.prefix import Prefix

    prefix = Prefix((Tagged("a"), ()))
    hits0 = _counter_value(names.CHECKPOINT_HITS)
    misses0 = _counter_value(names.CHECKPOINT_MISSES)
    writes0 = _counter_value(names.CHECKPOINT_WRITES)
    store = CheckpointStore(str(tmp_path))
    assert store.get_or_compute(prefix, lambda: "value") == "value"  # miss+write
    assert store.get_or_compute(prefix, lambda: "other") == "value"  # hit
    assert _counter_value(names.CHECKPOINT_HITS) == hits0 + 1
    assert _counter_value(names.CHECKPOINT_MISSES) == misses0 + 1
    assert _counter_value(names.CHECKPOINT_WRITES) == writes0 + 1


def test_serving_traces_propagate_submit_to_apply():
    from keystone_tpu.serving import PipelineServer, ServingConfig
    from keystone_tpu.serving.synthetic import synthetic_fitted_pipeline

    served0 = _counter_value(names.SERVING_REQUESTS, model="default")
    fp = synthetic_fitted_pipeline(d=8, depth=1)
    with spans.tracing_session() as session:
        with spans.span("client") as client:
            server = PipelineServer(
                fp, config=ServingConfig(max_batch=4, max_wait_ms=1.0)
            ).start()
            try:
                futures = [
                    server.submit(np.zeros((8,), np.float32)) for _ in range(3)
                ]
                for f in futures:
                    f.result(timeout=30)
            finally:
                server.stop()
    # every request span re-parents under the submitting client span
    request_spans = session.find("serve:request")
    assert len(request_spans) == 3
    assert {s.parent_id for s in request_spans} == {client.span_id}
    assert {s.trace_id for s in request_spans} == {client.trace_id}
    batch_spans = session.find("serve:batch")
    assert batch_spans and all(s.trace_id == client.trace_id for s in batch_spans)
    # submit events landed on the client span
    submit_events = [e for e in client.events if e.name == "serving.submit"]
    assert len(submit_events) == 3
    # registry parity: the serving counters moved with telemetry
    assert _counter_value(names.SERVING_REQUESTS, model="default") == served0 + 3


def test_serving_without_session_keeps_requests_unannotated():
    from keystone_tpu.serving import PipelineServer, ServingConfig
    from keystone_tpu.serving.synthetic import synthetic_fitted_pipeline

    fp = synthetic_fitted_pipeline(d=8, depth=1)
    server = PipelineServer(
        fp, config=ServingConfig(max_batch=4, max_wait_ms=1.0)
    ).start()
    try:
        future = server.submit(np.zeros((8,), np.float32))
        future.result(timeout=30)
    finally:
        server.stop()
    # no session → no trace context captured, no span machinery engaged
    assert spans.active_session() is None


def test_rule_executor_metrics_and_optimize_span():
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.stats.core import LinearRectifier

    runs0 = _counter_value(names.RULE_RUNS, rule="EquivalentNodeMergeRule")
    ds = ArrayDataset(np.ones((4, 3), np.float32))
    with spans.tracing_session() as session:
        LinearRectifier(0.0).to_pipeline()(ds).get()
    assert _counter_value(names.RULE_RUNS, rule="EquivalentNodeMergeRule") > runs0
    assert session.find("optimize")  # optimizer ran under a span
