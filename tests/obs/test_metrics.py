"""Metrics registry: counters/gauges/histograms, percentile parity with
the previous ServingTelemetry math, and the stable name schema."""

import numpy as np
import pytest

from keystone_tpu.obs import metrics, names
from keystone_tpu.obs.metrics import (
    Counter,
    Histogram,
    MetricsRegistry,
    delta,
    percentile,
)


def test_counter_labels_and_totals():
    reg = MetricsRegistry()
    c = reg.counter("x_total", "help", ("kind",))
    c.inc(kind="a")
    c.inc(2, kind="a")
    c.inc(kind="b")
    assert c.value(kind="a") == 3
    assert c.total() == 4
    with pytest.raises(ValueError):
        c.inc(-1, kind="a")
    with pytest.raises(ValueError):
        c.inc(kind="a", extra="nope")


def test_registry_get_or_create_idempotent_and_type_checked():
    reg = MetricsRegistry()
    a = reg.counter("x_total")
    assert reg.counter("x_total") is a
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    with pytest.raises(ValueError):
        reg.counter("x_total", labels=("k",))


def test_gauge_set_inc_max():
    reg = MetricsRegistry()
    g = reg.gauge("mem_bytes", labels=("stage",))
    g.set(100, stage="fit")
    g.max(50, stage="fit")
    assert g.value(stage="fit") == 100
    g.max(200, stage="fit")
    assert g.value(stage="fit") == 200
    g.inc(5, stage="fit")
    assert g.value(stage="fit") == 205


def test_histogram_buckets_cumulative_and_window():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0), window=4)
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count() == 4
    assert h.sum() == pytest.approx(5.555)
    # window bounded: oldest evicted
    for v in (0.2, 0.3):
        h.observe(v)
    assert h.count() == 6  # cumulative count keeps everything
    assert h.percentile(100) == pytest.approx(5.0)  # window kept [0.5,5,.2,.3]


def test_histogram_percentiles_match_serving_telemetry_previous_values():
    """The satellite contract: identical latency inputs → identical p50/
    p95/p99 between the absorbed Histogram math and what ServingTelemetry
    reports (which used this interpolation from PR 2 on)."""
    from keystone_tpu.serving.telemetry import ServingTelemetry

    rng = np.random.default_rng(3)
    latencies = rng.gamma(2.0, 0.01, size=257).tolist()

    telemetry = ServingTelemetry(window=2048)
    for lat in latencies:
        telemetry.record_request(latency_s=lat, queue_wait_s=lat / 3)
    snap = telemetry.snapshot()

    reg = MetricsRegistry()
    h = reg.histogram("lat", window=2048)
    for lat in latencies:
        h.observe(lat)
    for q, key in ((50, "p50_ms"), (95, "p95_ms"), (99, "p99_ms")):
        assert round(h.percentile(q) * 1e3, 3) == snap[key]
    # and the serving module's percentile() is literally the obs one
    from keystone_tpu.serving import telemetry as serving_telemetry

    assert serving_telemetry.percentile is percentile


def test_snapshot_and_delta():
    reg = MetricsRegistry()
    c = reg.counter("a_total")
    h = reg.histogram("b_seconds")
    c.inc(2)
    before = reg.snapshot()
    c.inc(3)
    h.observe(0.5)
    moved = delta(reg.snapshot(), before)
    assert moved["a_total"] == 3
    assert moved["b_seconds_count"] == 1
    assert moved["b_seconds_sum"] == pytest.approx(0.5)
    assert "untouched" not in moved


def test_schema_registers_cleanly_and_is_documented():
    reg = MetricsRegistry()
    names.register_all(reg)
    registered = set(reg.names())
    assert registered == set(names.ALL_METRIC_NAMES)
    # every name in the stable registry is documented
    import os

    docs = open(
        os.path.join(os.path.dirname(__file__), "..", "..", "docs", "OBSERVABILITY.md")
    ).read()
    missing = [n for n in names.ALL_METRIC_NAMES if n not in docs]
    assert not missing, f"metric names undocumented in docs/OBSERVABILITY.md: {missing}"


def test_autotuning_doc_in_sync_with_tune_surface():
    """docs/AUTOTUNING.md must document every keystone_tune_* /
    blocksparse / knob-rejected metric name and every KEYSTONE_TUNE_*
    env knob the tuner reads — the doc is the operator's contract for
    the search (PR satellite: docs-sync over the new names)."""
    import os
    import re

    root = os.path.join(os.path.dirname(__file__), "..", "..")
    doc = open(os.path.join(root, "docs", "AUTOTUNING.md")).read()
    tune_metrics = [
        n for n in names.ALL_METRIC_NAMES
        if n.startswith(("keystone_tune_", "keystone_blocksparse_"))
        or n == "keystone_knob_rejected_total"
    ]
    assert len(tune_metrics) >= 6
    missing = [n for n in tune_metrics if n not in doc]
    assert not missing, f"undocumented in docs/AUTOTUNING.md: {missing}"
    # every KEYSTONE_TUNE_* knob read by workflow/tune.py is documented
    src = open(
        os.path.join(root, "keystone_tpu", "workflow", "tune.py")
    ).read()
    knobs = set(re.findall(r"KEYSTONE_TUNE_[A-Z_]+", src))
    assert knobs  # the tuner actually reads budget knobs
    undocumented = [k for k in sorted(knobs) if k not in doc]
    assert not undocumented, (
        f"KEYSTONE_TUNE_* knobs undocumented in docs/AUTOTUNING.md: "
        f"{undocumented}"
    )


def test_register_all_idempotent_on_global_registry():
    names.register_all()
    names.register_all()  # second call must not raise or duplicate
    reg = metrics.get_registry()
    for name in names.ALL_METRIC_NAMES:
        assert reg.get(name) is not None
