"""Exporter correctness: Chrome trace JSON round-trips with valid
``ph``/``ts``/``dur``; Prometheus output parses line-by-line; the human
report renders the hierarchy."""

import json
import re

from keystone_tpu.obs import spans
from keystone_tpu.obs.export import chrome_trace, prometheus_text, report
from keystone_tpu.obs.metrics import MetricsRegistry


def _session_with_tree():
    with spans.tracing_session("export-test") as session:
        with spans.span("pipeline"):
            with spans.span("node:featurize", op="Featurize") as sp:
                sp.add_event("checkpoint", digest="abc")
            with spans.span("node:solve"):
                with spans.span("solver:iteration", rung_index=0):
                    pass
    return session


def test_chrome_trace_round_trips_with_valid_fields():
    session = _session_with_tree()
    payload = json.loads(json.dumps(chrome_trace(session)))
    events = payload["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    assert len(complete) == 4
    for e in complete:
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert e["pid"] > 0 and e["tid"] > 0
        assert e["args"]["span_id"]
    instants = [e for e in events if e["ph"] == "i"]
    assert len(instants) == 1 and instants[0]["name"] == "checkpoint"
    metas = [e for e in events if e["ph"] == "M"]
    assert metas and metas[0]["name"] == "thread_name"
    assert payload["otherData"]["trace_id"] == session.trace_id


def test_chrome_trace_children_contained_in_parents():
    session = _session_with_tree()
    events = [e for e in chrome_trace(session)["traceEvents"] if e["ph"] == "X"]
    by_id = {e["args"]["span_id"]: e for e in events}
    for e in events:
        parent = by_id.get(e["args"].get("parent_id"))
        if parent is None:
            continue
        assert parent["ts"] <= e["ts"]
        assert e["ts"] + e["dur"] <= parent["ts"] + parent["dur"] + 1e-3


_PROM_LINE = re.compile(
    r"^(?:# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^}]*\})? -?(?:[0-9.e+-]+|\+Inf|NaN))$"
)


def test_prometheus_output_parses_line_by_line():
    reg = MetricsRegistry()
    c = reg.counter("keystone_test_total", "a counter", ("kind",))
    c.inc(3, kind='we"ird\nlabel')  # escaping must keep the line one line
    g = reg.gauge("keystone_test_bytes", "a gauge")
    g.set(12.5)
    h = reg.histogram("keystone_test_seconds", "a histogram", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    text = prometheus_text(reg)
    lines = text.strip().splitlines()
    for line in lines:
        assert _PROM_LINE.match(line), f"unparseable prometheus line: {line!r}"
    # histogram structure: cumulative buckets, +Inf == count
    buckets = [l for l in lines if l.startswith("keystone_test_seconds_bucket")]
    counts = [int(l.rsplit(" ", 1)[1]) for l in buckets]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    inf_line = [l for l in buckets if 'le="+Inf"' in l]
    count_line = [l for l in lines if l.startswith("keystone_test_seconds_count")]
    assert inf_line[0].rsplit(" ", 1)[1] == count_line[0].rsplit(" ", 1)[1]


def test_prometheus_zero_series_metrics_still_exported():
    reg = MetricsRegistry()
    reg.counter("keystone_idle_total", "never incremented")
    reg.counter("keystone_labeled_total", "no series yet", ("k",))
    text = prometheus_text(reg)
    assert "keystone_idle_total 0" in text
    assert "# TYPE keystone_labeled_total counter" in text


def test_report_renders_hierarchy_and_durations():
    session = _session_with_tree()
    text = report(session)
    assert "pipeline" in text
    assert "  node:featurize" in text  # indented child
    assert "    solver:iteration" in text  # grandchild
    assert "ms" in text.splitlines()[0]


# ------------------------------------------------------- stream chunk slices


def test_stream_report_exports_perfetto_slices():
    """last_stream_report() per-chunk events render as ph:X slices on
    named stream-upload/stream-compute tracks, placed on the session
    timeline, so the double-buffer overlap is visually inspectable."""
    from keystone_tpu.obs.export import chrome_trace
    from keystone_tpu.obs.spans import TraceSession
    from keystone_tpu.workflow.streaming import StreamReport

    session = TraceSession("t")
    report = StreamReport(
        chunks=3, chunk_rows=64, num_examples=192,
        t0_s=session.started_s + 0.5,
        upload_issued_t=[0.0, 0.01, 0.02],
        dispatch_t=[0.005, 0.015, 0.025],
        compute_done_t=[0.012, 0.022, 0.032],
    )
    trace = chrome_trace(session, stream_report=report)
    slices = [e for e in trace["traceEvents"]
              if e.get("cat") == "stream" and e.get("ph") == "X"]
    assert len(slices) == 6  # 3 uploads + 3 computes
    uploads = [e for e in slices if "upload" in e["name"]]
    computes = [e for e in slices if "compute" in e["name"]]
    assert len(uploads) == len(computes) == 3
    # upload slice of chunk 1 starts before compute of chunk 0 ends —
    # the overlap is visible in the timestamps themselves
    assert uploads[1]["ts"] < computes[0]["ts"] + computes[0]["dur"]
    # anchored on the session timeline: chunk 0 upload at ~0.5 s
    assert abs(uploads[0]["ts"] - 0.5e6) < 1e3
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M"}
    assert {"stream-upload", "stream-compute"} <= names


def test_chrome_trace_without_stream_report_unchanged():
    from keystone_tpu.obs.export import chrome_trace
    from keystone_tpu.obs.spans import TraceSession

    trace = chrome_trace(TraceSession("t"))
    assert all(e.get("cat") != "stream" for e in trace["traceEvents"])


# ------------------------------------------------------- per-device payloads


def test_per_device_memory_gauges_and_payload():
    """CPU meshes collapse to one host entry; the gauges carry a device
    label and the dryrun payload is JSON-serializable."""
    import json as _json

    from keystone_tpu.obs import names as obs_names
    from keystone_tpu.obs.device import (
        device_obs_payload, per_device_snapshots, publish_per_device_memory,
    )

    snaps = per_device_snapshots()
    assert snaps, "at least the host fallback entry"
    assert all("device" in s and "bytes_in_use" in s for s in snaps)
    published = publish_per_device_memory(stage="test")
    gauge = obs_names.metric(obs_names.MEMORY_IN_USE_BYTES)
    for snap in published:
        assert gauge.value(
            source=snap["source"], device=snap["device"]
        ) == snap["bytes_in_use"]
    payload = device_obs_payload()
    assert _json.dumps(payload)  # artifact-embeddable
    assert payload["devices"] and "xla_compiles" in payload


def test_failing_device_yields_error_entry_not_omission(monkeypatch):
    """A chip whose memory_stats() raises (the wedged/OOMing one — exactly
    the chip the per-device series exists to expose) must appear as an
    error entry, not vanish from the list. Backends without memory_stats
    (AttributeError) still collapse to the host fallback."""
    import jax

    from keystone_tpu.obs.device import (
        device_obs_payload, per_device_snapshots, publish_per_device_memory,
    )

    class Wedged:
        platform, id = "tpu", 3

        def memory_stats(self):
            raise RuntimeError("RESOURCE_EXHAUSTED: stats unavailable")

    class Healthy:
        platform, id = "tpu", 0

        def memory_stats(self):
            return {"bytes_in_use": 123, "peak_bytes_in_use": 456}

    monkeypatch.setattr(jax, "local_devices", lambda: [Healthy(), Wedged()])
    snaps = per_device_snapshots()
    assert [s["device"] for s in snaps] == ["tpu:0", "tpu:3"]
    assert snaps[1]["source"] == "error"
    assert "RESOURCE_EXHAUSTED" in snaps[1]["error"]
    # publishing skips the error entry (no bytes) without raising
    published = publish_per_device_memory(stage="test")
    assert len(published) == 2
    # the payload reuses a passed snapshot instead of re-walking devices
    payload = device_obs_payload(snapshots=snaps)
    assert payload["devices"] is snaps


def test_cost_ledger_counter_track():
    """Perf-ledger entries export as ph:C counter events on a named
    cost-ledger track (docs/OBSERVABILITY.md "Cost observatory")."""
    import pytest

    from keystone_tpu.obs import cost
    from keystone_tpu.obs.export import chrome_trace, cost_ledger_events

    entries = [
        cost.PerfLedgerEntry(
            node="n0", seconds=0.01, synced=True, t_s=100.5, t_unix=0.0,
            flops_per_s=2e9, bytes_per_s=1e9, ratio=1.5,
        ),
        cost.PerfLedgerEntry(  # nothing measurable: no counter sample
            node="n1", seconds=0.01, synced=False, t_s=100.6, t_unix=0.0,
        ),
    ]
    events = cost_ledger_events(entries, base_s=100.0, pid=42)
    counters = [e for e in events if e.get("ph") == "C"]
    assert len(counters) == 1
    c = counters[0]
    assert c["ts"] == pytest.approx(0.5e6, rel=1e-3)
    assert c["args"]["gflops_per_s"] == pytest.approx(2.0)
    assert c["args"]["gbytes_per_s"] == pytest.approx(1.0)
    assert c["args"]["measured_vs_predicted"] == 1.5
    # the track is named for Perfetto
    assert any(
        e.get("ph") == "M" and e["args"]["name"] == "cost-ledger"
        for e in events
    )
    # and chrome_trace threads it through end to end
    with spans.tracing_session("t") as session:
        with spans.span("x"):
            pass
    trace = chrome_trace(session, cost_ledger=entries)
    assert any(e.get("ph") == "C" for e in trace["traceEvents"])
