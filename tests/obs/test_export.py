"""Exporter correctness: Chrome trace JSON round-trips with valid
``ph``/``ts``/``dur``; Prometheus output parses line-by-line; the human
report renders the hierarchy."""

import json
import re

from keystone_tpu.obs import spans
from keystone_tpu.obs.export import chrome_trace, prometheus_text, report
from keystone_tpu.obs.metrics import MetricsRegistry


def _session_with_tree():
    with spans.tracing_session("export-test") as session:
        with spans.span("pipeline"):
            with spans.span("node:featurize", op="Featurize") as sp:
                sp.add_event("checkpoint", digest="abc")
            with spans.span("node:solve"):
                with spans.span("solver:iteration", rung_index=0):
                    pass
    return session


def test_chrome_trace_round_trips_with_valid_fields():
    session = _session_with_tree()
    payload = json.loads(json.dumps(chrome_trace(session)))
    events = payload["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    assert len(complete) == 4
    for e in complete:
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert e["pid"] > 0 and e["tid"] > 0
        assert e["args"]["span_id"]
    instants = [e for e in events if e["ph"] == "i"]
    assert len(instants) == 1 and instants[0]["name"] == "checkpoint"
    metas = [e for e in events if e["ph"] == "M"]
    assert metas and metas[0]["name"] == "thread_name"
    assert payload["otherData"]["trace_id"] == session.trace_id


def test_chrome_trace_children_contained_in_parents():
    session = _session_with_tree()
    events = [e for e in chrome_trace(session)["traceEvents"] if e["ph"] == "X"]
    by_id = {e["args"]["span_id"]: e for e in events}
    for e in events:
        parent = by_id.get(e["args"].get("parent_id"))
        if parent is None:
            continue
        assert parent["ts"] <= e["ts"]
        assert e["ts"] + e["dur"] <= parent["ts"] + parent["dur"] + 1e-3


_PROM_LINE = re.compile(
    r"^(?:# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^}]*\})? -?(?:[0-9.e+-]+|\+Inf|NaN))$"
)


def test_prometheus_output_parses_line_by_line():
    reg = MetricsRegistry()
    c = reg.counter("keystone_test_total", "a counter", ("kind",))
    c.inc(3, kind='we"ird\nlabel')  # escaping must keep the line one line
    g = reg.gauge("keystone_test_bytes", "a gauge")
    g.set(12.5)
    h = reg.histogram("keystone_test_seconds", "a histogram", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    text = prometheus_text(reg)
    lines = text.strip().splitlines()
    for line in lines:
        assert _PROM_LINE.match(line), f"unparseable prometheus line: {line!r}"
    # histogram structure: cumulative buckets, +Inf == count
    buckets = [l for l in lines if l.startswith("keystone_test_seconds_bucket")]
    counts = [int(l.rsplit(" ", 1)[1]) for l in buckets]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    inf_line = [l for l in buckets if 'le="+Inf"' in l]
    count_line = [l for l in lines if l.startswith("keystone_test_seconds_count")]
    assert inf_line[0].rsplit(" ", 1)[1] == count_line[0].rsplit(" ", 1)[1]


def test_prometheus_zero_series_metrics_still_exported():
    reg = MetricsRegistry()
    reg.counter("keystone_idle_total", "never incremented")
    reg.counter("keystone_labeled_total", "no series yet", ("k",))
    text = prometheus_text(reg)
    assert "keystone_idle_total 0" in text
    assert "# TYPE keystone_labeled_total counter" in text


def test_report_renders_hierarchy_and_durations():
    session = _session_with_tree()
    text = report(session)
    assert "pipeline" in text
    assert "  node:featurize" in text  # indented child
    assert "    solver:iteration" in text  # grandchild
    assert "ms" in text.splitlines()[0]
