"""Fleet aggregation (obs/fleet.py): span fragments, the collector's
merge + incarnation-safe metric folding, and the /metrics exposition."""

import json
import time

import pytest

from keystone_tpu.obs import spans
from keystone_tpu.obs.fleet import (
    FleetTraceCollector,
    drain_fragments,
    fleet_prometheus_text,
    span_fragment,
)


def _fragment(name, trace_id, span_id, parent=None, start=100.0, end=100.01,
              tid=1, tn="main"):
    out = {"n": name, "t": trace_id, "s": span_id, "a": start, "b": end,
           "tid": tid, "tn": tn}
    if parent:
        out["p"] = parent
    return out


def test_span_fragment_absolute_times_and_shape():
    with spans.tracing_session("t") as session:
        with spans.span("outer", model="m") as outer:
            with spans.span("inner"):
                time.sleep(0.01)
        before, after = session.started_unix, time.time()
    inner, outer_f = [span_fragment(s, session) for s in session.spans()]
    assert outer_f["n"] == "outer"
    assert inner["p"] == outer_f["s"]
    assert inner["t"] == outer_f["t"] == session.trace_id
    # absolute unix timestamps inside the session's wall window
    for f in (inner, outer_f):
        assert before - 1 <= f["a"] <= f["b"] <= after + 1
    assert inner["b"] - inner["a"] >= 0.008
    assert outer_f["at"] == {"model": "m"}


def test_drain_fragments_cursor_ships_once_and_bounds():
    with spans.tracing_session("t") as session:
        for i in range(10):
            with spans.span(f"s{i}"):
                pass
        frags, cursor = drain_fragments(session, 0, limit=4)
        assert [f["n"] for f in frags] == ["s0", "s1", "s2", "s3"]
        frags, cursor = drain_fragments(session, cursor, limit=100)
        assert [f["n"] for f in frags] == [f"s{i}" for i in range(4, 10)]
        frags, cursor = drain_fragments(session, cursor)
        assert frags == [] and cursor == 10


def test_collector_merge_spans_processes_single_trace_id():
    collector = FleetTraceCollector()
    t = "aaaa0000aaaa0000"
    collector.add_fragments(
        "worker0", 101, [_fragment("worker:request", t, "s1", parent="d1")]
    )
    collector.add_fragments(
        "worker1", 102, [_fragment("worker:request", t, "s2", parent="d2")]
    )
    with spans.tracing_session("local") as session:
        with spans.span("http:apply"):
            pass
    merged = collector.merge(local_session=session, local_role="frontend")
    slices = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in slices} == {101, 102} | {
        e["pid"] for e in slices if e["name"] == "http:apply"
    }
    assert len({e["pid"] for e in slices}) == 3
    # worker fragments keep their shipped trace id; process tracks named
    metas = {
        (e["pid"], e["args"]["name"])
        for e in merged["traceEvents"] if e["name"] == "process_name"
    }
    roles = dict(metas)
    assert roles[101] == "worker0" and roles[102] == "worker1"
    assert "frontend" in roles.values()
    assert t in merged["otherData"]["trace_ids"]
    # normalized timestamps: everything >= 0
    assert all(e["ts"] >= 0 for e in slices)


def test_collector_clock_skew_published():
    collector = FleetTraceCollector()
    collector.observe_clock(
        "worker0", 101, {"unix": time.time() - 0.5, "perf": 1.0}
    )
    anchors = collector.clocks()[("worker0", 101)]
    assert 0.4 <= anchors["skew_s"] <= 2.0


def test_metric_deltas_fold_monotonically_across_incarnations():
    collector = FleetTraceCollector()
    collector.observe_metrics("0", 0, {"keystone_serving_requests_total": 5})
    collector.observe_metrics("0", 0, {"keystone_serving_requests_total": 3})
    assert collector.metric_totals()["keystone_serving_requests_total"] == 8
    # incarnation 1: the worker's registry restarted from zero — the
    # fleet total must NOT dip
    collector.observe_metrics("0", 1, {"keystone_serving_requests_total": 2})
    assert collector.metric_totals()["keystone_serving_requests_total"] == 10
    collector.observe_metrics("1", 0, {"keystone_serving_requests_total": 4})
    assert collector.metric_totals()["keystone_serving_requests_total"] == 14


def test_fragment_retention_bound_drops_oldest():
    import keystone_tpu.obs.fleet as fleet_mod

    collector = FleetTraceCollector()
    bound = fleet_mod.MAX_FRAGMENTS_PER_PROCESS
    batch = [_fragment(f"s{i}", "t0", f"id{i}") for i in range(200)]
    for _ in range((bound // 200) + 2):
        collector.add_fragments("worker0", 101, list(batch))
    kept = collector.fragments()[("worker0", 101)]
    assert len(kept) == bound
    assert collector.merge()["otherData"]["dropped_fragments"] > 0


class _FakeSupervisor:
    def fleet_counter_totals(self):
        return {
            "0": {"served": 12.0, "failures": 1.0},
            "1": {"served": 7.0, "failures": 0.0},
        }


def _series_value(text, prefix):
    for line in text.splitlines():
        if line.startswith(prefix):
            return float(line.rsplit(" ", 1)[1])
    raise AssertionError(f"{prefix} not in exposition")


def test_fleet_prometheus_text_counters_and_families():
    # The exposition publishes into the process-wide registry, so assert
    # high-water semantics (raised to at least the supervisor's totals,
    # never dipping) rather than exact values — other tests may have
    # published these series already.
    text = fleet_prometheus_text(_FakeSupervisor())
    assert text.count("# HELP") >= 5
    served0 = _series_value(text, 'keystone_fleet_requests_total{worker="0"}')
    served1 = _series_value(text, 'keystone_fleet_requests_total{worker="1"}')
    failures0 = _series_value(text, 'keystone_fleet_failures_total{worker="0"}')
    assert served0 >= 12 and served1 >= 7 and failures0 >= 1
    # monotonic: a second exposition over the same totals never dips
    text2 = fleet_prometheus_text(_FakeSupervisor())
    assert _series_value(
        text2, 'keystone_fleet_requests_total{worker="0"}'
    ) == served0


def test_drain_fragments_cursor_survives_ring_eviction():
    """A ring session outrunning the heartbeat skips evicted spans —
    never re-ships, never double-ships, never goes dark."""
    session = spans.TraceSession("w", max_spans=4, ring=True)
    spans._session = session
    try:
        for i in range(3):
            with spans.span(f"a{i}"):
                pass
        frags, cursor = drain_fragments(session, 0, limit=10)
        assert [f["n"] for f in frags] == ["a0", "a1", "a2"]
        # 6 more spans: the ring (cap 4) evicts a0..a4 — two of the
        # unshipped ones (b0, b1) are lost to eviction
        for i in range(6):
            with spans.span(f"b{i}"):
                pass
        frags, cursor = drain_fragments(session, cursor, limit=10)
        assert [f["n"] for f in frags] == ["b2", "b3", "b4", "b5"]
        frags, cursor = drain_fragments(session, cursor, limit=10)
        assert frags == []
    finally:
        spans._session = None


class _FakeCollectorSupervisor(_FakeSupervisor):
    class fleet:
        @staticmethod
        def metric_totals():
            return {"keystone_serving_retries_total": 7.0}


def test_worker_metric_deltas_surface_in_exposition():
    """The heartbeat-shipped metric deltas are CONSUMED: they surface as
    the keystone_fleet_worker_series gauge family in /metrics."""
    text = fleet_prometheus_text(_FakeCollectorSupervisor())
    assert _series_value(
        text,
        'keystone_fleet_worker_series{series="keystone_serving_retries_total"}',
    ) == 7.0
