"""CLI tests (the scopt-parse analog of each workload's config parsing,
reference: e.g. RandomPatchCifar.scala:101-114)."""

import json

import pytest

from keystone_tpu.cli import add_config_arguments, build_config, main


def test_list_workloads(capsys):
    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in (
        "mnist-random-fft",
        "timit",
        "voc-sift-fisher",
        "imagenet-sift-lcs-fv",
        "cifar-random-patch",
        "amazon-reviews",
        "newsgroups",
        "stupid-backoff",
    ):
        assert name in out


def test_dataclass_flag_generation():
    import argparse

    from keystone_tpu.pipelines.voc import SIFTFisherConfig

    parser = argparse.ArgumentParser()
    add_config_arguments(parser, SIFTFisherConfig)
    args = parser.parse_args(
        ["--desc-dim", "16", "--reg", "0.25", "--image-size", "64,48"]
    )
    config = build_config(SIFTFisherConfig, args)
    assert config.desc_dim == 16
    assert config.reg == 0.25
    assert config.image_size == (64, 48)
    assert config.vocab_size == 256  # untouched default


def test_run_mnist_synthetic_through_cli(capsys):
    # no train CSV → the workload generates synthetic data
    rc = main(["mnist-random-fft", "--num-ffts", "2", "--block-size", "512"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["workload"] == "mnist-random-fft"
    assert 0.0 <= payload["train_error"] <= 1.0


def test_printable_results_handles_arrays():
    """Scalars → float, small arrays → list, large arrays dropped — the
    per-class-AP crash fix (a (20,) ndarray must not hit float())."""
    import json

    import numpy as np

    from keystone_tpu.cli import printable_results

    out = printable_results(
        {
            "err": 0.5,
            "name": "voc",
            "scalar_arr": np.float32(1.5),
            "zero_d": np.asarray(2.0),
            "per_class_ap": np.linspace(0, 1, 20),
            "huge": np.zeros((1000,)),
            "obj": object(),
        }
    )
    assert out["err"] == 0.5 and out["name"] == "voc"
    assert out["scalar_arr"] == 1.5 and out["zero_d"] == 2.0
    assert isinstance(out["per_class_ap"], list) and len(out["per_class_ap"]) == 20
    assert "huge" not in out and "obj" not in out
    json.dumps(out)  # round-trips


def test_packaging_console_entry_point_resolves():
    """r4 verdict item 6: the installable build's console script must
    point at a callable (`pip install -e .` → `keystone-tpu <workload>`;
    reference analog: build.sbt:1-45 published artifact)."""
    import importlib
    import os

    try:
        import tomllib  # 3.11+ stdlib
    except ModuleNotFoundError:  # 3.10: same API under the backport name
        import tomli as tomllib

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "pyproject.toml"), "rb") as f:
        cfg = tomllib.load(f)
    target = cfg["project"]["scripts"]["keystone-tpu"]
    mod, fn = target.split(":")
    assert callable(getattr(importlib.import_module(mod), fn))
    # The native kernels and cost constants must ship with the wheel.
    pkg_data = cfg["tool"]["setuptools"]["package-data"]
    assert "src/*.cpp" in pkg_data["keystone_tpu.native"]
    assert "tpu_cost_constants.json" in pkg_data["keystone_tpu.ops.learning"]


def test_cli_distributed_hook_calls_init_before_workload(monkeypatch, capsys):
    """KEYSTONE_DISTRIBUTED=1 (what bin/launch-pod.sh exports) must make
    the CLI call distributed_init BEFORE the workload runs — on a real
    pod, touching devices before joining the distributed runtime is the
    regression this pins, so the ORDER is asserted, not just the call."""
    from keystone_tpu.parallel import mesh as mesh_mod
    from keystone_tpu.pipelines import mnist_random_fft as wl_mod

    order = []
    monkeypatch.setattr(mesh_mod, "distributed_init",
                        lambda *a, **k: order.append("init"))
    monkeypatch.setattr(wl_mod, "run",
                        lambda config: order.append("workload") or {})
    monkeypatch.setenv("KEYSTONE_DISTRIBUTED", "1")
    rc = main(["mnist-random-fft", "--num-ffts", "1", "--block-size", "256"])
    assert rc == 0 and order == ["init", "workload"]
    capsys.readouterr()


def test_launch_pod_rehearse_smoke():
    """bin/launch-pod.sh --rehearse resolves the rehearsal script with the
    installed-vs-source import fallback (argparse --help exits 0 without
    touching any backend)."""
    import os
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    proc = subprocess.run(
        [os.path.join(repo, "bin", "launch-pod.sh"), "--rehearse", "--help"],
        capture_output=True, text=True, timeout=120, env=env, cwd="/tmp",
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    assert "coordinator" in proc.stdout
