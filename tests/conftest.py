"""Test configuration: virtual 8-device CPU mesh + per-test env reset.

Mirrors the reference's test strategy of standing in for a cluster with
local-mode partitions (reference: src/test/scala/keystoneml/workflow/
PipelineContext.scala:9-25): here, N virtual CPU devices via
``--xla_force_host_platform_device_count`` stand in for a TPU slice, and
the process-wide PipelineEnv is reset after every test.
"""

import os
import tempfile

# Must run before any backend is touched. The session may preset
# JAX_PLATFORMS to a TPU platform and pre-import jax via sitecustomize, so
# set the config post-import too: tests always use the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"

# Isolate the persistent profile store per test session: tests must never
# warm-start from (or pollute) the developer's ~/.cache store — a warm
# store changes which tests sample-profile. Tests that need their own
# store monkeypatch KEYSTONE_PROFILE_STORE further.
os.environ["KEYSTONE_PROFILE_STORE"] = os.path.join(
    tempfile.mkdtemp(prefix="keystone-test-profile-store-"),
    "profile-store.jsonl",
)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_pipeline_env():
    from keystone_tpu.workflow.executor import PipelineEnv

    PipelineEnv.reset()
    yield
    PipelineEnv.reset()
