"""Test configuration: virtual 8-device CPU mesh + per-test env reset.

Mirrors the reference's test strategy of standing in for a cluster with
local-mode partitions (reference: src/test/scala/keystoneml/workflow/
PipelineContext.scala:9-25): here, N virtual CPU devices via
``--xla_force_host_platform_device_count`` stand in for a TPU slice, and
the process-wide PipelineEnv is reset after every test.
"""

import os
import tempfile

# Must run before any backend is touched. The session may preset
# JAX_PLATFORMS to a TPU platform and pre-import jax via sitecustomize, so
# set the config post-import too: tests always use the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"

# Isolate the persistent profile store per test session: tests must never
# warm-start from (or pollute) the developer's ~/.cache store — a warm
# store changes which tests sample-profile. Tests that need their own
# store monkeypatch KEYSTONE_PROFILE_STORE further.
os.environ["KEYSTONE_PROFILE_STORE"] = os.path.join(
    tempfile.mkdtemp(prefix="keystone-test-profile-store-"),
    "profile-store.jsonl",
)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_pipeline_env():
    from keystone_tpu.workflow.executor import PipelineEnv

    PipelineEnv.reset()
    yield
    PipelineEnv.reset()


# --------------------------------------------------------------- lock witness
#
# KEYSTONE_LOCK_WITNESS=1 wraps every test in the instrumented-lock
# witness (keystone_tpu/lint/lockwitness.py): locks the test constructs
# record their acquisition orders, and an observed edge between two
# model-known locks that is ABSENT from the static lock-order graph
# fails the test — the static model and the runtime cannot drift.
# KEYSTONE_LOCK_WITNESS=record only records (used to regenerate
# lint/lockorder_baseline.json); KEYSTONE_LOCK_WITNESS_OUT appends each
# test's observed edges as JSON lines for the baseline merge.

_witness_model = None


def _witness_static():
    global _witness_model
    if _witness_model is None:
        import keystone_tpu
        from keystone_tpu.lint.lockmodel import build_model

        _witness_model = build_model([os.path.dirname(keystone_tpu.__file__)])
    return _witness_model


@pytest.fixture(autouse=True)
def _lock_witness_fixture(request):
    from keystone_tpu.lint.lockwitness import witness_enabled

    if not witness_enabled():
        yield
        return
    import json

    from keystone_tpu.lint.lockwitness import lock_witness, witness_mode

    model = _witness_static()
    with lock_witness(site_names=model.alloc_sites()) as witness:
        yield
    observed = witness.observed_edges()
    out_path = os.environ.get("KEYSTONE_LOCK_WITNESS_OUT")
    if out_path and observed:
        with open(out_path, "a") as fh:
            fh.write(
                json.dumps(
                    {
                        "test": request.node.nodeid,
                        "edges": sorted(list(e) for e in observed),
                    }
                )
                + "\n"
            )
    if witness_mode() == "check":
        unknown = witness.unknown_edges(model.edge_pairs())
        assert not unknown, (
            "lock witness observed acquisition edges missing from the "
            f"static lock-order graph: {unknown} — extend the model "
            "(lint/lockmodel.py) or fix the locking"
        )
