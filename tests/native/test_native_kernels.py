"""Native C++ kernel tests: dense SIFT, GMM EM, Fisher encode, JPEG ingest.

Mirrors the reference's native-kernel suites (reference:
utils/external/VLFeatSuite.scala:34-52 — SIFT checked against an
independent implementation with a "99.5% of entries within 1" tolerance —
and utils/external/EncEvalSuite.scala). Here the independent
implementation is the framework's own XLA path, so native-vs-XLA parity is
the test.
"""

import io

import numpy as np
import pytest

from keystone_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(auto_build=True),
    reason="native library not built and toolchain unavailable",
)


# ------------------------------------------------------------------- SIFT


def test_native_sift_matches_xla():
    from keystone_tpu.ops.images.external.sift import NativeSIFTExtractor
    from keystone_tpu.ops.images.sift import SIFTExtractor

    rng = np.random.default_rng(0)
    imgs = rng.random((2, 48, 40), dtype=np.float32)
    kwargs = dict(step_size=4, bin_size=4, scales=2, scale_step=1)
    ref = np.asarray(SIFTExtractor(**kwargs).apply_arrays(imgs))
    out = NativeSIFTExtractor(**kwargs)._extract(imgs)
    assert out.shape == ref.shape
    # same tolerance style as the reference's VLFeat-vs-MATLAB check:
    # quantized descriptors, overwhelming majority of entries within 1
    close = np.abs(out - ref) <= 1.0
    assert close.mean() > 0.995


def test_native_sift_apply_batch_dataset():
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.images.external.sift import NativeSIFTExtractor

    rng = np.random.default_rng(1)
    imgs = rng.random((3, 48, 48, 1), dtype=np.float32)
    ext = NativeSIFTExtractor(step_size=4, bin_size=4, scales=1)
    out = ext.apply_batch(ArrayDataset(imgs))
    assert out.data.shape[0] == 3 and out.data.shape[2] == 128


# -------------------------------------------------------------------- GMM


def test_native_gmm_recovers_clusters():
    from keystone_tpu.ops.images.external.fisher import native_gmm_fit

    rng = np.random.default_rng(2)
    centers = np.array([[0.0, 0.0], [8.0, 8.0], [-8.0, 8.0]], np.float32)
    x = np.concatenate(
        [c + 0.3 * rng.standard_normal((200, 2)).astype(np.float32) for c in centers]
    )
    gmm = native_gmm_fit(x, k=3, seed=0)
    means = np.asarray(gmm.means).T  # (k, d)
    # every true center is recovered by some component
    for c in centers:
        assert np.min(np.linalg.norm(means - c, axis=1)) < 0.5
    np.testing.assert_allclose(np.asarray(gmm.weights).sum(), 1.0, atol=1e-4)


def test_native_fisher_matches_xla():
    from keystone_tpu.ops.images.external.fisher import NativeFisherVector
    from keystone_tpu.ops.images.fisher import FisherVector
    from keystone_tpu.ops.learning.gmm import GaussianMixtureModel

    rng = np.random.default_rng(3)
    d, k = 6, 4
    gmm = GaussianMixtureModel(
        means=rng.standard_normal((d, k)).astype(np.float32),
        variances=(0.5 + rng.random((d, k))).astype(np.float32),
        weights=np.full(k, 1.0 / k, np.float32),
    )
    x = rng.standard_normal((5, 30, d)).astype(np.float32)
    ref = np.asarray(FisherVector(gmm).apply_arrays(x))
    out = np.stack([NativeFisherVector(gmm).apply(m) for m in x])
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)


# ------------------------------------------------------------------ decode


def _jpeg_bytes(arr):
    from PIL import Image as PILImage

    img = PILImage.fromarray(arr, "RGB")
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=95)
    return buf.getvalue()


def test_native_jpeg_decode_matches_pil():
    pytest.importorskip("PIL")
    from keystone_tpu.data.loaders.archive import native_decode_batch
    from keystone_tpu.utils.image import load_image

    rng = np.random.default_rng(4)
    arrs = [
        rng.integers(0, 256, size=(32, 40, 3), dtype=np.uint8) for _ in range(3)
    ]
    raw = [_jpeg_bytes(a) for a in arrs]
    out, ok = native_decode_batch(raw + [b"not a jpeg"], resize=(32, 40))
    assert ok.tolist() == [True, True, True, False]
    for i, b in enumerate(raw):
        ref = load_image(b)  # PIL path, BGR (X=rows, Y=cols, C)
        assert out[i].shape == ref.shape
        # identical size → no resampling; decoders may differ by DCT rounding
        assert np.mean(np.abs(out[i] - ref)) < 1.5


def test_native_jpeg_resize_sane():
    pytest.importorskip("PIL")
    from keystone_tpu.data.loaders.archive import native_decode_batch

    solid = np.full((64, 48, 3), 128, dtype=np.uint8)
    solid[:, :, 0] = 200  # R=200 G=128 B=128
    out, ok = native_decode_batch([_jpeg_bytes(solid)], resize=(16, 16))
    assert ok[0]
    # BGR order: channel 2 is red
    assert abs(float(out[0][..., 2].mean()) - 200.0) < 6.0
    assert abs(float(out[0][..., 0].mean()) - 128.0) < 6.0


def test_loader_native_path_matches_pil_path(tmp_path):
    pytest.importorskip("PIL")
    import tarfile

    from keystone_tpu.data.loaders.archive import load_image_archives

    rng = np.random.default_rng(5)
    tar_path = tmp_path / "imgs.tar"
    with tarfile.open(tar_path, "w") as tar:
        for i in range(4):
            payload = _jpeg_bytes(
                rng.integers(0, 256, size=(40, 40, 3), dtype=np.uint8)
            )
            info = tarfile.TarInfo(f"cls/img{i}.jpg")
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))

    kwargs = dict(label_fn=lambda name: 0, resize=(24, 24))
    ds_native = load_image_archives(str(tar_path), use_native=True, **kwargs)
    ds_pil = load_image_archives(str(tar_path), use_native=False, **kwargs)
    assert len(ds_native) == len(ds_pil) == 4
    for a, b in zip(ds_native.collect(), ds_pil.collect()):
        assert a["filename"] == b["filename"]
        assert a["image"].shape == b["image"].shape
        # different resamplers (point-bilinear vs PIL filter): loose bound
        assert np.mean(np.abs(a["image"] - b["image"])) < 20.0


def test_native_jpeg_scaled_decode_matches_pil_resize():
    """Targets ≤ source/2 take the DCT-domain scaled-decode path
    (decode.cpp decode_rgb min_x/min_y): output must still track a
    full-decode + resize reference on smooth content."""
    pytest.importorskip("PIL")
    import io

    from PIL import Image as PILImage

    from keystone_tpu.data.loaders.archive import native_decode_batch

    # smooth gradient: decoder-scaling differences show as small shifts,
    # not structural error. Asymmetric outer product → three DISTINCT
    # channels, so a BGR/RGB channel-order regression fails the check.
    x = np.linspace(0, 255, 320)
    arr = np.clip(np.add.outer(x, 2 * x) / 3, 0, 255).astype(np.uint8)
    arr = np.stack([arr, arr[::-1], arr.T], axis=-1)
    raw = _jpeg_bytes(arr)

    out, ok = native_decode_batch([raw], resize=(64, 64))  # 320/64 -> denom 4
    assert ok[0]
    ref = PILImage.open(io.BytesIO(raw)).convert("RGB").resize(
        (64, 64), PILImage.BILINEAR
    )
    ref_bgr = np.asarray(ref, np.float32)[..., ::-1]
    assert np.mean(np.abs(out[0] - ref_bgr)) < 3.0, np.mean(np.abs(out[0] - ref_bgr))
