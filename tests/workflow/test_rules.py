"""Optimizer rule tests (reference: workflow/EquivalentNodeMergeRule,
UnusedBranchRemovalRule, SavedStateLoadRule suites)."""

from keystone_tpu.data.dataset import ObjectDataset
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.operators import DatasetOperator, Expression, ExpressionOperator
from keystone_tpu.workflow.rules import (
    EquivalentNodeMergeRule,
    SavedStateLoadRule,
    UnusedBranchRemovalRule,
)
from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.prefix import find_prefix
from tests.workflow.test_graph import Op


def test_cse_merges_equal_nodes():
    op = Op("same")  # same instance → equal
    g = Graph()
    g, src = g.add_source()
    g, a = g.add_node(op, [src])
    g, b = g.add_node(op, [src])
    g, s1 = g.add_sink(a)
    g, s2 = g.add_sink(b)
    merged, _ = EquivalentNodeMergeRule().apply(g, {})
    assert len(merged.nodes) == 1
    assert merged.get_sink_dependency(s1) == merged.get_sink_dependency(s2)


def test_cse_merges_chains_to_fixed_point():
    op1, op2 = Op("x"), Op("y")
    g = Graph()
    g, src = g.add_source()
    g, a1 = g.add_node(op1, [src])
    g, a2 = g.add_node(op1, [src])
    g, b1 = g.add_node(op2, [a1])
    g, b2 = g.add_node(op2, [a2])
    g, s1 = g.add_sink(b1)
    g, s2 = g.add_sink(b2)
    merged, _ = EquivalentNodeMergeRule().apply(g, {})
    assert len(merged.nodes) == 2


def test_cse_does_not_merge_different_ops():
    g = Graph()
    g, src = g.add_source()
    g, a = g.add_node(Op("x"), [src])
    g, b = g.add_node(Op("x"), [src])  # different instances: not equal
    g, s1 = g.add_sink(a)
    g, s2 = g.add_sink(b)
    merged, _ = EquivalentNodeMergeRule().apply(g, {})
    assert len(merged.nodes) == 2


def test_unused_branch_removal():
    g = Graph()
    g, src = g.add_source()
    g, a = g.add_node(Op("live"), [src])
    g, dead1 = g.add_node(Op("dead1"), [src])
    g, dead2 = g.add_node(Op("dead2"), [dead1])
    g, sink = g.add_sink(a)
    pruned, _ = UnusedBranchRemovalRule().apply(g, {})
    assert pruned.nodes == {a}


def test_prefix_none_with_unbound_source():
    g = Graph()
    g, src = g.add_source()
    g, a = g.add_node(Op("a"), [src])
    assert find_prefix(g, a) is None


def test_prefix_equality_across_graphs():
    op = Op("a")
    ds = ObjectDataset([1, 2])
    dop1, dop2 = DatasetOperator(ds), DatasetOperator(ds)

    g1 = Graph()
    g1, d1 = g1.add_node(dop1, [])
    g1, a1 = g1.add_node(op, [d1])

    g2 = Graph()
    g2, d2 = g2.add_node(dop2, [])
    g2, a2 = g2.add_node(op, [d2])

    assert find_prefix(g1, a1) == find_prefix(g2, a2)


def test_saved_state_load_splices_expression():
    op = Op("a")
    ds = ObjectDataset([1, 2])
    g = Graph()
    g, d = g.add_node(DatasetOperator(ds), [])
    g, a = g.add_node(op, [d])
    g, sink = g.add_sink(a)
    prefix = find_prefix(g, a)

    stored = Expression.of("stored-result")
    PipelineEnv.get_or_create().state[prefix] = stored
    new_graph, prefixes = SavedStateLoadRule().apply(g, {a: prefix})
    assert isinstance(new_graph.get_operator(a), ExpressionOperator)
    assert new_graph.get_dependencies(a) == ()
    assert a not in prefixes
