"""Pipeline semantics: laziness, chaining, fit-once, gather, FittedPipeline.

Mirrors the reference's behavioral contract
(reference: workflow/PipelineSuite.scala:28-52 "Do not fit estimators
multiple times", EstimatorSuite.scala, LabelEstimatorSuite.scala).
"""

import numpy as np

from keystone_tpu.data.dataset import ObjectDataset
from keystone_tpu.workflow import (
    Estimator,
    FittedPipeline,
    Identity,
    LabelEstimator,
    Pipeline,
    Transformer,
)


class Plus(Transformer):
    def __init__(self, k):
        self.k = k

    def apply(self, x):
        return x + self.k


class CountingEstimator(Estimator):
    """Fits a transformer adding the dataset mean; counts fit calls."""

    def __init__(self):
        self.fit_count = 0

    def fit(self, data):
        self.fit_count += 1
        mean = float(np.mean(data.collect()))
        return Plus(mean)


class CountingLabelEstimator(LabelEstimator):
    def __init__(self):
        self.fit_count = 0

    def fit(self, data, labels):
        self.fit_count += 1
        offset = float(np.mean(labels.collect())) - float(np.mean(data.collect()))
        return Plus(offset)


def test_transformer_single_and_batch():
    t = Plus(2)
    assert t(3) == 5
    out = t(ObjectDataset([1, 2, 3])).get()
    assert out.collect() == [3, 4, 5]


def test_chaining():
    pipe = Plus(1) >> Plus(10)
    assert pipe(1).get() == 12
    assert pipe(ObjectDataset([0, 5])).get().collect() == [11, 16]


def test_estimator_with_data():
    est = CountingEstimator()
    data = ObjectDataset([1.0, 2.0, 3.0])  # mean 2
    pipe = est.with_data(data)
    assert pipe(10.0).get() == 12.0
    assert est.fit_count == 1


def test_laziness_no_fit_until_forced():
    est = CountingEstimator()
    pipe = est.with_data(ObjectDataset([1.0, 3.0]))
    result = pipe(0.0)
    assert est.fit_count == 0  # nothing forced yet
    result.get()
    assert est.fit_count == 1


def test_fit_once_across_applications():
    """reference: PipelineSuite.scala:28-52"""
    est = CountingEstimator()
    pipe = est.with_data(ObjectDataset([2.0, 4.0]))  # mean 3
    assert pipe(1.0).get() == 4.0
    assert pipe(2.0).get() == 5.0
    assert pipe(ObjectDataset([0.0])).get().collect() == [3.0]
    assert est.fit_count == 1


def test_then_estimator():
    est = CountingEstimator()
    data = ObjectDataset([0.0, 2.0])
    pipe = Plus(1).then_estimator(est, data)  # est fits on [1,3]: mean 2
    assert pipe(0.0).get() == 3.0  # 0 +1 +2
    assert est.fit_count == 1


def test_then_label_estimator():
    lest = CountingLabelEstimator()
    data = ObjectDataset([1.0, 3.0])    # mean 2 after Plus(0)=identity path
    labels = ObjectDataset([11.0, 13.0])  # mean 12 -> offset 10
    pipe = Identity().then_label_estimator(lest, data, labels)
    assert pipe(5.0).get() == 15.0
    assert lest.fit_count == 1


def test_gather():
    pipe = Pipeline.gather([Plus(1), Plus(2), Plus(3)])
    assert pipe(10).get() == [11, 12, 13]
    batch = pipe(ObjectDataset([0, 10])).get().collect()
    assert batch == [[1, 2, 3], [11, 12, 13]]


def test_fit_produces_estimator_free_pipeline(tmp_path):
    est = CountingEstimator()
    pipe = Plus(1) >> est.with_data(ObjectDataset([2.0, 4.0]))  # mean 3
    fitted = pipe.fit()
    assert isinstance(fitted, FittedPipeline)
    assert est.fit_count == 1
    assert fitted.apply(0.0) == 4.0
    # fitting again or applying repeatedly never re-fits
    assert fitted.apply(1.0) == 5.0
    assert est.fit_count == 1
    # round-trips through pickle
    path = str(tmp_path / "pipe.pkl")
    fitted.save(path)
    loaded = FittedPipeline.load(path)
    assert loaded.apply(0.0) == 4.0


def test_fitted_pipeline_composes():
    est = CountingEstimator()
    # est fits on the raw bound data [0.0] (mean 0); the upstream Plus(1)
    # only feeds the apply-time path.
    fitted = (Plus(1) >> est.with_data(ObjectDataset([0.0]))).fit()
    pipe2 = fitted >> Plus(100)
    assert pipe2(0.0).get() == 101.0


def test_pipeline_tracing_records_per_op_timings():
    import numpy as np

    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.ops.stats.core import LinearRectifier, NormalizeRows
    from keystone_tpu.workflow.tracing import trace

    ds = ArrayDataset(np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32))
    pipeline = LinearRectifier(0.0).to_pipeline() >> NormalizeRows()
    with trace() as t:
        pipeline(ds).get()
    labels = [x.label for x in t.timings]
    assert any("LinearRectifier" in l for l in labels)
    assert any("NormalizeRows" in l for l in labels)
    assert t.total_seconds > 0
    assert "TOTAL" in t.report()


def test_tracing_off_by_default_keeps_laziness():
    from keystone_tpu.data.dataset import ObjectDataset
    from keystone_tpu.workflow.pipeline import Transformer
    from keystone_tpu.workflow.tracing import current_trace

    assert current_trace() is None

    calls = []

    class Probe(Transformer):
        def apply(self, x):
            calls.append(x)
            return x + 1

    result = Probe().to_pipeline()(ObjectDataset([1, 2]))
    assert calls == []  # untraced application stays lazy until forced
    assert result.get().collect() == [2, 3]
    assert calls == [1, 2]


def test_fitted_pipeline_apply_is_thread_safe():
    """Concurrent serving calls must each get their own datum's result
    (the memoized datum-graph fast path swaps a shared operator under a
    lock)."""
    from concurrent.futures import ThreadPoolExecutor

    est = CountingEstimator()
    fitted = (Plus(1) >> est.with_data(ObjectDataset([2.0, 4.0]))).fit()
    inputs = [float(i) for i in range(64)]
    expected = [fitted.apply(v) for v in inputs]
    with ThreadPoolExecutor(max_workers=8) as pool:
        got = list(pool.map(fitted.apply, inputs))
    assert got == expected
