"""Node-level optimization rule tests (reference:
workflow/NodeOptimizationRuleSuite.scala: hand-built graphs with toy
Optimizable operators, assertions on the chosen implementation) plus DOT
export and estimator-chaining equivalences the reference asserts in
EstimatorSuite/LabelEstimatorSuite.
"""

import numpy as np

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.workflow.optimize import (
    DataStats,
    NodeOptimizationRule,
    Optimizable,
)
from keystone_tpu.workflow.pipeline import (
    Estimator,
    LabelEstimator,
    Transformer,
)


class _Scale(Transformer):
    def __init__(self, factor):
        self.factor = factor

    def apply(self, x):
        return x * self.factor

    def apply_batch(self, ds):
        return ArrayDataset(np.asarray(ds.data) * self.factor, ds.num_examples)


class _ChooseByN(Transformer, Optimizable):
    """Toy optimizable: picks ×2 for small data, ×3 for large — and
    records what it saw, so the test can assert the rule fed it samples
    and FULL-data statistics (not sample-sized ones)."""

    def __init__(self, threshold=50):
        self.threshold = threshold
        self.seen = None

    def apply(self, x):
        return x  # default when optimization never ran

    def apply_batch(self, ds):
        return ds

    def optimize(self, samples, stats: DataStats):
        self.seen = (len(samples[0]), stats)
        return _Scale(2.0) if stats.n_total < self.threshold else _Scale(3.0)


def _run(pipe, data):
    out = pipe(data).get()
    return np.asarray(out.data)[: len(data)]


def test_rule_replaces_operator_using_full_data_stats():
    op = _ChooseByN(threshold=50)
    pipe = op.to_pipeline()
    data = ArrayDataset(np.ones((80, 2), np.float32))
    got = _run(pipe, data)
    np.testing.assert_allclose(got, 3.0 * np.ones((80, 2)))
    sample_len, stats = op.seen
    assert stats.n_total == 80  # full size, not the sample's
    assert sample_len <= NodeOptimizationRule().sample_size


def test_rule_picks_small_branch_below_threshold():
    op = _ChooseByN(threshold=50)
    data = ArrayDataset(np.ones((10, 2), np.float32))
    got = _run(op.to_pipeline(), data)
    np.testing.assert_allclose(got, 2.0 * np.ones((10, 2)))


def test_rule_failure_falls_back_to_default():
    class _Broken(_ChooseByN):
        def optimize(self, samples, stats):
            raise RuntimeError("boom")

    op = _Broken()
    data = ArrayDataset(np.ones((10, 2), np.float32))
    got = _run(op.to_pipeline(), data)  # default apply: identity
    np.testing.assert_allclose(got, np.ones((10, 2)))


# ------------------------------------------------------------- DOT export


def test_graph_dot_export_names_operators():
    pipe = _Scale(2.0).to_pipeline().then(_Scale(5.0))
    dot = pipe.graph.to_dot()
    assert dot.startswith("digraph")
    assert dot.count("_Scale") >= 2
    assert "->" in dot


# ----------------------------------------- estimator chaining equivalences


class _MeanEstimator(Estimator):
    def fit(self, data):
        mu = float(np.asarray(data.data)[: data.num_examples].mean())
        return _Scale(mu)


class _MeanLabelEstimator(LabelEstimator):
    def fit(self, data, labels):
        mu = float(np.asarray(labels.data)[: labels.num_examples].mean())
        return _Scale(mu)


def test_estimator_with_data_equals_direct_fit():
    """est.with_data(d) spliced into a pipeline computes the same model
    as est.fit(d) applied manually (reference: EstimatorSuite)."""
    rng = np.random.default_rng(0)
    train = ArrayDataset(rng.random((20, 3)).astype(np.float32))
    test = ArrayDataset(rng.random((5, 3)).astype(np.float32))

    pipe = _MeanEstimator().with_data(train)
    via_pipeline = np.asarray(pipe(test).get().data)[:5]

    model = _MeanEstimator().fit(train)
    direct = np.asarray(model.apply_batch(test).data)[:5]
    np.testing.assert_allclose(via_pipeline, direct)


def test_label_estimator_with_data_equals_direct_fit():
    rng = np.random.default_rng(1)
    train = ArrayDataset(rng.random((20, 3)).astype(np.float32))
    labels = ArrayDataset(rng.random((20, 1)).astype(np.float32))
    test = ArrayDataset(rng.random((5, 3)).astype(np.float32))

    pipe = _MeanLabelEstimator().with_data(train, labels)
    via_pipeline = np.asarray(pipe(test).get().data)[:5]

    model = _MeanLabelEstimator().fit(train, labels)
    direct = np.asarray(model.apply_batch(test).data)[:5]
    np.testing.assert_allclose(via_pipeline, direct)


def test_chained_estimator_sees_transformed_data():
    """prefix.then_estimator(est, data): est must fit on prefix(data),
    not raw data (reference: Chainable.andThen estimator overloads)."""
    rng = np.random.default_rng(2)
    raw = ArrayDataset(rng.random((16, 2)).astype(np.float32))
    test = ArrayDataset(np.ones((4, 2), np.float32))

    pipe = _Scale(10.0).to_pipeline().then_estimator(_MeanEstimator(), raw)
    got = np.asarray(pipe(test).get().data)[:4]

    want_mu = float((np.asarray(raw.data) * 10.0).mean())
    np.testing.assert_allclose(got, 10.0 * want_mu * np.ones((4, 2)), rtol=1e-6)
