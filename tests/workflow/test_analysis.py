"""Graph analyses (workflow/analysis.py): cycle detection, iterative
linearization, multi-consumer and sink-only edge cases."""

import sys

import pytest

from keystone_tpu.workflow.analysis import (
    GraphCycleError,
    find_cycle,
    get_ancestors,
    linearize,
    linearize_whole,
)
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.operators import Operator


class Op(Operator):
    def __init__(self, name):
        self.name = name

    @property
    def label(self):
        return self.name

    def execute(self, deps):  # pragma: no cover - analyses never execute
        raise AssertionError("analysis must not execute")


def _chain_graph(n):
    graph = Graph()
    graph, src = graph.add_source()
    prev = src
    nodes = []
    for i in range(n):
        graph, node = graph.add_node(Op(f"op{i}"), [prev])
        nodes.append(node)
        prev = node
    graph, sink = graph.add_sink(prev)
    return graph, src, nodes, sink


def test_acyclic_graph_has_no_cycle():
    graph, _src, _nodes, _sink = _chain_graph(5)
    assert find_cycle(graph) is None
    order = linearize_whole(graph)
    pos = {v: i for i, v in enumerate(order)}
    for node in graph.nodes:
        for dep in graph.get_dependencies(node):
            assert pos[dep] < pos[node]


def test_cycle_detected_with_exact_path():
    graph, _src, nodes, _sink = _chain_graph(4)
    cyclic = graph.set_dependencies(nodes[1], [nodes[3]])  # 1 ← 3: closes 1→2→3→1
    cycle = find_cycle(cyclic)
    assert cycle is not None
    assert cycle[0] == cycle[-1]  # closed path
    assert {nodes[1], nodes[2], nodes[3]} == set(cycle)
    with pytest.raises(GraphCycleError) as err:
        linearize_whole(cyclic)
    assert "dependency cycle" in str(err.value)
    assert err.value.cycle[0] == err.value.cycle[-1]


def test_self_loop_detected():
    graph, _src, nodes, _sink = _chain_graph(2)
    cyclic = graph.set_dependencies(nodes[0], [nodes[0]])
    cycle = find_cycle(cyclic)
    assert cycle is not None and len(cycle) == 2
    with pytest.raises(GraphCycleError):
        linearize(cyclic, nodes[1])


def test_cycle_unreachable_from_sinks_still_found():
    """A cyclic island with no sink: sink-driven walks never see it, the
    whole-graph walk must."""
    graph, _src, _nodes, _sink = _chain_graph(2)
    graph, a = graph.add_node(Op("a"), [])
    graph, b = graph.add_node(Op("b"), [a])
    cyclic = graph.set_dependencies(a, [b])
    assert find_cycle(cyclic) is not None
    with pytest.raises(GraphCycleError):
        linearize_whole(cyclic)


def test_multi_consumer_diamond_linearizes_once():
    graph = Graph()
    graph, src = graph.add_source()
    graph, head = graph.add_node(Op("head"), [src])
    graph, left = graph.add_node(Op("left"), [head])
    graph, right = graph.add_node(Op("right"), [head])
    graph, join = graph.add_node(Op("join"), [left, right])
    graph, sink = graph.add_sink(join)
    order = linearize_whole(graph)
    assert len(order) == len(set(order))  # each vertex exactly once
    pos = {v: i for i, v in enumerate(order)}
    assert pos[head] < pos[left] and pos[head] < pos[right]
    assert pos[left] < pos[join] and pos[right] < pos[join]
    assert find_cycle(graph) is None


def test_sink_only_graph_linearizes():
    """A sink hanging directly off a source — no nodes at all."""
    graph = Graph()
    graph, src = graph.add_source()
    graph, sink = graph.add_sink(src)
    order = linearize_whole(graph)
    assert order == [src, sink]
    assert find_cycle(graph) is None


def test_ancestors_of_multi_consumer_interior():
    graph = Graph()
    graph, src = graph.add_source()
    graph, head = graph.add_node(Op("head"), [src])
    graph, left = graph.add_node(Op("left"), [head])
    graph, right = graph.add_node(Op("right"), [head])
    assert get_ancestors(graph, left) == {src, head}
    assert get_ancestors(graph, right) == {src, head}


def test_deep_chain_beyond_recursion_limit():
    """The old recursive linearize overflowed on deep chains; the
    iterative DFS must not."""
    depth = sys.getrecursionlimit() + 200
    graph, _src, _nodes, _sink = _chain_graph(depth)
    order = linearize_whole(graph)
    assert len(order) == depth + 2  # source + nodes + sink
