"""``keystone-tpu explain`` (workflow/explain.py): the in-process flow —
per-node ledger report with predictions and provenance, the seeded
corruption helper, and the explain-grade optimizer stack. The full
3-run CLI drift cycle (seeded fire → stale → re-measure) is gated by
scripts/explain_smoke.sh in tier-1 CI."""

import argparse
import json
import os

import pytest

from keystone_tpu.obs import cost


@pytest.fixture(autouse=True)
def _fresh_observatory(tmp_path):
    env_before = os.environ.get("KEYSTONE_PROFILE_STORE")
    os.environ["KEYSTONE_PROFILE_STORE"] = str(tmp_path / "ps.jsonl")
    cost.reset_cost_observatory()
    yield
    if env_before is not None:
        os.environ["KEYSTONE_PROFILE_STORE"] = env_before
    else:
        os.environ.pop("KEYSTONE_PROFILE_STORE", None)
    cost.set_cost_observatory(None)
    cost.reset_cost_observatory()
    from keystone_tpu.obs.store import set_store

    set_store(None)


def _args(**overrides):
    base = dict(
        pipeline="synthetic", rows=512, dim=32, classes=3, passes=2,
        seed_drift=0.0, seed=0, out=None, as_json=False,
    )
    base.update(overrides)
    return argparse.Namespace(**base)


def test_explain_optimizer_swaps_profile_scales():
    from keystone_tpu.workflow.autocache import AutoCacheRule
    from keystone_tpu.workflow.explain import _explain_optimizer

    stack = _explain_optimizer()
    rules = [
        r for b in stack.batches for r in b.rules
        if isinstance(r, AutoCacheRule)
    ]
    assert len(rules) == 1
    assert rules[0].profile_scales == (128, 512)


def test_corrupt_store_predictions_scales_exactly_one_entry():
    from keystone_tpu.obs.store import get_store
    from keystone_tpu.workflow.explain import _corrupt_store_predictions

    store = get_store()
    base = cost.DriftSentinel.BASELINE_FIELD
    store.record("autocache:small", "n2^9", t0=0.1, t1=1e-5,
                 **{base: 0.01})
    store.record("autocache:big", "n2^9", t0=0.2, t1=2e-5,
                 **{base: 0.5})
    assert _corrupt_store_predictions(10.0) == 1
    # the LARGEST baseline was the target; the other survives intact
    big = store.lookup("autocache:big", "n2^9")
    assert big[base] == pytest.approx(0.05)
    assert big["t0"] == pytest.approx(0.02)
    small = store.lookup("autocache:small", "n2^9")
    assert small[base] == pytest.approx(0.01)
    # factor 1 / empty prefix are no-ops
    assert _corrupt_store_predictions(1) == 0


def test_explain_synthetic_reports_every_plan_node(tmp_path):
    """One in-process explain run: JSON report lands with a ledger entry
    per executed plan node, predictions + provenance on the compiled
    ones, a calibrated roofline, and zero harvest compiles."""
    from keystone_tpu.workflow.explain import explain_from_args

    out = str(tmp_path / "explain.json")
    rc = explain_from_args(_args(out=out, as_json=True))
    assert rc == 0  # no drift on a fresh store
    report = json.loads(open(out).read())
    assert report["harvest_compiles"] == 0
    assert report["roofline"]["peak_flops_per_s"] > 0
    assert report["drift_events"] == []

    nodes = report["nodes"]
    labels = [n["node"] for n in nodes]
    # the whole plan is in the ledger: data, chain, estimator, apply
    assert any(label.startswith("Dataset") for label in labels)
    assert any("BlockLeastSquares" in label or "StreamFit" in label
               for label in labels)
    compiled = [n for n in nodes if n.get("flops")]
    assert compiled, labels
    for node in compiled:
        assert node["seconds"] >= 0
        assert node.get("predicted_s") is not None
        assert node.get("intensity") is not None
        assert node.get("roofline") in ("compute-bound", "memory-bound")
        assert node.get("lowering_digest")
        prov = node["provenance"]
        assert prov.get("model") in (
            "autocache", "measured_knob", "solver_ladder", "roofline",
        )
        assert prov.get("computations"), node
    # observatory state was restored for the rest of the process
    # (explain enables it for its own run only)
    assert cost.get_ledger().cursor() >= len(nodes)
