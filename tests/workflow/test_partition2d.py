"""2-D partitioner layouts (docs/PARTITIONING.md "2-D layouts"): plan
decisions over data × model meshes, blocked-carry streamed-fit parity,
per-axis collective accounting, rung pricing on per-device state,
cross-mesh durable resume, and model-axis shard-loss salvage.

The invariant throughout: IDENTICAL pipeline code on 1×1, 1×8, 2×4 and
4×2 virtual-device meshes, parity ≤ 1e-5, 0 steady-state compiles."""

import numpy as np
import pytest

import jax

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.learning.least_squares import LeastSquaresEstimator
from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.parallel.partitioner import (
    ALL_REASON_KEYS,
    R_BELOW_WIDTH_FLOOR,
    R_MODEL_INDIVISIBLE,
    Partitioner,
    demote_model_axis,
    last_partition_report,
    partition_disabled,
)
from keystone_tpu.reliability import enable_checkpointing, faultinject
from keystone_tpu.reliability.faultinject import FaultSpec
from keystone_tpu.reliability.recovery import get_recovery_log
from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.pipeline import BatchTransformer
from keystone_tpu.workflow.streaming import last_stream_report

N, D, K, CHUNK = 512, 64, 3, 64  # D wide enough for 8 model shards
rng = np.random.default_rng(11)
X = rng.normal(size=(N, D)).astype(np.float32)
W = rng.normal(size=(D, K)).astype(np.float32)
Y = (X @ W + 0.01 * rng.normal(size=(N, K))).astype(np.float32)
PROBE = rng.normal(size=(32, D)).astype(np.float32)


class Scale(BatchTransformer):
    def __init__(self, c):
        self.c = float(c)

    def apply_arrays(self, a):
        return a * self.c


def build(x=X, y=Y, est=None):
    est = est or LinearMapEstimator(reg=1e-3)
    return Scale(2.0).to_pipeline().then_label_estimator(
        est, ArrayDataset(x), ArrayDataset(y)
    )


def preds(fitted):
    return np.asarray(fitted.apply_batch(ArrayDataset(PROBE)).data)


def rel_err(a, b):
    return float(np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-30))


@pytest.fixture()
def grid2d(monkeypatch):
    """2×4 layout: 4 model shards on the 8-virtual-device mesh, width
    floor lowered so D=64 clears it (64 ≥ 4 × 8)."""
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", str(CHUNK))
    monkeypatch.setenv("KEYSTONE_PARTITION_MODEL_SHARDS", "4")
    monkeypatch.setenv("KEYSTONE_PARTITION_MIN_WIDTH", "8")


@pytest.fixture()
def reference(monkeypatch):
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", str(CHUNK))
    PipelineEnv.reset()
    with partition_disabled():
        out = preds(build().fit())
    PipelineEnv.reset()
    return out


# ------------------------------------------------------------- decisions


def test_2d_stream_decision_shape_and_spec(grid2d):
    d = Partitioner().decide_stream(
        "t", CHUNK, rows=N, record=False, width=D, model_ok=True
    )
    assert d.eligible and d.reason == "sharded"
    assert (d.shards, d.model_shards) == (2, 4)
    assert d.total_shards == 8
    assert d.mesh_shape == (2, 4)
    assert d.carry_axes == ("data", "model")
    assert "data" in d.spec and "model" in d.spec
    assert d.to_json()["model_shards"] == 4


def test_width_floor_demotes_to_row_only(grid2d, monkeypatch):
    monkeypatch.setenv("KEYSTONE_PARTITION_MIN_WIDTH", "512")
    d = Partitioner().decide_stream(
        "t", CHUNK, rows=N, record=False, width=D, model_ok=True
    )
    assert d.eligible and d.model_shards == 1
    assert d.shards == len(jax.devices())
    assert d.model_fallback == R_BELOW_WIDTH_FLOOR
    assert "model" not in d.spec


def test_indivisible_width_demotes(grid2d):
    d = Partitioner().decide_stream(
        "t", CHUNK, rows=N, record=False, width=D - 2, model_ok=True
    )
    assert d.eligible and d.model_shards == 1
    assert d.model_fallback == R_MODEL_INDIVISIBLE


def test_model_shards_must_divide_device_count(grid2d, monkeypatch):
    monkeypatch.setenv("KEYSTONE_PARTITION_MODEL_SHARDS", "3")
    d = Partitioner().decide_stream(
        "t", CHUNK, rows=N, record=False, width=66, model_ok=True
    )
    assert d.eligible and d.model_shards == 1
    assert d.model_fallback == R_MODEL_INDIVISIBLE


def test_estimator_without_protocol_stays_row_only(grid2d):
    d = Partitioner().decide_stream(
        "t", CHUNK, rows=N, record=False, width=D, model_ok=False
    )
    assert d.eligible and d.model_shards == 1 and not d.model_fallback


def test_demote_model_axis_keeps_row_sharding(grid2d):
    d = Partitioner().decide_stream(
        "t", CHUNK, rows=N, record=False, width=D, model_ok=True
    )
    dem = demote_model_axis(d, R_MODEL_INDIVISIBLE, "test")
    assert dem.eligible and dem.model_shards == 1 and dem.shards == 2
    assert dem.model_fallback == R_MODEL_INDIVISIBLE
    assert "model" not in dem.spec


def test_demote_on_1x8_turns_ineligible(grid2d, monkeypatch):
    monkeypatch.setenv("KEYSTONE_PARTITION_MODEL_SHARDS", "8")
    d = Partitioner().decide_stream(
        "t", CHUNK, rows=N, record=False, width=D, model_ok=True
    )
    assert d.eligible and (d.shards, d.model_shards) == (1, 8)
    dem = demote_model_axis(d, R_BELOW_WIDTH_FLOOR)
    assert not dem.eligible and dem.reason == R_BELOW_WIDTH_FLOOR


def test_every_reason_key_reaches_the_docs_matrix():
    assert R_MODEL_INDIVISIBLE in ALL_REASON_KEYS
    assert R_BELOW_WIDTH_FLOOR in ALL_REASON_KEYS
    assert len(ALL_REASON_KEYS) == len(set(ALL_REASON_KEYS))


# ----------------------------------------------------- streamed execution


@pytest.mark.parametrize("model_shards,mesh_shape", [(4, (2, 4)), (2, (4, 2)), (8, (1, 8))])
def test_2d_fit_stream_parity_and_axis_accounting(
    grid2d, reference, monkeypatch, model_shards, mesh_shape
):
    monkeypatch.setenv("KEYSTONE_PARTITION_MODEL_SHARDS", str(model_shards))
    PipelineEnv.reset()
    fitted = build().fit()
    rep = last_stream_report()
    assert rep.mesh_shape == mesh_shape
    assert (rep.shards, rep.model_shards) == mesh_shape
    assert rep.compiles_steady_state == 0
    # per-axis collective payload is a pure function of the plan
    b_f = 4 * (D * D + D * K + D)
    b_r = 4 * K
    p_d, p_m = mesh_shape
    assert rep.collective_bytes_data == (b_f + p_m * b_r) * (p_d - 1)
    assert rep.collective_bytes_model == (b_f // p_m + b_r) * (p_m - 1)
    assert rep.collective_bytes == (
        rep.collective_bytes_data + rep.collective_bytes_model
    )
    # per-device state: one feature block + the replicated remainder
    assert rep.state_bytes_per_device == b_f // p_m + b_r
    assert rel_err(preds(fitted), reference) <= 1e-5


def test_per_device_state_shrinks_with_model_shards(grid2d, monkeypatch):
    state = {}
    for p_m in (1, 2, 4):
        monkeypatch.setenv("KEYSTONE_PARTITION_MODEL_SHARDS", str(p_m))
        PipelineEnv.reset()
        build().fit()
        state[p_m] = last_stream_report().state_bytes_per_device
    assert state[1] > state[2] > state[4]
    # feature state dominates at D=64: each doubling roughly halves it
    assert state[1] > 1.9 * state[2] and state[2] > 1.9 * state[4]


def test_sketched_rung_2d_parity(grid2d, monkeypatch, reference):
    # Force the sketch rung under the 2-D layout: the 5-leaf carry's
    # SA/Σx leaves block over the model axis.
    monkeypatch.setenv("KEYSTONE_SKETCH_MIN_WIDTH", "16")
    monkeypatch.setenv("KEYSTONE_SKETCH_SIZE", "512")
    PipelineEnv.reset()
    fitted = build(est=LeastSquaresEstimator(reg=1e-3)).fit()
    rep = last_stream_report()
    assert (rep.shards, rep.model_shards) == (2, 4)
    assert rep.compiles_steady_state == 0
    # sketched solve at s=512 ≥ 8·D is near-exact on this problem
    assert rel_err(preds(fitted), reference) <= 5e-2


def test_rung_pricing_scales_sketch_floor_per_device(monkeypatch):
    monkeypatch.setenv("KEYSTONE_SKETCH_MIN_WIDTH", "32")
    est = LeastSquaresEstimator(reg=1e-3)
    from keystone_tpu.sketch.solvers import SketchedLeastSquaresEstimator

    assert isinstance(est._stream_solver(64), SketchedLeastSquaresEstimator)
    # feature-sharded 4 ways, the same width stays on the exact rung
    assert not isinstance(
        est._stream_solver(64, model_shards=4), SketchedLeastSquaresEstimator
    )
    assert isinstance(
        est._stream_solver(128, model_shards=4), SketchedLeastSquaresEstimator
    )


def test_plan_report_carries_model_fallback(grid2d, monkeypatch):
    # An indivisible width demotes at plan time; the decision stays
    # eligible row-sharded and the report explains the demotion.
    x = np.ascontiguousarray(X[:, : D - 2])
    PipelineEnv.reset()
    fitted = build(x=x).fit()
    rep = last_stream_report()
    assert rep.shards == len(jax.devices()) and rep.model_shards == 1
    decisions = [d for d in last_partition_report() if d.eligible]
    assert decisions and decisions[0].model_fallback == R_MODEL_INDIVISIBLE
    narrow = ArrayDataset(np.ascontiguousarray(PROBE[:, : D - 2]))
    assert np.isfinite(np.asarray(fitted.apply_batch(narrow).data)).all()


# --------------------------------------------------------------- verifier


def test_kv304_accounts_model_axis_blocking(grid2d):
    from keystone_tpu.workflow.verify import verify_graph

    pipe = build()
    report = verify_graph(pipe.graph, device_memory_bytes=64, context="test")
    errors = report.by_code("KV304")
    assert errors, report.render()
    assert errors[0].details.get("model_shards") == 4
    # the 2-D decision rides the report for check --pipeline --json
    assert any(p.get("model_shards") == 4 for p in report.partition)


# ------------------------------------------------------ durable cross-mesh


def _crash_at(store_dir, call, env, monkeypatch):
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    PipelineEnv.reset()
    enable_checkpointing(str(store_dir))
    with pytest.raises(ConnectionError):
        with faultinject.injected(
            FaultSpec(match="streaming.chunk", kind="transient", calls=(call,))
        ):
            build().fit()


@pytest.mark.parametrize(
    "first,second", [("8", "4"), ("4", "8")], ids=["1x8-to-2x4", "2x4-to-1x8"]
)
def test_cross_mesh_durable_resume_parity(
    tmp_path, reference, monkeypatch, first, second
):
    """A fit checkpointed under one 2-D layout resumes under another:
    snapshots commit MERGED (mesh-independent), the layout is cursor
    metadata only."""
    monkeypatch.setenv("KEYSTONE_STREAM_CKPT_CHUNKS", "2")
    monkeypatch.setenv("KEYSTONE_PARTITION_MIN_WIDTH", "8")
    _crash_at(
        tmp_path, 5, {"KEYSTONE_PARTITION_MODEL_SHARDS": first}, monkeypatch
    )
    monkeypatch.setenv("KEYSTONE_PARTITION_MODEL_SHARDS", second)
    PipelineEnv.reset()
    enable_checkpointing(str(tmp_path))
    fitted = build().fit()
    rep = last_stream_report()
    assert rep.resumed_from_chunk == 4
    assert rep.model_shards == int(second)
    assert rel_err(preds(fitted), reference) <= 1e-6


def test_2d_checkpoint_resumes_single_device(
    tmp_path, reference, monkeypatch
):
    monkeypatch.setenv("KEYSTONE_STREAM_CKPT_CHUNKS", "2")
    monkeypatch.setenv("KEYSTONE_PARTITION_MIN_WIDTH", "8")
    _crash_at(
        tmp_path, 5, {"KEYSTONE_PARTITION_MODEL_SHARDS": "4"}, monkeypatch
    )
    PipelineEnv.reset()
    enable_checkpointing(str(tmp_path))
    with partition_disabled():
        fitted = build().fit()
    rep = last_stream_report()
    assert rep.resumed_from_chunk == 4 and rep.shards == 1
    assert rel_err(preds(fitted), reference) <= 1e-6


# -------------------------------------------------------- shard loss (2-D)


def test_model_axis_shard_loss_salvages_surviving_row_group(
    grid2d, reference
):
    """Losing flat shard 7 on the 2×4 mesh = (data row 1, model col 3).
    A feature column cannot be salvaged alone: the whole data row-group
    drops, the survivors' blocks reassemble, only row group 1's windows
    re-ingest."""
    PipelineEnv.reset()
    with faultinject.injected(
        FaultSpec(match="parallel.shard_loss", kind="transient", calls=(3,))
    ):
        fitted = build().fit()
    rep = last_stream_report()
    assert rep.shard_losses == 1
    assert rep.shards == 7 and rep.model_shards == 1  # row-only re-plan
    assert rep.reingested_chunks > 0
    assert rel_err(preds(fitted), reference) <= 1e-5
    kinds = {e.kind for e in get_recovery_log().events()}
    assert {"shard_loss", "shard_resume"} <= kinds


def test_seed_bearing_block_loss_readds_seed_2d(
    grid2d, reference, monkeypatch
):
    # Flat shard 0 = (data row 0, model col 0): the dropped row group
    # includes the seed block, which must re-add host-side.
    monkeypatch.setenv("KEYSTONE_SHARD_LOSS_INDEX", "0")
    PipelineEnv.reset()
    with faultinject.injected(
        FaultSpec(match="parallel.shard_loss", kind="transient", calls=(4,))
    ):
        fitted = build().fit()
    assert last_stream_report().shard_losses == 1
    assert rel_err(preds(fitted), reference) <= 1e-5


def test_1x8_loss_reingests_everything(grid2d, reference, monkeypatch):
    # On 1×8 every device is in the single data row-group: a loss keeps
    # nothing, the fold restarts from the seed — correct, just slow.
    monkeypatch.setenv("KEYSTONE_PARTITION_MODEL_SHARDS", "8")
    PipelineEnv.reset()
    with faultinject.injected(
        FaultSpec(match="parallel.shard_loss", kind="transient", calls=(3,))
    ):
        fitted = build().fit()
    rep = last_stream_report()
    assert rep.shard_losses == 1
    assert rel_err(preds(fitted), reference) <= 1e-5
