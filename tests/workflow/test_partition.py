"""PartitionPlanRule + sharded execution through the workflow layer:
the optimizer's partition batch pins decisions onto final operators, the
streaming engine runs the sharded chunk plan with finish-time reduction,
ineligible plans fall back cleanly, and the verifier explains both
(KV203) and errors on infeasible sharded residency (KV304).

The invariant throughout: IDENTICAL pipeline code on 1 and 8 virtual
devices, parity ≤ 1e-5."""

import numpy as np
import pytest

import jax

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.parallel.mesh import make_mesh, use_mesh
from keystone_tpu.parallel.partitioner import (
    last_partition_report,
    partition_disabled,
)
from keystone_tpu.workflow.executor import GraphExecutor, PipelineEnv
from keystone_tpu.workflow.pipeline import BatchTransformer
from keystone_tpu.workflow.streaming import (
    StreamingFitOperator,
    last_stream_report,
)

N, D, K = 512, 16, 3
CHUNK = 64


class Scale(BatchTransformer):
    def __init__(self, c):
        self.c = float(c)

    def apply_arrays(self, a):
        return a * self.c


@pytest.fixture
def data():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(N, D)).astype(np.float32)
    w = rng.normal(size=(D, K)).astype(np.float32)
    y = (x @ w + 0.01 * rng.normal(size=(N, K))).astype(np.float32)
    return x, y


def _stream_pipeline(x, y, est=None):
    est = est or BlockLeastSquaresEstimator(8, num_iter=1, reg=1e-3)
    return Scale(2.0).to_pipeline().then_label_estimator(
        est, ArrayDataset(x), ArrayDataset(y)
    )


def test_partition_batch_pins_decision_on_streaming_operator(
    data, monkeypatch
):
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", str(CHUNK))
    x, y = data
    pipe = _stream_pipeline(x, y)
    executor = GraphExecutor(pipe.graph)
    graph = executor.graph
    ops = [
        graph.get_operator(n)
        for n in graph.nodes
        if isinstance(graph.get_operator(n), StreamingFitOperator)
    ]
    assert len(ops) == 1
    decision = ops[0].partition
    assert decision is not None and decision.eligible
    assert decision.shards == len(jax.devices())
    assert decision.chunk_rows == CHUNK  # 64 already divides 8
    assert ops[0].chunk_rows == decision.chunk_rows
    # the executor captured the plan's decisions at optimize time
    assert any(d.eligible for d in executor.partition_decisions)


def test_sharded_fit_stream_parity_and_finish_reduce(data, monkeypatch):
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", str(CHUNK))
    x, y = data

    fitted = _stream_pipeline(x, y).fit()
    rep = last_stream_report()
    assert rep.shards == len(jax.devices())
    assert rep.mesh_shape == (len(jax.devices()),)
    # finish-reduce payload: the carry (G, C, Σx, Σy) × (shards−1)
    carry_bytes = 4 * (D * D + D * K + D + K)
    assert rep.collective_bytes == carry_bytes * (rep.shards - 1)
    assert rep.compiles_steady_state == 0
    preds = np.asarray(fitted.apply_batch(ArrayDataset(x[:32])).data)

    PipelineEnv.reset()
    with partition_disabled():
        fitted1 = _stream_pipeline(x, y).fit()
        assert last_stream_report().shards == 1
        preds1 = np.asarray(fitted1.apply_batch(ArrayDataset(x[:32])).data)

    rel = np.linalg.norm(preds - preds1) / max(np.linalg.norm(preds1), 1e-30)
    assert rel <= 1e-5, rel


def test_sharded_exact_fit_stream_parity(data, monkeypatch):
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", str(CHUNK))
    x, y = data
    est = LinearMapEstimator(reg=1e-3)
    fitted = _stream_pipeline(x, y, est=est).fit()
    assert last_stream_report().shards == len(jax.devices())
    preds = np.asarray(fitted.apply_batch(ArrayDataset(x[:32])).data)
    PipelineEnv.reset()
    with partition_disabled():
        fitted1 = _stream_pipeline(x, y, est=LinearMapEstimator(reg=1e-3)).fit()
        preds1 = np.asarray(fitted1.apply_batch(ArrayDataset(x[:32])).data)
    rel = np.linalg.norm(preds - preds1) / max(np.linalg.norm(preds1), 1e-30)
    assert rel <= 1e-5, rel


def test_chunk_rows_rounded_up_to_shard_multiple(data, monkeypatch):
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", "100")  # 100 % 8 != 0
    x, y = data
    _stream_pipeline(x, y).fit()
    rep = last_stream_report()
    assert rep.shards == len(jax.devices())
    assert rep.chunk_rows == 104


def test_in_core_fit_pins_partition_mesh(data):
    """Below the streaming floor the fit stays in-core; the partition
    batch still pins an eligible fit decision whose mesh the estimator
    consults (partitioner.fit_mesh), with 1-vs-8 parity."""
    x, y = data

    def fit_preds():
        PipelineEnv.reset()
        pipe = _stream_pipeline(x, y)  # n=512 < 2·4096 → in-core
        fitted = pipe.fit()
        decisions = [d for d in last_partition_report() if d.eligible]
        return (
            np.asarray(fitted.apply_batch(ArrayDataset(x[:32])).data),
            decisions,
        )

    preds8, decisions = fit_preds()
    assert decisions and decisions[0].kind == "fit"
    assert decisions[0].shards == len(jax.devices())
    with use_mesh(make_mesh(devices=jax.devices()[:1])):
        preds1, decisions1 = fit_preds()
        assert not decisions1  # single-shard mesh: recorded fallback
    rel = np.linalg.norm(preds8 - preds1) / max(np.linalg.norm(preds1), 1e-30)
    assert rel <= 1e-5, rel


def test_ineligible_chunk_falls_back_to_single_device_plan(data, monkeypatch):
    """chunk_rows below the shard count is a recorded fallback: the plan
    still fits, single-device, with the reason in the report."""
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", "4")
    monkeypatch.setenv("KEYSTONE_STREAM_MIN_ROWS", "1")
    x, y = data
    fitted = _stream_pipeline(x, y).fit()
    rep = last_stream_report()
    assert rep.shards == 1
    reasons = {d.reason for d in last_partition_report()}
    assert "chunk-below-shard-count" in reasons
    preds = np.asarray(fitted.apply_batch(ArrayDataset(x[:16])).data)
    assert np.isfinite(preds).all()


def test_partitionable_false_on_estimator_respected_through_streaming_wrap(
    data, monkeypatch
):
    """The opt-out lives on the estimator the user wrote; the planner's
    StreamingFitOperator wrapper must not mask it."""
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", str(CHUNK))
    x, y = data
    est = BlockLeastSquaresEstimator(8, num_iter=1, reg=1e-3)
    est.partitionable = False
    fitted = _stream_pipeline(x, y, est=est).fit()
    assert last_stream_report().shards == 1
    decisions = last_partition_report()
    assert decisions and decisions[0].reason == "operator-opt-out"
    assert decisions[0].kind == "fit_stream"
    assert np.isfinite(
        np.asarray(fitted.apply_batch(ArrayDataset(x[:8])).data)
    ).all()


def test_partition_disabled_records_empty_report(data, monkeypatch):
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", str(CHUNK))
    x, y = data
    with partition_disabled():
        _stream_pipeline(x, y).fit()
    assert last_partition_report() == []
    assert last_stream_report().shards == 1


# ------------------------------------------------------------------- verifier


def test_verify_emits_kv203_with_partitioner_reason(data):
    from keystone_tpu.workflow.verify import verify_graph

    x, y = data
    pipe = _stream_pipeline(x[:8], y[:8])  # 8 rows < 8 shards × 2 min
    report = verify_graph(pipe.graph, context="test")
    diags = report.by_code("KV203")
    assert diags, report.render()
    assert any(
        d.details.get("reason") == "below-rows-floor" for d in diags
    ), [d.to_json() for d in diags]
    # the decision list rides the report for check --pipeline --json
    assert any(not p["eligible"] for p in report.partition)


def test_verify_emits_kv304_when_sharded_residency_exceeds_budget(
    data, monkeypatch
):
    from keystone_tpu.workflow.verify import verify_graph

    x, y = data
    pipe = _stream_pipeline(x, y)
    # budget below even the O(d²) statistics: sharding cannot save it
    report = verify_graph(pipe.graph, device_memory_bytes=64, context="test")
    errors = report.by_code("KV304")
    assert errors, report.render()
    assert errors[0].severity == "error"
    assert errors[0].details.get("shards") == len(jax.devices())


def test_verify_no_kv304_within_budget(data):
    from keystone_tpu.workflow.verify import verify_graph

    x, y = data
    report = verify_graph(
        pipe := _stream_pipeline(x, y).graph,
        device_memory_bytes=int(1e12),
        context="test",
    )
    assert not report.by_code("KV304"), report.render()
    assert any(p["eligible"] for p in report.partition)
