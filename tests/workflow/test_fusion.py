"""Whole-pipeline fusion: chain detection, boundaries, parity, dispatch
accounting, and serving integration (workflow/fusion.py)."""

import pickle

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.obs import names as _names
from keystone_tpu.workflow import (
    BatchTransformer,
    FittedPipeline,
    FusedTransformerOperator,
    Pipeline,
    fuse_graph,
    fusion_disabled,
)
from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.fusion import NodeFusionRule, is_fusable
from keystone_tpu.workflow.rules import default_optimizer


class Scale(BatchTransformer):
    def __init__(self, c):
        self.c = float(c)

    @property
    def label(self):
        return f"Scale[{self.c}]"

    def apply_arrays(self, x):
        return x * self.c


class Shift(BatchTransformer):
    def __init__(self, c):
        self.c = float(c)

    @property
    def label(self):
        return f"Shift[{self.c}]"

    def apply_arrays(self, x):
        return x + self.c


class CustomBatch(BatchTransformer):
    """Overrides apply_batch → must never fuse."""

    def apply_arrays(self, x):
        return x

    def apply_batch(self, dataset):
        return dataset


def _chain(*ops):
    pipe = ops[0].to_pipeline()
    for op in ops[1:]:
        pipe = pipe.then(op)
    return pipe


def _append_operator(pipe, op):
    """Append a bare TransformerOperator (no Chainable mixin — e.g. a
    CacherOperator) to a pipeline's sink by direct graph surgery."""
    graph = pipe.graph
    graph, node = graph.add_node(op, [graph.get_sink_dependency(pipe.sink)])
    graph = graph.set_sink_dependency(pipe.sink, node)
    return Pipeline(graph, pipe.source, pipe.sink)


def _fused_ops(graph):
    return [
        op for op in graph.operators.values()
        if isinstance(op, FusedTransformerOperator)
    ]


def _labels(graph):
    return sorted(
        getattr(op, "label", type(op).__name__) for op in graph.operators.values()
    )


def _dispatch_counts():
    c = _names.metric(_names.FUSION_BATCH_DISPATCHES)
    return c.value(fused="1"), c.value(fused="0")


x4 = np.arange(24, dtype=np.float32).reshape(4, 6)


# ----------------------------------------------------------------- structure


def test_four_node_chain_fuses_to_one_node():
    pipe = _chain(Scale(2), Shift(1), Scale(3), Shift(-2))
    res = pipe(ArrayDataset(x4))
    res.get()
    graph = res._executor.graph
    fused = _fused_ops(graph)
    assert len(fused) == 1
    assert fused[0].member_labels == (
        "Scale[2.0]", "Shift[1.0]", "Scale[3.0]", "Shift[-2.0]",
    )
    # only the dataset node and the fused node remain
    assert len(graph.nodes) == 2


def test_fusion_rule_is_in_default_optimizer():
    # fusion is the last STRUCTURAL batch; only the streaming planner
    # (which absorbs already-fused chains), the measured-knob pass
    # (which re-parameterizes, never restructures), and the partition
    # pass (which pins placement decisions onto final operators) may
    # follow it.
    names = [b.name for b in default_optimizer().batches]
    assert names[-4:] == ["fusion", "streaming", "measured-knobs", "partition"]
    from keystone_tpu.workflow.rules import auto_caching_optimizer

    names = [b.name for b in auto_caching_optimizer().batches]
    # fusion strictly after auto-cache: cache planning sees real nodes
    assert names.index("fusion") == names.index("auto-cache") + 1
    assert names[-3:] == ["streaming", "measured-knobs", "partition"]


def test_cacher_is_a_fusion_boundary():
    from keystone_tpu.ops.util.misc import CacherOperator

    # Scale→Shift → Cacher → Scale→Shift: one fused chain each side
    pipe = _append_operator(_chain(Scale(2), Shift(1)), CacherOperator())
    pipe = pipe.then(Scale(3)).then(Shift(4))
    fused_graph = fuse_graph(pipe.graph)
    fused = _fused_ops(fused_graph)
    assert len(fused) == 2
    assert sorted(f.member_labels for f in fused) == [
        ("Scale[2.0]", "Shift[1.0]"),
        ("Scale[3.0]", "Shift[4.0]"),
    ]
    assert any(
        isinstance(op, CacherOperator) for op in fused_graph.operators.values()
    )


def test_prefix_marked_node_is_not_fused():
    pipe = _chain(Scale(2), Shift(1), Scale(3))
    graph = pipe.graph
    # mark the middle node as a saveable-prefix cut point
    middle = next(
        n for n in graph.nodes if graph.get_operator(n).label == "Shift[1.0]"
    )
    out, _ = NodeFusionRule().apply(graph, {middle: object()})
    # the cut point keeps its own node; the remaining neighbors are
    # singletons, so nothing fuses at all
    assert _fused_ops(out) == []
    assert middle in out.nodes


def test_branch_point_cuts_chain():
    """A node consumed by two downstream nodes stays host-visible."""
    a = Scale(2)
    pipe_a = a.to_pipeline()
    b1 = pipe_a.then(Shift(1)).then(Scale(5))
    gathered = Pipeline.gather([b1, pipe_a.then(Shift(3))])
    res = gathered(ArrayDataset(x4))
    got = res.get()
    graph = res._executor.graph
    for fused in _fused_ops(graph):
        # chains never swallow the shared Scale[2.0] producer
        assert "Scale[2.0]" not in fused.member_labels
    with fusion_disabled():
        PipelineEnv.reset()
        ref = gathered(ArrayDataset(x4)).get()
    for g, r in zip(got.collect(), ref.collect()):
        np.testing.assert_allclose(
            np.asarray(g, dtype=np.float32), np.asarray(r, dtype=np.float32),
            rtol=1e-6,
        )


def test_bespoke_apply_batch_is_not_fusable():
    assert is_fusable(Scale(2))
    assert not is_fusable(CustomBatch())  # overrides apply_batch
    from keystone_tpu.ops.util.misc import CacherOperator

    assert not is_fusable(CacherOperator())  # not a BatchTransformer
    from keystone_tpu.ops.learning.kernel import KernelBlockLinearMapper

    assert KernelBlockLinearMapper.fusable is False  # explicit opt-out


def test_fusable_opt_out_flag():
    class OptedOut(Scale):
        fusable = False

    pipe = _chain(OptedOut(2), Shift(1), Scale(3))
    out = fuse_graph(pipe.graph)
    fused = _fused_ops(out)
    assert len(fused) == 1
    assert "Scale" in fused[0].member_labels[0] or fused[0].member_labels == (
        "Shift[1.0]", "Scale[3.0]",
    )


def test_nested_fusion_flattens():
    inner = FusedTransformerOperator([Scale(2), Shift(1)])
    outer = FusedTransformerOperator([inner, Scale(3)])
    assert outer.member_labels == ("Scale[2.0]", "Shift[1.0]", "Scale[3.0]")


# --------------------------------------------------------------------- parity


def _parity(pipe, data, rel=1e-5):
    PipelineEnv.reset()
    got = pipe(data).get()
    PipelineEnv.reset()
    with fusion_disabled():
        ref = pipe(data).get()
    g = np.asarray(got.data, dtype=np.float64)
    r = np.asarray(ref.data, dtype=np.float64)
    err = np.linalg.norm(g - r) / max(np.linalg.norm(r), 1e-30)
    assert err <= rel, f"fused vs unfused rel_err {err}"
    return g


def test_parity_mnist_fft_featurizer():
    from keystone_tpu.pipelines.mnist_random_fft import (
        MnistRandomFFTConfig,
        build_featurizer,
    )

    featurizer = build_featurizer(MnistRandomFFTConfig(num_ffts=2), image_size=64)
    x = np.random.default_rng(0).normal(size=(16, 64)).astype(np.float32)
    _parity(featurizer, ArrayDataset(x))


def test_parity_cifar_patch_chain():
    from keystone_tpu.ops.images.core import (
        Convolver,
        ImageVectorizer,
        Pooler,
        SymmetricRectifier,
    )

    rng = np.random.default_rng(1)
    filters = rng.normal(size=(4, 3 * 3 * 3)).astype(np.float32)
    chain = _chain(
        Convolver(filters, img_channels=3, normalize_patches=False),
        SymmetricRectifier(alpha=0.25),
        Pooler(2, 2, None, "sum"),
        ImageVectorizer(),
    )
    imgs = rng.normal(size=(6, 8, 8, 3)).astype(np.float32)
    res = chain(ArrayDataset(imgs))
    graph = res._executor.graph
    assert len(_fused_ops(graph)) == 1
    assert len(_fused_ops(graph)[0].members) == 4
    _parity(chain, ArrayDataset(imgs))


def test_parity_with_cacher_boundary():
    from keystone_tpu.ops.util.misc import CacherOperator

    pipe = _append_operator(_chain(Scale(2), Shift(1)), CacherOperator())
    pipe = pipe.then(Scale(0.5)).then(Shift(-3))
    _parity(pipe, ArrayDataset(x4), rel=1e-6)


def test_parity_padded_rows_stay_zero():
    """Pad-row re-zeroing once at the end equals once per member."""
    data = ArrayDataset(np.ones((6, 4), np.float32), num_examples=4)
    pipe = _chain(Shift(2), Scale(3), Shift(-1))
    PipelineEnv.reset()
    out = pipe(data).get()
    assert out.num_examples == 4
    arr = np.asarray(out.data)
    np.testing.assert_array_equal(arr[4:], 0.0)
    PipelineEnv.reset()
    with fusion_disabled():
        ref = pipe(data).get()
    np.testing.assert_allclose(arr, np.asarray(ref.data), rtol=1e-6)


# ---------------------------------------------------------- dispatch counting


def test_four_node_chain_is_exactly_one_dispatch():
    pipe = _chain(Scale(2), Shift(1), Scale(3), Shift(-2))
    data = ArrayDataset(np.ones((4, 6), np.float32))

    PipelineEnv.reset()
    before_f, before_u = _dispatch_counts()
    pipe(data).get()
    after_f, after_u = _dispatch_counts()
    assert after_f - before_f == 1, "fused chain must dispatch exactly once"
    assert after_u - before_u == 0

    PipelineEnv.reset()
    with fusion_disabled():
        before_f, before_u = _dispatch_counts()
        pipe(data).get()
        after_f, after_u = _dispatch_counts()
    assert after_f - before_f == 0
    assert after_u - before_u == 4, "unfused chain pays one dispatch per node"


def test_fused_chain_compiles_once():
    from keystone_tpu.utils.compilation_cache import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    PipelineEnv.reset()
    fitted = _chain(Scale(7), Shift(2), Scale(0.5), Shift(1)).fit()
    assert len(_fused_ops(fitted.graph)) == 1
    # fresh, never-seen shape so the fused executable must compile here
    before = compile_count()
    fitted.apply_batch(ArrayDataset(np.ones((5, 11), np.float32)))
    delta = compile_count() - before
    assert delta == 1, f"4-node fused chain compiled {delta} executables"
    # steady state (the serving contract): same shape, zero compiles
    before = compile_count()
    fitted.apply_batch(ArrayDataset(np.ones((5, 11), np.float32)))
    assert compile_count() - before == 0


def test_fusion_metrics_move():
    reg_before = {
        "chains": _names.metric(_names.FUSION_CHAINS).total(),
        "nodes": _names.metric(_names.FUSION_FUSED_NODES).total(),
        "saved": _names.metric(_names.FUSION_DISPATCHES_SAVED).total(),
        "compiles": _names.metric(_names.FUSION_COMPILES).total(),
    }
    pipe = _chain(Scale(2), Shift(1), Scale(3))
    PipelineEnv.reset()
    pipe(ArrayDataset(np.ones((3, 9), np.float32))).get()
    assert _names.metric(_names.FUSION_CHAINS).total() - reg_before["chains"] == 1
    assert _names.metric(_names.FUSION_FUSED_NODES).total() - reg_before["nodes"] == 3
    assert _names.metric(_names.FUSION_DISPATCHES_SAVED).total() - reg_before["saved"] == 2
    assert _names.metric(_names.FUSION_COMPILES).total() - reg_before["compiles"] >= 1


def test_repeated_unfitted_apply_shares_one_compiled_chain():
    """Every optimizer run builds a fresh FusedTransformerOperator, but
    chains over the same member instances share one jitted callable —
    re-applying an unfitted pipeline must not retrace/recompile."""
    from keystone_tpu.utils.compilation_cache import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    pipe = _chain(Scale(1.5), Shift(2), Scale(3))
    PipelineEnv.reset()
    pipe(ArrayDataset(np.ones((6, 7), np.float32))).get()  # compiles once
    before = compile_count()
    for _ in range(3):
        PipelineEnv.reset()
        pipe(ArrayDataset(np.ones((6, 7), np.float32))).get()
    assert compile_count() - before == 0, (
        "re-optimized fused chains over the same members recompiled"
    )


def test_untraceable_member_falls_back_to_eager():
    class HostBranch(BatchTransformer):
        """Reads a concrete value at trace time — not jit-traceable."""

        def apply_arrays(self, x):
            if float(np.asarray(x).sum()) >= 0:  # host read of a tracer
                return x * 2.0
            return x

    pipe = _chain(Shift(1), HostBranch())
    PipelineEnv.reset()
    fitted = pipe.fit()
    (fused,) = _fused_ops(fitted.graph)
    out = fitted.apply_batch(ArrayDataset(np.ones((3, 4), np.float32)))
    np.testing.assert_allclose(np.asarray(out.data), 4.0)
    assert fused._eager_fallback is True


def test_runtime_errors_propagate_without_unfusing():
    """Only trace failures demote to eager; a runtime error from the
    chain must propagate (reliability layer's business) and must NOT
    silently drop the single-dispatch guarantee."""
    class Boom(Scale):
        def apply_arrays(self, x):
            raise RuntimeError("device exploded")

    pipe = _chain(Scale(2), Boom(1))
    PipelineEnv.reset()
    fitted = pipe.fit()
    (fused,) = _fused_ops(fitted.graph)
    with pytest.raises(RuntimeError, match="device exploded"):
        fitted.apply_batch(ArrayDataset(np.ones((3, 4), np.float32)))
    assert fused._eager_fallback is False


# ------------------------------------------------------- autocache stability


def test_autocache_decisions_identical_with_fusion_on():
    """Cache insertion happens before fusion, so the set of inserted
    Cacher nodes must not depend on the fusion switch."""
    from keystone_tpu.ops.util.misc import CacherOperator
    from keystone_tpu.workflow.rules import auto_caching_optimizer

    def cachers(with_fusion: bool):
        PipelineEnv.reset()
        env = PipelineEnv.get_or_create()
        env.optimizer = auto_caching_optimizer(strategy="aggressive")
        shared = _chain(Scale(2), Shift(1))
        fan = Pipeline.gather([shared.then(Scale(3)), shared.then(Shift(5))])
        if with_fusion:
            res = fan(ArrayDataset(x4))
        else:
            with fusion_disabled():
                res = fan(ArrayDataset(x4))
        graph = res._executor.graph
        return sum(
            isinstance(op, CacherOperator) for op in graph.operators.values()
        )

    assert cachers(True) == cachers(False)


# -------------------------------------------------------------- serialization


def test_fused_fitted_pipeline_pickles(tmp_path):
    pipe = _chain(Scale(2), Shift(1), Scale(3))
    PipelineEnv.reset()
    fitted = pipe.fit()
    assert len(_fused_ops(fitted.graph)) == 1
    path = str(tmp_path / "fused.pkl")
    fitted.save(path)
    loaded = FittedPipeline.load(path)
    out = loaded.apply_batch(ArrayDataset(x4))
    ref = fitted.apply_batch(ArrayDataset(x4))
    np.testing.assert_allclose(np.asarray(out.data), np.asarray(ref.data))


def test_registry_refuses_nothing_and_refuses_loaded_artifacts(tmp_path):
    """Artifacts saved UNFUSED are re-fused by the serving registry —
    through both load doors (fitted artifact and reliability checkpoint)."""
    import pickle as _pickle

    from keystone_tpu.serving.registry import ModelRegistry

    with fusion_disabled():
        PipelineEnv.reset()
        fitted = _chain(Scale(2), Shift(1), Scale(3)).fit()
    assert _fused_ops(fitted.graph) == []
    path = str(tmp_path / "unfused.pkl")
    fitted.save(path)
    registry = ModelRegistry()
    entry = registry.load_fitted("m", path)
    assert len(_fused_ops(entry.model.graph)) == 1
    out = entry.batch_apply(ArrayDataset(x4))
    np.testing.assert_allclose(
        np.asarray(out.data),
        np.asarray(fitted.apply_batch(ArrayDataset(x4)).data),
        rtol=1e-6,
    )
    # checkpoint door: same re-fusion
    with open(tmp_path / "abcdef123456.pkl", "wb") as f:
        _pickle.dump(fitted, f)
    ckpt = registry.load_checkpoint("c", str(tmp_path), "abcdef")
    assert len(_fused_ops(ckpt.model.graph)) == 1


# ------------------------------------------------------------------- serving


@pytest.mark.serving
def test_serving_zero_compiles_after_warmup_with_fusion():
    from keystone_tpu.serving import PipelineServer, ServingConfig
    from keystone_tpu.serving.synthetic import (
        synthetic_chain_pipeline,
        synthetic_requests,
    )

    d = 16
    fitted = synthetic_chain_pipeline(num_nodes=4, d=d, fused=True)
    assert len(_fused_ops(fitted.graph)) == 1
    server = PipelineServer(
        fitted, config=ServingConfig(max_batch=4, max_wait_ms=1.0, queue_depth=64)
    ).start()
    try:
        server.warmup(np.zeros((d,), np.float32))
        for f in server.submit_many(synthetic_requests(24, d=d)):
            f.result(timeout=30)
        stats = server.stats()
    finally:
        server.stop()
    assert stats["served"] == 24
    assert stats["xla_compiles_since_warmup"] == 0


def test_synthetic_chain_fused_unfused_parity():
    from keystone_tpu.serving.synthetic import synthetic_chain_pipeline

    d = 8
    x = np.random.default_rng(3).normal(size=(5, d)).astype(np.float32)
    fused = synthetic_chain_pipeline(num_nodes=5, d=d, seed=7, fused=True)
    unfused = synthetic_chain_pipeline(num_nodes=5, d=d, seed=7, fused=False)
    assert len(_fused_ops(fused.graph)) == 1
    assert _fused_ops(unfused.graph) == []
    a = np.asarray(fused.apply_batch(ArrayDataset(x)).data, dtype=np.float64)
    b = np.asarray(unfused.apply_batch(ArrayDataset(x)).data, dtype=np.float64)
    err = np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-30)
    assert err <= 1e-5
