"""AutoCacheRule × ProfileStore: warm-starting the cost model from
persisted profiles (docs/OBSERVABILITY.md, docs/OPTIMIZER.md).

The acceptance contract: a second fit of an identical pipeline — in a
FRESH PROCESS — skips sample execution entirely (zero profiling-
interpreter runs) and reaches byte-identical cache decisions from the
stored linear-fit coefficients. KEYSTONE_PROFILE_STORE=off restores the
always-reprofile behavior; an environment-fingerprint change invalidates
the warm start.
"""

import json
import os
import subprocess
import sys

import numpy as np

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.obs.store import ProfileStore
from keystone_tpu.ops.util.misc import CacherOperator
from keystone_tpu.workflow.autocache import AutoCacheRule
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.operators import DatasetOperator, TransformerOperator

FP = {"jax": "test", "backend": "cpu", "device_kind": "virtual"}


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class CountingOp(TransformerOperator):
    """Identity op counting sample executions, charging fake time."""

    def __init__(self, name, delay_s=0.0, clock=None):
        self.name = name
        self.delay_s = delay_s
        self.clock = clock
        self.batch_calls = 0

    @property
    def label(self):
        return self.name

    def single_transform(self, datums):
        return datums[0]

    def batch_transform(self, datasets):
        self.batch_calls += 1
        if self.delay_s and self.clock is not None:
            self.clock.t += self.delay_s
        return datasets[0]


def diamond(clock, n=64):
    """dataset → expensive shared → two consumers → sinks; returns
    (graph, ops)."""
    data = ArrayDataset(np.ones((n, 4), dtype=np.float32))
    g = Graph()
    g, d = g.add_node(DatasetOperator(data), [])
    ops = [CountingOp("shared", delay_s=0.01, clock=clock)]
    g, sh = g.add_node(ops[0], [d])
    for name in ("left", "right"):
        op = CountingOp(name, clock=clock)
        ops.append(op)
        g, c = g.add_node(op, [sh])
        g, _ = g.add_sink(c)
    return g, ops


def decisions(graph):
    """Sorted labels of the nodes the planner chose to cache."""
    return sorted(
        graph.get_operator(graph.get_dependencies(c)[0]).label
        for c in graph.nodes
        if isinstance(graph.get_operator(c), CacherOperator)
    )


def rule(tmp_path, clock, fp=FP):
    store = ProfileStore(str(tmp_path / "ps.jsonl"), fingerprint=dict(fp))
    return AutoCacheRule(
        budget_bytes=1 << 30, clock=clock, profile_store=store
    )


def test_warm_store_skips_sampling_with_identical_decisions(tmp_path):
    clock = FakeClock()
    g1, ops1 = diamond(clock)
    out1, _ = rule(tmp_path, clock).apply(g1, {})
    assert sum(op.batch_calls for op in ops1) > 0  # cold: sampled
    first = decisions(out1)
    assert first  # the expensive shared node was worth caching

    # Fresh rule + fresh store INSTANCE over the same file + structurally
    # identical graph: zero sample executions, identical cache set.
    clock2 = FakeClock()
    g2, ops2 = diamond(clock2)
    out2, _ = rule(tmp_path, clock2).apply(g2, {})
    assert sum(op.batch_calls for op in ops2) == 0
    assert decisions(out2) == first


def test_off_switch_reprofiles_every_plan(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_PROFILE_STORE", "off")
    for _ in range(2):
        clock = FakeClock()
        g, ops = diamond(clock)
        AutoCacheRule(budget_bytes=1 << 30, clock=clock).apply(g, {})
        assert sum(op.batch_calls for op in ops) > 0


def test_fingerprint_change_forces_reprofile(tmp_path):
    clock = FakeClock()
    g, _ = diamond(clock)
    rule(tmp_path, clock).apply(g, {})
    clock2 = FakeClock()
    g2, ops2 = diamond(clock2)
    out, _ = rule(
        tmp_path, clock2, fp={**FP, "jax": "different-version"}
    ).apply(g2, {})
    assert sum(op.batch_calls for op in ops2) > 0  # re-sampled
    assert decisions(out)  # and still decided from the fresh samples


def test_changed_data_changes_digest_and_reprofiles(tmp_path):
    clock = FakeClock()
    g, _ = diamond(clock, n=64)
    rule(tmp_path, clock).apply(g, {})
    clock2 = FakeClock()
    g2, ops2 = diamond(clock2, n=32)  # different training data
    rule(tmp_path, clock2).apply(g2, {})
    assert sum(op.batch_calls for op in ops2) > 0


# ------------------------------------------------------ fresh-process contract

_FIT_SCRIPT = r"""
import json, os
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.obs import metrics as obs_metrics
from keystone_tpu.obs import names as obs_names
from keystone_tpu.obs.store import get_store
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.ops.stats.core import LinearRectifier, RandomSignNode
from keystone_tpu.ops.util.misc import CacherOperator
from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.rules import auto_caching_optimizer

rng = np.random.default_rng(0)
x = rng.normal(size=(96, 8)).astype(np.float32)
y = rng.normal(size=(96, 2)).astype(np.float32)
feat = RandomSignNode.create(8, seed=3).to_pipeline().then(LinearRectifier(0.0))
pipe = feat.then_label_estimator(
    BlockLeastSquaresEstimator(4, num_iter=2, reg=1e-3),
    ArrayDataset(x), ArrayDataset(y),
)
env = PipelineEnv.get_or_create()
env.optimizer = auto_caching_optimizer()

# The same optimize step Pipeline.fit() runs first — captured here so the
# chosen cache set is observable, then the fit itself completes end to end.
graph, prefixes = env.optimizer.execute(pipe.graph)
cached = sorted(
    type(graph.get_operator(graph.get_dependencies(c)[0])).__name__
    for c in graph.nodes
    if isinstance(graph.get_operator(c), CacherOperator)
)
fitted = pipe.fit()
out = np.asarray(fitted(ArrayDataset(x)).get().data)
assert out.shape[0] == 96 and np.isfinite(out).all()

hist = obs_metrics.get_registry().get(obs_names.AUTOCACHE_PROFILE_SECONDS)
store = get_store()
print("RESULT " + json.dumps({
    "sampling_runs": hist.count() if hist is not None else 0,
    "decisions": cached,
    "store": store.stats() if store is not None else None,
}))
"""


def test_second_fit_in_fresh_process_skips_sampling(tmp_path):
    """The acceptance contract, end to end: run the SAME real pipeline
    fit in two fresh processes sharing one persisted store. Run 1
    sample-profiles and records; run 2 performs ZERO sample-interpreter
    runs and reaches byte-identical cache decisions."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["KEYSTONE_PROFILE_STORE"] = str(tmp_path / "ps.jsonl")
    env.pop("KEYSTONE_MEASURED_KNOBS", None)

    def run():
        proc = subprocess.run(
            [sys.executable, "-c", _FIT_SCRIPT], env=env,
            capture_output=True, text=True, timeout=240,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")]
        assert line, proc.stdout[-2000:]
        return json.loads(line[0][len("RESULT "):])

    first = run()
    assert first["sampling_runs"] > 0, first
    assert first["store"]["writes"] > 0, first

    second = run()
    assert second["sampling_runs"] == 0, second  # zero sample-interpreter runs
    assert second["decisions"] == first["decisions"]  # byte-identical choices
    assert second["store"]["hits"] > 0, second


def test_changed_profiling_config_reprofiles(tmp_path):
    """Warm-start entries only cover plans profiled under the SAME
    profiling config: a rule reconfigured with different sample scales or
    trial counts must re-execute sample profiling, not silently reuse
    coefficients measured under the old config."""
    clock = FakeClock()
    g, ops = diamond(clock)
    rule(tmp_path, clock).apply(g, {})
    assert sum(op.batch_calls for op in ops) > 0  # cold: sampled

    clock2 = FakeClock()
    g2, ops2 = diamond(clock2)
    rule(tmp_path, clock2).apply(g2, {})
    assert sum(op.batch_calls for op in ops2) == 0  # warm, same config

    clock3 = FakeClock()
    g3, ops3 = diamond(clock3)
    store = ProfileStore(str(tmp_path / "ps.jsonl"), fingerprint=dict(FP))
    AutoCacheRule(
        budget_bytes=1 << 30, clock=clock3, profile_store=store,
        profile_scales=(2, 4, 8),
    ).apply(g3, {})
    # same store, different scales: measured afresh, not silently reused
    assert sum(op.batch_calls for op in ops3) > 0
