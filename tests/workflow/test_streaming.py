"""Streaming chunked execution: plan rewrite, boundaries, parity,
bounded memory, compile/overlap invariants, and failure shutdown
(workflow/streaming.py, docs/STREAMING.md)."""

import threading

import numpy as np
import pytest

from keystone_tpu.data.dataset import (
    ArrayDataset,
    ObjectDataset,
    default_ingest_workers,
    transfer_dtype,
)
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.ops.util.misc import CacherOperator
from keystone_tpu.workflow import (
    BatchTransformer,
    LabelEstimator,
    Pipeline,
    streaming_disabled,
)
from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.streaming import (
    ChunkStream,
    StreamingFitOperator,
    last_stream_report,
)

CHUNK = 64


@pytest.fixture(autouse=True)
def _small_chunks(monkeypatch):
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", str(CHUNK))


class Scale(BatchTransformer):
    def __init__(self, c):
        self.c = float(c)

    def apply_arrays(self, x):
        return x * self.c


class Shift(BatchTransformer):
    def __init__(self, c):
        self.c = float(c)

    def apply_arrays(self, x):
        return x + self.c


def _problem(n=8 * CHUNK, d=32, k=4, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(dtype)
    w = rng.normal(size=(d, k)).astype(np.float32)
    y = (x.astype(np.float32) @ w + 0.01 * rng.normal(size=(n, k))).astype(
        np.float32
    )
    return x, y


def _chain_pipeline(x, y, est=None):
    feat = Scale(2.0).to_pipeline().then(Shift(0.5))
    est = est or BlockLeastSquaresEstimator(16, num_iter=2, reg=1e-3)
    return feat.then_label_estimator(est, ArrayDataset(x), ArrayDataset(y))


def _fit_predict(pipe, x):
    handle = pipe.apply(ArrayDataset(x))
    return handle, np.asarray(handle.get().data)[: x.shape[0]]


def _stream_ops(graph):
    return [
        op
        for op in graph.operators.values()
        if isinstance(op, StreamingFitOperator)
    ]


# ---------------------------------------------------------------- plan rewrite


def test_plan_rewrites_eligible_chain():
    x, y = _problem()
    handle = _chain_pipeline(x, y).apply(ArrayDataset(x))
    graph = handle._executor.graph
    ops = _stream_ops(graph)
    assert len(ops) == 1
    # The fit-side featurize chain was absorbed (flattened out of the
    # fused node) and its nodes removed from the graph.
    assert [type(m).__name__ for m in ops[0].members] == ["Scale", "Shift"]
    # The apply side keeps its own (fused) chain: output is still the
    # model applied to featurized input.
    _, preds = handle._executor, np.asarray(handle.get().data)
    assert preds.shape[1] == y.shape[1]


def test_no_rewrite_without_fit_stream_support():
    class ToyEstimator(LabelEstimator):
        def fit(self, data, labels):
            return Shift(0.0)

    x, y = _problem(n=4 * CHUNK)
    handle = _chain_pipeline(x, y, est=ToyEstimator()).apply(ArrayDataset(x))
    assert not _stream_ops(handle._executor.graph)


def test_no_rewrite_below_row_floor():
    x, y = _problem(n=CHUNK)  # one chunk: materialized path wins
    handle = _chain_pipeline(x, y).apply(ArrayDataset(x))
    assert not _stream_ops(handle._executor.graph)


def test_no_rewrite_when_disabled():
    x, y = _problem()
    with streaming_disabled():
        handle = _chain_pipeline(x, y).apply(ArrayDataset(x))
        assert not _stream_ops(handle._executor.graph)


# -------------------------------------------------------------------- parity


def test_parity_synthetic_chain():
    x, y = _problem()
    _, streamed = _fit_predict(_chain_pipeline(x, y), x)
    assert last_stream_report() is not None
    assert last_stream_report().chunks == 8
    PipelineEnv.reset()
    with streaming_disabled():
        _, materialized = _fit_predict(_chain_pipeline(x, y), x)
    rel = np.linalg.norm(streamed - materialized) / np.linalg.norm(materialized)
    assert rel <= 1e-5


def test_parity_mnist_fft_features():
    """Streaming-vs-materialized on MNIST-FFT featurized data — the
    reg-floor (reg=0) block solve, the realistic parity risk. A 64-pixel
    variant keeps the system overdetermined (n > d): parity at the
    reg FLOOR is only meaningful when the solution is data-determined,
    not floor-determined."""
    from keystone_tpu.pipelines.mnist_random_fft import (
        MnistRandomFFTConfig,
        build_featurizer,
    )

    n, pixels = 8 * CHUNK, 64
    rng = np.random.default_rng(3)
    x = rng.normal(size=(n, pixels)).astype(np.float32)
    feats_handle = build_featurizer(
        MnistRandomFFTConfig(num_ffts=2), image_size=pixels
    ).apply(ArrayDataset(x))
    feats = np.asarray(feats_handle.get().data)[:n].astype(np.float32)
    assert feats.shape[1] < n  # overdetermined by construction
    y = -np.ones((n, 10), np.float32)
    y[np.arange(n), rng.integers(0, 10, n)] = 1.0

    def build():
        est = BlockLeastSquaresEstimator(64, num_iter=1, reg=0.0)
        return est.with_data(ArrayDataset(feats), ArrayDataset(y))

    handle, streamed = _fit_predict(build(), feats)
    assert _stream_ops(handle._executor.graph), "direct dataset→fit did not stream"
    PipelineEnv.reset()
    with streaming_disabled():
        _, materialized = _fit_predict(build(), feats)
    rel = np.linalg.norm(streamed - materialized) / np.linalg.norm(materialized)
    assert rel <= 1e-5


def test_parity_cacher_boundary():
    """A Cacher between featurize stages cuts the streamed chain: the
    stream starts from the cached materialization, and results match the
    materialized path exactly."""
    x, y = _problem()

    def build():
        graph_pipe = Scale(3.0).to_pipeline()
        # splice a CacherOperator after Scale by direct surgery
        graph = graph_pipe.graph
        graph, cache_node = graph.add_node(
            CacherOperator("t"), [graph.get_sink_dependency(graph_pipe.sink)]
        )
        graph = graph.set_sink_dependency(graph_pipe.sink, cache_node)
        cached = Pipeline(graph, graph_pipe.source, graph_pipe.sink)
        feat = cached.then(Shift(-0.25))
        return feat.then_label_estimator(
            BlockLeastSquaresEstimator(16, num_iter=1, reg=1e-3),
            ArrayDataset(x),
            ArrayDataset(y),
        )

    handle, streamed = _fit_predict(build(), x)
    ops = _stream_ops(handle._executor.graph)
    assert len(ops) == 1
    # Chain stops AT the cacher: only Shift is streamed.
    assert [type(m).__name__ for m in ops[0].members] == ["Shift"]
    assert any(
        isinstance(op, CacherOperator)
        for op in handle._executor.graph.operators.values()
    )
    PipelineEnv.reset()
    with streaming_disabled():
        _, materialized = _fit_predict(build(), x)
    rel = np.linalg.norm(streamed - materialized) / np.linalg.norm(materialized)
    assert rel <= 1e-5


def test_fit_stream_linear_map_exact_parity():
    x, y = _problem(d=24, k=3)
    est = LinearMapEstimator(reg=1e-2)
    stream = ChunkStream(ArrayDataset(x), ArrayDataset(y), (), chunk_rows=CHUNK)
    streamed = est.fit_stream(stream)
    materialized = est.fit(ArrayDataset(x), ArrayDataset(y))
    a = np.asarray(streamed.apply_arrays(x))
    b = np.asarray(materialized.apply_arrays(x))
    assert np.linalg.norm(a - b) / np.linalg.norm(b) <= 1e-5


# ---------------------------------------------------- memory/compile/overlap


def test_bounded_host_memory():
    """Dataset 10× chunk; peak concurrently-live host chunk buffers stay
    under 2× one chunk's bytes (queue depth 1 + one in hand)."""
    x, y = _problem(n=10 * CHUNK, d=64, k=4)
    _fit_predict(_chain_pipeline(x, y), x)
    rep = last_stream_report()
    assert rep is not None and rep.chunks == 10
    chunk_bytes = CHUNK * 64 * 4 + CHUNK * 4 * 4 + CHUNK * 4  # x + y + mask
    assert rep.host_buffer_peak_bytes <= 2 * chunk_bytes
    assert rep.host_buffer_peak_bytes < x.nbytes / 2  # O(chunk), not O(n)


def test_one_compile_per_chunk_shape_and_overlap():
    x, y = _problem()
    pipe = _chain_pipeline(x, y)
    _fit_predict(pipe, x)
    rep = last_stream_report()
    assert rep.compiles_first_chunk == 1  # one fused step trace
    assert rep.compiles_steady_state == 0  # tail chunk padded to same shape
    assert rep.overlap_ok()
    # Re-fit of the same pipeline (fresh planning, same member
    # instances): the shared step jit is reused — zero new traces.
    PipelineEnv.reset()
    _fit_predict(pipe, x)
    rep2 = last_stream_report()
    assert rep2.compiles_first_chunk == 1
    assert rep2.compiles_steady_state == 0


def test_uint8_chunks_cross_narrow_and_cast_on_device():
    rng = np.random.default_rng(5)
    n, h = 8 * CHUNK, 16
    imgs = rng.integers(0, 256, size=(n, h), dtype=np.uint8)
    w = rng.normal(size=(h, 3)).astype(np.float32)
    y = (imgs.astype(np.float32) @ w).astype(np.float32)
    pipe = _chain_pipeline(imgs, y)  # Scale casts on device (uint8 input)
    handle, _ = _fit_predict(pipe, imgs.astype(np.float32))
    rep = last_stream_report()
    per_chunk = CHUNK * h * 1 + CHUNK * 3 * 4 + CHUNK * 4  # uint8 x + y + mask
    assert rep.bytes_transferred == 8 * per_chunk


def test_object_dataset_streams_via_worker_stacking():
    """Host ObjectDataset (the ingest staging ground) streams too: the
    prefetch workers stack item windows into chunks."""
    x, y = _problem(n=6 * CHUNK, d=16, k=2)
    rows = ObjectDataset([x[i] for i in range(len(x))])
    est = BlockLeastSquaresEstimator(8, num_iter=1, reg=1e-3)
    pipe = Scale(1.5).to_pipeline().then_label_estimator(
        est, rows, ArrayDataset(y)
    )
    handle, streamed = _fit_predict(pipe, x)
    assert _stream_ops(handle._executor.graph)
    assert last_stream_report().chunks == 6
    PipelineEnv.reset()
    with streaming_disabled():
        pipe2 = Scale(1.5).to_pipeline().then_label_estimator(
            est, ObjectDataset([x[i] for i in range(len(x))]), ArrayDataset(y)
        )
        _, materialized = _fit_predict(pipe2, x)
    rel = np.linalg.norm(streamed - materialized) / np.linalg.norm(materialized)
    assert rel <= 1e-5


def test_runtime_fallback_on_unchunkable_dataset():
    """A planned stream whose data turns out unchunkable at run time
    (here a BucketedDataset) must take the materialized path, not crash."""
    from keystone_tpu.data.dataset import BucketedDataset
    from keystone_tpu.workflow.streaming import StreamingFitOperator

    x, y = _problem(n=4 * CHUNK, d=16, k=2)
    buckets = BucketedDataset(
        [ArrayDataset(x[i : i + CHUNK]) for i in range(0, len(x), CHUNK)]
    )
    op = StreamingFitOperator(
        BlockLeastSquaresEstimator(8, num_iter=1, reg=1e-3), (Scale(2.0),)
    )
    model = op.fit_datasets([buckets, ArrayDataset(y)])
    ref = BlockLeastSquaresEstimator(8, num_iter=1, reg=1e-3).fit(
        Scale(2.0).apply_batch(ArrayDataset(x)), ArrayDataset(y)
    )
    a = np.asarray(model.apply_arrays(x))
    b = np.asarray(ref.apply_arrays(x))
    assert np.linalg.norm(a - b) / np.linalg.norm(b) <= 1e-6


# ------------------------------------------------------------------ failure


def test_prefetch_shutdown_on_midstream_failure():
    from keystone_tpu.reliability.faultinject import FaultSpec, injected

    x, y = _problem()
    pipe = _chain_pipeline(x, y)
    with injected(FaultSpec(match="streaming.chunk", kind="transient", calls=(3,))):
        with pytest.raises(ConnectionError):
            pipe.apply(ArrayDataset(x)).get()
    for _ in range(50):
        if not [
            t
            for t in threading.enumerate()
            if "prefetch" in t.name and t.is_alive()
        ]:
            break
        import time

        time.sleep(0.05)
    leaked = [t.name for t in threading.enumerate() if "prefetch" in t.name]
    assert not leaked, f"leaked prefetch workers: {leaked}"


# ------------------------------------------------------------- data plumbing


def test_iter_chunks_array_and_object():
    x = np.arange(10 * 3, dtype=np.float32).reshape(10, 3)
    chunks = list(ArrayDataset(x).iter_chunks(4))
    assert [n for _, n in chunks] == [4, 4, 2]
    assert np.allclose(np.concatenate([c for c, _ in chunks]), x)
    obj = ObjectDataset([x[i] for i in range(10)])
    chunks_o = list(obj.iter_chunks(4))
    assert [n for _, n in chunks_o] == [4, 4, 2]
    assert np.allclose(np.concatenate([c for c, _ in chunks_o]), x)


def test_dtype_preserved_through_pad_and_shard():
    import jax

    from keystone_tpu.parallel.mesh import get_mesh

    ds = ArrayDataset(np.zeros((10, 4, 4, 3), np.uint8))
    padded = ds.padded_to(8)
    assert all(
        l.dtype == np.uint8 for l in jax.tree_util.tree_leaves(padded.data)
    )
    sharded = ds.shard(get_mesh())
    assert all(
        l.dtype == np.uint8 for l in jax.tree_util.tree_leaves(sharded.data)
    )
    # 64-bit host data narrows to 32-bit for the transfer
    wide = ArrayDataset(np.zeros((10, 4), np.float64)).shard(get_mesh())
    assert all(
        l.dtype == np.float32 for l in jax.tree_util.tree_leaves(wide.data)
    )
    assert transfer_dtype(np.float64) == np.float32
    assert transfer_dtype(np.uint8) == np.uint8


def test_ingest_workers_env(monkeypatch):
    monkeypatch.setenv("KEYSTONE_INGEST_WORKERS", "3")
    assert default_ingest_workers() == 3
    monkeypatch.delenv("KEYSTONE_INGEST_WORKERS")
    assert default_ingest_workers() >= 2


def test_prefetch_queue_order_errors_and_close():
    from keystone_tpu.data.ingest import PrefetchQueue

    q = PrefetchQueue(iter(range(20)), lambda i: i * i, depth=3, workers=3)
    assert list(q) == [i * i for i in range(20)]
    q.close()

    def boom(i):
        if i == 5:
            raise ValueError("bad item")
        return i

    q2 = PrefetchQueue(iter(range(10)), boom, depth=2, workers=2)
    got = []
    with pytest.raises(ValueError, match="bad item"):
        for v in q2:
            got.append(v)
    assert got == [0, 1, 2, 3, 4]  # order preserved up to the failure
    q2.close()
    assert not [t for t in threading.enumerate() if "prefetch" in t.name]
