"""Offline autotuner: jax-free search-core tests on a deterministic
synthetic cost surface, plus the store round-trip that proves a tuned
entry actually lands in a plan knob (docs/AUTOTUNING.md)."""

import json
import time

import numpy as np
import pytest

from keystone_tpu.obs import names as _names
from keystone_tpu.obs.store import ProfileStore
from keystone_tpu.workflow.tune import (
    Measurement,
    RidgeCostModel,
    Tuner,
    TuneSpace,
)

FP = {"jax": "test", "backend": "cpu", "device_kind": "virtual"}


def store(tmp_path):
    return ProfileStore(str(tmp_path / "ps.jsonl"), fingerprint=dict(FP))


# ----------------------------------------------------------- the cost model


def test_ridge_model_ranks_a_loglinear_surface():
    space = TuneSpace("t", {"chunk_rows": [256, 512, 1024, 2048, 4096]})
    cands = space.grid()
    # wall grows with |log2(c) - log2(1024)|: optimum at 1024
    cost = [2.0 ** abs(np.log2(c["chunk_rows"]) - 10.0) for c in cands]
    model = RidgeCostModel().fit([space.encode(c) for c in cands], cost)
    preds = model.predict([space.encode(c) for c in cands])
    # the model need not be exact — it must RANK the optimum's basin
    # first (the quadratic log2 features capture the V shape)
    assert cands[int(np.argmin(preds))]["chunk_rows"] == 1024


def test_space_encoding_numeric_and_categorical():
    space = TuneSpace(
        "t", {"block": [32, 64], "precision": ["default", "highest"]}
    )
    f = space.encode({"block": 32, "precision": "highest"})
    # log2 + log2² features + one-hot(2)
    assert len(f) == 4
    assert f != space.encode({"block": 64, "precision": "highest"})


# --------------------------------------------------------------- the search


def _surface(cand):
    """Deterministic synthetic cost surface with a unique known optimum
    at (chunk_rows=2048, prefetch=2): smooth in log2(chunk), small
    additive prefetch effect — the shape a real chunk sweep has."""
    wall = 2.0 ** abs(np.log2(cand["chunk_rows"]) - 11.0)
    wall += 0.25 if cand["prefetch"] == 1 else 0.0
    return wall


SPACE = TuneSpace(
    "synthetic",
    {"chunk_rows": [256, 512, 1024, 2048, 4096, 8192], "prefetch": [1, 2]},
)


def test_converges_to_known_optimum_within_budget():
    # 12-point grid, budget 7: the model must steer to the optimum — an
    # exhaustive sweep could not fit the budget.
    tuner = Tuner(budget=7, explore=0.25, seed=0, time_budget_s=60)
    out = tuner.search(
        SPACE, _surface, default={"chunk_rows": 4096, "prefetch": 1}
    )
    assert len(out.measured) <= 7 < len(SPACE.grid())
    assert out.winner.knobs == {"chunk_rows": 2048, "prefetch": 2}
    assert out.improved  # the env default was beaten on the same surface
    assert out.default.proposed_by == "default"


def test_model_proposals_actually_steer():
    tuner = Tuner(budget=8, explore=0.0, seed=3, time_budget_s=60)
    out = tuner.search(
        SPACE, _surface, default={"chunk_rows": 256, "prefetch": 1}
    )
    assert any(m.proposed_by == "model" for m in out.measured)
    assert out.winner.knobs["chunk_rows"] == 2048


def test_budget_and_failed_candidates():
    calls = []

    def flaky(cand):
        calls.append(cand)
        if cand["chunk_rows"] == 512:
            raise RuntimeError("boom")
        return _surface(cand)

    tuner = Tuner(budget=5, explore=1.0, seed=1, time_budget_s=60)
    out = tuner.search(SPACE, flaky, default={"chunk_rows": 512, "prefetch": 1})
    # failures consume attempts but never land in measured
    assert all(m.knobs["chunk_rows"] != 512 for m in out.measured)
    assert len(out.measured) <= 5


def test_time_budget_stops_search():
    def slow(cand):
        time.sleep(0.05)
        return _surface(cand)

    tuner = Tuner(budget=100, explore=1.0, seed=0, time_budget_s=0.12)
    out = tuner.search(SPACE, slow)
    assert 1 <= len(out.measured) <= 4


def test_maximize_objective():
    tuner = Tuner(budget=12, explore=1.0, seed=0, time_budget_s=60)
    out = tuner.search(
        SPACE, lambda c: 1.0 / _surface(c),
        default={"chunk_rows": 256, "prefetch": 1}, maximize=True,
    )
    assert out.winner.knobs == {"chunk_rows": 2048, "prefetch": 2}
    assert out.improved


def test_outcome_json_shape():
    tuner = Tuner(budget=3, explore=1.0, seed=0, time_budget_s=60)
    out = tuner.search(SPACE, _surface, default={"chunk_rows": 256, "prefetch": 1})
    payload = json.loads(json.dumps(out.to_json()))
    assert payload["task"] == "synthetic"
    assert payload["candidates_measured"] == len(out.measured)
    assert {"knobs", "objective", "proposed_by"} <= set(payload["measured"][0])


def test_candidate_metric_counted():
    before = _names.metric(_names.TUNE_CANDIDATES).value(task="synthetic")
    Tuner(budget=3, explore=1.0, seed=0, time_budget_s=60).search(
        SPACE, _surface
    )
    after = _names.metric(_names.TUNE_CANDIDATES).value(task="synthetic")
    assert after == before + 3


# ------------------------------------------------------- store round-trip


def test_tuned_store_entry_flows_into_plan_chunk_rows(tmp_path, monkeypatch):
    """The whole point of the loop: a tuner-written entry (source=tune)
    must be picked up by MeasuredKnobRule into an actual plan knob with
    zero plan-semantics change."""
    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.obs.store import dataset_shape_class
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
    from keystone_tpu.workflow.graph import Graph
    from keystone_tpu.workflow.knobs import MeasuredKnobRule
    from keystone_tpu.workflow.operators import DatasetOperator
    from keystone_tpu.workflow.streaming import StreamingFitOperator, chain_class

    st = store(tmp_path)
    data = ArrayDataset(np.ones((4096, 8), dtype=np.float32))
    shape = dataset_shape_class(data)
    # what tune_stream persists for the winning candidate
    st.record(
        f"stream:{chain_class(())}:cr1536", shape,
        chunk_rows=1536, rows_per_s=9e5, wall_s=0.01, source="tune",
    )
    # a worse passively-observed entry must lose to the tuned one
    st.record(
        f"stream:{chain_class(())}:cr4096", shape,
        chunk_rows=4096, rows_per_s=1e5, wall_s=0.09,
    )
    g = Graph()
    g, d = g.add_node(DatasetOperator(data), [])
    g, s = g.add_node(
        StreamingFitOperator(
            BlockLeastSquaresEstimator(512, num_iter=1, reg=1e-3), ()
        ),
        [d],
    )
    g, _ = g.add_sink(s)
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    assert out.get_operator(s).chunk_rows == 1536


def test_tuned_solver_entry_flows_into_block_size(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    monkeypatch.delenv("KEYSTONE_SOLVER_BLOCK", raising=False)
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.obs.store import shape_class
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
    from keystone_tpu.workflow.graph import Graph
    from keystone_tpu.workflow.knobs import MeasuredKnobRule
    from keystone_tpu.workflow.operators import DatasetOperator

    st = store(tmp_path)
    st.record(
        "solver:block_ls:bs64:prechighest", shape_class(4096, (8,), "float32"),
        wall_s=0.005, block_size=64, precision="highest", donate=True,
        source="tune",
    )
    data = ArrayDataset(np.ones((4096, 8), dtype=np.float32))
    g = Graph()
    g, d = g.add_node(DatasetOperator(data), [])
    g, s = g.add_node(_estimator_node(), [d])
    g, _ = g.add_sink(s)
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    op = out.get_operator(s)
    assert op.block_size == 64
    assert op.solver_precision == "highest"


def _estimator_node():
    from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator

    return BlockLeastSquaresEstimator(512, num_iter=1, reg=1e-3)


# --------------------------------------------------- rejected-knob metric


def test_non_unanimous_winner_counted_not_silent(tmp_path, monkeypatch):
    """Two widths in the same rows bucket disagreeing on block_size must
    not override — and must be COUNTED as a rejection, not dropped
    silently (the PR's satellite)."""
    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    monkeypatch.delenv("KEYSTONE_SOLVER_BLOCK", raising=False)
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.obs.store import shape_class
    from keystone_tpu.workflow.graph import Graph
    from keystone_tpu.workflow.knobs import MeasuredKnobRule
    from keystone_tpu.workflow.operators import DatasetOperator

    st = store(tmp_path)
    st.record(
        "solver:block_ls:bs64:prechighest", shape_class(4096, (8,), "float32"),
        wall_s=0.005, block_size=64, precision="highest",
    )
    st.record(
        "solver:block_ls:bs128:prechighest", shape_class(4096, (16,), "float32"),
        wall_s=0.004, block_size=128, precision="highest",
    )
    data = ArrayDataset(np.ones((4096, 8), dtype=np.float32))
    g = Graph()
    g, d = g.add_node(DatasetOperator(data), [])
    g, s = g.add_node(_estimator_node(), [d])
    g, _ = g.add_sink(s)
    rejected = _names.metric(_names.KNOB_REJECTED)
    before = rejected.value(knob="solver_block_size", reason="non_unanimous")
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    assert out.get_operator(s).block_size == 512  # untouched
    after = rejected.value(knob="solver_block_size", reason="non_unanimous")
    assert after > before


def test_warm_rows_from_store_history(tmp_path):
    """Prior persisted measurements train the surrogate for free; rows
    missing any space axis are skipped, never padded with fabricated
    knob values."""
    from keystone_tpu.workflow.tune import _warm_from_store

    st = store(tmp_path)
    space = TuneSpace(
        "solver",
        {"block_size": [32, 64], "precision": ["default", "highest"],
         "donation": [True, False]},
    )
    st.record(  # complete row: usable
        "solver:block_ls:bs64:prechighest", "n2^10|64|float32",
        wall_s=0.01, block_size=64, precision="highest", donate=True,
    )
    st.record(  # missing the donation axis: skipped
        "solver:block_ls:bs32:precdefault", "n2^10|64|float32",
        wall_s=0.02, block_size=32, precision="default",
    )
    warm = _warm_from_store(
        st, "solver:block_ls:", "n2^10|64|float32", space,
        {"block_size": "block_size", "precision": "precision",
         "donation": "donate"},
        "wall_s", maximize=False,
    )
    assert warm == [
        ({"block_size": 64, "precision": "highest", "donation": True}, 0.01)
    ]


# ------------------------------------------------------- store provenance


def test_source_provenance_default_and_by_source(tmp_path):
    st = store(tmp_path)
    st.record("solver:block_ls:bs64:prechighest", "n2^12|8|float32",
              wall_s=0.1, block_size=64)
    st.record("blocksparse:threshold", "n2^12|8|float32",
              threshold=0.1, source="tune")
    assert st.by_source() == {"observed": 1, "tune": 1}
    # provenance round-trips through the file
    st2 = ProfileStore(st.path, fingerprint=dict(FP))
    assert st2.by_source() == {"observed": 1, "tune": 1}
    m = st2.lookup("blocksparse:threshold", "n2^12|8|float32")
    assert m["source"] == "tune"
    # any_env reporting sees entries regardless of fingerprint
    other = ProfileStore(st.path, fingerprint={"jax": "x", "backend": "tpu",
                                               "device_kind": "v9"})
    assert list(other.entries(any_env=True))
    assert not list(other.entries())
