"""timed_execute's device-sync policy: real per-node timings only when a
trace/span session asks for them; metrics-only runs keep async dispatch."""

import numpy as np

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.obs import spans as _spans
from keystone_tpu.workflow import BatchTransformer, trace
from keystone_tpu.workflow.executor import PipelineEnv


class Double(BatchTransformer):
    def apply_arrays(self, x):
        return x * 2.0


def _run_pipeline():
    PipelineEnv.reset()
    pipe = Double().to_pipeline()
    return pipe(ArrayDataset(np.ones((3, 4), np.float32))).get()


def _forced_calls(monkeypatch):
    from keystone_tpu.workflow import tracing

    calls = []
    real = tracing._force
    monkeypatch.setattr(tracing, "_force", lambda v: calls.append(1) or real(v))
    return calls


def test_no_session_no_forced_sync(monkeypatch):
    calls = _forced_calls(monkeypatch)
    _run_pipeline()
    assert calls == [], "metrics-only execution must not block per node"


def test_trace_shim_forces(monkeypatch):
    calls = _forced_calls(monkeypatch)
    with trace() as t:
        _run_pipeline()
    assert len(calls) >= 1
    assert any(op.label == "Double" for op in t.timings)


def test_sync_session_forces(monkeypatch):
    calls = _forced_calls(monkeypatch)
    with _spans.tracing_session("t") as session:
        assert session.sync_timings is True
        _run_pipeline()
    assert len(calls) >= 1


def test_nosync_session_skips_force_but_keeps_spans(monkeypatch):
    calls = _forced_calls(monkeypatch)
    with _spans.tracing_session("t", sync_timings=False) as session:
        _run_pipeline()
    assert calls == [], "sync_timings=False session must keep async dispatch"
    node_spans = session.find("node:")
    assert node_spans, "node spans still recorded (dispatch-timed)"
    assert all(s.attributes.get("synced") is False for s in node_spans)
