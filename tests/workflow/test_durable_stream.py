"""Durable elastic fits (docs/RELIABILITY.md "Durable fits"): mid-stream
checkpoints, crash-resume parity, KV306 stale-entry refusal, shard-loss
elasticity, and the no-leaked-threads contract of an abandoned fold.

The cross-PROCESS face (a real SIGKILL + fresh-process resume) is
scripts/elastic_smoke.sh; these tests pin the same machinery in-process:
a fault aborts the fold, ``PipelineEnv.reset()`` stands in for the fresh
process, and the re-planned pipeline must find, validate, and seed from
the persisted cursor.
"""

import threading

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.parallel.partitioner import partition_disabled
from keystone_tpu.reliability import enable_checkpointing, faultinject
from keystone_tpu.reliability.durable import (
    load_resume_entry,
    resume_key,
    stream_ckpt_chunks,
)
from keystone_tpu.reliability.faultinject import FaultSpec
from keystone_tpu.reliability.recovery import get_recovery_log
from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.pipeline import BatchTransformer
from keystone_tpu.workflow.streaming import last_stream_report
from keystone_tpu.workflow.verify import VerificationError, verify_stream_resume

N, D, K, CHUNK = 512, 8, 2, 64  # 8 chunks; divisible by the 8-device mesh
rng = np.random.default_rng(7)
X = rng.normal(size=(N, D)).astype(np.float32)
W = rng.normal(size=(D, K)).astype(np.float32)
Y = (X @ W + 0.01 * rng.normal(size=(N, K))).astype(np.float32)
PROBE = rng.normal(size=(32, D)).astype(np.float32)


class Scale(BatchTransformer):
    def __init__(self, c):
        self.c = float(c)

    def apply_arrays(self, a):
        return a * self.c


def build(x=X, y=Y):
    return Scale(2.0).to_pipeline().then_label_estimator(
        LinearMapEstimator(reg=1e-3), ArrayDataset(x), ArrayDataset(y)
    )


def preds(fitted):
    return np.asarray(fitted.apply_batch(ArrayDataset(PROBE)).data)


def rel_err(a, b):
    return float(np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-30))


@pytest.fixture()
def chunked(monkeypatch):
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", str(CHUNK))
    monkeypatch.setenv("KEYSTONE_STREAM_CKPT_CHUNKS", "2")


@pytest.fixture()
def reference(chunked):
    """Uninterrupted single-device predictions (no store attached)."""
    PipelineEnv.reset()
    with partition_disabled():
        out = preds(build().fit())
    PipelineEnv.reset()
    return out


def _crash_at(store_dir, call, spec_kind="transient"):
    """Run a durable fit that dies at streaming.chunk call ``call``."""
    PipelineEnv.reset()
    enable_checkpointing(str(store_dir))
    with pytest.raises(ConnectionError):
        with faultinject.injected(
            FaultSpec(match="streaming.chunk", kind=spec_kind, calls=(call,))
        ):
            build().fit()


# ----------------------------------------------------------- checkpoints


def test_mid_fit_checkpoints_commit_and_retire(tmp_path, chunked):
    PipelineEnv.reset()
    store = enable_checkpointing(str(tmp_path))
    fitted = build().fit()
    report = last_stream_report()
    # 8 chunks, K=2 → commits before chunks 3, 5, 7 (dispatched = 2/4/6).
    assert report.checkpoints == 3
    assert report.resumed_from_chunk is None
    kinds = [e.kind for e in get_recovery_log().events()]
    assert kinds.count("stream_checkpoint") == 3
    # A COMPLETED fit retires its resume entry — nothing to mis-resume.
    est = LinearMapEstimator(reg=1e-3)
    key = resume_key(est, (Scale(2.0),), N)
    assert load_resume_entry(store, key) is None
    assert preds(fitted).shape == (32, K)


def test_checkpoint_off_path_untouched(tmp_path, chunked, monkeypatch):
    # Explicit 0 disables even with a store attached: no durable plan,
    # no commits, no resume machinery — today's fold.
    monkeypatch.setenv("KEYSTONE_STREAM_CKPT_CHUNKS", "0")
    PipelineEnv.reset()
    enable_checkpointing(str(tmp_path))
    build().fit()
    report = last_stream_report()
    assert report.checkpoints == 0 and report.resumed_from_chunk is None
    assert not get_recovery_log().events("stream_checkpoint")


def test_auto_arm_above_row_threshold(monkeypatch):
    monkeypatch.delenv("KEYSTONE_STREAM_CKPT_CHUNKS", raising=False)
    monkeypatch.setenv("KEYSTONE_STREAM_CKPT_AUTO_ROWS", "1000")
    assert stream_ckpt_chunks(999) == 0
    assert stream_ckpt_chunks(1000) == 32
    monkeypatch.setenv("KEYSTONE_STREAM_CKPT_CHUNKS", "5")
    assert stream_ckpt_chunks(10) == 5
    monkeypatch.setenv("KEYSTONE_STREAM_CKPT_CHUNKS", "0")
    assert stream_ckpt_chunks(10**9) == 0


# ---------------------------------------------------------- crash-resume


def test_crash_resume_parity_sharded(tmp_path, reference):
    _crash_at(tmp_path, call=5)
    assert last_stream_report().chunks == 4
    PipelineEnv.reset()  # the "fresh process"
    enable_checkpointing(str(tmp_path))
    fitted = build().fit()
    report = last_stream_report()
    assert report.resumed_from_chunk == 4
    assert report.reingested_chunks == 8 - 4 == report.chunks
    assert report.shards == 8
    assert rel_err(preds(fitted), reference) <= 1e-6
    kinds = {e.kind for e in get_recovery_log().events()}
    assert "stream_resume" in kinds


def test_crash_resume_parity_one_device_from_sharded_checkpoint(
    tmp_path, reference
):
    # The cursor snapshot is mesh-independent: a fit killed on the
    # 8-device mesh resumes on ONE device with exact parity.
    _crash_at(tmp_path, call=3)
    PipelineEnv.reset()
    enable_checkpointing(str(tmp_path))
    with partition_disabled():
        fitted = build().fit()
    report = last_stream_report()
    assert report.resumed_from_chunk == 2 and report.shards == 1
    assert rel_err(preds(fitted), reference) <= 1e-6


def test_stale_resume_refused_kv306_warn_mode(tmp_path, reference):
    _crash_at(tmp_path, call=5)
    PipelineEnv.reset()
    enable_checkpointing(str(tmp_path))
    # Same shapes, same key — different dataset CONTENT.
    drifted_x = X + np.float32(0.25)
    fitted = build(x=drifted_x).fit()
    report = last_stream_report()
    assert report.resumed_from_chunk is None  # refused → from scratch
    assert report.chunks == 8
    kinds = {e.kind for e in get_recovery_log().events()}
    assert "resume_refused" in kinds
    # The refused fit is the DRIFTED data's correct fit, not a blend.
    PipelineEnv.reset()
    with partition_disabled():
        clean = preds(build(x=drifted_x).fit())
    assert rel_err(preds(fitted), clean) <= 1e-6


def test_stale_resume_raises_in_strict_mode_and_preserves_entry(
    tmp_path, reference, monkeypatch
):
    _crash_at(tmp_path, call=5)
    PipelineEnv.reset()
    enable_checkpointing(str(tmp_path))
    monkeypatch.setenv("KEYSTONE_VERIFY", "strict")
    with pytest.raises(VerificationError, match="KV306"):
        build(x=X + np.float32(0.25)).fit()
    # Strict refuses the FIT, not the entry: the mismatch may have been
    # this run's mistake, and the legitimate job's checkpoint work must
    # survive it — the original fit still resumes.
    monkeypatch.setenv("KEYSTONE_VERIFY", "warn")
    PipelineEnv.reset()
    enable_checkpointing(str(tmp_path))
    fitted = build().fit()
    assert last_stream_report().resumed_from_chunk == 4
    assert rel_err(preds(fitted), reference) <= 1e-6


def test_geometry_drift_discards_entry(tmp_path, chunked, monkeypatch):
    _crash_at(tmp_path, call=5)
    PipelineEnv.reset()
    enable_checkpointing(str(tmp_path))
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", str(CHUNK * 2))
    fitted = build().fit()
    report = last_stream_report()
    assert report.resumed_from_chunk is None
    kinds = {e.kind for e in get_recovery_log().events()}
    assert "resume_discard" in kinds
    assert preds(fitted).shape == (32, K)


def test_verify_stream_resume_flags_each_field():
    from keystone_tpu.reliability.durable import StreamCursor

    cursor = StreamCursor(
        chunk_index=4,
        rows_consumed=256,
        chunk_rows=64,
        dataset_digest="aaa",
        labels_digest="bbb",
        chain_digest="ccc",
        feature_width=8,
        feature_dtype="float32",
    )
    same = {
        "dataset_digest": "aaa",
        "labels_digest": "bbb",
        "chain_digest": "ccc",
        "feature_width": 8,
        "feature_dtype": "float32",
    }
    assert verify_stream_resume(cursor, same).ok
    for field, bad in (
        ("dataset_digest", "zzz"),
        ("labels_digest", "zzz"),
        ("chain_digest", "zzz"),
        ("feature_width", 16),
        ("feature_dtype", "float64"),
    ):
        report = verify_stream_resume(cursor, {**same, field: bad})
        assert not report.ok
        (diag,) = report.errors()
        assert diag.code == "KV306" and diag.details["field"] == field


# ------------------------------------------------------------ shard loss


def test_shard_loss_mid_stream_completes_on_survivors(reference):
    PipelineEnv.reset()
    with faultinject.injected(
        FaultSpec(match="parallel.shard_loss", kind="transient", calls=(3,))
    ):
        fitted = build().fit()
    report = last_stream_report()
    assert report.shard_losses == 1
    assert report.shards == 7  # continued on the shrunken mesh
    assert report.reingested_chunks == 2  # the lost slices of chunks 1-2
    assert rel_err(preds(fitted), reference) <= 1e-5
    kinds = {e.kind for e in get_recovery_log().events()}
    assert {"shard_loss", "shard_resume"} <= kinds


def test_seed_bearing_shard_zero_loss_recovers_exactly(
    reference, monkeypatch
):
    # Shard 0 carries the fold's seed block: its loss must re-add the
    # host-side seed, not silently drop it.
    monkeypatch.setenv("KEYSTONE_SHARD_LOSS_INDEX", "0")
    PipelineEnv.reset()
    with faultinject.injected(
        FaultSpec(match="parallel.shard_loss", kind="transient", calls=(4,))
    ):
        fitted = build().fit()
    assert last_stream_report().shard_losses == 1
    assert rel_err(preds(fitted), reference) <= 1e-5


def test_loss_before_first_chunk_keeps_compile_accounting_exact(reference):
    # A loss at the very first dispatch re-plans before anything folded:
    # the shrunken-mesh attempt's first chunk is the fold's first chunk,
    # and its compiles must not double-count as steady-state.
    PipelineEnv.reset()
    with faultinject.injected(
        FaultSpec(match="parallel.shard_loss", kind="transient", calls=(1,))
    ):
        fitted = build().fit()
    report = last_stream_report()
    assert report.shard_losses == 1 and report.reingested_chunks == 0
    assert report.compiles_steady_state == 0
    assert rel_err(preds(fitted), reference) <= 1e-5


def test_dataset_fingerprint_bounded_and_sensitive(monkeypatch):
    from keystone_tpu.reliability import durable

    big = np.arange(64 * 16, dtype=np.float32).reshape(64, 16)
    ds = ArrayDataset(big.copy())
    base = durable.dataset_fingerprint(ds)
    assert base == durable.dataset_fingerprint(ArrayDataset(big.copy()))
    # Force the sampled path: every row lands in the sample at this size.
    monkeypatch.setattr(durable, "FULL_HASH_MAX_BYTES", 16)
    sampled = durable.dataset_fingerprint(ArrayDataset(big.copy()))
    assert sampled != base  # different scheme, still deterministic
    assert sampled == durable.dataset_fingerprint(ArrayDataset(big.copy()))
    drifted = big.copy()
    drifted[0, 0] += 1.0  # first row is always sampled
    assert durable.dataset_fingerprint(ArrayDataset(drifted)) != sampled
    # The sample is bounded: a huge leaf hashes ≤ FINGERPRINT_SAMPLE_ROWS
    # rows, not the matrix (shape/length changes still always differ).
    assert (
        durable.dataset_fingerprint(ArrayDataset(big[:32].copy())) != sampled
    )


def test_two_sequential_losses_still_converge(reference):
    PipelineEnv.reset()
    with faultinject.injected(
        FaultSpec(match="parallel.shard_loss", kind="transient", calls=(2, 6))
    ):
        fitted = build().fit()
    report = last_stream_report()
    assert report.shard_losses == 2 and report.shards == 6
    assert rel_err(preds(fitted), reference) <= 1e-5


# --------------------------------------------------------- thread hygiene


def _prefetch_threads():
    return [
        t
        for t in threading.enumerate()
        if t.is_alive() and "prefetch" in t.name
    ]


def test_faulted_fold_joins_prefetch_workers(tmp_path, chunked):
    # An abandoned fold (fault mid-stream, resume-abort, shard loss —
    # any exit) must join its PrefetchQueue workers before re-raising:
    # leaked decode threads outlive the fit and pin chunk buffers.
    assert not _prefetch_threads()
    _crash_at(tmp_path, call=3)
    assert not _prefetch_threads()
    # The shard-loss recovery path swaps queues mid-fold: every
    # abandoned attempt's workers must be joined too.
    PipelineEnv.reset()
    with faultinject.injected(
        FaultSpec(match="parallel.shard_loss", kind="transient", calls=(2,))
    ):
        build().fit()
    assert not _prefetch_threads()
