"""Randomized optimizer-equivalence invariant: for any randomly composed
pipeline, executing through the optimizer stack must produce exactly the
results of the same computation composed by hand. Instrumented coverage:
CSE fires heavily on the shared structures; the saved-state path fires on
the second (no-reset) execution of each trial. (Dead-branch pruning has
its own point tests in test_rules.py — the generator here builds no
unused limbs.) The reference asserted this contract piecewise across its
workflow suites; random composition covers rule interactions those point
tests can't.
"""

import numpy as np

from keystone_tpu.data.dataset import ObjectDataset
from keystone_tpu.workflow import Estimator, Pipeline, Transformer
from keystone_tpu.workflow.executor import PipelineEnv


class Affine(Transformer):
    """Deterministic, hashable-by-construction arithmetic op."""

    def __init__(self, a, b):
        self.a, self.b = a, b

    def apply(self, x):
        return self.a * x + self.b


class MeanShift(Estimator):
    def fit(self, data):
        return Affine(1.0, float(np.mean(data.collect())))


def test_randomized_optimizer_equivalence():
    rng = np.random.default_rng(0)
    for trial in range(20):
        PipelineEnv.reset()
        xs = [float(v) for v in rng.integers(-5, 6, size=6)]
        fit_xs = [float(v) for v in rng.integers(-5, 6, size=5)]
        data = ObjectDataset(list(fit_xs))
        depth = int(rng.integers(2, 7))

        # Build op list with positionally-unique markers so the reference
        # evaluator can recurse unambiguously.
        ops = []
        pipe = None
        for i in range(depth):
            kind = int(rng.integers(0, 3))
            if kind == 0 or pipe is None:
                a, b = float(rng.integers(1, 4)), float(rng.integers(-3, 4))
                t = Affine(a, b)
                pipe = t.to_pipeline() if pipe is None else pipe.then(t)
                ops.append(("affine", a, b))
            elif kind == 1:
                pipe = pipe.then_estimator(MeanShift(), data)
                ops.append(("meanshift", i))
            else:
                t = Affine(2.0, 1.0)
                pipe = pipe.then(t)
                ops.append(("affine", 2.0, 1.0))

        def reference(values, upto=len(ops)):
            vals = list(values)
            for j, op in enumerate(ops[:upto]):
                if op[0] == "affine":
                    vals = [op[1] * v + op[2] for v in vals]
                else:
                    mean = float(np.mean(reference(fit_xs, j)))
                    vals = [v + mean for v in vals]
            return vals

        got = pipe(ObjectDataset(list(xs))).get().collect()
        expect = reference(xs)
        np.testing.assert_allclose(got, expect, rtol=1e-6, atol=1e-6,
                                   err_msg=f"trial {trial}, ops={ops}")
        # Second execution WITHOUT resetting PipelineEnv: the saved-state
        # load rule now splices stored estimator/cacher results back in —
        # values must be unchanged by that reuse path.
        again = pipe(ObjectDataset(list(xs))).get().collect()
        np.testing.assert_allclose(again, expect, rtol=1e-6, atol=1e-6,
                                   err_msg=f"trial {trial} (reuse), ops={ops}")


def test_equivalence_with_explicit_shared_branches_and_gather():
    """Gather of two branches that share a common prefix: optimizer CSE
    must not change values."""
    PipelineEnv.reset()
    xs = [1.0, 2.0, 3.0]
    shared = Affine(2.0, 1.0).to_pipeline()
    left = shared.then(Affine(1.0, 5.0))
    right = shared.then(Affine(3.0, 0.0))
    gathered = Pipeline.gather([left, right])
    got = gathered(ObjectDataset(list(xs))).get().collect()
    expect = [[2 * x + 1 + 5, 3 * (2 * x + 1)] for x in xs]
    np.testing.assert_allclose(got, expect)


def test_equivalence_under_auto_caching_optimizer():
    """The auto-caching optimizer (profiling + Cacher insertion) must be
    value-neutral: same random pipelines, same results."""
    from keystone_tpu.workflow.executor import PipelineEnv
    from keystone_tpu.workflow.rules import auto_caching_optimizer

    rng = np.random.default_rng(7)
    for trial in range(5):
        PipelineEnv.reset()
        PipelineEnv.get_or_create().optimizer = auto_caching_optimizer()
        try:
            xs = [float(v) for v in rng.integers(-5, 6, size=6)]
            fit_xs = [float(v) for v in rng.integers(-5, 6, size=5)]
            data = ObjectDataset(list(fit_xs))
            depth = int(rng.integers(2, 6))

            ops = []
            pipe = None
            for i in range(depth):
                kind = int(rng.integers(0, 2))
                if kind == 0 or pipe is None:
                    a, b = float(rng.integers(1, 4)), float(rng.integers(-3, 4))
                    t = Affine(a, b)
                    pipe = t.to_pipeline() if pipe is None else pipe.then(t)
                    ops.append(("affine", a, b))
                else:
                    pipe = pipe.then_estimator(MeanShift(), data)
                    ops.append(("meanshift", i))

            def reference(values, upto=len(ops)):
                vals = list(values)
                for j, op in enumerate(ops[:upto]):
                    if op[0] == "affine":
                        vals = [op[1] * v + op[2] for v in vals]
                    else:
                        mean = float(np.mean(reference(fit_xs, j)))
                        vals = [v + mean for v in vals]
                return vals

            got = pipe(ObjectDataset(list(xs))).get().collect()
            np.testing.assert_allclose(got, reference(xs), rtol=1e-6,
                                       atol=1e-6, err_msg=f"trial {trial}")
        finally:
            PipelineEnv.reset()
