"""Auto-cache planner tests (reference: workflow/AutocCacheRuleSuite.scala:27-50).

The reference suite builds graphs by hand with toy transformers and
weighted estimators, then asserts on the selected cache set; same here.
"""

import numpy as np

from keystone_tpu.data.dataset import ArrayDataset, Dataset
from keystone_tpu.ops.util.misc import CacherOperator
from keystone_tpu.workflow.autocache import AutoCacheRule, Profile, _fit_linear, SampleProfile
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.operators import DatasetOperator, TransformerOperator
from keystone_tpu.workflow.pipeline import Estimator, Transformer


class FakeClock:
    """Deterministic clock: ops advance it explicitly instead of sleeping,
    so profile-driven cache choices are load-independent."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


class CountingOp(TransformerOperator):
    """Identity-ish op that counts batch executions and can charge fake time."""

    def __init__(self, name, delay_s=0.0, weight=1, clock=None):
        self.name = name
        self.delay_s = delay_s
        self.weight = weight
        self.clock = clock
        self.batch_calls = 0

    @property
    def label(self):
        return self.name

    def single_transform(self, datums):
        return datums[0]

    def batch_transform(self, datasets):
        self.batch_calls += 1
        if self.delay_s and self.clock is not None:
            self.clock.t += self.delay_s
        return datasets[0]


def diamond_graph(n=64, delay_s=0.0, weight=1, clock=None):
    """source-bound dataset → expensive shared node → two consumers → sinks."""
    data = ArrayDataset(np.ones((n, 4), dtype=np.float32))
    g = Graph()
    g, d = g.add_node(DatasetOperator(data), [])
    shared = CountingOp("shared", delay_s=delay_s, clock=clock)
    g, sh = g.add_node(shared, [d])
    g, c1 = g.add_node(CountingOp("left", weight=weight), [sh])
    g, c2 = g.add_node(CountingOp("right"), [sh])
    g, s1 = g.add_sink(c1)
    g, s2 = g.add_sink(c2)
    return g, sh, shared


def cacher_nodes(graph):
    return [n for n in graph.nodes if isinstance(graph.get_operator(n), CacherOperator)]


def test_aggressive_caches_every_reused_node():
    g, shared_id, _ = diamond_graph()
    out, _ = AutoCacheRule(strategy="aggressive").apply(g, {})
    caches = cacher_nodes(out)
    assert len(caches) == 1
    assert out.get_dependencies(caches[0]) == (shared_id,)
    # both consumers repointed at the cacher
    consumers = [
        n
        for n in out.nodes
        if caches[0] in out.get_dependencies(n) and n != caches[0]
    ]
    assert len(consumers) == 2


def test_greedy_caches_expensive_shared_node_under_budget():
    clock = FakeClock()
    g, shared_id, _ = diamond_graph(delay_s=0.01, clock=clock)
    out, _ = AutoCacheRule(
        budget_bytes=1 << 30, strategy="greedy", clock=clock
    ).apply(g, {})
    caches = cacher_nodes(out)
    assert len(caches) == 1
    assert out.get_dependencies(caches[0]) == (shared_id,)


def test_greedy_zero_budget_caches_nothing():
    clock = FakeClock()
    g, _, _ = diamond_graph(delay_s=0.01, clock=clock)
    out, _ = AutoCacheRule(budget_bytes=0, strategy="greedy", clock=clock).apply(g, {})
    assert cacher_nodes(out) == []


def test_single_use_node_never_cached():
    clock = FakeClock()
    data = ArrayDataset(np.ones((16, 4), dtype=np.float32))
    g = Graph()
    g, d = g.add_node(DatasetOperator(data), [])
    g, a = g.add_node(CountingOp("a", delay_s=0.005, clock=clock), [d])
    g, b = g.add_node(CountingOp("b"), [a])
    g, s = g.add_sink(b)
    out, _ = AutoCacheRule(strategy="aggressive").apply(g, {})
    assert cacher_nodes(out) == []


def test_weighted_consumer_counts_as_multiple_uses():
    """A single downstream consumer with weight>1 (iterative solver) makes
    its input cache-worthy (reference: WeightedNode, BCD weight 3·iter+1)."""
    data = ArrayDataset(np.ones((16, 4), dtype=np.float32))
    g = Graph()
    g, d = g.add_node(DatasetOperator(data), [])
    g, a = g.add_node(CountingOp("feat"), [d])
    g, b = g.add_node(CountingOp("solver", weight=7), [a])
    g, s = g.add_sink(b)
    out, _ = AutoCacheRule(strategy="aggressive").apply(g, {})
    caches = cacher_nodes(out)
    assert len(caches) == 1
    assert out.get_dependencies(caches[0]) == (a,)


def test_already_cached_node_not_recached():
    g, shared_id, _ = diamond_graph()
    g, _ = AutoCacheRule(strategy="aggressive").apply(g, {})
    out, _ = AutoCacheRule(strategy="aggressive").apply(g, {})
    assert len(cacher_nodes(out)) == 1


def test_execution_still_correct_and_shared_runs_once():
    """End-to-end through the executor: cache insertion preserves results and
    collapses recomputation (reference: PipelineSuite fit-once semantics)."""
    from keystone_tpu.workflow.executor import GraphExecutor

    g, shared_id, shared_op = diamond_graph(n=8)
    out, _ = AutoCacheRule(strategy="aggressive").apply(g, {})
    sinks = sorted(out.sinks)
    executor = GraphExecutor(out, optimize=False)
    results = [executor.execute(s).get() for s in sinks]
    for r in results:
        assert isinstance(r, Dataset)
        np.testing.assert_allclose(np.asarray(r.data), np.ones((8, 4)))
    assert shared_op.batch_calls == 1


def test_greedy_credits_ancestor_recompute_savings():
    """Caching a cheap shared node whose ancestor is expensive must win over
    caching a moderately expensive independent shared node: the cost model
    sees the ancestor's time through the runs() recursion."""
    clock = FakeClock()
    data = ArrayDataset(np.ones((64, 4), dtype=np.float32))
    g = Graph()
    g, d = g.add_node(DatasetOperator(data), [])
    g, a = g.add_node(CountingOp("expensive-ancestor", delay_s=0.02, clock=clock), [d])
    g, s_cheap = g.add_node(CountingOp("cheap-shared"), [a])
    g, c1 = g.add_node(CountingOp("u1"), [s_cheap])
    g, c2 = g.add_node(CountingOp("u2"), [s_cheap])
    g, b = g.add_node(CountingOp("independent-shared", delay_s=0.005, clock=clock), [d])
    g, c3 = g.add_node(CountingOp("u3"), [b])
    g, c4 = g.add_node(CountingOp("u4"), [b])
    for n in (c1, c2, c3, c4):
        g, _ = g.add_sink(n)
    # Budget fits exactly one cached copy of (64,4) float32 = 1024 bytes.
    out, _ = AutoCacheRule(budget_bytes=1100, strategy="greedy", clock=clock).apply(g, {})
    caches = cacher_nodes(out)
    assert len(caches) == 1
    assert out.get_dependencies(caches[0]) == (s_cheap,)


def test_linear_fit_extrapolates():
    samples = [SampleProfile(2, 0.2, 200), SampleProfile(4, 0.4, 400)]
    p = _fit_linear(samples, 100)
    assert abs(p.run_time_s - 10.0) < 1e-6
    assert p.size_bytes == 10_000


# ----------------------------------------------------- serving reuse pattern


class CountingEstimator(Estimator):
    """Estimator that counts fits."""

    def __init__(self):
        self.fit_calls = 0

    def fit(self, data):
        self.fit_calls += 1
        return Transformer.from_fn(lambda x: x, name="fitted")


def test_repeated_apply_of_fitted_prefix_does_not_refit():
    """The serving reuse pattern: one fitted prefix applied per-request,
    many times. The prefix table must hand every application the SAME
    fitted transformer — refitting per request would put estimator cost
    on the serving hot path."""
    from keystone_tpu.workflow.executor import PipelineEnv

    est = CountingEstimator()
    data = ArrayDataset(np.ones((8, 4), dtype=np.float32))
    pipeline = est.with_data(data)
    for i in range(5):
        result = pipeline.apply(ArrayDataset(np.full((2, 4), float(i), np.float32)))
        assert len(result.get()) == 2
    assert est.fit_calls == 1
    # The fitted expression lives in the process-wide prefix table — a
    # SECOND structurally identical pipeline over the same data reuses it.
    pipeline2 = est.with_data(data)
    pipeline2.apply(ArrayDataset(np.zeros((2, 4), np.float32))).get()
    assert est.fit_calls == 1
    assert len(PipelineEnv.get_or_create().state) >= 1


def test_cache_decisions_stable_across_repeated_planning():
    """Serving re-plans the same graph repeatedly (hot-swap republish,
    restart): with identical profiles the greedy planner must pick the
    identical cache set every time — nondeterministic placement would
    recompile the serving path on every swap."""
    chosen = []
    for _ in range(3):
        clock = FakeClock()
        g, shared_id, _ = diamond_graph(delay_s=0.01, clock=clock)
        out, _ = AutoCacheRule(
            budget_bytes=1 << 30, strategy="greedy", clock=clock
        ).apply(g, {})
        chosen.append(
            tuple(sorted(out.get_dependencies(c)[0] for c in cacher_nodes(out)))
        )
    assert chosen[0] == chosen[1] == chosen[2] == (shared_id,)
