"""Expression.get thread-safety: the memo is lock-guarded, so concurrent
forcings (a deadline-abandoned watchdog racing a retry, or serving
threads sharing a memoized result) run the thunk exactly once."""

import pickle
import threading
import time

import pytest

from keystone_tpu.workflow.operators import Expression


def test_concurrent_get_runs_thunk_once():
    calls = []
    barrier = threading.Barrier(2)

    def thunk():
        calls.append(threading.get_ident())
        time.sleep(0.05)  # widen the race window
        return {"value": len(calls)}

    expr = Expression(thunk)
    results = [None, None]

    def hammer(i):
        barrier.wait()
        results[i] = expr.get()

    threads = [threading.Thread(target=hammer, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(calls) == 1, f"thunk ran {len(calls)} times under contention"
    assert results[0] is results[1]  # both readers see the one memo
    assert results[0] == {"value": 1}


def test_many_threads_hammering_one_expression():
    calls = []
    n_threads = 8
    barrier = threading.Barrier(n_threads)

    def thunk():
        calls.append(1)
        time.sleep(0.02)
        return object()

    expr = Expression(thunk)
    seen = []
    lock = threading.Lock()

    def hammer():
        barrier.wait()
        for _ in range(50):
            value = expr.get()
            with lock:
                seen.append(value)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(calls) == 1
    assert len(set(id(v) for v in seen)) == 1


def test_failing_thunk_can_be_reforced():
    """A failing thunk leaves the memo unset (the retry contract) and the
    lock released, so a later forcing re-executes."""
    attempts = []

    def thunk():
        attempts.append(1)
        if len(attempts) == 1:
            raise RuntimeError("transient")
        return 42

    expr = Expression(thunk)
    with pytest.raises(RuntimeError):
        expr.get()
    assert expr.get() == 42
    assert len(attempts) == 2


def test_forced_expression_pickles_without_lock():
    expr = Expression.of([1, 2, 3])
    restored = pickle.loads(pickle.dumps(expr))
    assert restored.get() == [1, 2, 3]
    # the restored expression has a working lock again
    assert restored._lock is not None
    with restored._lock:
        pass
