"""Plan-time static verification (workflow/verify.py): diagnostics,
spec propagation, enforcement modes, and the zero-compile guarantee."""

import numpy as np
import pytest

import jax

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.obs import names as _names
from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.ops.learning.logistic import LogisticRegressionEstimator
from keystone_tpu.utils.compilation_cache import install_compile_counter
from keystone_tpu.workflow import BatchTransformer, Pipeline
from keystone_tpu.workflow.analysis import GraphCycleError, linearize_whole
from keystone_tpu.workflow.operators import EstimatorOperator
from keystone_tpu.workflow.pipeline import Estimator
from keystone_tpu.workflow.verify import (
    CODES,
    ERROR,
    INFO,
    WARNING,
    SpecMismatch,
    TransformerSpec,
    VerificationError,
    dense_fit_spec,
    elementwise_fit_spec,
    projection_fit_spec,
    verification_mode,
    verify_and_enforce,
    verify_graph,
    verify_pipeline,
)


class Scale(BatchTransformer):
    def __init__(self, c=2.0):
        self.c = float(c)

    @property
    def label(self):
        return f"Scale[{self.c}]"

    def apply_arrays(self, x):
        return x * self.c


class ScaleWithSpec(Scale):
    """Same op, explicit out_spec — for fallback-parity assertions."""

    def out_spec(self, in_specs):
        spec = in_specs[0]
        leaves = jax.tree_util.tree_leaves(spec)
        if not leaves or not hasattr(leaves[0], "shape"):
            from keystone_tpu.workflow.verify import UNKNOWN

            return UNKNOWN
        return spec


class WidenToF64(BatchTransformer):
    """Declares (via out_spec) that it emits float64 — the silent
    widening hazard KV102 exists for."""

    @property
    def label(self):
        return "WidenToF64"

    def apply_arrays(self, x):  # pragma: no cover - never executed here
        return x

    def out_spec(self, in_specs):
        leaf = jax.tree_util.tree_leaves(in_specs[0])[0]
        return jax.ShapeDtypeStruct(tuple(leaf.shape), np.float64)


class CustomBatch(BatchTransformer):
    """Bespoke apply_batch → fusion-ineligible (KV201)."""

    @property
    def label(self):
        return "CustomBatch"

    def apply_arrays(self, x):
        return x

    def apply_batch(self, dataset):
        return dataset


class NoSpecEstimator(Estimator):
    """An estimator family that has not adopted the out_spec protocol."""

    @property
    def label(self):
        return "NoSpecEstimator"

    def fit(self, data):  # pragma: no cover - never executed here
        raise AssertionError("verification must not fit")


def _xy(n=64, d=8, k=3, rows_y=None):
    x = ArrayDataset(np.zeros((n, d), dtype=np.float32))
    y = ArrayDataset(np.zeros((rows_y or n, k), dtype=np.float32))
    return x, y


def _codes(report):
    return [d.code for d in report.diagnostics]


# ------------------------------------------------------------- diagnostics


def test_row_mismatch_is_kv101_error():
    x, y = _xy(n=64, rows_y=32)
    report = verify_pipeline(LinearMapEstimator().with_data(x, y))
    kv101 = report.by_code("KV101")
    assert len(kv101) == 1 and kv101[0].severity == ERROR
    assert "64 rows" in kv101[0].message and "32 rows" in kv101[0].message
    assert not report.ok


def test_clean_pipeline_verifies_ok():
    x, y = _xy()
    report = verify_pipeline(LinearMapEstimator().with_data(x, y))
    assert report.ok
    assert not report.by_code("KV101")
    # The fitted-transformer edge got a real spec, not UNKNOWN.
    assert any("TransformerSpec" in a.spec for a in report.annotations)


def test_eval_shape_fallback_catches_bad_width():
    """A fusable apply_arrays chain with no out_spec still verifies via
    jax.eval_shape — the planted-width CLI scenario."""
    from keystone_tpu.serving.synthetic import synthetic_chain_pipeline

    pipeline = synthetic_chain_pipeline(num_nodes=3, d=64)
    bad = jax.ShapeDtypeStruct((16, 63), np.dtype("float32"))
    report = verify_pipeline(pipeline, bad)
    assert [d.code for d in report.errors()] == ["KV101"]
    good = jax.ShapeDtypeStruct((16, 64), np.dtype("float32"))
    assert verify_pipeline(pipeline, good).ok


def test_eval_shape_fallback_matches_explicit_out_spec():
    """Parity: the same op with and without out_spec annotates the same
    propagated spec."""
    spec = jax.ShapeDtypeStruct((32, 4), np.dtype("float32"))

    def annotations(op):
        pipe = op.to_pipeline()
        report = verify_pipeline(pipe, spec)
        assert report.ok
        return [a.spec for a in report.annotations]

    assert annotations(Scale(3.0)) == annotations(ScaleWithSpec(3.0))


def test_float64_widening_is_kv102_warning():
    pipe = Scale(1.0).to_pipeline().then(WidenToF64())
    spec = jax.ShapeDtypeStruct((8, 4), np.dtype("float32"))
    report = verify_pipeline(pipe, spec)
    kv102 = report.by_code("KV102")
    assert len(kv102) == 1 and kv102[0].severity == WARNING
    assert report.ok  # warning, not error


def test_no_widening_diag_when_input_already_f64():
    pipe = WidenToF64().to_pipeline()
    spec = jax.ShapeDtypeStruct((8, 4), np.dtype("float64"))
    report = verify_pipeline(pipe, spec)
    assert not report.by_code("KV102")


def test_no_widening_diag_on_float64_source_data():
    """A dataset that simply IS float64 widened nothing — KV102 must not
    fire on zero-input source nodes (it used to)."""
    x = ArrayDataset(np.zeros((16, 4), dtype=np.float64))
    y = ArrayDataset(np.zeros((16, 2), dtype=np.float64))
    report = verify_pipeline(LinearMapEstimator().with_data(x, y))
    assert not report.by_code("KV102")


def test_dense_fit_spec_carries_training_float64():
    """An estimator fitted on float64 produces a float64 map even for
    float32 apply inputs — the captured training dtype must participate
    (a bare np.dtype used to be silently dropped)."""
    f32, f64 = np.dtype("float32"), np.dtype("float64")
    ts = dense_fit_spec(
        [jax.ShapeDtypeStruct((10, 4), f64), jax.ShapeDtypeStruct((10, 2), f64)],
        "T",
    )
    out = ts.apply_spec(jax.ShapeDtypeStruct((3, 4), f32))
    assert out.dtype == f64


def test_fusion_ineligibility_reasons():
    from keystone_tpu.ops.util.misc import CacherOperator

    pipe = Scale(2.0).to_pipeline().then(CustomBatch())
    graph = pipe.graph
    graph, cacher = graph.add_node(
        CacherOperator(), [graph.get_sink_dependency(pipe.sink)]
    )
    graph = graph.set_sink_dependency(pipe.sink, cacher)
    report = verify_graph(graph)
    reasons = {d.details.get("reason") for d in report.by_code("KV201")}
    assert "bespoke-apply" in reasons
    assert "cacher-boundary" in reasons
    assert all(d.severity == INFO for d in report.by_code("KV201"))


def test_multi_consumer_interior_reported():
    op = Scale(2.0)
    pipe = op.to_pipeline()
    graph = pipe.graph
    head = graph.get_sink_dependency(pipe.sink)
    graph, n2 = graph.add_node(Scale(3.0), [head])
    graph, n3 = graph.add_node(Scale(4.0), [head])
    graph, _s2 = graph.add_sink(n2)
    graph, _s3 = graph.add_sink(n3)
    report = verify_graph(graph)
    reasons = {d.details.get("reason") for d in report.by_code("KV201")}
    assert "multi-consumer" in reasons


def test_streaming_ineligibility_reasons():
    x, y = _xy(n=64)
    report = verify_pipeline(LinearMapEstimator().with_data(x, y))
    kv202 = report.by_code("KV202")
    assert len(kv202) == 1
    assert kv202[0].details["reason"] == "below-row-floor"

    xl = ArrayDataset(np.zeros((64, 8), dtype=np.float32))
    yl = ArrayDataset(np.zeros((64,), dtype=np.int32))
    report = verify_pipeline(
        LogisticRegressionEstimator(num_classes=3).with_data(xl, yl)
    )
    kv202 = report.by_code("KV202")
    assert len(kv202) == 1
    assert kv202[0].details["reason"] == "no-fit-stream"


def test_bucket_mismatch_is_kv301_error():
    from keystone_tpu.serving.synthetic import synthetic_chain_pipeline

    pipeline = synthetic_chain_pipeline(num_nodes=2, d=64)
    report = verify_pipeline(
        pipeline, buckets=[8, 32], warmed_buckets=[8]
    )
    kv301 = report.by_code("KV301")
    assert len(kv301) == 1 and kv301[0].severity == ERROR
    assert kv301[0].details == {"missing": [32], "warmed": [8]}
    assert verify_pipeline(
        pipeline, buckets=[8, 32], warmed_buckets=[8, 32, 64]
    ).ok


def test_peak_memory_budget_is_kv302_warning():
    x, y = _xy(n=4096, d=64)
    report = verify_pipeline(
        LinearMapEstimator().with_data(x, y), device_memory_bytes=10_000
    )
    kv302 = report.by_code("KV302")
    assert len(kv302) == 1 and kv302[0].severity == WARNING
    assert kv302[0].details["peak_bytes"] > 10_000
    assert verify_pipeline(
        LinearMapEstimator().with_data(x, y), device_memory_bytes=None
    ).by_code("KV302") == []


def test_gram_infeasibility_is_kv303():
    from keystone_tpu.workflow.streaming import StreamingFitOperator

    d = 4096
    x = ArrayDataset(np.zeros((8, d), dtype=np.float32))
    y = ArrayDataset(np.zeros((8, 4), dtype=np.float32))
    pipe = LinearMapEstimator().with_data(x, y)
    graph = pipe.graph
    est_node = next(
        n
        for n in graph.nodes
        if isinstance(graph.get_operator(n), EstimatorOperator)
        and not hasattr(graph.get_operator(n), "dataset")
    )
    graph = graph.set_operator(
        est_node,
        StreamingFitOperator(graph.get_operator(est_node), members=()),
    )
    # gram state ~2*4*(d² + d·k) ≈ 134 MB >> 1 MB budget
    report = verify_graph(graph, device_memory_bytes=1_000_000)
    kv303 = report.by_code("KV303")
    assert len(kv303) == 1
    assert kv303[0].details["d"] == d
    assert verify_graph(graph, device_memory_bytes=None).by_code("KV303") == []


def test_sketch_infeasibility_is_kv308(monkeypatch):
    """The sketched tier's feasibility is KV308 (ERROR — it is the LAST
    memory rung, nothing to degrade to) and the dispatch routes sketch-
    kind fits AWAY from the Gram tier's KV303 warning."""
    from keystone_tpu.sketch.core import sketch_state_bytes
    from keystone_tpu.sketch.solvers import SketchedLeastSquaresEstimator
    from keystone_tpu.workflow.streaming import StreamingFitOperator

    d = 8192
    x = ArrayDataset(np.zeros((8, d), dtype=np.float32))
    y = ArrayDataset(np.zeros((8, 4), dtype=np.float32))

    def sketch_graph():
        pipe = SketchedLeastSquaresEstimator(reg=1e-3).with_data(x, y)
        graph = pipe.graph
        est_node = next(
            n
            for n in graph.nodes
            if isinstance(graph.get_operator(n), EstimatorOperator)
            and not hasattr(graph.get_operator(n), "dataset")
        )
        return graph.set_operator(
            est_node,
            StreamingFitOperator(graph.get_operator(est_node), members=()),
        )

    # Conditioning floor: checked on ANY device (no budget needed).
    monkeypatch.setenv("KEYSTONE_SKETCH_SIZE", "4")
    report = verify_graph(sketch_graph(), device_memory_bytes=None)
    kv308 = report.by_code("KV308")
    assert len(kv308) == 1 and kv308[0].severity == ERROR
    assert kv308[0].details["floor"] == max(32, 4 * (4 + 1))

    # Memory: 2× the O(s·d) carry vs the budget — and the sketch-kind
    # dispatch must NOT also warn KV303 (that is the Gram tier's check).
    monkeypatch.delenv("KEYSTONE_SKETCH_SIZE", raising=False)
    report = verify_graph(sketch_graph(), device_memory_bytes=1_000_000)
    kv308 = report.by_code("KV308")
    assert len(kv308) == 1
    assert kv308[0].details["state_bytes"] == 2 * sketch_state_bytes(
        4096, d, 4
    )
    assert report.by_code("KV303") == []

    # Feasible sketch plan: a budget the carry fits leaves no diagnostic.
    assert verify_graph(
        sketch_graph(), device_memory_bytes=1 << 30
    ).by_code("KV308") == []


def test_cycle_is_kv401_and_linearize_raises():
    pipe = Scale(2.0).to_pipeline().then(Scale(3.0)).then(Scale(4.0))
    graph = pipe.graph
    nodes = sorted(graph.nodes)
    cyclic = graph.set_dependencies(nodes[0], [nodes[2]])
    with pytest.raises(GraphCycleError) as err:
        linearize_whole(cyclic)
    assert len(err.value.cycle) >= 3  # closed path, first == last
    assert err.value.cycle[0] == err.value.cycle[-1]
    report = verify_graph(cyclic)
    assert [d.code for d in report.errors()] == ["KV401"]
    assert report.annotations == []  # propagation never ran


def test_estimator_without_out_spec_is_kv402():
    x = ArrayDataset(np.zeros((16, 4), dtype=np.float32))
    report = verify_pipeline(NoSpecEstimator().with_data(x))
    kv402 = [
        d for d in report.by_code("KV402") if "NoSpecEstimator" in d.message
    ]
    assert len(kv402) == 1 and kv402[0].severity == INFO
    assert report.ok


def test_broken_out_spec_never_kills_planning():
    class Broken(Scale):
        def out_spec(self, in_specs):
            raise RuntimeError("boom")

    spec = jax.ShapeDtypeStruct((4, 4), np.dtype("float32"))
    report = verify_pipeline(Broken(1.0).to_pipeline(), spec)
    assert report.ok
    assert any("out_spec failed" in d.message for d in report.by_code("KV402"))


# -------------------------------------------------------- zero device work


def test_verification_compiles_and_executes_nothing():
    from keystone_tpu.serving.synthetic import synthetic_chain_pipeline

    counter = install_compile_counter()
    before = counter()
    pipeline = synthetic_chain_pipeline(num_nodes=4, d=64)
    x, y = _xy(n=128, rows_y=64)
    bad_fit = LinearMapEstimator().with_data(x, y)
    r1 = verify_pipeline(
        pipeline, jax.ShapeDtypeStruct((16, 63), np.dtype("float32"))
    )
    r2 = verify_pipeline(bad_fit)
    assert not r1.ok and not r2.ok
    assert counter() - before == 0
    assert r1.seconds < 1.0 and r2.seconds < 1.0


# ------------------------------------------------------------- out_spec lib


def test_dense_fit_spec_contract():
    f32 = np.dtype("float32")
    x = jax.ShapeDtypeStruct((100, 8), f32)
    y = jax.ShapeDtypeStruct((100, 3), f32)
    ts = dense_fit_spec([x, y], "T")
    out = ts.apply_spec(jax.ShapeDtypeStruct((7, 8), f32))
    assert tuple(out.shape) == (7, 3) and out.dtype == f32
    with pytest.raises(SpecMismatch):
        ts.apply_spec(jax.ShapeDtypeStruct((7, 9), f32))
    with pytest.raises(SpecMismatch):
        dense_fit_spec([jax.ShapeDtypeStruct((100,), f32), y], "T")
    with pytest.raises(SpecMismatch):
        dense_fit_spec([x, jax.ShapeDtypeStruct((99, 3), f32)], "T")


def test_projection_and_elementwise_fit_specs():
    f32 = np.dtype("float32")
    stack = jax.ShapeDtypeStruct((10, 21, 128), f32)
    ts = projection_fit_spec([stack], "PCA", dims=64)
    out = ts.apply_spec(jax.ShapeDtypeStruct((5, 33, 128), f32))
    assert tuple(out.shape) == (5, 33, 64)
    with pytest.raises(SpecMismatch):
        ts.apply_spec(jax.ShapeDtypeStruct((5, 127), f32))

    flat = jax.ShapeDtypeStruct((10, 16), f32)
    ts = elementwise_fit_spec([flat], "Scaler")
    same = ts.apply_spec(jax.ShapeDtypeStruct((3, 16), f32))
    assert tuple(same.shape) == (3, 16)
    with pytest.raises(SpecMismatch):
        ts.apply_spec(jax.ShapeDtypeStruct((3, 17), f32))


def test_operator_family_out_specs():
    """The protocol across the op families: each estimator's declared
    fitted-transformer spec maps apply inputs correctly."""
    from keystone_tpu.ops.learning.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.ops.learning.kmeans import KMeansPlusPlusEstimator
    from keystone_tpu.ops.learning.pca import PCAEstimator
    from keystone_tpu.ops.stats.core import StandardScaler

    f32 = np.dtype("float32")
    x = jax.ShapeDtypeStruct((100, 8), f32)
    data = jax.ShapeDtypeStruct((7, 8), f32)

    out = PCAEstimator(dims=3).out_spec([x]).apply_spec(data)
    assert tuple(out.shape) == (7, 3)
    out = (
        KMeansPlusPlusEstimator(num_means=5, max_iterations=3)
        .out_spec([x])
        .apply_spec(data)
    )
    assert tuple(out.shape) == (7, 5)
    out = GaussianMixtureModelEstimator(k=4).out_spec([x]).apply_spec(data)
    assert tuple(out.shape) == (7, 4)
    out = StandardScaler().out_spec([x]).apply_spec(data)
    assert tuple(out.shape) == (7, 8)
    with pytest.raises(SpecMismatch):
        PCAEstimator(dims=3).out_spec([x]).apply_spec(
            jax.ShapeDtypeStruct((7, 9), f32)
        )


def test_transformer_spec_unknown_fn_propagates_unknown():
    from keystone_tpu.workflow.verify import UNKNOWN

    assert TransformerSpec(None).apply_spec(object()) is UNKNOWN


# ------------------------------------------------------------- enforcement


def test_mode_parsing(monkeypatch):
    for raw, want in [
        ("", "warn"), ("warn", "warn"), ("strict", "strict"),
        ("off", "off"), ("0", "off"), ("STRICT", "strict"),
    ]:
        monkeypatch.setenv("KEYSTONE_VERIFY", raw)
        assert verification_mode() == want


def test_enforce_warn_vs_strict_vs_off(monkeypatch):
    x, y = _xy(n=64, rows_y=32)
    graph = LinearMapEstimator().with_data(x, y).graph

    monkeypatch.setenv("KEYSTONE_VERIFY", "warn")
    report = verify_and_enforce(graph, context="t")
    assert report is not None and not report.ok  # logged, not raised

    monkeypatch.setenv("KEYSTONE_VERIFY", "strict")
    with pytest.raises(VerificationError) as err:
        verify_and_enforce(graph, context="t")
    assert "KV101" in str(err.value)

    monkeypatch.setenv("KEYSTONE_VERIFY", "off")
    assert verify_and_enforce(graph, context="t") is None


def test_strict_mode_raises_at_fit(monkeypatch):
    monkeypatch.setenv("KEYSTONE_VERIFY", "strict")
    x, y = _xy(n=64, rows_y=32)
    with pytest.raises(VerificationError):
        LinearMapEstimator().with_data(x, y).fit()


def test_fit_proceeds_under_warn(monkeypatch):
    monkeypatch.setenv("KEYSTONE_VERIFY", "warn")
    rng = np.random.default_rng(0)
    x = ArrayDataset(rng.standard_normal((64, 4)).astype(np.float32))
    y = ArrayDataset(rng.standard_normal((64, 2)).astype(np.float32))
    fitted = LinearMapEstimator().with_data(x, y).fit()
    out = fitted.apply(np.zeros((5, 4), dtype=np.float32))
    assert np.asarray(out).shape == (5, 2)


def test_internal_verifier_failure_is_swallowed(monkeypatch):
    monkeypatch.setenv("KEYSTONE_VERIFY", "strict")
    import keystone_tpu.workflow.verify as verify_mod

    def boom(*a, **k):
        raise RuntimeError("verifier bug")

    monkeypatch.setattr(verify_mod, "verify_graph", boom)
    x, y = _xy(n=16, d=4, k=2)
    graph = LinearMapEstimator().with_data(x, y).graph
    assert verify_mod.verify_and_enforce(graph, context="t") is None


def test_strict_load_fitted_raises_on_bucket_mismatch(tmp_path, monkeypatch):
    from keystone_tpu.serving.registry import ModelRegistry
    from keystone_tpu.serving.synthetic import synthetic_fitted_pipeline

    path = str(tmp_path / "model")
    synthetic_fitted_pipeline(d=16, depth=1).save(path)

    registry = ModelRegistry()
    monkeypatch.setenv("KEYSTONE_VERIFY", "strict")
    with pytest.raises(VerificationError):
        registry.load_fitted(
            "m", path, buckets=[8, 32], warmed_buckets=[8]
        )
    # Same artifact with a warmed set that covers the plan publishes.
    entry = registry.load_fitted(
        "m", path, buckets=[8, 32], warmed_buckets=[8, 32]
    )
    assert entry is not None


def test_load_fitted_unconvertible_example_still_publishes(tmp_path, monkeypatch):
    """Spec-building from a weird example must degrade to an unbound
    verify, never crash publication (the warn contract)."""
    from keystone_tpu.serving.registry import ModelRegistry
    from keystone_tpu.serving.synthetic import synthetic_fitted_pipeline

    path = str(tmp_path / "model")
    synthetic_fitted_pipeline(d=16, depth=1).save(path)
    monkeypatch.setenv("KEYSTONE_VERIFY", "warn")

    class Unconvertible:
        def __array__(self, *a, **k):
            raise ValueError("no dice")

    entry = ModelRegistry().load_fitted("m", path, example=Unconvertible())
    assert entry is not None


def test_load_fitted_example_reads_dtype_from_metadata(tmp_path, monkeypatch):
    """A device-like example leaf must never be materialized host-side
    just to read its dtype."""
    from keystone_tpu.serving.registry import ModelRegistry
    from keystone_tpu.serving.synthetic import synthetic_fitted_pipeline

    path = str(tmp_path / "model")
    synthetic_fitted_pipeline(d=16, depth=1).save(path)
    monkeypatch.setenv("KEYSTONE_VERIFY", "warn")

    class DeviceLeaf:
        shape = (16,)
        dtype = np.dtype("float32")

        def __array__(self, *a, **k):  # pragma: no cover - must not run
            raise AssertionError("host copy just to read metadata")

    entry = ModelRegistry().load_fitted("m", path, example=DeviceLeaf())
    assert entry is not None


# ------------------------------------------------------------------ metrics


def test_verify_publishes_metrics():
    runs = _names.metric(_names.VERIFY_RUNS)
    diags = _names.metric(_names.VERIFY_DIAGNOSTICS)
    before_runs = runs.value(context="metrics-test")
    before_diag = diags.value(code="KV101", severity=ERROR)
    x, y = _xy(n=64, rows_y=32)
    verify_pipeline(
        LinearMapEstimator().with_data(x, y), context="metrics-test"
    )
    assert runs.value(context="metrics-test") == before_runs + 1
    assert diags.value(code="KV101", severity=ERROR) == before_diag + 1


def test_every_code_has_severity_and_title():
    for code, (severity, title) in CODES.items():
        assert severity in (ERROR, WARNING, INFO)
        assert title
        assert code.startswith("KV") and code[2:].isdigit()


def test_docs_codes_sync():
    """Every diagnostic code — verifier KV1xx-4xx, lint KV5xx, and
    concurrency KV6xx — is documented in docs/VERIFICATION.md, or this
    fails. New codes cannot land undocumented."""
    import os

    from keystone_tpu.lint import CONCURRENCY_CODES, LINT_CODES

    doc = open(
        os.path.join(
            os.path.dirname(__file__), "..", "..", "docs", "VERIFICATION.md"
        )
    ).read()
    missing = [
        code
        for code in (
            list(CODES) + list(LINT_CODES) + list(CONCURRENCY_CODES) + ["KV500"]
        )
        if f"`{code}`" not in doc
    ]
    assert not missing, f"codes undocumented in docs/VERIFICATION.md: {missing}"

    # The partitioner's reason keys are a documented surface too: every
    # key must appear in docs/PARTITIONING.md's fallback matrix.
    from keystone_tpu.parallel.partitioner import ALL_REASON_KEYS

    pdoc = open(
        os.path.join(
            os.path.dirname(__file__), "..", "..", "docs", "PARTITIONING.md"
        )
    ).read()
    missing = [key for key in ALL_REASON_KEYS if f"`{key}`" not in pdoc]
    assert not missing, f"reason keys undocumented in PARTITIONING.md: {missing}"


def test_report_json_roundtrip():
    x, y = _xy(n=64, rows_y=32)
    report = verify_pipeline(LinearMapEstimator().with_data(x, y))
    payload = report.to_json()
    assert payload["ok"] is False
    assert any(d["code"] == "KV101" for d in payload["diagnostics"])
    assert all({"node", "label", "spec"} <= set(n) for n in payload["nodes"])
