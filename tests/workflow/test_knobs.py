"""MeasuredKnobRule: plan knobs overridden from the profile store's best
recorded observations (docs/OPTIMIZER.md).

Default mode (``on``) applies only the semantics-free chunk-rows
override; precision and block size — which move numerics within solver
tolerance — require ``KEYSTONE_MEASURED_KNOBS=all``; explicit env knobs
always beat measurements.
"""

import numpy as np

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.obs.store import ProfileStore, dataset_shape_class, shape_class
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.knobs import MeasuredKnobRule, knob_mode
from keystone_tpu.workflow.operators import DatasetOperator
from keystone_tpu.workflow.streaming import StreamingFitOperator, chain_class

FP = {"jax": "test", "backend": "cpu", "device_kind": "virtual"}
N_ROWS = 4096


def store(tmp_path):
    return ProfileStore(str(tmp_path / "ps.jsonl"), fingerprint=dict(FP))


def stream_graph(chunk_rows=None):
    """dataset → StreamingFitOperator(estimator) → sink, the shape the
    rule sees after the streaming batch ran."""
    data = ArrayDataset(np.ones((N_ROWS, 8), dtype=np.float32))
    est = BlockLeastSquaresEstimator(512, num_iter=1, reg=1e-3)
    op = StreamingFitOperator(est, (), chunk_rows=chunk_rows)
    g = Graph()
    g, d = g.add_node(DatasetOperator(data), [])
    g, s = g.add_node(op, [d])
    g, _ = g.add_sink(s)
    return g, s, data


def record_stream_obs(st, data, best_rows=1024, worse_rows=256):
    shape = dataset_shape_class(data)
    cc = chain_class(())
    st.record(f"stream:{cc}:cr{worse_rows}", shape,
              chunk_rows=worse_rows, rows_per_s=1e5)
    st.record(f"stream:{cc}:cr{best_rows}", shape,
              chunk_rows=best_rows, rows_per_s=5e5)
    return shape


def test_knob_mode_parsing(monkeypatch):
    monkeypatch.delenv("KEYSTONE_MEASURED_KNOBS", raising=False)
    assert knob_mode() == "on"
    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    assert knob_mode() == "all"
    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "off")
    assert knob_mode() == "off"


def test_chunk_rows_overridden_from_best_recorded_throughput(
    tmp_path, monkeypatch
):
    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    st = store(tmp_path)
    g, node, data = stream_graph()
    record_stream_obs(st, data, best_rows=1024)
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    assert out.get_operator(node).chunk_rows == 1024


def test_explicit_env_knob_beats_measurement(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_STREAM_CHUNK_ROWS", "2048")
    st = store(tmp_path)
    g, node, data = stream_graph()
    record_stream_obs(st, data)
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    assert out.get_operator(node).chunk_rows is None  # untouched


def test_operator_pinned_chunk_rows_untouched(tmp_path, monkeypatch):
    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    st = store(tmp_path)
    g, node, data = stream_graph(chunk_rows=512)
    record_stream_obs(st, data)
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    assert out.get_operator(node).chunk_rows == 512


def test_no_matching_shape_class_no_override(tmp_path, monkeypatch):
    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    st = store(tmp_path)
    g, node, data = stream_graph()
    # observation from a 100x larger dataset: different rows bucket
    st.record(f"stream:{chain_class(())}:cr8192",
              shape_class(100 * N_ROWS, (8,), "float32"),
              chunk_rows=8192, rows_per_s=1e6)
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    assert out.get_operator(node).chunk_rows is None


def test_off_mode_is_a_no_op(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "off")
    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    st = store(tmp_path)
    g, node, data = stream_graph()
    record_stream_obs(st, data)
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    assert out.get_operator(node).chunk_rows is None


def test_precision_override_requires_all_mode(tmp_path, monkeypatch):
    from keystone_tpu.parallel import linalg

    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    st = store(tmp_path)
    st.record("solver:block_ls:bs512:precdefault",
              shape_class(N_ROWS, (8,), "float32"),
              wall_s=0.1, block_size=512, precision="default")
    st.record("solver:block_ls:bs512:precrefine",
              shape_class(N_ROWS, (8,), "float32"),
              wall_s=0.9, block_size=512, precision="refine")
    g, node, data = stream_graph()
    # default mode: numerics-touching knobs stay put
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    assert out.get_operator(node).solver_precision is None
    # all mode: fastest recorded precision is pinned onto the OPERATOR —
    # never installed as process state, so solver_mode() outside the
    # planned fit stays at the shipped default
    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    assert out.get_operator(node).solver_precision == "default"
    assert linalg.solver_mode() == "refine"
    # an explicit env choice beats the measurement: the rule skips
    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "highest")
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    assert out.get_operator(node).solver_precision is None
    assert linalg.solver_mode() == "highest"


def test_pinned_precision_scopes_only_the_planned_fit(tmp_path, monkeypatch):
    """The operator's measured precision applies around ITS fit via
    linalg.solver_mode_scope and is restored afterwards — unplanned
    solves and other threads never observe it."""
    import threading

    from keystone_tpu.parallel import linalg
    from keystone_tpu.workflow.operators import EstimatorOperator

    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    seen = {}

    class Probe(EstimatorOperator):
        label = "Probe"

        def fit_datasets(self, datasets):
            seen["during"] = linalg.solver_mode()
            other = {}
            t = threading.Thread(
                target=lambda: other.setdefault("mode", linalg.solver_mode())
            )
            t.start()
            t.join()
            seen["other_thread"] = other["mode"]
            return None

    class Dep:
        def get(self):
            return ArrayDataset(np.ones((4, 2), dtype=np.float32))

    op = Probe()
    op.solver_precision = "default"
    op.execute([Dep()]).get()
    assert seen["during"] == "default"
    assert seen["other_thread"] == "refine"  # thread-local, no leak
    assert linalg.solver_mode() == "refine"  # restored after the fit


def test_block_size_override_in_all_mode(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    from keystone_tpu.parallel import linalg

    st = store(tmp_path)
    st.record("solver:block_ls:bs128:precrefine",
              shape_class(N_ROWS, (8,), "float32"),
              wall_s=0.05, block_size=128, precision="refine")
    g, node, data = stream_graph()
    try:
        out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
        tuned = out.get_operator(node)
        assert isinstance(tuned, StreamingFitOperator)
        assert tuned.estimator.block_size == 128
    finally:
        linalg.set_solver_mode_override(None)


def test_override_metrics_are_counted(tmp_path, monkeypatch):
    from keystone_tpu.obs import names as obs_names

    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    st = store(tmp_path)
    g, node, data = stream_graph()
    record_stream_obs(st, data)
    counter = obs_names.metric(obs_names.PROFILE_STORE_KNOB_OVERRIDES)
    before = counter.value(knob="stream_chunk_rows")
    MeasuredKnobRule(profile_store=st).apply(g, {})
    assert counter.value(knob="stream_chunk_rows") == before + 1


def test_precisionless_best_entry_does_not_veto_override(tmp_path, monkeypatch):
    """The meta-solver's rung entries carry walls but no precision; a
    cheap one winning on wall_s must not disable the precision knob."""
    from keystone_tpu.parallel import linalg

    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    st = store(tmp_path)
    st.record("solver:least_squares:rung_dense_lbfgs",
              shape_class(N_ROWS, (8,), "float32"), wall_s=0.001)
    st.record("solver:block_ls:bs512:precdefault",
              shape_class(N_ROWS, (8,), "float32"),
              wall_s=0.2, block_size=512, precision="default")
    g, node, data = stream_graph()
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    assert out.get_operator(node).solver_precision == "default"
    assert linalg.solver_mode() == "refine"  # pinned, not process state


def test_stale_precision_override_cleared_by_next_plan(tmp_path, monkeypatch):
    """A plan with no measured winner for ITS shape class must clear a
    previous plan's process-global override, not inherit it."""
    from keystone_tpu.parallel import linalg

    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    st = store(tmp_path)  # empty: nothing measured
    linalg.set_solver_mode_override("default")  # leftover from elsewhere
    g, node, data = stream_graph()
    try:
        MeasuredKnobRule(profile_store=st).apply(g, {})
        assert linalg.solver_mode() == "refine"  # back to the default
    finally:
        linalg.set_solver_mode_override(None)


def test_stream_solver_walls_do_not_drive_block_size(tmp_path, monkeypatch):
    """block_ls_stream walls cover the whole ingest+featurize+Gram fold;
    they must not win the in-core block-size selection."""
    from keystone_tpu.parallel import linalg

    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    st = store(tmp_path)
    st.record("solver:block_ls_stream:bs32:precrefine",
              shape_class(N_ROWS, (8,), "float32"),
              wall_s=0.001, block_size=32, precision="refine")
    g, node, data = stream_graph()
    try:
        out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
        assert out.get_operator(node).estimator.block_size == 512  # untouched
    finally:
        linalg.set_solver_mode_override(None)


def test_override_cleared_even_when_rule_disabled(tmp_path, monkeypatch):
    """Flipping KEYSTONE_MEASURED_KNOBS off (or disabling the store) must
    not preserve a previously-installed measured precision."""
    from keystone_tpu.parallel import linalg

    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "off")
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    linalg.set_solver_mode_override("default")
    g, node, data = stream_graph()
    try:
        MeasuredKnobRule(profile_store=store(tmp_path)).apply(g, {})
        assert linalg.solver_mode() == "refine"
    finally:
        linalg.set_solver_mode_override(None)


def test_disagreeing_widths_block_solver_overrides(tmp_path, monkeypatch):
    """Absolute walls from different feature widths are incommensurable:
    when the widths in a rows bucket disagree on the winner, neither
    block size nor precision is overridden."""
    from keystone_tpu.parallel import linalg

    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    st = store(tmp_path)
    # d=8: tiny problem, tiny wall, block 16 / precision default
    st.record("solver:block_ls:bs16:precdefault",
              shape_class(N_ROWS, (8,), "float32"),
              wall_s=0.001, block_size=16, precision="default")
    # d=4096: real problem, its own winner is block 512 / refine
    st.record("solver:block_ls:bs512:precrefine",
              shape_class(N_ROWS, (4096,), "float32"),
              wall_s=2.0, block_size=512, precision="refine")
    g, node, data = stream_graph()
    try:
        out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
        assert out.get_operator(node).estimator.block_size == 512  # untouched
        assert out.get_operator(node).solver_precision is None  # no pin
        assert linalg.solver_mode() == "refine"  # no override installed
    finally:
        linalg.set_solver_mode_override(None)


def test_solver_block_env_pins_block_size(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    monkeypatch.setenv("KEYSTONE_SOLVER_BLOCK", "keep")
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    from keystone_tpu.parallel import linalg

    st = store(tmp_path)
    st.record("solver:block_ls:bs128:precrefine",
              shape_class(N_ROWS, (8,), "float32"),
              wall_s=0.05, block_size=128, precision="refine")
    g, node, data = stream_graph()
    try:
        out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
        assert out.get_operator(node).estimator.block_size == 512
    finally:
        linalg.set_solver_mode_override(None)


# ----------------------------------------------------- stale winners (drift)


def test_stale_winner_skipped_then_rerecorded(tmp_path, monkeypatch):
    """The drift sentinel's staleness contract (docs/OBSERVABILITY.md
    "Cost observatory"): a stale: winner must not be replayed; a fresh
    re-measurement of the same key re-arms the override."""
    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    st = store(tmp_path)
    g, node, data = stream_graph()
    shape = record_stream_obs(st, data, best_rows=1024)

    # the winning entry drifts: marked stale → no override
    assert st.mark_stale(f"stream:{chain_class(())}:cr1024", shape)
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    # the stale 1024 winner is skipped; the surviving (worse-throughput)
    # 256 observation becomes the defensible best
    assert out.get_operator(node).chunk_rows == 256

    # a completed fold re-records the key fresh → winner re-arms
    st.record(f"stream:{chain_class(())}:cr1024", shape,
              chunk_rows=1024, rows_per_s=5e5)
    out2, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    assert out2.get_operator(node).chunk_rows == 1024


def test_override_pins_prediction_for_the_cost_observatory(
    tmp_path, monkeypatch
):
    """Every measured override carries its stored claim as a
    predicted_cost (obs.cost.Prediction) so the perf ledger can join it
    against the measured wall — calibrated for chunk-rows (exact key +
    shape class), displayed-only for solver knobs (walls across widths
    are incommensurable)."""
    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    st = store(tmp_path)
    g, node, data = stream_graph()
    shape = record_stream_obs(st, data, best_rows=1024)
    out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
    pred = out.get_operator(node).predicted_cost
    assert pred is not None
    assert pred.model == "measured_knob"
    assert pred.key == f"stream:{chain_class(())}:cr1024"
    assert pred.shape == shape
    assert pred.rows_per_s == 5e5
    assert pred.calibrated is True


def test_stale_winner_skipped_in_fresh_process(tmp_path, monkeypatch):
    """The stale mark is file provenance: a FRESH process planning from
    the same store must also skip the marked winner."""
    import json as _json
    import os as _os
    import subprocess
    import sys

    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    st = store(tmp_path)
    g, node, data = stream_graph()
    shape = record_stream_obs(st, data, best_rows=1024)
    assert st.mark_stale(f"stream:{chain_class(())}:cr1024", shape)

    code = """
import json, sys
import numpy as np
from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.obs.store import ProfileStore
from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.knobs import MeasuredKnobRule
from keystone_tpu.workflow.operators import DatasetOperator
from keystone_tpu.workflow.streaming import StreamingFitOperator

fp = {"jax": "test", "backend": "cpu", "device_kind": "virtual"}
st = ProfileStore(sys.argv[1], fingerprint=fp)
data = ArrayDataset(np.ones((4096, 8), dtype=np.float32))
est = BlockLeastSquaresEstimator(512, num_iter=1, reg=1e-3)
g = Graph()
g, d = g.add_node(DatasetOperator(data), [])
g, s = g.add_node(StreamingFitOperator(est, ()), [d])
g, _ = g.add_sink(s)
out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
print(json.dumps({"chunk_rows": out.get_operator(s).chunk_rows}))
"""
    env = {**_os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("KEYSTONE_STREAM_CHUNK_ROWS", None)
    result = subprocess.run(
        [sys.executable, "-c", code, st.path],
        capture_output=True, text=True, check=True, env=env,
    )
    payload = _json.loads(result.stdout.strip().splitlines()[-1])
    # the stale 1024 winner is skipped in the fresh process too
    assert payload["chunk_rows"] == 256


# ------------------------------------------------------------- sketch size


def sketch_graph(est=None):
    """dataset → StreamingFitOperator(meta least-squares) → sink: the
    shape whose width dispatch may route onto the sketched rung."""
    from keystone_tpu.ops.learning.least_squares import LeastSquaresEstimator

    data = ArrayDataset(np.ones((N_ROWS, 8), dtype=np.float32))
    est = est or LeastSquaresEstimator(reg=1e-3)
    op = StreamingFitOperator(est, ())
    g = Graph()
    g, d = g.add_node(DatasetOperator(data), [])
    g, s = g.add_node(op, [d])
    g, _ = g.add_sink(s)
    return g, s, data


def record_sketch_obs(st, s=256, wall_s=0.02, rows=N_ROWS, d=8):
    st.record(f"solver:sketch_ls:bs{s}:precrefine",
              shape_class(rows, (d,), "float32"),
              wall_s=wall_s, sketch_size=s, sketch_variant="countsketch")


def test_sketch_size_override_in_all_mode(tmp_path, monkeypatch):
    """The best-wall sketch_ls observation rides onto the meta-solver as
    _tuned_sketch_size — the width dispatch AND the ladder's pricing
    both read it (docs/SOLVERS.md)."""
    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    monkeypatch.delenv("KEYSTONE_SKETCH_SIZE", raising=False)
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    from keystone_tpu.parallel import linalg

    st = store(tmp_path)
    record_sketch_obs(st, s=512, wall_s=0.1)
    record_sketch_obs(st, s=256, wall_s=0.02)  # best wall wins
    g, node, _ = sketch_graph()
    try:
        out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
        tuned = out.get_operator(node).estimator
        assert tuned._tuned_sketch_size == 256
    finally:
        linalg.set_solver_mode_override(None)


def test_sketch_env_knob_beats_measurement(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    monkeypatch.setenv("KEYSTONE_SKETCH_SIZE", "1024")
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    from keystone_tpu.parallel import linalg

    st = store(tmp_path)
    record_sketch_obs(st)
    g, node, _ = sketch_graph()
    try:
        out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
        assert getattr(
            out.get_operator(node).estimator, "_tuned_sketch_size", None
        ) is None
    finally:
        linalg.set_solver_mode_override(None)


def test_constructor_pinned_sketch_size_untouched(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    monkeypatch.delenv("KEYSTONE_SKETCH_SIZE", raising=False)
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    from keystone_tpu.parallel import linalg
    from keystone_tpu.sketch.solvers import SketchedLeastSquaresEstimator

    st = store(tmp_path)
    record_sketch_obs(st)
    g, node, _ = sketch_graph(
        est=SketchedLeastSquaresEstimator(reg=1e-3, sketch_size=128)
    )
    try:
        out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
        assert getattr(
            out.get_operator(node).estimator, "_tuned_sketch_size", None
        ) is None
    finally:
        linalg.set_solver_mode_override(None)


def test_disagreeing_widths_block_sketch_override(tmp_path, monkeypatch):
    """Unanimity across feature widths in the rows bucket, same as the
    block-size knob: disagreeing widths veto the override and count a
    non_unanimous rejection."""
    from keystone_tpu.obs import names as obs_names
    from keystone_tpu.parallel import linalg

    monkeypatch.setenv("KEYSTONE_MEASURED_KNOBS", "all")
    monkeypatch.delenv("KEYSTONE_SKETCH_SIZE", raising=False)
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    monkeypatch.delenv("KEYSTONE_STREAM_CHUNK_ROWS", raising=False)
    st = store(tmp_path)
    record_sketch_obs(st, s=256, d=8)
    record_sketch_obs(st, s=512, d=16)  # another width disagrees
    counter = obs_names.metric(obs_names.KNOB_REJECTED)
    before = counter.value(knob="sketch_size", reason="non_unanimous")
    g, node, _ = sketch_graph()
    try:
        out, _ = MeasuredKnobRule(profile_store=st).apply(g, {})
        assert getattr(
            out.get_operator(node).estimator, "_tuned_sketch_size", None
        ) is None
        assert counter.value(
            knob="sketch_size", reason="non_unanimous"
        ) == before + 1
    finally:
        linalg.set_solver_mode_override(None)
