"""Graph IR surgery invariants (reference: workflow/GraphSuite.scala)."""

import pytest

from keystone_tpu.workflow.graph import Graph, NodeId, SinkId, SourceId
from keystone_tpu.workflow.operators import TransformerOperator
from keystone_tpu.workflow import analysis


class Op(TransformerOperator):
    def __init__(self, name):
        self.name = name

    @property
    def label(self):
        return self.name

    def single_transform(self, datums):
        return datums[0]


def simple_graph():
    g = Graph()
    g, src = g.add_source()
    g, a = g.add_node(Op("a"), [src])
    g, b = g.add_node(Op("b"), [a])
    g, sink = g.add_sink(b)
    return g, src, a, b, sink


def test_add_node_and_sink():
    g, src, a, b, sink = simple_graph()
    assert g.sources == {src}
    assert g.nodes == {a, b}
    assert g.get_sink_dependency(sink) == b
    assert g.get_dependencies(b) == (a,)


def test_ids_are_unique():
    g, src, a, b, sink = simple_graph()
    ids = {src.id, a.id, b.id, sink.id}
    assert len(ids) == 4


def test_remove_referenced_node_fails():
    g, src, a, b, sink = simple_graph()
    with pytest.raises(ValueError):
        g.remove_node(a)  # b depends on a
    with pytest.raises(ValueError):
        g.remove_source(src)  # a depends on src


def test_remove_after_redirect():
    g, src, a, b, sink = simple_graph()
    g = g.replace_dependency(a, src)
    g = g.remove_node(a)
    assert g.nodes == {b}
    assert g.get_dependencies(b) == (src,)


def test_replace_dependency_affects_sinks():
    g, src, a, b, sink = simple_graph()
    g = g.replace_dependency(b, a)
    assert g.get_sink_dependency(sink) == a


def test_add_graph_remaps_ids_disjointly():
    g1, src1, a1, b1, sink1 = simple_graph()
    g2, src2, a2, b2, sink2 = simple_graph()
    combined, source_map, sink_map = g1.add_graph(g2)
    assert len(combined.nodes) == 4
    assert len(combined.sources) == 2
    assert len(combined.sinks) == 2
    assert source_map[src2] != src1
    # original graph untouched
    assert len(g1.nodes) == 2


def test_connect_graph_splices():
    g1, src1, a1, b1, sink1 = simple_graph()
    g2, src2, c, d, sink2 = simple_graph()
    combined, source_map, sink_map = g1.connect_graph(g2, {src2: sink1})
    # spliced source and sink are gone
    assert len(combined.sources) == 1
    assert len(combined.sinks) == 1
    # g2's first node now depends on g1's last node
    new_sink = sink_map[sink2]
    order = analysis.linearize(combined, new_sink)
    assert order[0] == src1
    assert len([v for v in order if isinstance(v, NodeId)]) == 4


def test_operator_update():
    g, src, a, b, sink = simple_graph()
    new_op = Op("z")
    g = g.set_operator(a, new_op)
    assert g.get_operator(a) is new_op


def test_dot_export_contains_all_vertices():
    g, src, a, b, sink = simple_graph()
    dot = g.to_dot()
    for vid in [src, a, b, sink]:
        assert repr(vid) in dot


def test_analysis_ancestors_descendants():
    g, src, a, b, sink = simple_graph()
    assert analysis.get_ancestors(g, sink) == {src, a, b}
    assert analysis.get_descendants(g, src) == {a, b, sink}
    assert analysis.get_children(g, a) == {b}
    assert analysis.get_parents(g, b) == [a]


def test_linearize_is_topological():
    g, src, a, b, sink = simple_graph()
    order = analysis.linearize(g, sink)
    assert order.index(src) < order.index(a) < order.index(b) < order.index(sink)
