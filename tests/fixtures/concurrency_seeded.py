"""Seeded concurrency bugs — the CI negative control for KV6xx.

``scripts/check_smoke.sh`` runs ``keystone-tpu check --concurrency``
over this file and REQUIRES it to fail with KV601 (the unlocked guarded
write in ``Telemetry._loop``) and KV602 (the ``Gate``/``Ledger``
lock-order cycle). An analyzer that stops flagging these planted bugs
fails the smoke, not a user. Never "fix" this file.
"""

import threading


class Telemetry:
    """KV601 seed: ``_served`` is lock-guarded everywhere except the
    mutation on the worker thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._served = 0
        self._worker = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while True:
            self._served += 1  # planted: majority-guarded, mutated unlocked

    def record(self):
        with self._lock:
            self._served += 1

    def snapshot(self):
        with self._lock:
            return self._served


class Gate:
    """KV602 seed, half one: holds its lock while poking the ledger."""

    def __init__(self, ledger: "Ledger"):
        self._lock = threading.Lock()
        self._ledger = ledger

    def poke(self):
        with self._lock:
            pass

    def admit(self):
        with self._lock:
            self._ledger.poke()  # planted: Gate._lock held -> Ledger._lock


class Ledger:
    """KV602 seed, half two: the opposite order."""

    def __init__(self, gate: Gate):
        self._lock = threading.Lock()
        self._gate = gate

    def poke(self):
        with self._lock:
            pass

    def record(self):
        with self._lock:
            self._gate.poke()  # planted: Ledger._lock held -> Gate._lock
