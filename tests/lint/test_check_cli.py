"""``keystone-tpu check`` end-to-end: the static tier's CLI contract
(exit codes, JSON shape, zero-compile guarantee) that
scripts/check_smoke.sh builds on."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_check(*args):
    return subprocess.run(
        [sys.executable, "-m", "keystone_tpu", "check", *args],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )


def test_check_help_is_jax_free():
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.modules['jax'] = None; "
         "from keystone_tpu.cli import main; main(['check', '--help'])"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "--pipeline" in proc.stdout and "--lint" in proc.stdout
    assert "--concurrency" in proc.stdout


@pytest.mark.slow
def test_check_lint_shipped_tree_clean():
    proc = run_check("--lint", "keystone_tpu", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["ok"] is True
    assert payload["lint"]["findings"] == []


@pytest.mark.slow
def test_check_pipeline_seeded_mismatch_zero_compiles():
    proc = run_check(
        "--pipeline", "synthetic", "--seed-mismatch",
        "--buckets", "8,32", "--warmed-buckets", "8", "--json",
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    codes = [d["code"] for d in payload["pipeline"]["diagnostics"]]
    assert "KV101" in codes and "KV301" in codes
    assert payload["xla_compiles"] == 0
    assert payload["pipeline"]["seconds"] < 1.0


@pytest.mark.slow
def test_check_pipeline_clean_synthetic_passes():
    proc = run_check(
        "--pipeline", "synthetic",
        "--buckets", "8,32", "--warmed-buckets", "8,32", "--json",
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["pipeline"]["ok"] is True
    assert payload["xla_compiles"] == 0


def test_check_concurrency_seeded_fixture_jax_free():
    """The smoke's concurrency contract end-to-end: the seeded fixture
    (lock-order cycle + unlocked guarded write) exits 1 with KV601+KV602,
    fast, without importing jax."""
    proc = run_check(
        "--concurrency",
        os.path.join("tests", "fixtures", "concurrency_seeded.py"),
        "--json",
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    conc = payload["concurrency"]
    codes = {f["rule"] for f in conc["findings"]}
    assert {"KV601", "KV602"} <= codes
    assert conc["jax_free"] is True
    assert conc["seconds"] < 1.0


@pytest.mark.slow
def test_check_lint_and_concurrency_shipped_tree_one_payload():
    """KV5xx and KV6xx findings ride one --json payload (both clean on
    the shipped tree), and the lock graph is exported for the witness."""
    proc = run_check(
        "--lint", "keystone_tpu", "--concurrency", "keystone_tpu", "--json"
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["lint"]["findings"] == []
    assert payload["concurrency"]["findings"] == []
    graph = payload["concurrency"]["lock_graph"]
    assert len(graph["locks"]) >= 25
    assert graph["edges"]


def test_check_without_flags_is_usage_error():
    from argparse import Namespace

    from keystone_tpu.lint.check import check_from_args

    args = Namespace(
        lint=None, concurrency=None, pipeline=None, input_spec=None,
        buckets=None, warmed_buckets=None, seed_mismatch=False, as_json=False,
    )
    assert check_from_args(args) == 2
