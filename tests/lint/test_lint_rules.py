"""keystone-lint rules (lint/rules.py) on fixture snippets, plus the
shipped-tree cleanliness gate CI relies on."""

import os
import textwrap

import pytest

from keystone_tpu.lint import (
    LINT_CODES,
    Finding,
    LintContext,
    build_context,
    lint_paths,
    lint_source,
)

CTX = LintContext(
    metric_names={"keystone_good_total"},
    probe_sites={"serving.apply"},
)


def run(src, path="pkg/mod.py", ctx=CTX):
    return lint_source(textwrap.dedent(src), path=path, context=ctx)


def codes(src, path="pkg/mod.py", ctx=CTX):
    return [f.rule for f in run(src, path, ctx)]


# ------------------------------------------------------------------- KV501


def test_env_read_flagged():
    assert codes("import os\nx = os.environ.get('KEYSTONE_FOO')\n") == ["KV501"]
    assert codes("import os\nx = os.getenv('KEYSTONE_FOO')\n") == ["KV501"]
    assert codes("import os\nx = os.environ['KEYSTONE_FOO']\n") == ["KV501"]
    assert codes("import os\nok = 'X' in os.environ\n") == ["KV501"]
    assert codes("import os\nenv = dict(os.environ)\n") == ["KV501"]


def test_env_write_allowed():
    assert codes("import os\nos.environ['X'] = 'y'\n") == []
    assert codes("import os\nos.environ.pop('X', None)\n") == []
    assert codes("import os\nos.environ.update({'X': 'y'})\n") == []


def test_env_pragma_same_line_and_above():
    assert codes(
        "import os\nenv = dict(os.environ)  # keystone: allow-env\n"
    ) == []
    assert codes(
        """\
        import os
        # child env is a structural clone  # keystone: allow-env
        env = dict(os.environ)
        """
    ) == []


def test_env_rule_skips_envknobs_module():
    src = "import os\nx = os.environ.get('K')\n"
    assert codes(src, path="keystone_tpu/envknobs.py") == []
    assert codes(src, path="keystone_tpu/other.py") == ["KV501"]


# ------------------------------------------------------------------- KV502

HOT = os.path.join("keystone_tpu", "serving", "server.py")


def test_sync_flagged_only_in_hot_modules():
    src = "import jax\njax.block_until_ready(x)\n"
    assert codes(src, path=HOT) == ["KV502"]
    assert codes(src, path="keystone_tpu/ops/learning/zca.py") == []


def test_sync_variants_flagged():
    assert codes("v = x.item()\n", path=HOT) == ["KV502"]
    assert codes("import numpy as np\nv = np.asarray(x)\n", path=HOT) == [
        "KV502"
    ]
    # .item(i) (indexed) and non-numpy asarray are not the sync idiom
    assert codes("v = x.item(3)\n", path=HOT) == []
    assert codes("v = obj.asarray(x)\n", path=HOT) == []


def test_sync_under_sync_gate_allowed():
    assert codes(
        """\
        def timed(sync):
            if sync:
                x.block_until_ready()
        """,
        path=HOT,
    ) == []
    assert codes(
        """\
        def force_sync(value):
            value.block_until_ready()
        """,
        path=HOT,
    ) == []


def test_sync_pragma_allowed():
    assert codes(
        "x.block_until_ready()  # completion barrier  # keystone: allow-sync\n",
        path=HOT,
    ) == []


# ------------------------------------------------------------------- KV503


def test_undeclared_metric_name_flagged():
    assert codes("m = metric('keystone_bad_total')\n") == ["KV503"]
    assert codes("m = metric('keystone_good_total')\n") == []


def test_metric_shape_excludes_package_paths_and_docstrings():
    assert codes("import_module('keystone_tpu.data.dataset')\n") == []
    assert codes("x = 'keystone_tpu'\n") == []
    assert codes('"""mentions keystone_bad_total in a docstring"""\n') == []
    # no schema context → rule disabled, not a false positive storm
    assert codes("m = metric('keystone_bad_total')\n", ctx=LintContext()) == []


# ------------------------------------------------------------------- KV504


def test_unregistered_probe_site_flagged():
    assert codes("probe('serving.apply')\n") == []
    assert codes("probe('serving.unknown')\n") == ["KV504"]


def test_probe_site_resolved_through_module_constant():
    assert codes(
        "SITE = 'serving.unknown'\ndef f():\n    probe(SITE)\n"
    ) == ["KV504"]
    assert codes(
        "SITE = 'serving.apply'\ndef f():\n    probe(SITE)\n"
    ) == []
    # unresolvable labels are skipped, not guessed at
    assert codes("def f(site):\n    probe(site)\n") == []


# ------------------------------------------------------------------- KV505


def test_donation_requires_ownership_annotation():
    assert codes(
        "import jax\nf = jax.jit(g, donate_argnums=(0,))\n"
    ) == ["KV505"]
    assert codes(
        """\
        import jax
        # carry is loop-owned  # keystone: owns-donated
        f = jax.jit(g, donate_argnums=(0,))
        """
    ) == []
    # an unconditionally empty tuple donates nothing
    assert codes(
        "import jax\nf = jax.jit(g, donate_argnums=())\n"
    ) == []
    # a conditional donation still donates on one branch
    assert codes(
        "import jax\nf = jax.jit(g, donate_argnums=(0,) if d else ())\n"
    ) == ["KV505"]


# ------------------------------------------------------------------ driver


def test_syntax_error_reported_not_raised():
    findings = run("def broken(:\n")
    assert [f.rule for f in findings] == ["KV500"]


def test_finding_render_and_json():
    f = Finding("KV501", "a.py", 3, "msg")
    assert f.render() == "a.py:3: KV501 msg"
    assert f.to_json() == {
        "rule": "KV501", "path": "a.py", "line": 3, "message": "msg",
    }


def test_lint_codes_table():
    assert set(LINT_CODES) == {
        "KV501", "KV502", "KV503", "KV504", "KV505", "KV506",
    }


def test_build_context_reads_real_registries():
    import keystone_tpu

    root = os.path.dirname(keystone_tpu.__file__)
    ctx = build_context(root)
    assert "keystone_verify_runs_total" in ctx.metric_names
    assert "serving.apply" in ctx.probe_sites


def test_shipped_tree_is_clean():
    """The CI gate: keystone-lint over the shipped package finds
    nothing. A new finding means either fix the code or annotate the
    reviewed exception — never ignore."""
    import keystone_tpu

    root = os.path.dirname(keystone_tpu.__file__)
    findings = lint_paths([root])
    assert findings == [], "\n".join(f.render() for f in findings)


# ------------------------------------------- pinned true-positive fixes


def test_device_annotations_env_read_is_call_time(monkeypatch):
    """KV501 true positive fixed: KEYSTONE_DEVICE_ANNOTATIONS used to be
    read at import time, so flipping it after import (or monkeypatching
    in a test, like this one) was silently ignored."""
    from keystone_tpu.obs import device

    monkeypatch.setattr(device, "_annotations_enabled", None)
    monkeypatch.delenv("KEYSTONE_DEVICE_ANNOTATIONS", raising=False)
    assert device.annotations_enabled() is False
    monkeypatch.setenv("KEYSTONE_DEVICE_ANNOTATIONS", "1")
    assert device.annotations_enabled() is True
    device.set_device_annotations(False)
    try:
        assert device.annotations_enabled() is False  # override wins
    finally:
        device.set_device_annotations(None)
    assert device.annotations_enabled() is True  # env default restored


def test_group_batch_reads_metadata_without_host_sync():
    """KV502 true positive fixed: batch grouping used np.asarray on every
    payload leaf — a synchronous device→host copy per request — just to
    read the shape. It must use leaf metadata."""
    from keystone_tpu.serving.config import Request
    from keystone_tpu.serving.server import PipelineServer

    class DeviceLeaf:
        shape = (4,)
        dtype = "float32"

        def __array__(self, *a, **k):  # pragma: no cover - must not run
            raise AssertionError("host sync on the grouping path")

    reqs = [Request(payload=DeviceLeaf(), model="m") for _ in range(3)]
    groups = PipelineServer._group_batch(reqs)
    assert len(groups) == 1 and len(groups[0]) == 3


# ------------------------------------------------------------------- KV506


def test_cost_analysis_outside_home_flagged():
    src = """
    def harvest(compiled):
        return compiled.cost_analysis()
    """
    assert codes(src) == ["KV506"]
    # bare-name calls count too
    assert codes("x = cost_analysis()\n") == ["KV506"]


def test_cost_analysis_in_obs_cost_allowed():
    src = "facts = lowered.cost_analysis()\n"
    assert codes(src, path=os.path.join("pkg", "obs", "cost.py")) == []


def test_cost_analysis_mention_without_call_ok():
    # docstrings/comments/attribute references don't flag — only calls
    src = '"""uses cost_analysis() downstream"""\nname = "cost_analysis"\n'
    assert codes(src) == []


def test_kv506_registered():
    assert "KV506" in LINT_CODES
