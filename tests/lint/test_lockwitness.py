"""Lock witness (lint/lockwitness.py): wrapper mechanics, the
model-vs-runtime cross-check over real threaded components, and the
committed lock-order baseline's subset invariant."""

import json
import os
import threading

import pytest

from keystone_tpu.lint.lockmodel import CALLBACK, build_model
from keystone_tpu.lint.lockwitness import LockWitness, lock_witness

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PACKAGE = os.path.join(REPO, "keystone_tpu")
BASELINE = os.path.join(PACKAGE, "lint", "lockorder_baseline.json")

_model_cache = {}


def model():
    if "m" not in _model_cache:
        _model_cache["m"] = build_model([PACKAGE])
    return _model_cache["m"]


# ----------------------------------------------------------------- wrapper


def test_nested_acquisition_records_one_edge():
    with lock_witness(site_names={}) as w:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with a:  # repeat: same edge, higher count
            with b:
                pass
    edges = w.observed_edges()
    assert len(edges) == 1
    ((edge, count),) = edges.items()
    assert count == 2


def test_reentrant_rlock_records_no_self_edge():
    with lock_witness(site_names={}) as w:
        r = threading.RLock()
        with r:
            with r:
                pass
    assert w.observed_edges() == {}


def test_release_unwinds_held_stack():
    with lock_witness(site_names={}) as w:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            pass
        with b:  # a released first: no a->b edge
            pass
    assert w.observed_edges() == {}


def test_condition_over_witnessed_lock_works():
    with lock_witness(site_names={}) as w:
        lk = threading.Lock()
        cond = threading.Condition(lk)
        hits = []

        def waiter():
            with cond:
                cond.wait(1.0)
                hits.append(1)

        t = threading.Thread(target=waiter)
        t.start()
        deadline = 50
        while not lk.locked() and deadline:
            deadline -= 1
            import time

            time.sleep(0.01)
        with cond:
            cond.notify_all()
        t.join(2.0)
        assert hits == [1]


def test_uninstall_restores_factories():
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    with lock_witness(site_names={}):
        assert threading.Lock is not orig_lock
    assert threading.Lock is orig_lock
    assert threading.RLock is orig_rlock


def test_site_naming_against_static_table():
    w = LockWitness(site_names={(os.path.join("serving", "batcher.py"), 46): "X"})
    name, known = w._name_for("/somewhere/keystone_tpu/serving/batcher.py", 46)
    assert (name, known) == ("X", True)
    name, known = w._name_for("/somewhere/else/other.py", 3)
    assert known is False and name.endswith("other.py:3")


def test_unknown_edges_respects_open_world_holders():
    w = LockWitness(site_names={("a.py", 1): "A", ("a.py", 2): "B", ("a.py", 3): "C"})
    w._edges[("A", "B")] = 1  # anticipated via A -> <callback>
    w._edges[("B", "C")] = 1  # genuine drift
    w._edges[("B", "zz.py:9")] = 1  # foreign endpoint: recorded, not drift
    static = {("A", CALLBACK)}
    assert w.unknown_edges(static) == [("B", "C")]


# --------------------------------------------- runtime vs static cross-check


def test_threaded_components_take_no_edge_missing_from_model():
    """The acceptance invariant, in-process: drive the threaded serving/
    ingest components under the witness; every acquisition edge between
    model-known locks must be in the static graph (or covered by an
    open-world holder)."""
    m = model()
    with lock_witness(site_names=m.alloc_sites()) as w:
        from keystone_tpu.serving.batcher import MicroBatcher
        from keystone_tpu.serving.config import Request

        mb = MicroBatcher(64)
        stop = threading.Event()

        def producer():
            for i in range(100):
                mb.offer(Request(payload=[float(i)], model="m"))

        def consumer():
            while not stop.is_set() or mb.depth():
                mb.next_batch(8, 0.001, stop=stop)

        cons = threading.Thread(target=consumer)
        cons.start()
        producers = [threading.Thread(target=producer) for _ in range(2)]
        for t in producers:
            t.start()
        for t in producers:
            t.join()
        stop.set()
        cons.join(5.0)

        from keystone_tpu.data.ingest import PrefetchQueue

        with PrefetchQueue(
            range(40), prepare=lambda x: x * 2, depth=2, workers=2
        ) as pq:
            assert len(list(pq)) == 40

        from keystone_tpu.serving.registry import ModelRegistry

        registry = ModelRegistry()

        class M:
            def apply_batch(self, ds):
                return ds

        def swapper():
            for _ in range(50):
                registry.publish("m", M())

        def resolver():
            for _ in range(50):
                registry.resolve("m")
                registry.describe()

        registry.publish("m", M())
        ts = [threading.Thread(target=f) for f in (swapper, resolver)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

        from keystone_tpu.serving.admission import AdmissionController
        from keystone_tpu.serving.telemetry import ServingTelemetry

        telemetry = ServingTelemetry()
        admission = AdmissionController(16)

        def hammer():
            for i in range(100):
                telemetry.record_request(0.001, 0.0005)
                telemetry.record_batch(4, 4, 8)
                try:
                    admission.admit(i % 20)
                except Exception:
                    pass
            telemetry.snapshot()
            admission.stats()

        ts = [threading.Thread(target=hammer) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    assert w.observed_edges(), "witness saw no edges — instrumentation broken"
    unknown = w.unknown_edges(m.edge_pairs())
    assert unknown == [], (
        f"runtime acquisition edges missing from the static graph: {unknown}"
    )


# ------------------------------------------------------------------ baseline


def test_baseline_observed_edges_subset_of_static_graph():
    """The committed baseline (edges the threaded tier-1 suites actually
    took) must stay inside the CURRENT static graph: a model change that
    loses an edge the runtime takes fails here, not silently."""
    with open(BASELINE) as fh:
        baseline = json.load(fh)
    m = model()
    static = m.edge_pairs()
    open_world = {a for (a, b) in static if b == CALLBACK}
    missing = [
        (a, b)
        for a, b in (tuple(e) for e in baseline["observed_edges"])
        if (a, b) not in static and a not in open_world
    ]
    assert missing == [], (
        f"baseline edges no longer in the static lock-order graph: {missing} "
        "— regenerate lint/lockorder_baseline.json or fix the model"
    )


def test_baseline_locks_still_exist():
    with open(BASELINE) as fh:
        baseline = json.load(fh)
    names = set(model().locks) | {CALLBACK}
    for a, b in baseline["static_edges"]:
        assert a in names, f"baseline references unknown lock {a!r}"
        assert b in names, f"baseline references unknown lock {b!r}"
