"""Concurrency tier (lint/lockmodel.py + lint/concurrency.py): KV6xx
rules on fixture snippets, the model's inference machinery, and the
shipped-tree cleanliness gate CI relies on."""

import os
import textwrap

import pytest

from keystone_tpu.lint import (
    CONCURRENCY_CODES,
    analyze_paths,
    analyze_sources,
    build_model,
)
from keystone_tpu.lint.lockmodel import CALLBACK

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
SEEDED = os.path.join(REPO, "tests", "fixtures", "concurrency_seeded.py")


def codes(sources):
    if isinstance(sources, str):
        sources = {"mod.py": textwrap.dedent(sources)}
    findings, _model = analyze_sources(
        {k: textwrap.dedent(v) for k, v in sources.items()}
    )
    return [f.rule for f in findings]


# ------------------------------------------------------------------- KV601

GUARDED = """
    import threading

    class Telemetry:
        def __init__(self):
            self._lock = threading.Lock()
            self._served = 0
            self._t = threading.Thread(target=self._loop, daemon=True)

        def _loop(self):
            while True:
                self._served += 1{pragma}

        def record(self):
            with self._lock:
                self._served += 1

        def snapshot(self):
            with self._lock:
                return self._served
"""


def test_unlocked_guarded_write_flagged():
    findings, _ = analyze_sources(
        {"mod.py": textwrap.dedent(GUARDED.format(pragma=""))}
    )
    assert [f.rule for f in findings] == ["KV601"]
    f = findings[0]
    assert f.details["guard"].endswith("Telemetry._lock")
    assert f.details["thread_reachable"] is True


def test_unlocked_guarded_write_pragma():
    assert codes(
        GUARDED.format(pragma="  # reviewed  # keystone: allow-unguarded(benign)")
    ) == []


def test_unguarded_attr_not_flagged():
    # No majority guard inferred -> no KV601 (unlocked everywhere is a
    # different bug class the rule deliberately does not guess at).
    assert codes(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                self._n += 1

            def read(self):
                return self._n
        """
    ) == []


def test_reads_outside_lock_are_snapshot_idiom():
    assert codes(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def other(self):
                with self._lock:
                    self._n = 0

            def read_racy_snapshot(self):
                return self._n
        """
    ) == []


def test_locked_suffix_methods_inherit_callers_held_set():
    # The house convention: *_locked helpers run with the caller's lock.
    assert codes(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def _drain_locked(self):
                self._items.clear()

            def use(self):
                with self._lock:
                    self._items.append(1)
                    self._drain_locked()

            def use2(self):
                with self._lock:
                    self._drain_locked()
        """
    ) == []


def test_condition_counts_as_its_wrapped_lock():
    assert codes(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._items = []

            def put(self):
                with self._cond:
                    self._items.append(1)

            def also(self):
                with self._lock:
                    self._items.append(2)

            def peek(self):
                with self._cond:
                    return len(self._items)
        """
    ) == []


def test_init_writes_never_flagged():
    src = GUARDED.format(pragma="")
    # __init__ writes self._served = 0 unlocked; only _loop is flagged.
    findings, _ = analyze_sources({"mod.py": textwrap.dedent(src)})
    assert all("__init__" not in f.details["func"] for f in findings)


# ------------------------------------------------------------------- KV602

CYCLE = """
    import threading

    class A:
        def __init__(self, b: "B"):
            self._lock = threading.Lock()
            self._b = b

        def poke(self):
            with self._lock:
                pass

        def cross(self):
            with self._lock:
                self._b.poke(){pragma}

    class B:
        def __init__(self, a: A):
            self._lock = threading.Lock()
            self._a = a

        def poke(self):
            with self._lock:
                pass

        def cross(self):
            with self._lock:
                self._a.poke()
"""


def test_lock_order_cycle_flagged_with_path():
    findings, model = analyze_sources(
        {"mod.py": textwrap.dedent(CYCLE.format(pragma=""))}
    )
    assert [f.rule for f in findings] == ["KV602"]
    cycle = findings[0].details["cycle"]
    assert cycle[0] == cycle[-1] and len(cycle) == 3  # A -> B -> A
    assert ("mod.A._lock", "mod.B._lock") in model.edge_pairs()
    assert ("mod.B._lock", "mod.A._lock") in model.edge_pairs()


def test_lock_order_pragma_drops_edge_from_cycles_not_graph():
    findings, model = analyze_sources(
        {
            "mod.py": textwrap.dedent(
                CYCLE.format(pragma="  # keystone: allow-lock-order(disjoint)")
            )
        }
    )
    assert [f.rule for f in findings] == []
    # The edge stays in the graph (the witness still compares against it).
    assert ("mod.A._lock", "mod.B._lock") in model.edge_pairs()


def test_lock_order_pragma_is_per_site_not_per_pair():
    """One annotated site must not hide an UNREVIEWED site elsewhere
    producing the same (holder, acquired) pair."""
    findings, _ = analyze_sources(
        {
            "mod.py": textwrap.dedent(
                CYCLE.format(pragma="  # keystone: allow-lock-order(disjoint)")
                + """

                class A2:
                    def __init__(self, b: "B"):
                        self._lock_extra = threading.Lock()

                def second_site(a: A, b: "B"):
                    with a._lock:
                        b.poke()
                """
            )
        }
    )
    # The pragmaed site is excused, but second_site re-creates the
    # A._lock -> B._lock edge without review: the cycle must come back.
    assert [f.rule for f in findings] == ["KV602"]


def test_closure_bodies_are_analyzed():
    """A guarded-write bug written as a closure spawned on a thread is
    the same bug as a method — the model walks nested defs with their
    own (fresh) held set."""
    findings, model = analyze_sources(
        {
            "mod.py": textwrap.dedent(
                """
                import threading

                class C:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._served = 0

                    def start(self):
                        def loop():
                            while True:
                                self._served += 1
                        threading.Thread(target=loop, daemon=True).start()

                    def record(self):
                        with self._lock:
                            self._served += 1

                    def snapshot(self):
                        with self._lock:
                            return self._served
                """
            )
        }
    )
    assert [f.rule for f in findings] == ["KV601"]
    assert "<local loop>" in findings[0].details["func"]
    assert findings[0].details["thread_reachable"] is True


def test_self_deadlock_on_plain_lock_flagged():
    findings, _ = analyze_sources(
        {
            "mod.py": textwrap.dedent(
                """
                import threading

                class C:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
                """
            )
        }
    )
    assert [f.rule for f in findings] == ["KV602"]
    assert "self-deadlock" in findings[0].message


def test_rlock_reentry_not_flagged():
    assert codes(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
        """
    ) == []


def test_cross_module_transitive_edge():
    findings, model = analyze_sources(
        {
            "a.py": textwrap.dedent(
                """
                import threading

                class Ledger:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def record(self):
                        with self._lock:
                            pass
                """
            ),
            "b.py": textwrap.dedent(
                """
                import threading
                from a import Ledger

                class Gate:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._ledger = Ledger()

                    def admit(self):
                        with self._lock:
                            self._ledger.record()
                """
            ),
        }
    )
    assert ("b.Gate._lock", "a.Ledger._lock") in model.edge_pairs()
    assert findings == []


# ------------------------------------------------------------------- KV603


def test_blocking_under_lock_flagged():
    findings, _ = analyze_sources(
        {
            "mod.py": textwrap.dedent(
                """
                import threading, time

                class C:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def bad(self, future):
                        with self._lock:
                            time.sleep(1.0)
                            y = future.result(timeout=2)
                        return y
                """
            )
        }
    )
    assert [f.rule for f in findings] == ["KV603", "KV603"]
    kinds = {f.details["kind"] for f in findings}
    assert kinds == {"sleep", "result"}


def test_blocking_outside_lock_not_flagged():
    assert codes(
        """
        import threading, time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def ok(self, future):
                y = future.result()
                time.sleep(0.1)
                with self._lock:
                    pass
                return y
        """
    ) == []


def test_condition_wait_on_held_lock_is_the_idiom():
    assert codes(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)

            def consume(self):
                with self._cond:
                    self._cond.wait(0.05)
        """
    ) == []


def test_string_join_not_flagged():
    assert codes(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def render(self, parts, sep):
                with self._lock:
                    return ",".join(parts) + sep.join(parts)
        """
    ) == []


def test_thread_join_under_lock_flagged_and_pragma():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._monitor_thread = threading.Thread(target=self.run, daemon=True)

            def run(self):
                pass

            def stop(self):
                with self._lock:
                    self._monitor_thread.join(1.0){pragma}
    """
    assert codes(src.format(pragma="")) == ["KV603"]
    assert codes(
        src.format(pragma="  # keystone: allow-block-under-lock(shutdown only)")
    ) == []


# ------------------------------------------------------------------- KV604


def test_thread_hygiene():
    findings, _ = analyze_sources(
        {
            "mod.py": textwrap.dedent(
                """
                import threading

                def anonymous():
                    threading.Thread(target=work).start()

                def local_unjoined():
                    t = threading.Thread(target=work)
                    t.start()

                def daemonized():
                    t = threading.Thread(target=work, daemon=True)
                    t.start()

                def joined():
                    t = threading.Thread(target=work)
                    t.start()
                    t.join()

                def work():
                    pass
                """
            )
        }
    )
    assert [f.rule for f in findings] == ["KV604", "KV604"]
    # Another function's local `t.join()` must not excuse this one's `t`.
    assert {f.details["bound_to"] for f in findings} == {None, "t"}


def test_thread_hygiene_pragma():
    assert codes(
        """
        import threading

        def fire_and_forget():
            # process-lifetime watcher  # keystone: allow-unjoined(watcher)
            threading.Thread(target=work).start()

        def work():
            pass
        """
    ) == []


# ------------------------------------------------------------------- KV605


def test_raw_settle_flagged_and_pragma():
    src = """
        from concurrent.futures import Future

        def settle(f: Future):
            f.set_result(1){pragma}
    """
    assert codes(src.format(pragma="")) == ["KV605"]
    assert codes(
        src.format(pragma="  # keystone: allow-settle(single owner)")
    ) == []


def test_settle_module_exempt():
    findings, _ = analyze_sources(
        {
            os.path.join("serving", "config.py"): textwrap.dedent(
                """
                def settle_result(future, value):
                    try:
                        future.set_result(value)
                    except Exception:
                        pass
                """
            )
        }
    )
    assert findings == []


# ------------------------------------------------------------- model facts


def test_callback_under_lock_marks_holder_open_world():
    _, model = analyze_sources(
        {
            "mod.py": textwrap.dedent(
                """
                import threading

                class Expressionish:
                    def __init__(self, thunk):
                        self._lock = threading.Lock()
                        self._thunk = thunk

                    def get(self):
                        with self._lock:
                            return self._thunk()
                """
            )
        }
    )
    assert ("mod.Expressionish._lock", CALLBACK) in model.edge_pairs()


def test_alloc_sites_cover_every_lock():
    model = build_model([os.path.join(REPO, "keystone_tpu")])
    sites = model.alloc_sites()
    assert set(sites.values()) == set(model.locks)
    # The witness keys on (relpath, line): every site must be unique.
    assert len(sites) == len(model.locks)


def test_concurrency_codes_table():
    assert set(CONCURRENCY_CODES) == {
        "KV601", "KV602", "KV603", "KV604", "KV605",
    }


# -------------------------------------------------------------- tree gates


def test_shipped_tree_is_clean():
    """The CI gate: the concurrency tier over the shipped package finds
    nothing. A new finding means fix the locking or annotate the
    reviewed exception — never ignore."""
    import keystone_tpu

    root = os.path.dirname(keystone_tpu.__file__)
    findings, model = analyze_paths([root])
    assert findings == [], "\n".join(f.render() for f in findings)
    # The model actually engaged: the runtime's lock population is known.
    assert len(model.locks) >= 25
    assert len(model.edges) >= 10


def test_seeded_fixture_fires_kv601_and_kv602():
    """The smoke's negative control: the committed seeded fixture must
    keep tripping the analyzer."""
    findings, _ = analyze_paths([SEEDED])
    found = {f.rule for f in findings}
    assert "KV601" in found and "KV602" in found


# --------------------------------------------- pinned true-positive fixes


def test_batcher_settles_through_shared_helpers():
    """KV605 true positives fixed: the batcher settled futures raw; a
    future already settled by a shutdown race must be tolerated by the
    settle-once helpers, not by scattered try/except."""
    from keystone_tpu.reliability.retry import Deadline
    from keystone_tpu.serving.batcher import MicroBatcher
    from keystone_tpu.serving.config import Request, ServerClosed

    mb = MicroBatcher(8)
    req = Request(payload=[1.0], model="m", deadline=Deadline(0.0))
    req.future.set_result("already-won")  # the race, pre-settled
    live = Request(payload=[2.0], model="m")
    assert mb.offer(req)
    assert mb.offer(live)
    batch = mb.next_batch(4, 0.001)
    assert batch == [live]  # expired path consumed req without raising
    assert req.future.result() == "already-won"  # settle-once preserved

    req2 = Request(payload=[2.0], model="m")
    req2.future.set_result("kept")
    assert mb.offer(req2)
    assert mb.fail_all(ServerClosed()) == 1  # no raise on settled future
    assert req2.future.result() == "kept"


def test_supervisor_submit_many_settles_through_shared_helpers():
    """KV605 true positive fixed: shed/closed futures out of submit_many
    go through settle_exception."""
    from keystone_tpu.serving.config import ServerClosed
    from keystone_tpu.serving.supervisor import SupervisorConfig, WorkerSupervisor

    sup = WorkerSupervisor({"stub": {}}, SupervisorConfig(workers=1))
    sup._closed = True  # never started; submit must refuse
    futures = sup.submit_many([[1.0], [2.0]])
    assert len(futures) == 2
    for f in futures:
        with pytest.raises(ServerClosed):
            f.result(timeout=0)


def test_profile_store_counters_are_lock_guarded():
    """KV601-class hardening pinned: hits/misses/writes are mutated
    under the state lock, so concurrent lookup/record cannot drop
    counts."""
    import tempfile
    import threading

    from keystone_tpu.obs.store import ProfileStore

    fp = {"jax": "x", "backend": "cpu", "device_kind": "cpu"}
    store = ProfileStore(
        os.path.join(tempfile.mkdtemp(), "s.jsonl"), fingerprint=fp
    )
    n_threads, n_iter = 4, 50

    def hammer(i):
        for j in range(n_iter):
            store.record(f"k{i}", "n2^4|8|float32", backend="cpu", wall_s=j)
            assert store.lookup(f"k{i}", "n2^4|8|float32", backend="cpu")

    threads = [
        threading.Thread(target=hammer, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stats = store.stats()
    assert stats["writes"] == n_threads * n_iter
    assert stats["hits"] == n_threads * n_iter
    assert stats["misses"] == 0
