"""Boot images: KV307 staleness gate (fast, host-side) and the real
build → load → serve round trip (slow-marked: pays jax export/compile).

The KV307 verifier is a pure fingerprint comparison — tier-1 covers the
refusal matrix without touching a device. The slow tests build a real
image from a synthetic fitted pipeline and pin the contract: loaded
executables match the classic apply path bit-for-bit-ish on real AND pad
rows, a stale image is refused into the classic fallback, and the
refused worker still serves."""

import json
import os

import numpy as np
import pytest

from keystone_tpu.workflow.verify import BOOT_IMAGE_FINGERPRINTS, verify_boot_image

pytestmark = pytest.mark.serving

FP = {
    "format_version": 1,
    "jax_version": "0.4.37",
    "backend": "cpu",
    "device_kind": "cpu",
    "weights_digest": "abc123",
}


# ------------------------------------------------------------- KV307 (tier-1)


def test_kv307_clean_when_fingerprints_match():
    report = verify_boot_image(dict(FP), dict(FP))
    assert report.ok
    assert report.context == "boot-image"


@pytest.mark.parametrize("field", [name for name, _ in BOOT_IMAGE_FINGERPRINTS])
def test_kv307_flags_each_mismatched_field(field):
    current = dict(FP)
    current[field] = "something-else"
    report = verify_boot_image(dict(FP), current)
    assert not report.ok
    codes = [d.code for d in report.errors()]
    assert codes == ["KV307"]
    diag = report.errors()[0]
    assert diag.details["field"] == field
    assert diag.details["image"] == str(FP[field])[:24]


def test_kv307_missing_field_is_a_mismatch():
    manifest = dict(FP)
    del manifest["weights_digest"]  # pre-digest image format
    report = verify_boot_image(manifest, dict(FP))
    assert [d.details["field"] for d in report.errors()] == ["weights_digest"]


def test_kv307_multiple_drifts_all_reported():
    current = dict(FP, jax_version="9.9.9", backend="tpu")
    report = verify_boot_image(dict(FP), current)
    assert sorted(d.details["field"] for d in report.errors()) == [
        "backend", "jax_version",
    ]


# --------------------------------------------------- real build/load (slow)

D = 8
SPEC = {"synthetic": {"d": D, "seed": 0}}
slow = pytest.mark.slow


@pytest.fixture(scope="module")
def image_dir(tmp_path_factory):
    from keystone_tpu.serving.bootimage import build_boot_image

    out = str(tmp_path_factory.mktemp("bootimage") / "image")
    manifest = build_boot_image(SPEC, out, buckets=(1, 2, 4), model_name="default")
    return out, manifest


@slow
def test_build_writes_a_complete_versioned_artifact(image_dir):
    out, manifest = image_dir
    assert manifest["format_version"] == 1
    assert manifest["buckets"] == [1, 2, 4]
    assert manifest["example"] == {"shape": [D], "dtype": "float32"}
    import jax

    assert manifest["jax_version"] == jax.__version__
    for b in (1, 2, 4):
        assert os.path.exists(os.path.join(out, f"bucket_{b}.bin"))
    assert os.path.exists(os.path.join(out, "model.pkl"))
    assert os.path.exists(os.path.join(out, "manifest.json"))
    assert os.listdir(os.path.join(out, "cache")), (
        "no persistent-cache entries bundled"
    )


@slow
def test_load_serves_parity_with_classic_on_real_and_pad_rows(image_dir):
    from keystone_tpu.data.dataset import ArrayDataset
    from keystone_tpu.serving.bootimage import load_boot_image
    from keystone_tpu.serving.registry import ModelRegistry
    from keystone_tpu.serving.worker import _load_spec

    out, _ = image_dir
    image = load_boot_image(out)
    assert image.buckets == (1, 2, 4)

    registry = ModelRegistry()
    _load_spec(registry, "classic", SPEC)
    classic = registry.resolve("classic").batch_apply

    rng = np.random.default_rng(1)
    for b, n in [(4, 4), (4, 2), (2, 1), (1, 1)]:
        data = rng.standard_normal((b, D)).astype(np.float32)
        want = np.asarray(classic(ArrayDataset(data, num_examples=n)).data)
        got = np.asarray(image.apply_batch(ArrayDataset(data, num_examples=n)).data)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        # Pad rows are zeroed exactly like the classic path zeroes them.
        assert not got[n:].any()
    assert image.fallback_batches == 0

    # A bucket the image never exported falls back to the classic path —
    # slower, never wrong.
    data = rng.standard_normal((8, D)).astype(np.float32)
    got = np.asarray(image.apply_batch(ArrayDataset(data, num_examples=8)).data)
    want = np.asarray(classic(ArrayDataset(data, num_examples=8)).data)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert image.fallback_batches == 1

    # warm() executes the exported buckets (single-bucket form included).
    assert image.warm(only=2) >= 0.0
    assert image.warm() >= 0.0


@slow
def test_stale_image_refused_with_kv307_and_ledgered(tmp_path, image_dir):
    import shutil

    from keystone_tpu.reliability.recovery import get_recovery_log
    from keystone_tpu.serving.bootimage import BootImageRefused, load_boot_image

    out, _ = image_dir
    stale = str(tmp_path / "stale-image")
    shutil.copytree(out, stale)
    manifest_path = os.path.join(stale, "manifest.json")
    with open(manifest_path) as f:
        manifest = json.load(f)
    manifest["jax_version"] = "0.0.1"
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)

    with pytest.raises(BootImageRefused, match="KV307") as exc_info:
        load_boot_image(stale)
    report = exc_info.value.report
    assert [d.details["field"] for d in report.errors()] == ["jax_version"]
    refused = get_recovery_log().events("bootimage_refused")
    assert refused and refused[-1].detail["fields"] == ["jax_version"]


@slow
def test_tampered_weights_change_the_digest_and_refuse(tmp_path, image_dir):
    import shutil

    from keystone_tpu.serving.bootimage import BootImageRefused, load_boot_image

    out, _ = image_dir
    tampered = str(tmp_path / "tampered-image")
    shutil.copytree(out, tampered)
    with open(os.path.join(tampered, "model.pkl"), "ab") as f:
        f.write(b"garbage")  # executables no longer match the weights
    with pytest.raises(BootImageRefused) as exc_info:
        load_boot_image(tampered)
    fields = [d.details["field"] for d in exc_info.value.report.errors()]
    assert fields == ["weights_digest"]


@slow
def test_verify_off_skips_the_gate(tmp_path, image_dir, monkeypatch):
    import shutil

    from keystone_tpu.serving.bootimage import load_boot_image

    out, _ = image_dir
    stale = str(tmp_path / "stale-but-forced")
    shutil.copytree(out, stale)
    manifest_path = os.path.join(stale, "manifest.json")
    with open(manifest_path) as f:
        manifest = json.load(f)
    manifest["device_kind"] = "TPU v99"
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)
    monkeypatch.setenv("KEYSTONE_VERIFY", "off")
    image = load_boot_image(stale)  # operator override: load anyway
    assert image.buckets == (1, 2, 4)


@slow
def test_refused_worker_falls_back_to_classic_warm_and_serves(tmp_path, image_dir):
    """The worker-level fallback: a ServerBackend pointed at a STALE
    image refuses it (KV307) and still comes up through the classic warm
    path, serving correct numbers."""
    import shutil

    from keystone_tpu.serving.worker import ServerBackend, add_worker_arguments

    out, _ = image_dir
    stale = str(tmp_path / "stale-worker-image")
    shutil.copytree(out, stale)
    manifest_path = os.path.join(stale, "manifest.json")
    with open(manifest_path) as f:
        manifest = json.load(f)
    manifest["backend"] = "not-this-backend"
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)

    import argparse

    parser = argparse.ArgumentParser()
    add_worker_arguments(parser)
    args = parser.parse_args(["--spec", json.dumps(SPEC), "--boot-image", stale])
    backend = ServerBackend(SPEC, args)
    try:
        assert backend.boot_image == "refused"
        assert backend._warmed  # classic warm path ran
        y = backend.server.submit(
            np.ones((D,), np.float32), deadline_s=30.0
        ).result(timeout=30)
        assert np.asarray(y).shape[-1] >= 1
    finally:
        backend.server.stop(drain=True)


@slow
def test_fresh_worker_boots_from_image_and_serves(image_dir):
    """The happy path at backend level: boot_image == "loaded", the
    registry serves through BootImageModel, and provenance names the
    image."""
    import argparse

    from keystone_tpu.serving.worker import ServerBackend, add_worker_arguments

    out, _ = image_dir
    parser = argparse.ArgumentParser()
    add_worker_arguments(parser)
    args = parser.parse_args(["--spec", json.dumps(SPEC), "--boot-image", out])
    backend = ServerBackend(SPEC, args)
    try:
        assert backend.boot_image == "loaded"
        entry = backend.registry.resolve("default")
        assert entry.source == f"bootimage:{out}"
        y = backend.server.submit(
            np.ones((D,), np.float32), deadline_s=30.0
        ).result(timeout=30)
        assert np.asarray(y).shape[-1] >= 1
    finally:
        backend.server.stop(drain=True)
