"""Model registry: versioning, hot-swap atomicity, checkpoint loading."""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.serving.config import UnknownModel
from keystone_tpu.serving.registry import ModelRegistry

pytestmark = pytest.mark.serving


def test_publish_versions_and_rollback():
    r = ModelRegistry()
    v1 = r.publish("m", "model-one")
    v2 = r.publish("m", "model-two")
    assert (v1.version, v2.version) == (1, 2)
    assert r.resolve("m").model == "model-two"
    assert r.resolve("m", version=1).model == "model-one"
    assert r.versions("m") == [1, 2]
    r.rollback("m", 1)
    assert r.resolve("m").model == "model-one"
    assert r.swaps == 2  # publish-over + rollback


def test_unknown_model_raises():
    r = ModelRegistry()
    with pytest.raises(UnknownModel):
        r.resolve("missing")
    r.publish("m", object())
    with pytest.raises(UnknownModel):
        r.resolve("m", version=99)


def test_load_fitted_artifact(tmp_path):
    from keystone_tpu.serving.synthetic import synthetic_fitted_pipeline

    path = str(tmp_path / "model.pkl")
    synthetic_fitted_pipeline(d=4, seed=3).save(path)
    r = ModelRegistry()
    entry = r.load_fitted("m", path)
    assert entry.source == f"fitted:{path}"
    out = entry.batch_apply(ArrayDataset(np.ones((2, 4), np.float32)))
    assert np.asarray(out.data).shape == (2, 4)


def test_load_checkpoint_by_digest_prefix(tmp_path):
    """Training persists fitted state into a CheckpointStore; serving
    loads the same artifact by structural digest — one format, two uses
    (the RELIABILITY.md -> SERVING.md handoff path)."""
    from keystone_tpu.reliability.checkpoint import CheckpointStore, prefix_digest
    from keystone_tpu.workflow.pipeline import Identity
    from keystone_tpu.workflow.prefix import Prefix

    store = CheckpointStore(str(tmp_path))
    fitted = Identity()
    prefix = Prefix((fitted, ()))
    digest = prefix_digest(prefix)
    assert store.save(prefix, fitted, digest=digest)

    r = ModelRegistry()
    entry = r.load_checkpoint("m", str(tmp_path), digest[:12])
    assert entry.source.endswith(f"{digest}.pkl")
    ds = ArrayDataset(np.arange(8, dtype=np.float32).reshape(2, 4))
    out = entry.batch_apply(ds)
    np.testing.assert_array_equal(np.asarray(out.data), np.asarray(ds.data))


def test_load_checkpoint_missing_or_ambiguous(tmp_path):
    (tmp_path / "abc111.pkl").write_bytes(b"x")
    (tmp_path / "abc222.pkl").write_bytes(b"x")
    r = ModelRegistry()
    with pytest.raises(FileNotFoundError):
        r.load_checkpoint("m", str(tmp_path), "fff")
    with pytest.raises(ValueError):
        r.load_checkpoint("m", str(tmp_path), "abc")


def test_entry_without_apply_path_raises():
    r = ModelRegistry()
    entry = r.publish("m", object())
    with pytest.raises(TypeError):
        entry.batch_apply(ArrayDataset(np.ones((1, 2), np.float32)))
