"""WorkerSupervisor: crash/hang recovery, requeue, routing, admission.

These tests run against STUB workers (``{"stub": ...}`` spec — the
jax-free echo backend in serving/worker.py): the supervisor's contracts
(process monitoring, restart backoff, the zero-dropped-requests requeue
invariant, consistent-hash routing, deadline propagation) are properties
of the control pipe, not of what computes ``y``; real-jax workers are
covered by test_multiworker_e2e.py and scripts/serve_chaos_smoke.sh."""

import json
import sys
import time

import pytest

from keystone_tpu.reliability.recovery import get_recovery_log
from keystone_tpu.serving.config import (
    RequestShed,
    RequestTimeout,
    ServerClosed,
    ServingError,
)
from keystone_tpu.serving.supervisor import (
    HashRing,
    SupervisorConfig,
    WorkerSupervisor,
)

pytestmark = pytest.mark.serving


def make_supervisor(workers=2, delay_ms=0, chaos=None, **cfg):
    """Stub-worker supervisor tuned for test speed (fast beats, tight
    hang detection, sub-second backoff)."""
    defaults = dict(
        workers=workers,
        heartbeat_s=0.05,
        hang_timeout_s=0.8,
        ready_timeout_s=15.0,
        monitor_interval_s=0.02,
    )
    defaults.update(cfg)
    env = {}
    for worker_id, specs in (chaos or {}).items():
        env[f"KEYSTONE_FAULT_SPECS_WORKER_{worker_id}"] = json.dumps(specs)
    return WorkerSupervisor(
        {"stub": {"delay_ms": delay_ms}}, SupervisorConfig(**defaults), env=env
    )


def settle(futures, timeout=30):
    return [f.result(timeout=timeout) for f in futures]


# ------------------------------------------------------------------ routing


def test_hash_ring_spreads_and_is_consistent():
    ring = HashRing(["0", "1", "2", "3"])
    first = {f"k{i}": next(iter(ring.walk(f"k{i}"))) for i in range(400)}
    by_node = {}
    for node in first.values():
        by_node[node] = by_node.get(node, 0) + 1
    assert set(by_node) == {"0", "1", "2", "3"}
    assert min(by_node.values()) > 40  # no starved node at 400 keys
    # Same ring → identical placement (routing is a pure function).
    again = HashRing(["0", "1", "2", "3"])
    assert {k: next(iter(again.walk(k))) for k in first} == first
    # walk yields every node exactly once
    assert sorted(ring.walk("anything")) == ["0", "1", "2", "3"]


def test_hash_ring_failover_moves_only_dead_nodes_keys():
    ring = HashRing(["0", "1", "2"])
    keys = [f"k{i}" for i in range(300)]
    placements = {k: list(ring.walk(k)) for k in keys}
    for k in keys:
        order = placements[k]
        # Skipping a dead first choice lands on the SECOND ring node —
        # keys owned by healthy nodes never move.
        assert order[1] != order[0]


# ----------------------------------------------------------------- lifecycle


def test_round_trip_and_aggregated_stats():
    sup = make_supervisor(workers=2).start()
    try:
        sup.wait_ready()
        futures = [sup.submit([float(i)]) for i in range(30)]
        results = settle(futures)
        assert [r[0] for r in results] == [2.0 * i for i in range(30)]
        time.sleep(0.15)  # one beat so worker stats reach the supervisor
        stats = sup.stats()
        assert stats["served"] == 30
        assert set(stats["workers"]) == {"0", "1"}
        assert stats["supervisor"]["alive"] == 2
        assert stats["supervisor"]["requeued"] == 0
        # both workers took traffic (hash spread over request ids)
        per_worker = [w["stats"].get("served", 0) for w in stats["workers"].values()]
        assert all(v > 0 for v in per_worker), per_worker
    finally:
        sup.stop()


def test_affinity_key_pins_one_worker():
    sup = make_supervisor(workers=2).start()
    try:
        sup.wait_ready()
        settle([sup.submit([1.0], key="tenant-A") for _ in range(12)])
        time.sleep(0.15)
        served = [
            w["stats"].get("served", 0) for w in sup.stats()["workers"].values()
        ]
        assert sorted(served) == [0, 12], served
    finally:
        sup.stop()


def test_submit_after_stop_refuses():
    sup = make_supervisor(workers=1).start()
    sup.wait_ready()
    sup.stop()
    with pytest.raises(ServerClosed):
        sup.submit([1.0])


# ------------------------------------------------------------ chaos: crash


def test_sigkill_mid_load_drops_nothing_and_restarts():
    """THE supervisor invariant: a worker SIGKILLed mid-load loses zero
    requests — its in-flight work is requeued onto the healthy worker —
    and the supervisor restarts it with backoff, landing worker_crash +
    worker_restart in the recovery ledger."""
    sup = make_supervisor(
        workers=2,
        delay_ms=2,
        chaos={"0": [{"match": "serving.worker.request", "kind": "kill",
                      "calls": [4]}]},
    ).start()
    try:
        sup.wait_ready()
        futures = [sup.submit([float(i)], deadline_s=30) for i in range(50)]
        results = settle(futures)
        assert [r[0] for r in results] == [2.0 * i for i in range(50)]
        assert sup.requeued > 0  # the kill really stranded work
        sup.wait_ready(timeout_s=20)  # the killed worker comes back
        kinds = [e.kind for e in get_recovery_log().events()]
        assert "worker_crash" in kinds
        crash = get_recovery_log().events("worker_crash")[0]
        assert crash.detail["reason"] == "crash"
        # restart lands (backoff schedule is sub-second in this config)
        assert get_recovery_log().events("worker_restart"), kinds
        # the fleet serves again after recovery
        assert settle([sup.submit([3.0])])[0] == [6.0]
    finally:
        sup.stop()


def test_single_worker_kill_parks_requests_until_restart():
    """With no healthy sibling, stranded requests PARK (pending queue)
    rather than fail, and the restarted worker serves them."""
    sup = make_supervisor(
        workers=1,
        delay_ms=2,
        chaos={"0": [{"match": "serving.worker.request", "kind": "kill",
                      "calls": [3]}]},
    ).start()
    try:
        sup.wait_ready()
        futures = [sup.submit([float(i)], deadline_s=30) for i in range(10)]
        results = settle(futures)
        assert [r[0] for r in results] == [2.0 * i for i in range(10)]
        assert sup.stats()["supervisor"]["restarts"] == 1
    finally:
        sup.stop()


def test_restart_budget_exhaustion_fails_outstanding_loudly():
    """A crash-looping worker (exits immediately, never ready) consumes
    its restart budget and outstanding requests fail with a classified
    UNAVAILABLE error instead of hanging forever."""
    sup = WorkerSupervisor(
        {"stub": {}},
        SupervisorConfig(
            workers=1,
            max_restarts=2,
            monitor_interval_s=0.02,
            restart_policy=__import__(
                "keystone_tpu.reliability.retry", fromlist=["RetryPolicy"]
            ).RetryPolicy(max_attempts=3, base_delay_s=0.02, max_delay_s=0.05),
        ),
        worker_cmd=lambda wid: [sys.executable, "-c", "import sys; sys.exit(3)"],
    ).start()
    try:
        future = sup.submit([1.0])
        with pytest.raises(ServingError, match="restart budget"):
            future.result(timeout=20)
        assert sup.stats()["workers"]["0"]["state"] == "failed"
        assert get_recovery_log().events("worker_failed")
        # A submit AFTER the fleet failed must fail fast too — parking it
        # would strand the future (no worker will ever be ready again).
        late = sup.submit([2.0])
        with pytest.raises(ServingError, match="restart budget"):
            late.result(timeout=5)
    finally:
        sup.stop(drain=False)


# ------------------------------------------------------------- chaos: hang


def test_stopped_heartbeats_detected_as_hang_and_restarted():
    sup = make_supervisor(
        workers=1,
        chaos={"0": [{"match": "serving.worker.heartbeat", "kind": "hang",
                      "calls": [2], "hang_s": 60.0}]},
    ).start()
    try:
        sup.wait_ready()
        deadline = time.monotonic() + 20
        while not get_recovery_log().events("worker_crash"):
            assert time.monotonic() < deadline, "hang never detected"
            time.sleep(0.05)
        crash = get_recovery_log().events("worker_crash")[0]
        assert crash.detail["reason"] == "hang"
        sup.wait_ready(timeout_s=20)
        assert settle([sup.submit([1.0])])[0] == [2.0]
    finally:
        sup.stop()


def test_corrupt_heartbeats_are_not_heartbeats():
    """A garbled heartbeat line must not refresh liveness: a worker whose
    channel is corrupt gets hang-detected and recycled."""
    sup = make_supervisor(
        workers=1,
        chaos={"0": [{"match": "serving.worker.heartbeat", "kind": "corrupt",
                      "first_n": 10000}]},
    ).start()
    try:
        deadline = time.monotonic() + 20
        while not get_recovery_log().events("worker_crash"):
            assert time.monotonic() < deadline, "corrupt channel never detected"
            time.sleep(0.05)
        assert get_recovery_log().events("worker_crash")[0].detail["reason"] == "hang"
        sup.wait_ready(timeout_s=20)  # clean incarnation takes over
        assert settle([sup.submit([2.0])])[0] == [4.0]
    finally:
        sup.stop()


# ------------------------------------------------- deadlines and admission


def test_deadline_budget_crosses_the_boundary():
    """The REMAINING deadline crosses supervisor → worker: the worker
    sees a positive budget no larger than what was submitted, and a
    request submitted without a deadline crosses with none."""
    sup = make_supervisor(workers=1).start()
    try:
        sup.wait_ready()
        echoed = sup.submit(["deadline-echo"], deadline_s=5.0).result(timeout=10)
        assert 0.0 < echoed[0] <= 5000.0, echoed
        bare = sup.submit(["deadline-echo"]).result(timeout=10)
        assert bare[0] == -1.0  # no deadline submitted → none forwarded
    finally:
        sup.stop()


def test_expired_requeue_fails_as_timeout_not_zombie():
    """A request whose deadline lapses while parked fails with
    RequestTimeout instead of dispatching with zero budget."""
    sup = WorkerSupervisor(
        {"stub": {}},
        SupervisorConfig(workers=1, monitor_interval_s=0.02, ready_timeout_s=15),
        worker_cmd=lambda wid: [sys.executable, "-c", "import time; time.sleep(60)"],
    ).start()
    try:
        future = sup.submit([1.0], deadline_s=0.2)  # parked: worker never ready
        with pytest.raises(RequestTimeout):
            future.result(timeout=10)
    finally:
        sup.stop(drain=False)


def test_swap_survives_a_dead_worker_mid_broadcast():
    """A worker whose pipe is already gone when the swap broadcast
    reaches it fails ITS ack (swap_failed) — the remaining workers must
    still receive and ack the swap, and swap() must not raise."""
    sup = make_supervisor(workers=2).start()
    try:
        sup.wait_ready()
        # Close worker 0's stdin under the supervisor: the write path
        # raises deterministically while state still reads "ready".
        sup._workers["0"].proc.stdin.close()
        acks = sup.swap({"stub": {}})
        assert set(acks) == {"0", "1"}
        assert acks["0"]["kind"] == "swap_failed"
        assert acks["1"]["kind"] == "swapped"
    finally:
        sup.stop(drain=False)


def test_every_pipe_broken_parks_without_recursing():
    """When EVERY ready worker's pipe breaks inside one routing pass, the
    route loop must walk each worker once and park — not ping-pong
    between two broken pipes until RecursionError. The parked request is
    then served by the restarted fleet (EOF on stdin ends the workers,
    the monitor recycles them)."""
    sup = make_supervisor(workers=2).start()
    try:
        sup.wait_ready()
        for worker in sup._workers.values():
            worker.proc.stdin.close()  # every write now raises
        future = sup.submit([5.0], deadline_s=30)
        assert sup.requeued >= 2  # both pipes were tried, then it parked
        assert future.result(timeout=20) == [10.0]
    finally:
        sup.stop()


def test_park_after_final_drain_settles_closed_not_stranded():
    """A submit that races stop() past the final drain must settle its
    future with ServerClosed instead of parking on a queue nothing will
    ever drain again."""
    sup = make_supervisor(workers=1)  # never started: no ready workers
    sup._drained = True  # the state stop() leaves behind
    future = sup.submit([1.0])
    with pytest.raises(ServerClosed):
        future.result(timeout=5)


def test_admission_sheds_at_capacity():
    sup = make_supervisor(workers=1, delay_ms=200, queue_depth=4).start()
    try:
        sup.wait_ready()
        futures, sheds = [], 0
        for i in range(16):
            try:
                futures.append(sup.submit([float(i)]))
            except RequestShed:
                sheds += 1
        assert sheds > 0, "capacity 4 never shed under 16 instant submits"
        settle(futures)  # admitted requests all complete
    finally:
        sup.stop()


# ------------------------------------------- restart-monotonic aggregation


def test_restart_keeps_aggregated_counters_monotonic():
    """The satellite fix: a restarted worker's telemetry counters restart
    from zero, but stats() aggregates per-worker high-water marks — the
    fleet's `served` is LIFETIME and never resets across incarnations."""
    sup = make_supervisor(
        workers=1,
        chaos={"0": [{"match": "serving.worker.request", "kind": "kill",
                      "calls": [6]}]},
    ).start()
    try:
        sup.wait_ready()
        settle([sup.submit([float(i)], deadline_s=30) for i in range(5)])
        time.sleep(0.3)  # beats carry served=5 into the high-water mark
        before = sup.stats()
        assert before["served"] == 5
        # Request 6 kills the worker pre-completion; it requeues onto the
        # restarted incarnation, whose own counters restart from zero.
        settle([sup.submit([float(i)], deadline_s=30) for i in range(5, 10)])
        time.sleep(0.3)
        after = sup.stats()
        assert after["workers"]["0"]["incarnation"] >= 1
        # incarnation-local counter really did reset...
        assert after["workers"]["0"]["stats"]["served"] < 10
        # ...but the aggregate is lifetime: 5 before the kill + 5 after.
        assert after["served"] == 10
        # fleet_counter_totals (the /metrics source) agrees
        assert sup.fleet_counter_totals()["0"]["served"] == 10.0
    finally:
        sup.stop()


# --------------------------------------------------- cross-process tracing


def test_trace_context_crosses_the_pipe_and_fragments_return():
    """Fleet tracing end to end over stub workers: the submit-time trace
    context rides every dispatch line, the worker re-parents its spans
    under it, and the fragments come back on heartbeats — the merged
    trace shows ONE trace id across supervisor + both worker processes."""
    from keystone_tpu.obs import spans

    with spans.tracing_session("sup-trace", sync_timings=False) as session:
        sup = WorkerSupervisor(
            {"stub": {}},
            SupervisorConfig(
                workers=2, heartbeat_s=0.05, hang_timeout_s=5.0,
                ready_timeout_s=15.0, monitor_interval_s=0.02,
            ),
            env={"KEYSTONE_FLEET_TRACE": "1"},
        ).start()
        try:
            sup.wait_ready()
            with spans.span("ingress"):
                settle([sup.submit([1.0, float(i)]) for i in range(12)])
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                fragments = sup.fleet.fragments()
                worker_requests = [
                    f for frags in fragments.values() for f in frags
                    if f["n"] == "worker:request"
                ]
                if len(worker_requests) >= 12 and len(fragments) >= 2:
                    break
                time.sleep(0.05)
            merged = sup.fleet.merge(local_session=session)
        finally:
            sup.stop()

    # supervisor-side dispatch spans parent under the ingress span
    dispatches = [s for s in session.spans() if s.name == "supervisor:dispatch"]
    ingress = next(s for s in session.spans() if s.name == "ingress")
    assert len(dispatches) == 12
    assert all(s.trace_id == session.trace_id for s in dispatches)
    assert all(s.parent_id == ingress.span_id for s in dispatches)
    # worker fragments carry the SAME trace id, parented under a dispatch
    dispatch_ids = {s.span_id for s in dispatches}
    assert len(worker_requests) >= 12
    assert all(f["t"] == session.trace_id for f in worker_requests)
    assert all(f.get("p") in dispatch_ids for f in worker_requests)
    # both worker processes shipped, and the merged Perfetto artifact has
    # the single trace id across >= 3 pids (supervisor + 2 workers)
    assert len(fragments) >= 2
    pids = {
        e["pid"] for e in merged["traceEvents"]
        if e.get("ph") == "X" and e["args"].get("trace_id") == session.trace_id
    }
    assert len(pids) >= 3
    assert session.trace_id in merged["otherData"]["trace_ids"]
    # clock anchors arrived via the ready/heartbeat handshake
    assert merged["otherData"]["clock_skew_s"]


def test_tracing_off_adds_no_wire_field():
    """With no session, submit captures no context and the control line
    carries no trace field — tracing off is zero wire bytes."""
    captured = []
    sup = make_supervisor(workers=1).start()
    try:
        sup.wait_ready()
        worker = sup._workers["0"]
        real_stdin = worker.proc.stdin

        class _Spy:
            def write(self, line):
                captured.append(line)
                return real_stdin.write(line)

            def flush(self):
                return real_stdin.flush()

        worker.proc.stdin = _Spy()
        settle([sup.submit([1.0])])
        worker.proc.stdin = real_stdin
        requests = [json.loads(l) for l in captured if l.strip()]
        assert requests and all("trace" not in r for r in requests)
    finally:
        sup.stop()
