"""Sharded bucketed serving: warmup decides the row sharding once
(attach_serving_partition), every divisible bucket's batch rows land
NamedSharding-sharded on the warmed executables, steady state compiles
nothing, and results match the single-device server exactly."""

import numpy as np
import pytest

import jax

from concurrent.futures import wait

from keystone_tpu.parallel.mesh import make_mesh, use_mesh
from keystone_tpu.parallel.partitioner import (
    attach_serving_partition,
    partition_disabled,
)
from keystone_tpu.serving.config import ServingConfig
from keystone_tpu.serving.server import PipelineServer
from keystone_tpu.serving.synthetic import synthetic_fitted_pipeline

D = 12


def _serve(payloads, shard: bool):
    model = synthetic_fitted_pipeline(d=D)
    srv = PipelineServer(
        model=model,
        config=ServingConfig(max_batch=8, max_wait_ms=1.0, queue_depth=256),
    )
    if shard:
        warm = srv.warmup(payloads[0])
    else:
        with partition_disabled():
            warm = srv.warmup(payloads[0])
    srv.start()
    futures = srv.submit_many(payloads)
    wait(futures, timeout=60)
    rows = np.stack([np.asarray(f.result()) for f in futures])
    stats = srv.stats()
    srv.stop()
    return warm, rows, stats


def test_warmup_attaches_eligible_decision_and_zero_steady_compiles():
    rng = np.random.default_rng(1)
    payloads = [rng.normal(size=(D,)).astype(np.float32) for _ in range(48)]

    warm, rows, stats = _serve(payloads, shard=True)
    decision = warm["partition_decisions"]["default"]
    assert decision["eligible"] and decision["kind"] == "serve"
    assert decision["shards"] == len(jax.devices())
    # zero steady-state XLA compiles WITH row sharding on
    assert stats["xla_compiles_since_warmup"] == 0

    _, rows_ref, stats_ref = _serve(payloads, shard=False)
    assert stats_ref["xla_compiles_since_warmup"] == 0
    rel = np.linalg.norm(rows - rows_ref) / max(
        np.linalg.norm(rows_ref), 1e-30
    )
    assert rel <= 1e-5, rel


def test_compiled_apply_places_divisible_batches_sharded():
    from keystone_tpu.data.dataset import ArrayDataset

    model = synthetic_fitted_pipeline(d=D)
    decision = attach_serving_partition(model, [1, 2, 4, 8])
    assert decision.eligible
    handle = model.compiled_apply()
    assert handle.partition is decision

    shards = len(jax.devices())
    batch = np.zeros((shards, D), np.float32)
    out = handle(ArrayDataset(batch, num_examples=shards))
    assert np.isfinite(np.asarray(out.data)).all()


def test_indivisible_buckets_serve_on_default_placement():
    model = synthetic_fitted_pipeline(d=D)
    decision = attach_serving_partition(model, [1, 2])  # no bucket ≥ 8 shards
    assert not decision.eligible
    assert decision.reason == "buckets-indivisible"
    assert model.compiled_apply().partition is None


def test_conflicting_reattach_keeps_first_installed_decision():
    """The CompiledApply handle is shared by every server over a
    pipeline; its installed (warmed) layout must win over a later,
    conflicting attach — re-deciding would hand steady-state batches
    layouts nobody warmed."""
    model = synthetic_fitted_pipeline(d=D)
    first = attach_serving_partition(model, [1, 2, 4, 8])
    assert first.eligible
    handle = model.compiled_apply()
    assert handle.partition is first

    # a second consumer with an indivisible bucket set must not strip
    # (or re-shape) the layout the first warmup compiled
    second = attach_serving_partition(model, [1, 2])
    assert second is first
    assert handle.partition is first

    # re-attaching the SAME contract is idempotent
    again = attach_serving_partition(model, [1, 2, 4, 8])
    assert handle.partition is not None
    assert handle.partition.shards == first.shards


def test_serving_attach_does_not_pollute_plan_report():
    from keystone_tpu.parallel.partitioner import (
        last_partition_report,
        reset_partition_report,
    )

    reset_partition_report()
    model = synthetic_fitted_pipeline(d=D)
    attach_serving_partition(model, [1, 2, 4, 8])
    assert last_partition_report() == []


def test_single_device_mesh_serves_unsharded():
    with use_mesh(make_mesh(devices=jax.devices()[:1])):
        model = synthetic_fitted_pipeline(d=D)
        decision = attach_serving_partition(model, [1, 2, 4, 8])
        assert not decision.eligible
        assert decision.reason == "single-shard-mesh"
