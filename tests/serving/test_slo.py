"""SLOController: p99-driven admission ladder transitions.

The controller is pure control logic over an external-mode
AdmissionController — a fake clock and hand-built worker snapshots
exercise every transition rule without processes."""

import pytest

from keystone_tpu.obs import names as obs_names
from keystone_tpu.reliability.recovery import get_recovery_log
from keystone_tpu.serving.admission import AdmissionController
from keystone_tpu.serving.config import RequestShed
from keystone_tpu.serving.slo import SLO_RUNGS, SLOController

pytestmark = pytest.mark.serving


class Clock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now


def make_controller(target=50.0, **kw):
    clock = Clock()
    admission = AdmissionController(64, rungs=SLO_RUNGS, external=True)
    controller = SLOController(
        admission, target_p99_ms=target, clock=clock, min_served=4, **kw
    )
    return controller, admission, clock


def snap(p99, served):
    return {"0": {"p99_ms": p99, "served": served}}


def test_requires_external_admission():
    with pytest.raises(ValueError, match="external"):
        SLOController(AdmissionController(8), target_p99_ms=10.0)


def test_degrades_on_p99_over_target_and_records_ledger():
    controller, admission, clock = make_controller(target=50.0)
    record = controller.observe(snap(80.0, served=20))
    assert record == {
        "direction": "degrade",
        "from_rung": "normal",
        "to_rung": "pressure",
        "rung_index": 1,
        "p99_ms": 80.0,
        "target_ms": 50.0,
    }
    assert admission.rung_index == 1
    events = get_recovery_log().events("slo")
    assert events and events[0].detail["direction"] == "degrade"


def test_cooldown_rate_limits_degrades():
    controller, admission, clock = make_controller(target=50.0, cooldown_s=1.0)
    assert controller.observe(snap(80.0, 20)) is not None
    # p99 still bad immediately after: within cooldown, no second step.
    assert controller.observe(snap(90.0, 40)) is None
    clock.now += 1.5
    assert controller.observe(snap(90.0, 60))["to_rung"] == "overload"
    # bottom of the ladder: nowhere further to degrade
    clock.now += 1.5
    assert controller.observe(snap(99.0, 80)) is None
    assert admission.rung_index == 2


def test_stale_windows_are_not_signal():
    controller, admission, clock = make_controller(target=50.0)
    # below min_served: ignored
    assert controller.observe(snap(500.0, served=2)) is None
    # served unchanged since last sweep: the p99 is history, ignored
    assert controller.observe(snap(80.0, served=20)) is not None
    clock.now += 10.0
    assert controller.observe(snap(80.0, served=20)) is None
    assert admission.rung_index == 1


def test_recovery_needs_sustained_settle_under_threshold():
    controller, admission, clock = make_controller(
        target=50.0, recover_factor=0.5, settle_s=2.0
    )
    controller.observe(snap(80.0, 20))
    assert admission.rung_index == 1
    # under the recovery threshold but not yet settled
    clock.now += 1.0
    assert controller.observe(snap(10.0, 40)) is None
    clock.now += 1.0
    assert controller.observe(snap(10.0, 60)) is None  # starts the window
    clock.now += 2.5
    record = controller.observe(snap(10.0, 80))
    assert record["direction"] == "recover" and admission.rung_index == 0
    # middle band (between recover threshold and target): holds steady
    clock.now += 5.0
    assert controller.observe(snap(40.0, 100)) is None


def test_worst_worker_is_the_aggregate_signal():
    controller, admission, clock = make_controller(target=50.0)
    stats = {
        "0": {"p99_ms": 5.0, "served": 50},
        "1": {"p99_ms": 120.0, "served": 50},  # the straggler
    }
    record = controller.observe(stats)
    assert record["direction"] == "degrade" and record["p99_ms"] == 120.0
    gauge = obs_names.metric(obs_names.SERVING_SLO_P99_MS)
    assert gauge.value(worker="aggregate") == 120.0
    assert gauge.value(worker="1") == 120.0


def test_metrics_published():
    controller, admission, clock = make_controller(target=75.0)
    transitions = obs_names.metric(obs_names.SERVING_SLO_TRANSITIONS)
    before = transitions.value(direction="degrade")
    controller.observe(snap(100.0, 20))
    assert transitions.value(direction="degrade") == before + 1
    assert obs_names.metric(obs_names.SERVING_SLO_TARGET_MS).value() == 75.0
    assert obs_names.metric(obs_names.SERVING_SLO_RUNG).value() == 1


def test_external_admission_sheds_earlier_at_degraded_rungs():
    controller, admission, clock = make_controller(target=50.0, cooldown_s=0.0)
    assert admission.admit(50) is not None  # normal: full capacity bound
    controller.observe(snap(80.0, 20))      # → pressure (frac 0.6 of 64)
    admission.admit(30)
    with pytest.raises(RequestShed):
        admission.admit(50)
    clock.now += 10.0
    controller.observe(snap(90.0, 40))      # → overload (frac 0.3)
    with pytest.raises(RequestShed):
        admission.admit(30)
    assert admission.admit(10) is not None
