"""Autoscaler: the control law (clock-injected, deterministic) and the
elastic-fleet machinery it drives (stub workers, real processes).

The control-law tests drive ``Autoscaler.step(now=...)`` against a fake
supervisor so hysteresis/cooldown/bounds are exact. The fleet tests run
real STUB worker processes through ``WorkerSupervisor.add_worker`` /
``remove_worker`` — the zero-dropped-in-flight invariant under scale
events, including a worker SIGKILLed mid-scale-event (the chaos case the
failure matrix in docs/SERVING.md pins). Real-jax scale behavior is
covered by scripts/autoscale_smoke.sh and the serving_autoscale bench
leg."""

import json
import time

import pytest

from keystone_tpu.reliability.recovery import get_recovery_log
from keystone_tpu.serving.autoscaler import Autoscaler, AutoscalerConfig
from keystone_tpu.serving.supervisor import SupervisorConfig, WorkerSupervisor

pytestmark = pytest.mark.serving


# ----------------------------------------------------- control law (no procs)


class FakeFleet:
    """The stats/add_worker/remove_worker surface the autoscaler drives,
    with hand-cranked traffic."""

    def __init__(self, workers=1):
        self.rows = {}
        self._next = 0
        for _ in range(workers):
            self._add("ready")
        self.pending = 0
        self.added = []
        self.removed = []

    def _add(self, state):
        worker_id = str(self._next)
        self._next += 1
        self.rows[worker_id] = {
            "state": state, "inflight": 0,
            "stats": {"served": 0, "p99_ms": 1.0},
        }
        return worker_id

    def tick(self, p99_ms, served_inc=32, worker_id=None):
        """One window of traffic: bump served (freshness) and set p99."""
        for wid, row in self.rows.items():
            if row["state"] == "ready" and worker_id in (None, wid):
                row["stats"]["served"] += served_inc
                row["stats"]["p99_ms"] = p99_ms

    def stats(self):
        states = [r["state"] for r in self.rows.values()]
        return {
            "workers": {
                wid: {
                    "state": r["state"], "inflight": r["inflight"],
                    "stats": dict(r["stats"]),
                }
                for wid, r in self.rows.items()
            },
            "supervisor": {
                "alive": states.count("ready"),
                "booting": sum(1 for s in states if s in ("new", "spawning")),
                "draining": states.count("draining"),
                "pending": self.pending,
            },
        }

    def add_worker(self, reason="scale_up"):
        worker_id = self._add("spawning")
        self.added.append((worker_id, reason))
        return worker_id

    def remove_worker(self, worker_id=None, reason="scale_down"):
        ready = [w for w, r in self.rows.items() if r["state"] == "ready"]
        if len(ready) <= 1:
            return None
        target = worker_id or ready[-1]
        self.rows[target]["state"] = "draining"
        self.removed.append((target, reason))
        return target


def make_scaler(fleet, **cfg):
    defaults = dict(
        target_p99_ms=50.0, min_workers=1, max_workers=3,
        pressure_s=1.0, idle_s=2.0, cooldown_s=5.0, min_served=16,
    )
    defaults.update(cfg)
    return Autoscaler(fleet, AutoscalerConfig(**defaults))


def test_config_bounds_validate():
    with pytest.raises(ValueError):
        Autoscaler(FakeFleet(), AutoscalerConfig(min_workers=0))
    with pytest.raises(ValueError):
        Autoscaler(
            FakeFleet(), AutoscalerConfig(min_workers=3, max_workers=2)
        )


def test_sustained_pressure_scales_up_and_cooldown_limits_rate():
    fleet = FakeFleet(workers=1)
    scaler = make_scaler(fleet)
    # Pressure must PERSIST pressure_s before an event fires.
    fleet.tick(p99_ms=200.0)
    assert scaler.step(now=0.0) is None
    fleet.tick(p99_ms=200.0)
    assert scaler.step(now=0.5) is None
    fleet.tick(p99_ms=200.0)
    assert scaler.step(now=1.0) == "up:1"
    assert fleet.added == [("1", "slo_pressure")]
    # Cooldown: continued pressure cannot fire again inside cooldown_s.
    fleet.rows["1"]["state"] = "ready"
    for now in (1.5, 3.0, 5.0, 5.9):
        fleet.tick(p99_ms=200.0)
        assert scaler.step(now=now) is None
    # Pressure that PERSISTED through the whole cooldown means the first
    # scale-up didn't absorb it: the next event fires as soon as the
    # cooldown expires.
    fleet.tick(p99_ms=200.0)
    assert scaler.step(now=6.5) == "up:2"
    assert scaler.stats()["scale_ups"] == 2


def test_one_slow_window_is_not_pressure():
    fleet = FakeFleet(workers=1)
    scaler = make_scaler(fleet)
    fleet.tick(p99_ms=200.0)  # one bad window...
    assert scaler.step(now=0.0) is None
    fleet.tick(p99_ms=5.0)  # ...then healthy: the pressure timer resets
    assert scaler.step(now=0.9) is None
    fleet.tick(p99_ms=200.0)
    assert scaler.step(now=1.8) is None  # window restarted at 1.8
    assert fleet.added == []


def test_stale_window_contributes_no_pressure():
    """A worker whose served count stopped moving reports a p99 from OLD
    traffic — it must not drive scale-up."""
    fleet = FakeFleet(workers=1)
    scaler = make_scaler(fleet)
    fleet.tick(p99_ms=500.0)
    assert scaler.step(now=0.0) is None  # fresh once: pressure starts
    # served never moves again: every later step reads the window stale.
    for now in (1.0, 2.0, 3.0):
        assert scaler.step(now=now) is None
    assert fleet.added == []


def test_small_window_is_too_noisy_to_act_on():
    fleet = FakeFleet(workers=1)
    scaler = make_scaler(fleet, min_served=64)
    for now in (0.0, 1.0, 2.0):
        fleet.tick(p99_ms=500.0, served_inc=4)  # 4, 8, 12 < 64 served
        assert scaler.step(now=now) is None
    assert fleet.added == []


def test_backlog_pressure_fires_even_with_healthy_p99():
    """The pipe-backlog signal: a serial worker's percentile window can
    look healthy while dispatched-but-unanswered work piles up."""
    fleet = FakeFleet(workers=1)
    scaler = make_scaler(fleet, backlog_per_worker=8.0)
    fleet.rows["0"]["inflight"] = 20  # 20 in flight per 1 unit capacity
    fleet.tick(p99_ms=1.0)
    assert scaler.step(now=0.0) is None
    fleet.tick(p99_ms=1.0)
    assert scaler.step(now=1.0) == "up:1"


def test_booting_worker_counts_toward_capacity():
    """Pressure during a boot must not spawn a second worker for the
    same spike — and at max_workers the fleet stops growing."""
    fleet = FakeFleet(workers=1)
    scaler = make_scaler(fleet, max_workers=2, cooldown_s=0.0)
    fleet.tick(p99_ms=200.0)
    scaler.step(now=0.0)
    fleet.tick(p99_ms=200.0)
    assert scaler.step(now=1.0) == "up:1"
    # Worker 1 still spawning: capacity is 2 == max, no second spawn.
    for now in (2.5, 4.0, 6.0):
        fleet.tick(p99_ms=200.0)
        assert scaler.step(now=now) is None
    assert len(fleet.added) == 1


def test_sustained_idle_scales_down_to_min_and_stops():
    fleet = FakeFleet(workers=3)
    scaler = make_scaler(fleet, min_workers=1, cooldown_s=0.0, idle_s=2.0)
    assert scaler.step(now=0.0) is None  # idle timer starts
    assert scaler.step(now=1.0) is None
    assert scaler.step(now=2.0) == "down:2"
    assert fleet.removed == [("2", "idle")]
    # The draining worker blocks further events until it retires.
    assert scaler.step(now=4.5) is None
    del fleet.rows["2"]  # retire lands
    assert scaler.step(now=5.0) is None  # idle window restarts post-event
    assert scaler.step(now=7.0) == "down:1"
    del fleet.rows["1"]
    # At min_workers: never below.
    for now in (9.0, 12.0, 20.0):
        assert scaler.step(now=now) is None
    assert len(fleet.removed) == 2
    assert scaler.stats()["scale_downs"] == 2


def test_pending_queue_blocks_idle_and_reads_as_pressure():
    fleet = FakeFleet(workers=2)
    scaler = make_scaler(fleet, cooldown_s=0.0)
    fleet.pending = 3  # parked requests: the fleet is NOT idle
    assert scaler.step(now=0.0) is None
    fleet.tick(p99_ms=1.0)
    assert scaler.step(now=1.5) == "up:2"
    assert fleet.removed == []


def test_remove_refusal_is_not_a_scale_event():
    class StubbornFleet(FakeFleet):
        def remove_worker(self, worker_id=None, reason="scale_down"):
            return None  # nothing sparable (e.g. all holding in-flight)

    fleet = StubbornFleet(workers=2)
    scaler = make_scaler(fleet, cooldown_s=0.0, idle_s=1.0)
    scaler.step(now=0.0)
    assert scaler.step(now=1.5) is None
    assert scaler.events == []  # a refused remove is not an event


# ------------------------------------------------- elastic fleet (stub procs)


def make_supervisor(workers=1, delay_ms=0, chaos=None, **cfg):
    defaults = dict(
        workers=workers,
        heartbeat_s=0.05,
        hang_timeout_s=0.8,
        ready_timeout_s=15.0,
        monitor_interval_s=0.02,
    )
    defaults.update(cfg)
    env = {}
    for worker_id, specs in (chaos or {}).items():
        env[f"KEYSTONE_FAULT_SPECS_WORKER_{worker_id}"] = json.dumps(specs)
    return WorkerSupervisor(
        {"stub": {"delay_ms": delay_ms}}, SupervisorConfig(**defaults), env=env
    )


def settle(futures, timeout=30):
    return [f.result(timeout=timeout) for f in futures]


def test_scale_up_then_down_zero_dropped_and_ledgered():
    """The elastic-fleet invariant end to end: grow under load, shrink
    on idle, and every submitted request answers — the departing worker
    drains instead of dropping."""
    sup = make_supervisor(workers=1, delay_ms=2).start()
    try:
        sup.wait_ready()
        futures = [sup.submit([float(i)], deadline_s=30) for i in range(20)]
        new_id = sup.add_worker(reason="slo_pressure")
        assert new_id == "1"
        sup.wait_ready(n=2, timeout_s=15)
        futures += [
            sup.submit([float(i)], deadline_s=30) for i in range(20, 40)
        ]
        removed = sup.remove_worker()
        assert removed == "1"  # newest ready worker drains by default
        # Keep submitting THROUGH the drain: the ring already excludes
        # the draining worker, so these all land on worker 0.
        futures += [
            sup.submit([float(i)], deadline_s=30) for i in range(40, 60)
        ]
        results = settle(futures)
        assert [r[0] for r in results] == [2.0 * i for i in range(60)]
        # The drain retires the worker (in-flight empties fast here).
        deadline = time.monotonic() + 10
        while "1" in sup.stats()["workers"]:
            assert time.monotonic() < deadline, "drained worker never retired"
            time.sleep(0.05)
        kinds = {e.kind for e in get_recovery_log().events()}
        assert {"scale_up", "scale_down", "worker_retired"} <= kinds
        retired = get_recovery_log().events("worker_retired")[-1]
        assert retired.detail["crashed"] is False
        stats = sup.stats()
        assert stats["supervisor"]["workers"] == 1
        assert stats["supervisor"]["retired"] == 1
        # Lifetime counters survive retirement (the /metrics contract).
        assert "1" in sup.fleet_counter_totals()
        assert sup.fleet_counter_totals()["1"]["served"] > 0
    finally:
        sup.stop()


def test_worker_ids_never_recycle():
    sup = make_supervisor(workers=1).start()
    try:
        sup.wait_ready()
        assert sup.add_worker() == "1"
        sup.wait_ready(n=2, timeout_s=15)
        assert sup.remove_worker(worker_id="1") == "1"
        deadline = time.monotonic() + 10
        while "1" in sup.stats()["workers"]:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        # A later scale-up must NOT reuse "1": stats, ledger entries and
        # retained counters keyed by id would alias two lifetimes.
        assert sup.add_worker() == "2"
    finally:
        sup.stop()


def test_remove_refuses_last_capable_worker():
    sup = make_supervisor(workers=1).start()
    try:
        sup.wait_ready()
        assert sup.remove_worker() is None
        assert settle([sup.submit([3.0])])[0] == [6.0]
    finally:
        sup.stop()


# ------------------------------------------------------ chaos: kill mid-scale


def test_sigkill_new_worker_mid_scale_up_resolves_consistent():
    """A scale-up worker SIGKILLed right after joining must resolve to a
    consistent fleet: no dropped requests (stranded work requeues), no
    orphaned in-flight, and the supervisor restarts it like any other
    member."""
    chaos = {"1": [{"match": "serving.worker.request", "kind": "kill",
                    "calls": [3]}]}
    sup = make_supervisor(workers=1, delay_ms=2, chaos=chaos).start()
    try:
        sup.wait_ready()
        assert sup.add_worker(reason="slo_pressure") == "1"
        sup.wait_ready(n=2, timeout_s=15)
        futures = [sup.submit([float(i)], deadline_s=30) for i in range(40)]
        results = settle(futures)
        assert [r[0] for r in results] == [2.0 * i for i in range(40)]
        assert sup.requeued > 0, "the kill stranded no in-flight work"
        kinds = {e.kind for e in get_recovery_log().events()}
        assert "scale_up" in kinds and "worker_crash" in kinds
        # The killed scale-up worker restarts and the ring serves again.
        sup.wait_ready(n=2, timeout_s=20)
        assert settle([sup.submit([5.0])])[0] == [10.0]
    finally:
        sup.stop()


def test_sigkill_draining_worker_requeues_and_retires_as_crash():
    """Kill DURING the drain: a scale-down worker that dies mid-drain
    must still strand zero requests — its remaining in-flight requeues
    onto the survivors and the retire is recorded as a crash."""
    chaos = {"1": [{"match": "serving.worker.request", "kind": "kill",
                    "calls": [6]}]}
    sup = make_supervisor(
        workers=2, delay_ms=40, chaos=chaos, worker_queue_depth=256,
    ).start()
    try:
        sup.wait_ready()
        # ~20 requests per worker in flight at 40ms each: worker 1 is
        # still on its first few when the drain starts, and its 6th
        # (the kill) lands mid-drain.
        futures = [sup.submit([float(i)], deadline_s=60) for i in range(40)]
        removed = sup.remove_worker(worker_id="1")
        assert removed == "1"
        results = settle(futures, timeout=60)
        assert [r[0] for r in results] == [2.0 * i for i in range(40)]
        assert sup.requeued > 0, "the mid-drain kill stranded no work"
        kinds = {e.kind for e in get_recovery_log().events()}
        assert {"scale_down", "worker_crash", "worker_retired"} <= kinds
        retired = get_recovery_log().events("worker_retired")[-1]
        assert retired.detail["crashed"] is True
        # Consistent end state: the dead drainer is GONE (a draining
        # worker is never restarted), worker 0 owns the whole ring.
        deadline = time.monotonic() + 10
        while "1" in sup.stats()["workers"]:
            assert time.monotonic() < deadline, "crashed drainer never retired"
            time.sleep(0.05)
        assert settle([sup.submit([7.0], deadline_s=30)])[0] == [14.0]
        assert sup.stats()["supervisor"]["workers"] == 1
    finally:
        sup.stop()


# ------------------------------------------- autoscaler over the stub fleet


def test_autoscaler_closes_the_loop_on_a_real_stub_fleet():
    """Live wiring: a backlog spike on a 1-worker stub fleet drives a
    real add_worker through Autoscaler.step, and post-spike idle drains
    the fleet back down — zero dropped either way."""
    sup = make_supervisor(workers=1, delay_ms=15, worker_queue_depth=256).start()
    scaler = Autoscaler(
        sup,
        AutoscalerConfig(
            target_p99_ms=100.0, max_workers=2, backlog_per_worker=4.0,
            pressure_s=0.1, idle_s=0.4, cooldown_s=0.3, min_served=4,
        ),
    )
    try:
        sup.wait_ready()
        # Spike: 30 requests at 15ms each against one serial worker.
        futures = [sup.submit([float(i)], deadline_s=60) for i in range(30)]
        deadline = time.monotonic() + 10
        while not scaler.events:
            scaler.step()
            assert time.monotonic() < deadline, "spike never drove scale-up"
            time.sleep(0.05)
        assert scaler.events[0][0] == "up"
        results = settle(futures, timeout=60)
        assert [r[0] for r in results] == [2.0 * i for i in range(30)]
        # Idle: the loop drains the fleet back to min_workers.
        deadline = time.monotonic() + 15
        while scaler.stats()["scale_downs"] == 0:
            scaler.step()
            assert time.monotonic() < deadline, "idle never drove scale-down"
            time.sleep(0.05)
        deadline = time.monotonic() + 10
        while sup.stats()["supervisor"]["workers"] > 1:
            assert time.monotonic() < deadline, "fleet never shrank"
            time.sleep(0.05)
        kinds = {e.kind for e in get_recovery_log().events()}
        assert {"scale_up", "scale_down"} <= kinds
    finally:
        scaler.stop()
        sup.stop()
