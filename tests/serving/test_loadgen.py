"""Seeded arrival processes + the replay harness (serving/loadgen.py).

The generators must be deterministic under a seed (the autoscale bench
and smoke replay the SAME trace across configurations), hit their target
average rates, and the replay's dropped/completed accounting must be
exact — ``dropped == 0`` is a hard gate downstream."""

import concurrent.futures

import pytest

from keystone_tpu.serving.loadgen import (
    LoadReport,
    bursty_offsets,
    diurnal_offsets,
    heavy_tail_offsets,
    run_load,
)

pytestmark = pytest.mark.serving


@pytest.mark.parametrize(
    "make",
    [
        lambda seed: diurnal_offsets(20.0, 10.0, 60.0, seed=seed),
        lambda seed: bursty_offsets(20.0, 5.0, 80.0, seed=seed),
        lambda seed: heavy_tail_offsets(20.0, 30.0, seed=seed),
    ],
    ids=["diurnal", "bursty", "heavy_tail"],
)
def test_generators_are_seeded_sorted_and_bounded(make):
    a, b = make(7), make(7)
    assert a == b, "same seed must replay the same trace"
    assert a != make(8), "different seeds must differ"
    assert a == sorted(a)
    assert all(0.0 <= t < 20.0 for t in a)
    assert len(a) > 50  # the trace actually carries load


def test_diurnal_rate_swings_between_base_and_peak():
    offsets = diurnal_offsets(60.0, 5.0, 100.0, period_s=60.0, seed=3)
    # Sinusoid starts at the BASE (cos term): the first quarter is quiet,
    # mid-trace is near peak.
    quiet = sum(1 for t in offsets if t < 15.0) / 15.0
    busy = sum(1 for t in offsets if 22.5 <= t < 37.5) / 15.0
    assert busy > 3 * quiet, (quiet, busy)
    # Total mass ~ mean rate (52.5 rps) within loose stochastic bounds.
    assert 0.6 * 52.5 * 60 < len(offsets) < 1.4 * 52.5 * 60


def test_bursty_has_bursts_and_quiet_stretches():
    offsets = bursty_offsets(
        30.0, 2.0, 200.0, burst_len_s=0.5, quiet_len_s=2.0, seed=5
    )
    # Per-100ms histogram: burst bins see many arrivals, quiet bins ~0.
    bins = [0] * 300
    for t in offsets:
        bins[int(t * 10)] += 1
    assert max(bins) >= 10, "no burst ever materialized"
    assert sum(1 for b in bins if b == 0) > 50, "no quiet stretch"


def test_heavy_tail_mean_rate_and_refusal():
    offsets = heavy_tail_offsets(120.0, 50.0, alpha=1.5, seed=11)
    assert 0.4 * 50 * 120 < len(offsets) < 1.6 * 50 * 120
    with pytest.raises(ValueError, match="alpha"):
        heavy_tail_offsets(10.0, 5.0, alpha=1.0)
    with pytest.raises(ValueError, match="peak_rps"):
        diurnal_offsets(10.0, 20.0, 5.0)


def test_run_load_accounts_completed_dropped_and_submit_refusals():
    def submit(x, deadline_s=None):
        future = concurrent.futures.Future()
        if x % 5 == 4:
            raise RuntimeError("shed at the door")  # admission refusal
        if x % 5 == 3:
            future.set_exception(TimeoutError("expired in flight"))
        else:
            future.set_result(x * 2)
        return future

    report = run_load(
        submit,
        offsets=[i * 0.001 for i in range(50)],
        payload=lambda i: i,
        time_scale=1.0,
    )
    assert report.offered == 50
    assert report.completed == 30  # i%5 in {0,1,2}
    assert report.dropped == 20
    assert report.errors == {"RuntimeError": 10, "TimeoutError": 10}
    assert len(report.latencies_ms) == 30
    assert report.summary()["dropped"] == 20


def test_run_load_flags_unsettled_futures_instead_of_hanging():
    hung = []

    def submit(x, deadline_s=None):
        future = concurrent.futures.Future()
        hung.append(future)  # never resolved
        return future

    report = run_load(
        submit,
        offsets=[0.0, 0.0],
        payload=lambda i: i,
        settle_timeout_s=0.2,
    )
    assert report.completed == 0
    assert report.dropped == 2
    assert report.errors["Unsettled"] == 2


def test_report_percentiles():
    report = LoadReport(
        offered=4, completed=4, duration_s=2.0,
        latencies_ms=[1.0, 2.0, 3.0, 100.0],
    )
    assert report.rps == 2.0
    assert report.p(50) <= report.p(99) <= 100.0
