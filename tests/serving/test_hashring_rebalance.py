"""HashRing rebalance properties under elastic membership (satellite of
the autoscaling PR).

Consistent hashing's whole value to an autoscaler is the rebalance
bound: adding or removing ONE worker from an N-worker ring must remap
only ~K/N of K keys (the departing/arriving worker's own keyspace), not
reshuffle the world. And affinity keys must never split across the old
and new owner mid-drain — the supervisor rebuilds the ring WITHOUT the
draining worker the moment the drain starts, so every post-drain submit
routes to the key's single new owner while the old owner only finishes
work it already holds."""

import time

import pytest

from keystone_tpu.serving.supervisor import (
    HashRing,
    SupervisorConfig,
    WorkerSupervisor,
)

pytestmark = pytest.mark.serving

KEYS = [f"tenant-{i}" for i in range(1000)]


def owners(ring):
    return {k: next(iter(ring.walk(k))) for k in KEYS}


@pytest.mark.parametrize("n", [2, 4, 8])
def test_adding_one_worker_remaps_about_k_over_n_keys(n):
    before = owners(HashRing([str(i) for i in range(n)]))
    after = owners(HashRing([str(i) for i in range(n + 1)]))
    moved = [k for k in KEYS if before[k] != after[k]]
    expected = len(KEYS) / (n + 1)
    # Every moved key moved TO the new worker (nothing reshuffles between
    # survivors), and the count is ~K/(N+1) within loose vnode variance.
    assert all(after[k] == str(n) for k in moved)
    assert 0.4 * expected < len(moved) < 2.0 * expected, (
        f"{len(moved)} keys moved, expected ~{expected:.0f}"
    )


@pytest.mark.parametrize("n", [3, 5, 8])
def test_removing_one_worker_remaps_only_its_own_keys(n):
    members = [str(i) for i in range(n)]
    before = owners(HashRing(members))
    departed = str(n - 1)
    after = owners(HashRing([m for m in members if m != departed]))
    for k in KEYS:
        if before[k] == departed:
            assert after[k] != departed
        else:
            # A key owned by a survivor NEVER moves on a removal.
            assert after[k] == before[k], k
    orphaned = sum(1 for k in KEYS if before[k] == departed)
    expected = len(KEYS) / n
    assert 0.4 * expected < orphaned < 2.0 * expected


def test_failover_order_is_the_removal_order():
    """walk()'s second choice IS the owner after removal: the failover
    path and the rebalance path agree, so a key that failed over to its
    second choice during a crash lands on the same worker the rebuilt
    ring assigns it — no double-dispatch window between the two views."""
    members = ["0", "1", "2", "3"]
    full = HashRing(members)
    for key in KEYS[:200]:
        first, second = list(full.walk(key))[:2]
        rebuilt = HashRing([m for m in members if m != first])
        assert next(iter(rebuilt.walk(key))) == second


# ---------------------------------------------------- live drain (stub fleet)


def test_affinity_key_never_splits_across_old_and_new_owner_mid_drain():
    """Pin an affinity key to a worker, drain that worker, and keep
    submitting on the key THROUGH the drain: every post-drain request
    must land on the key's single new owner (the draining worker serves
    only what it already held)."""
    sup = WorkerSupervisor(
        {"stub": {"delay_ms": 20}},
        SupervisorConfig(
            workers=2, heartbeat_s=0.05, hang_timeout_s=5.0,
            ready_timeout_s=15.0, monitor_interval_s=0.02,
        ),
    ).start()
    try:
        sup.wait_ready()
        # Find a key worker 1 owns so the test drains the owner no matter
        # how the vnodes landed (routing hashes "model:key").
        ring = sup._ring
        model = sup.config.model_name
        key = next(
            k for k in KEYS
            if next(iter(ring.walk(f"{model}:{k}"))) == "1"
        )
        pre = [sup.submit([1.0], key=key, deadline_s=30) for _ in range(6)]
        assert sup.remove_worker(worker_id="1") == "1"
        new_owner = next(iter(sup._ring.walk(f"{model}:{key}")))
        assert new_owner == "0", "draining worker still owns its keyspace"
        post = [sup.submit([2.0], key=key, deadline_s=30) for _ in range(6)]
        assert [f.result(timeout=30) for f in pre] == [[2.0]] * 6
        assert [f.result(timeout=30) for f in post] == [[4.0]] * 6
        # The drained worker retires; worker 0 served every post-drain
        # request (no split: total served splits exactly 6 / 6+pre-spill).
        deadline = time.monotonic() + 10
        while "1" in sup.stats()["workers"]:
            assert time.monotonic() < deadline, "drained worker never retired"
            time.sleep(0.05)
        totals = sup.fleet_counter_totals()
        assert totals["0"]["served"] >= 6
        assert totals["1"]["served"] <= 6, (
            "draining worker took post-drain traffic"
        )
        assert totals["0"]["served"] + totals["1"]["served"] == 12
    finally:
        sup.stop()
