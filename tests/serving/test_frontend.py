"""HTTP front-end: routes, status mapping, health, deadline forwarding.

Runs against a fake in-process dispatcher — the HTTP layer's contract
(JSON in/out, status codes per failure class, health states) is
independent of worker processes; the full stack is covered by
test_multiworker_e2e.py and the chaos smoke."""

import json
import threading
import urllib.error
import urllib.request
from concurrent.futures import Future

import pytest

from keystone_tpu.serving.config import RequestShed, ServerClosed
from keystone_tpu.serving.frontend import ServingFrontend, parse_listen

pytestmark = pytest.mark.serving


class FakeSupervisor:
    """submit/stats/config shape the frontend consumes."""

    class config:
        drain_timeout_s = 5.0

    def __init__(self):
        self.lock = threading.Lock()
        self.submissions = []
        self.mode = "ok"
        self.worker_states = {"0": "ready", "1": "ready"}

    def submit(self, payload, deadline_s=None, model=None, key=None):
        with self.lock:
            self.submissions.append(
                {"x": payload, "deadline_s": deadline_s, "model": model, "key": key}
            )
        future = Future()
        if self.mode == "shed":
            raise RequestShed("queue full (test)")
        if self.mode == "closed":
            raise ServerClosed()
        if self.mode == "hang":
            return future  # never settles → deadline/timeout path
        if self.mode == "error":
            future.set_exception(RuntimeError("apply exploded"))
        else:
            future.set_result([2.0 * v for v in payload])
        return future

    def stats(self):
        alive = sum(1 for s in self.worker_states.values() if s == "ready")
        return {
            "served": len(self.submissions),
            "workers": {k: {"state": v} for k, v in self.worker_states.items()},
            "supervisor": {"alive": alive},
        }


@pytest.fixture()
def frontend():
    supervisor = FakeSupervisor()
    front = ServingFrontend(supervisor, "127.0.0.1", 0).start()
    yield front, supervisor
    front.stop()


def _post(front, path, obj, timeout=10):
    request = urllib.request.Request(
        f"http://{front.host}:{front.port}{path}",
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(request, timeout=timeout) as response:
            return response.status, json.loads(response.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def _get(front, path, timeout=10):
    try:
        with urllib.request.urlopen(
            f"http://{front.host}:{front.port}{path}", timeout=timeout
        ) as response:
            return response.status, json.loads(response.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def test_apply_round_trip_forwards_everything(frontend):
    front, supervisor = frontend
    code, out = _post(front, "/v1/apply", {
        "x": [1.0, 2.0], "model": "m2", "deadline_ms": 1500, "key": "tenant",
    })
    assert code == 200
    assert out["y"] == [2.0, 4.0] and out["latency_ms"] >= 0
    sub = supervisor.submissions[0]
    assert sub == {"x": [1.0, 2.0], "deadline_s": 1.5, "model": "m2",
                   "key": "tenant"}


def test_status_codes_per_failure_class(frontend):
    front, supervisor = frontend
    assert _post(front, "/v1/apply", {"x": "nope"})[0] == 400
    assert _post(front, "/v1/apply", {})[0] == 400
    supervisor.mode = "shed"
    assert _post(front, "/v1/apply", {"x": [1.0]})[0] == 429
    supervisor.mode = "closed"
    assert _post(front, "/v1/apply", {"x": [1.0]})[0] == 503
    supervisor.mode = "error"
    code, out = _post(front, "/v1/apply", {"x": [1.0]})
    assert code == 500 and "apply exploded" in out["error"]
    supervisor.mode = "hang"
    code, out = _post(front, "/v1/apply", {"x": [1.0], "deadline_ms": 100})
    assert code == 504
    assert _get(front, "/nowhere")[0] == 404


def test_healthz_tracks_worker_states(frontend):
    front, supervisor = frontend
    assert _get(front, "/healthz") == (
        200, {"status": "ok", "alive": 2, "booting": 0, "draining": 0,
              "workers": {"0": "ready", "1": "ready"}},
    )
    supervisor.worker_states["1"] = "dead"
    code, out = _get(front, "/healthz")
    assert (code, out["status"]) == (200, "degraded")
    supervisor.worker_states = {"0": "dead", "1": "failed"}
    code, out = _get(front, "/healthz")
    assert (code, out["status"]) == (503, "down")


def test_healthz_represents_booting_and_draining_distinctly(frontend):
    """The elastic-fleet bugfix: a worker that is booting (scale-up in
    progress) or draining (scale-down in progress) is NOT a degraded
    fleet — /healthz must say "scaling" and carry the counts, so a probe
    watching a scale event doesn't page on normal autoscaler motion."""
    front, supervisor = frontend
    supervisor.worker_states = {"0": "ready", "1": "spawning"}
    code, out = _get(front, "/healthz")
    assert (code, out["status"]) == (200, "scaling")
    assert (out["alive"], out["booting"], out["draining"]) == (1, 1, 0)
    supervisor.worker_states = {"0": "ready", "1": "draining"}
    code, out = _get(front, "/healthz")
    assert (code, out["status"]) == (200, "scaling")
    assert (out["alive"], out["booting"], out["draining"]) == (1, 0, 1)
    # A genuinely dead worker still degrades even while another boots.
    supervisor.worker_states = {"0": "ready", "1": "spawning", "2": "dead"}
    code, out = _get(front, "/healthz")
    assert (code, out["status"]) == (200, "degraded")
    # Booting-only fleet (cold start): down until the first ready.
    supervisor.worker_states = {"0": "new", "1": "spawning"}
    code, out = _get(front, "/healthz")
    assert (code, out["status"]) == (503, "down")


def test_stats_route_returns_supervisor_snapshot(frontend):
    front, supervisor = frontend
    _post(front, "/v1/apply", {"x": [1.0]})
    code, out = _get(front, "/stats")
    assert code == 200 and out["served"] == 1 and "workers" in out


def test_default_deadline_applies_when_request_carries_none():
    """--deadline-ms on the multiworker path: requests without their own
    budget get the default; an explicit deadline_ms still wins."""
    supervisor = FakeSupervisor()
    front = ServingFrontend(
        supervisor, "127.0.0.1", 0, default_deadline_s=0.25
    ).start()
    try:
        assert _post(front, "/v1/apply", {"x": [1.0]})[0] == 200
        assert _post(front, "/v1/apply", {"x": [1.0], "deadline_ms": 1500})[0] == 200
    finally:
        front.stop()
    assert [s["deadline_s"] for s in supervisor.submissions] == [0.25, 1.5]


def test_deadline_ms_zero_is_exhausted_not_default():
    """deadline_ms=0 means the budget is gone — it must forward 0.0 (and
    time out), never fall through to the default by truthiness."""
    supervisor = FakeSupervisor()
    supervisor.mode = "hang"
    front = ServingFrontend(
        supervisor, "127.0.0.1", 0, default_deadline_s=30.0
    ).start()
    try:
        code, out = _post(front, "/v1/apply", {"x": [1.0], "deadline_ms": 0})
    finally:
        front.stop()
    assert code == 504
    assert supervisor.submissions[0]["deadline_s"] == 0.0


def test_wedged_fleet_without_deadline_is_503_not_504():
    """A request that carried NO deadline and hit the drain-ceiling wait
    bound was failed by a wedged fleet, not by its own budget: 503."""
    supervisor = FakeSupervisor()
    supervisor.mode = "hang"
    supervisor.config = type("C", (), {"drain_timeout_s": 0.2})
    front = ServingFrontend(supervisor, "127.0.0.1", 0).start()
    try:
        code, out = _post(front, "/v1/apply", {"x": [1.0]})
    finally:
        front.stop()
    assert code == 503 and "UNAVAILABLE" in out["error"]


def test_malformed_deadline_ms_answers_400_not_dropped_connection(frontend):
    front, _ = frontend
    for bad in ("abc", [100], {"ms": 100}):
        code, out = _post(front, "/v1/apply", {"x": [1.0], "deadline_ms": bad})
        assert code == 400 and "deadline_ms" in out["error"], (bad, code, out)


def test_worker_zero_remaining_deadline_is_forwarded_not_unbounded():
    """The supervisor sends REMAINING budget; 0.0 means exhausted. The
    worker must forward deadline_s=0.0 (which times out at assembly),
    never drop the deadline and serve unbounded."""
    from concurrent.futures import Future

    from keystone_tpu.serving import worker as worker_mod

    forwarded = []

    class FakeServer:
        def submit(self, payload, deadline_s=None, model=None):
            forwarded.append(deadline_s)
            future = Future()
            future.set_result([0.0])
            return future

    backend = worker_mod.ServerBackend.__new__(worker_mod.ServerBackend)
    backend.server = FakeServer()
    backend._warmed = True
    emitted = []

    class Emitter:
        emit = staticmethod(emitted.append)

    backend.handle({"id": 1, "x": [1.0], "deadline_ms": 0.0}, Emitter)
    backend.handle({"id": 2, "x": [1.0]}, Emitter)
    assert forwarded == [0.0, None]
    assert len(emitted) == 2


def test_fleet_exhausted_unavailable_maps_to_503(frontend):
    """UNAVAILABLE (every worker out of restart budget) is retryable
    against another replica — 503, not a 500 server bug."""
    from keystone_tpu.serving.config import ServingError

    front, supervisor = frontend

    def submit(payload, deadline_s=None, model=None, key=None):
        future = Future()
        future.set_exception(
            ServingError("UNAVAILABLE: every worker exhausted its restart budget")
        )
        return future

    supervisor.submit = submit
    code, out = _post(front, "/v1/apply", {"x": [1.0]})
    assert code == 503 and "UNAVAILABLE" in out["error"]


def test_stdin_parser_carries_model_and_key_to_both_doors():
    """parse_stdin_request is the one parser behind every door: the
    model and affinity key a stdin client sends must reach submit()."""
    from keystone_tpu.serving.config import parse_stdin_request

    rid, x, deadline_s, key, model = parse_stdin_request(
        {"id": 7, "x": [1.0], "model": "m2", "key": "tenant",
         "deadline_ms": 100}
    )
    assert (rid, x, deadline_s, key, model) == (7, [1.0], 0.1, "tenant", "m2")
    assert parse_stdin_request([1.0], 0.5) == (None, [1.0], 0.5, None, None)


def test_parse_listen():
    assert parse_listen("0.0.0.0:8080") == ("0.0.0.0", 8080)
    assert parse_listen(":9000") == ("127.0.0.1", 9000)
    assert parse_listen("9000") == ("127.0.0.1", 9000)
    with pytest.raises(ValueError):
        parse_listen("localhost")


def _get_text(front, path, timeout=10):
    with urllib.request.urlopen(
        f"http://{front.host}:{front.port}{path}", timeout=timeout
    ) as response:
        return response.status, response.headers.get("Content-Type", ""), \
            response.read().decode()


def test_metrics_route_prometheus_exposition(frontend):
    """GET /metrics: text exposition (not JSON), the full pre-registered
    schema (>= 5 families even on a fresh process), serving and fleet
    families present."""
    front, _ = frontend
    code, content_type, text = _get_text(front, "/metrics")
    assert code == 200
    assert content_type.startswith("text/plain")
    assert text.count("# HELP") >= 5
    for family in (
        "keystone_serving_workers_alive",
        "keystone_fleet_requests_total",
        "keystone_flight_dumps_total",
    ):
        assert f"# TYPE {family}" in text, family


def test_metrics_route_aggregates_supervisor_counters(frontend):
    """A supervisor exposing fleet_counter_totals gets its per-worker
    lifetime counters published as keystone_fleet_* series."""
    front, supervisor = frontend
    supervisor.fleet_counter_totals = lambda: {
        "0": {"served": 1e9, "failures": 3.0}
    }
    _, _, text = _get_text(front, "/metrics")
    line = next(
        l for l in text.splitlines()
        if l.startswith('keystone_fleet_requests_total{worker="0"}')
    )
    assert float(line.rsplit(" ", 1)[1]) >= 1e9


def test_ingress_span_opens_per_apply(frontend):
    """The http:apply ingress span is the trace root the supervisor's
    dispatch (and the workers, cross-process) re-parent under."""
    from keystone_tpu.obs import spans

    front, supervisor = frontend
    with spans.tracing_session("http", sync_timings=False) as session:
        code, _ = _post(front, "/v1/apply", {"x": [1.0]})
        assert code == 200
    ingress = [s for s in session.spans() if s.name == "http:apply"]
    assert len(ingress) == 1
    assert ingress[0].trace_id == session.trace_id
    assert ingress[0].attributes.get("http_status") == 200
