"""`keystone-tpu serve` front-end over stdin/JSON (subprocess; slow-marked
— scripts/serve_smoke.sh runs the same path out-of-band and CI's tier-1
stays inside its budget)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.serving, pytest.mark.slow]

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_serve_synthetic_roundtrip(tmp_path):
    requests = "\n".join(
        [json.dumps({"id": i, "x": [float(i)] * 8}) for i in range(20)]
        # Malformed payloads must answer with an error line, not kill the
        # stream for the valid requests around them.
        + [json.dumps({"id": 98, "x": "abc"}), json.dumps({"id": 97, "x": None})]
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               KEYSTONE_COMPILATION_CACHE=str(tmp_path / "cache"))
    proc = subprocess.run(
        [sys.executable, "-m", "keystone_tpu", "serve",
         "--synthetic", "8", "--max-batch", "4", "--max-wait-ms", "5"],
        input=requests, capture_output=True, text=True, timeout=300,
        env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    stats_lines = [l for l in lines if l.startswith("SERVE_STATS:")]
    assert len(stats_lines) == 1
    stats = json.loads(stats_lines[0][len("SERVE_STATS:"):])
    responses = [json.loads(l) for l in lines if not l.startswith("SERVE_STATS:")]
    assert len(responses) == 22
    by_id = {r["id"]: r for r in responses}
    assert set(by_id) == set(range(20)) | {97, 98}
    for i in range(20):
        r = by_id[i]
        assert "error" not in r, r
        assert len(r["y"]) == 8 and r["latency_ms"] >= 0
    assert "bad payload" in by_id[98]["error"]
    assert "bad payload" in by_id[97]["error"]
    assert stats["served"] == 20
    assert stats["sheds"] == 0 and stats["failures"] == 0
    assert stats["models"]["default"]["source"] == "synthetic:d=8"
