"""Micro-batcher: bounded queue, max-wait, deadline-aware assembly."""

import time

import pytest

from keystone_tpu.reliability.retry import Deadline
from keystone_tpu.serving.batcher import MicroBatcher
from keystone_tpu.serving.config import Request, RequestTimeout

pytestmark = pytest.mark.serving


def req(payload=0, deadline_s=None):
    return Request(
        payload=payload,
        model="m",
        deadline=Deadline(deadline_s) if deadline_s is not None else None,
    )


def test_offer_is_bounded():
    b = MicroBatcher(capacity=2)
    assert b.offer(req()) and b.offer(req())
    assert not b.offer(req())
    assert b.refused == 1 and b.depth() == 2


def test_full_batch_dispatches_before_max_wait():
    b = MicroBatcher(capacity=8)
    for i in range(4):
        b.offer(req(i))
    t0 = time.monotonic()
    batch = b.next_batch(max_batch=4, max_wait_s=5.0)
    elapsed = time.monotonic() - t0
    assert [r.payload for r in batch] == [0, 1, 2, 3]
    assert elapsed < 1.0  # did NOT hold the full 5 s max-wait


def test_partial_batch_respects_max_wait():
    b = MicroBatcher(capacity=8)
    b.offer(req("solo"))
    t0 = time.monotonic()
    batch = b.next_batch(max_batch=4, max_wait_s=0.08)
    elapsed = time.monotonic() - t0
    assert [r.payload for r in batch] == ["solo"]
    assert 0.06 <= elapsed < 2.0


def test_expired_request_fails_at_assembly_not_on_device():
    expired_seen = []
    b = MicroBatcher(capacity=8, on_expired=expired_seen.append)
    dead = req("dead", deadline_s=0.0)
    live = req("live")
    time.sleep(0.01)  # the 0-second deadline is now past
    b.offer(dead)
    b.offer(live)
    batch = b.next_batch(max_batch=2, max_wait_s=0.01)
    assert [r.payload for r in batch] == ["live"]
    assert b.expired == 1 and expired_seen == [dead]
    with pytest.raises(RequestTimeout):
        dead.future.result(timeout=0)


def test_batch_closes_early_for_member_deadline():
    """A queued request about to expire closes the batch instead of the
    batch's max-wait expiring it: deadline-aware assembly."""
    b = MicroBatcher(capacity=8)
    b.offer(req("urgent", deadline_s=0.08))
    t0 = time.monotonic()
    batch = b.next_batch(max_batch=4, max_wait_s=10.0)
    elapsed = time.monotonic() - t0
    assert [r.payload for r in batch] == ["urgent"]
    assert not batch[0].future.done()  # dispatched, not expired
    assert elapsed < 5.0  # nowhere near the 10 s max-wait


def test_fail_all_drains_queue():
    b = MicroBatcher(capacity=4)
    requests = [req(i) for i in range(3)]
    for r in requests:
        b.offer(r)
    assert b.fail_all(RuntimeError("shutdown")) == 3
    assert b.depth() == 0
    for r in requests:
        with pytest.raises(RuntimeError):
            r.future.result(timeout=0)
