"""Admission control: ladder-driven service degradation + loud sheds."""

import pytest

from keystone_tpu.reliability.recovery import get_recovery_log
from keystone_tpu.serving.admission import AdmissionController, AdmissionRung
from keystone_tpu.serving.config import RequestShed

pytestmark = pytest.mark.serving


def controller(capacity=10):
    return AdmissionController(capacity=capacity)


def test_normal_admission_at_low_depth():
    a = controller()
    rung = a.admit(depth=0)
    assert rung.name == "normal" and rung.wait_scale == 1.0
    assert a.stats()["rung"] == "normal"


def test_degrades_under_pressure_and_records_once():
    a = controller(capacity=10)
    assert a.admit(depth=6).name == "pressure"  # past 0.5x10, under 0.75x10
    assert a.wait_scale() == 0.5
    events = get_recovery_log().events("degrade")
    assert len(events) == 1 and events[0].label == "serving-admission"
    # Steady-state admits at the same rung must NOT append more events
    # (a long-running server under load cannot grow the ledger per request).
    for _ in range(50):
        a.admit(depth=6)
    assert len(get_recovery_log().events("degrade")) == 1


def test_overload_rung_then_shed_at_capacity():
    a = controller(capacity=10)
    assert a.admit(depth=9).name == "overload"
    with pytest.raises(RequestShed):
        a.admit(depth=10)
    assert a.stats()["sheds"] == 1
    assert a.stats()["consecutive_sheds"] == 1
    a.admit(depth=1)  # success resets the consecutive counter
    assert a.stats()["consecutive_sheds"] == 0


def test_recovers_to_normal_when_queue_drains():
    a = controller(capacity=10)
    a.admit(depth=9)
    assert a.rung_index == 2
    assert a.admit(depth=0).name == "normal"
    assert a.wait_scale() == 1.0


def test_rung_fracs_must_be_monotone():
    with pytest.raises(ValueError):
        AdmissionController(
            capacity=4,
            rungs=[AdmissionRung(0.9, 1.0), AdmissionRung(0.5, 0.5)],
        )


# ----------------------------------------------- external (SLO-driven) mode


def test_external_mode_never_walks_on_depth():
    from keystone_tpu.serving.slo import SLO_RUNGS

    controller = AdmissionController(100, rungs=SLO_RUNGS, external=True)
    # deep queue at the normal rung: admitted right up to the full bound
    assert controller.admit(99).name == "normal"
    assert controller.rung_index == 0  # depth moved nothing
    with pytest.raises(RequestShed):
        controller.admit(100)


def test_force_rung_pins_and_reports_previous():
    from keystone_tpu.serving.slo import SLO_RUNGS

    controller = AdmissionController(100, rungs=SLO_RUNGS, external=True)
    assert controller.force_rung(2) == 0
    assert controller.force_rung(2) is None  # already there
    assert controller.rungs[controller.rung_index].name == "overload"
    with pytest.raises(RequestShed):
        controller.admit(40)  # 0.3 * 100 bound now
    assert controller.force_rung(0) == 2
    with pytest.raises(ValueError):
        controller.force_rung(7)


def test_external_mode_allows_non_monotonic_rungs():
    from keystone_tpu.serving.admission import AdmissionRung

    shrinking = (
        AdmissionRung(queue_frac=1.0, wait_scale=1.0, name="a"),
        AdmissionRung(queue_frac=0.5, wait_scale=0.5, name="b"),
    )
    with pytest.raises(ValueError):
        AdmissionController(10, rungs=shrinking)  # depth mode refuses
    assert AdmissionController(10, rungs=shrinking, external=True)
