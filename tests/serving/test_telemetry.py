"""Telemetry: percentile math, snapshot shape, bucket-warmth accounting."""

import pytest

from keystone_tpu.serving.telemetry import ServingTelemetry, percentile

pytestmark = pytest.mark.serving


def test_percentile_interpolation():
    data = [1.0, 2.0, 3.0, 4.0]
    assert percentile(data, 0) == 1.0
    assert percentile(data, 100) == 4.0
    assert percentile(data, 50) == 2.5
    assert percentile([], 50) == 0.0
    assert percentile([7.0], 99) == 7.0


def test_snapshot_fields_and_percentiles():
    t = ServingTelemetry(window=16)
    for ms in (1, 2, 3, 4, 5, 6, 7, 8, 9, 10):
        t.record_request(latency_s=ms / 1e3, queue_wait_s=ms / 2e3)
    t.record_batch(size=5, bucket=8, max_batch=10)
    t.record_shed()
    t.record_timeout()
    snap = t.snapshot(queue_depth=3)
    assert snap["served"] == 10 and snap["batches"] == 1
    assert snap["sheds"] == 1 and snap["timeouts"] == 1
    assert snap["queue_depth"] == 3
    assert snap["p50_ms"] == pytest.approx(5.5, abs=0.01)
    assert snap["p99_ms"] <= 10.0 and snap["p99_ms"] >= snap["p50_ms"]
    assert snap["batch_occupancy"] == 0.5


def test_bucket_warmth_hit_rate():
    t = ServingTelemetry()
    t.mark_bucket_warm(4)
    t.record_batch(3, bucket=4, max_batch=8)   # warm → hit
    t.record_batch(7, bucket=8, max_batch=8)   # cold → compile
    t.record_batch(8, bucket=8, max_batch=8)   # now warm → hit
    assert t.bucket_hits == 2 and t.bucket_compiles == 1
    assert t.snapshot()["bucket_hit_rate"] == pytest.approx(2 / 3, abs=1e-4)


def test_maybe_log_rate_limited():
    clock = {"t": 0.0}
    t = ServingTelemetry(clock=lambda: clock["t"])
    assert not t.maybe_log(interval_s=30.0)  # within the first interval
    clock["t"] = 31.0
    assert t.maybe_log(interval_s=30.0)
    assert not t.maybe_log(interval_s=30.0)  # immediately after: limited
