"""PipelineServer acceptance: warm-bucket no-recompile, overload shedding,
hot-swap with zero drops, deadline expiry, fault-injected retry."""

import time

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.reliability.faultinject import FaultSpec, injected
from keystone_tpu.reliability.retry import RetryPolicy
from keystone_tpu.serving import (
    PipelineServer,
    RequestShed,
    RequestTimeout,
    ServerClosed,
    ServingConfig,
)
from keystone_tpu.serving.synthetic import synthetic_fitted_pipeline, synthetic_requests
from keystone_tpu.workflow.pipeline import Transformer

pytestmark = pytest.mark.serving

D = 8


class ScaleModel(Transformer):
    """k·x with an optional pre-apply sleep (stands in for heavy compute:
    makes queue buildup and in-flight batches controllable in tests)."""

    def __init__(self, k, delay_s=0.0):
        self.k = k
        self.delay_s = delay_s

    def apply(self, x):
        return np.asarray(x) * self.k

    def apply_batch(self, dataset):
        if self.delay_s:
            time.sleep(self.delay_s)
        return ArrayDataset(np.asarray(dataset.data) * self.k, dataset.num_examples)


def serve(model, **kw):
    defaults = dict(max_batch=8, max_wait_ms=10.0, queue_depth=64)
    defaults.update(kw)
    return PipelineServer(model, config=ServingConfig(**defaults))


def test_results_match_direct_apply():
    fp = synthetic_fitted_pipeline(d=D, seed=2)
    payloads = synthetic_requests(13, d=D)
    expected = np.asarray(fp.apply_batch(ArrayDataset(np.stack(payloads))).data)
    with serve(fp) as server:
        futures = server.submit_many(payloads)
        results = np.stack([f.result(timeout=30) for f in futures])
    np.testing.assert_allclose(results, expected, rtol=1e-5, atol=1e-6)


def test_bucket_padding_never_recompiles_after_warmup():
    """The tentpole property: after AOT bucket warmup, NO request size
    triggers an XLA compile — asserted two ways (a trace-time counter in
    the jitted body, and the jax.monitoring backend-compile counter)."""
    trace = []
    fp = synthetic_fitted_pipeline(d=D, trace_log=trace)
    with serve(fp) as server:
        server.warmup(np.zeros((D,), np.float32))
        buckets = server.config.buckets()
        assert len(trace) == len(buckets)  # one trace per bucket
        traces_after_warmup = len(trace)
        for n in (3, 5, 2, 7, 1, 8):  # sizes that all pad to some bucket
            futures = server.submit_many(synthetic_requests(n, d=D, seed=n))
            for f in futures:
                f.result(timeout=30)
        stats = server.stats()
    assert len(trace) == traces_after_warmup, f"recompiled: {trace}"
    assert stats["xla_compiles_since_warmup"] == 0
    assert stats["bucket_compiles"] == 0  # every batch hit a warm bucket
    assert stats["bucket_hit_rate"] == 1.0
    assert stats["served"] == 26 and stats["failures"] == 0


def test_overload_sheds_instead_of_queueing_unboundedly():
    model = ScaleModel(2, delay_s=0.05)
    with serve(model, queue_depth=8, max_wait_ms=1.0) as server:
        futures = server.submit_many(synthetic_requests(80, d=D))
        assert server.batcher.depth() <= 8  # the queue never grew past capacity
        outcomes = []
        for f in futures:
            try:
                f.result(timeout=30)
                outcomes.append("ok")
            except RequestShed:
                outcomes.append("shed")
        stats = server.stats()
    assert "shed" in outcomes and "ok" in outcomes  # degraded, not dead
    assert stats["sheds"] == outcomes.count("shed") > 0
    assert stats["admission"]["sheds"] > 0
    assert stats["failures"] == 0  # sheds are refusals, not apply failures


def test_hot_swap_serves_new_version_with_zero_dropped_requests():
    with serve(ScaleModel(1), max_wait_ms=2.0) as server:
        payloads = synthetic_requests(60, d=D)
        first = server.submit_many(payloads[:30])
        server.registry.publish("default", ScaleModel(3))  # hot-swap mid-stream
        second = server.submit_many(payloads[30:])
        results = [f.result(timeout=30) for f in first + second]  # zero drops
    for x, y in zip(payloads, results):
        ratio = np.asarray(y) / np.asarray(x)
        # Every request was served by exactly one version, never a mix.
        assert np.allclose(ratio, 1.0) or np.allclose(ratio, 3.0)
    # Requests submitted after the swap resolve the new version.
    for x, y in zip(payloads[30:], results[30:]):
        np.testing.assert_allclose(np.asarray(y), np.asarray(x) * 3, rtol=1e-6)
    assert server.registry.swaps == 1


def test_deadline_expires_in_queue_while_worker_busy():
    model = ScaleModel(2, delay_s=0.3)
    with serve(model, max_wait_ms=1.0) as server:
        blocker = server.submit(synthetic_requests(1, d=D)[0])
        time.sleep(0.05)  # the blocker's batch is now on the worker
        doomed = server.submit(synthetic_requests(1, d=D, seed=9)[0], deadline_s=0.05)
        with pytest.raises(RequestTimeout):
            doomed.result(timeout=30)
        blocker.result(timeout=30)  # the in-flight batch still completes
        assert server.stats()["timeouts"] == 1


def test_transient_fault_in_apply_is_retried_per_policy():
    fp = synthetic_fitted_pipeline(d=D)
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.01, max_delay_s=0.02)
    with injected(
        FaultSpec(match="serving.apply", kind="transient", calls=(1,))
    ) as injector:
        with serve(fp, retry_policy=policy) as server:
            futures = server.submit_many(synthetic_requests(3, d=D))
            results = [f.result(timeout=30) for f in futures]
            stats = server.stats()
    assert len(results) == 3 and all(np.asarray(r).shape == (D,) for r in results)
    # One probe call per batch plus exactly one retried attempt (only the
    # first call faults), regardless of how the 3 requests batched up.
    assert injector.calls("serving.apply") == stats["batches"] + 1
    assert stats["retries"] == 1 and stats["failures"] == 0
    from keystone_tpu.reliability.recovery import get_recovery_log

    assert len(get_recovery_log().events("retry")) == 1


def test_exhausted_retries_fail_the_batch_loudly():
    fp = synthetic_fitted_pipeline(d=D)
    policy = RetryPolicy(max_attempts=2, base_delay_s=0.01, max_delay_s=0.02)
    with injected(FaultSpec(match="serving.apply", kind="transient", first_n=5)):
        with serve(fp, retry_policy=policy) as server:
            future = server.submit(synthetic_requests(1, d=D)[0])
            with pytest.raises(ConnectionError):
                future.result(timeout=30)
            assert server.stats()["failures"] == 1


def test_model_returning_short_rows_fails_tail_instead_of_hanging():
    """A model that returns fewer rows than its batch (filtering
    ObjectDataset) must fail the unmatched requests loudly — a zip
    truncation would leave their futures unsettled forever."""
    from keystone_tpu.data.dataset import ObjectDataset

    class FirstRowOnly(Transformer):
        def apply(self, x):
            return np.asarray(x)

        def apply_batch(self, dataset):
            return ObjectDataset(dataset.collect()[:1])

    with serve(FirstRowOnly(), max_wait_ms=30.0) as server:
        futures = server.submit_many(synthetic_requests(3, d=D))
        outcomes = []
        for f in futures:
            try:
                f.result(timeout=10)
                outcomes.append("ok")
            except Exception as exc:
                assert "returned 1 rows for a batch of" in str(exc)
                outcomes.append("short")
        stats = server.stats()
    # One "ok" per assembled batch; every other request fails loudly —
    # and critically, ALL futures settled (no result() hang above).
    assert outcomes.count("short") >= 1
    assert outcomes.count("ok") + outcomes.count("short") == 3
    assert stats["failures"] == outcomes.count("short")


def test_submit_after_stop_raises():
    server = serve(ScaleModel(1)).start()
    server.stop()
    with pytest.raises(ServerClosed):
        server.submit(np.zeros((D,), np.float32))


def test_restart_after_stop_serves_again():
    server = serve(ScaleModel(2))
    server.start()
    assert server.submit(np.ones((D,), np.float32)).result(timeout=30) is not None
    server.stop()
    server.start()  # must clear the stop signal: a restarted worker serves
    out = server.submit(np.ones((D,), np.float32)).result(timeout=30)
    np.testing.assert_allclose(np.asarray(out), 2.0)
    server.stop()


def test_wrong_shaped_request_fails_alone_not_its_batchmates():
    """One client sending shape (D+1,) into a batch of (D,) requests must
    not poison np.stack for everyone: groups stack per payload signature."""
    with serve(synthetic_fitted_pipeline(d=D), max_wait_ms=30.0) as server:
        good = server.submit_many(synthetic_requests(3, d=D))
        bad = server.submit(np.zeros((D + 1,), np.float32))
        for f in good:
            assert np.asarray(f.result(timeout=30)).shape == (D,)
        with pytest.raises(Exception):
            bad.result(timeout=30)
        assert server.stats()["failures"] == 1


def test_stop_without_drain_fails_queued_requests():
    model = ScaleModel(1, delay_s=0.2)
    server = serve(model, max_wait_ms=1.0).start()
    futures = server.submit_many(synthetic_requests(12, d=D))
    server.stop(drain=False)
    settled = 0
    for f in futures:
        try:
            f.result(timeout=5)
            settled += 1
        except (ServerClosed, RequestShed):
            settled += 1
    assert settled == 12  # every future resolves one way or the other


def test_two_model_registry_keeps_metric_series_distinct():
    """Multi-tenant bugfix pin: two models behind one registry must emit
    two distinct keystone_serving_* series (model label), not collapse
    into a single aggregate — the per-model quality/SLO views read these."""
    from keystone_tpu.obs import metrics, names
    from keystone_tpu.serving.registry import ModelRegistry

    requests_metric = metrics.get_registry().counter(
        names.SERVING_REQUESTS, labels=("model",)
    )
    alpha0 = requests_metric.value(model="alpha")
    beta0 = requests_metric.value(model="beta")
    registry = ModelRegistry()
    registry.publish("alpha", ScaleModel(2))
    registry.publish("beta", ScaleModel(5))
    with PipelineServer(
        config=ServingConfig(max_batch=8, max_wait_ms=2.0), registry=registry,
        name="alpha",
    ) as server:
        payloads = synthetic_requests(9, d=D)
        futures = [server.submit(p, model="alpha") for p in payloads[:5]]
        futures += [server.submit(p, model="beta") for p in payloads[5:]]
        results = [f.result(timeout=30) for f in futures]
        stats = server.stats()
    for x, y in zip(payloads[:5], results[:5]):
        np.testing.assert_allclose(np.asarray(y), np.asarray(x) * 2, rtol=1e-6)
    for x, y in zip(payloads[5:], results[5:]):
        np.testing.assert_allclose(np.asarray(y), np.asarray(x) * 5, rtol=1e-6)
    # one series per tenant, each counting only its own traffic
    assert requests_metric.value(model="alpha") == alpha0 + 5
    assert requests_metric.value(model="beta") == beta0 + 4
    # latency histogram split the same way
    latency = metrics.get_registry().get(names.SERVING_LATENCY_SECONDS)
    assert latency.count(model="alpha") >= 5
    assert latency.count(model="beta") >= 4
    # snapshot carries the per-tenant breakdown next to the flat totals
    assert stats["served"] == 9
    assert stats["per_model"]["alpha"]["served"] == 5
    assert stats["per_model"]["beta"]["served"] == 4
