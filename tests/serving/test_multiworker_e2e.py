"""Multi-worker serving, end to end with REAL jax worker processes.

Two invariants the ISSUE pins, exercised against actual
``keystone_tpu.serving.worker`` subprocesses sharing one persistent XLA
cache:

1. **hot-swap under multi-process load** — swapping the live model
   version mid-sweep across 2 workers drops/fails zero requests, and
   once the swap settles every worker serves at zero steady-state XLA
   compiles (each worker re-warms before acking; siblings and restarts
   warm from the shared on-disk cache).
2. **chaos: SIGKILL mid-sweep** — a worker killed mid-load loses zero
   requests (requeued and completed), the supervisor restarts it within
   the backoff budget, and worker_crash/worker_restart land in the
   recovery ledger.

Lean on purpose (d=8, 2 workers) — but each worker still pays a jax
import (and the chaos test pays a third for the restart), so the module
is slow-marked: tier-1 keeps the same invariants via the jax-free stub
workers in test_supervisor.py, and CI exercises THIS real-process path
through scripts/serve_chaos_smoke.sh. The offered-load version runs in
bench.py's serving_multiworker leg.
"""

import json
import time

import pytest

from keystone_tpu.reliability.recovery import get_recovery_log
from keystone_tpu.serving.supervisor import SupervisorConfig, WorkerSupervisor

pytestmark = [pytest.mark.serving, pytest.mark.slow]

D = 8
SPEC = {"synthetic": {"d": D, "seed": 0}}


def make_supervisor(tmp_path, chaos=None):
    env = {"KEYSTONE_COMPILATION_CACHE": str(tmp_path / "shared-xla-cache")}
    for worker_id, specs in (chaos or {}).items():
        env[f"KEYSTONE_FAULT_SPECS_WORKER_{worker_id}"] = json.dumps(specs)
    return WorkerSupervisor(
        SPEC,
        SupervisorConfig(
            workers=2,
            heartbeat_s=0.2,
            hang_timeout_s=5.0,
            ready_timeout_s=180.0,
            max_batch=4,
            restart_policy=__import__(
                "keystone_tpu.reliability.retry", fromlist=["RetryPolicy"]
            ).RetryPolicy(max_attempts=4, base_delay_s=0.1, max_delay_s=1.0),
        ),
        env=env,
    )


def settle(futures, timeout=120):
    return [f.result(timeout=timeout) for f in futures]


def test_hot_swap_mid_sweep_zero_dropped_zero_steady_compiles(tmp_path):
    sup = make_supervisor(tmp_path).start()
    try:
        sup.wait_ready()  # BOTH workers, so the sweep loads both
        x = [0.5] * D
        before = settle([sup.submit(x, deadline_s=90) for _ in range(24)])

        # Mid-sweep: keep load in flight while the fleet swaps versions.
        inflight = [sup.submit([float(i % 3)] * D, deadline_s=90) for i in range(24)]
        acks = sup.swap({"synthetic": {"d": D, "seed": 2}})
        settle(inflight)
        assert set(acks) == {"0", "1"}
        for ack in acks.values():
            assert ack["kind"] == "swapped", acks
            assert ack["version"] == 2

        # Post-settle traffic: zero dropped, answered by the NEW weights.
        after = settle([sup.submit(x, deadline_s=90) for _ in range(24)])
        assert before[0] != after[0], "swap did not change the served model"
        assert all(len(y) == D for y in after)

        time.sleep(0.5)  # one beat: post-swap stats reach the supervisor
        stats = sup.stats()
        assert stats["failures"] == 0 and stats["timeouts"] == 0
        assert stats["supervisor"]["requeued"] == 0
        for worker_id, worker in stats["workers"].items():
            assert worker["stats"].get("served", 0) > 0, (
                f"worker {worker_id} took no traffic: load not multi-process"
            )
            assert worker["stats"]["xla_compiles_since_warmup"] == 0, (
                f"worker {worker_id} compiled in steady state after the swap"
            )
    finally:
        sup.stop()


def test_sigkill_mid_sweep_zero_dropped_restart_in_budget(tmp_path):
    chaos = {"0": [{"match": "serving.worker.request", "kind": "kill",
                    "calls": [6]}]}
    sup = make_supervisor(tmp_path, chaos=chaos).start()
    try:
        sup.wait_ready()
        futures = [
            sup.submit([float(i % 5)] * D, deadline_s=120) for i in range(48)
        ]
        results = settle(futures)
        assert all(len(y) == D for y in results), "a request was dropped/failed"
        assert sup.requeued > 0, "the kill stranded no in-flight work"

        crashes = get_recovery_log().events("worker_crash")
        assert crashes and crashes[0].detail["reason"] == "crash"
        # Restart within the backoff budget: schedule sum + spawn slack.
        policy = sup.config.restart_policy
        budget_s = sum(policy.backoff_schedule()) + 60.0
        sup.wait_ready(timeout_s=budget_s)
        assert get_recovery_log().events("worker_restart"), (
            "restart never recorded"
        )
        # The recycled worker serves again — and from the shared cache it
        # re-warmed without steady-state compiles.
        settle([sup.submit([1.0] * D, deadline_s=120) for _ in range(8)])
        time.sleep(0.5)
        stats = sup.stats()
        worker0 = stats["workers"]["0"]
        assert worker0["state"] == "ready" and worker0["incarnation"] == 1
        assert worker0["stats"]["xla_compiles_since_warmup"] == 0
    finally:
        sup.stop()
