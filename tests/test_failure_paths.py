"""Failure-path coverage (round-2 verdict item 8): OOM adaptation in the
bench helpers, masked extractors at degenerate sizes, and solver
validation on misconfigured meshes/shapes."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.parallel import linalg
from keystone_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh, use_mesh


@pytest.fixture(autouse=True)
def _no_ambient_onchip_capture(monkeypatch):
    """Leg adoption (r5) reads real watchdog captures from
    docs/measurements/*onchip_bench.json; tests must not see whatever
    this machine's watchdog happened to capture. Subprocess tests
    inherit the pin through os.environ."""
    monkeypatch.setenv("KEYSTONE_ONCHIP_CAPTURE", "/nonexistent/onchip.json")


# ------------------------------------------------------------ bench helpers


def test_imagenet_bench_ladder_reduces_on_oom(monkeypatch):
    """The imagenet_fv bench walks its reduction ladder on
    RESOURCE_EXHAUSTED and marks the result."""
    import bench

    calls = []

    def fake_at(n_img, size, num_classes, small):
        calls.append((n_img, size, num_classes))
        if size > 64:
            raise RuntimeError("RESOURCE_EXHAUSTED: fake OOM")
        return {"num_images": n_img, "image_size": size}

    monkeypatch.setattr(bench, "_imagenet_fv_at", fake_at)
    out = bench._bench_imagenet_fv(small=False)
    assert out["extrapolated"] is True
    assert out["reduced_from"]["image_size"] == 256
    assert out["reduced_from"]["num_classes"] == 1000
    assert out["num_classes"] == 16
    assert "RESOURCE_EXHAUSTED" in out["reduction_reason"]
    assert len(calls) == 5  # walked every >64 rung before succeeding


def test_imagenet_bench_deadline_abort_not_swallowed_as_oom(monkeypatch):
    """The per-rung deadline gate quotes the PRIOR rung's error, which may
    contain RESOURCE_EXHAUSTED — the abort must still propagate (typed
    DeadlineExceeded), not be misread as an OOM and walked through every
    remaining rung."""
    import bench

    calls = []

    def fake_at(n_img, size, num_classes, small):
        calls.append((n_img, size))
        raise RuntimeError("RESOURCE_EXHAUSTED: fake OOM")

    monkeypatch.setattr(bench, "_imagenet_fv_at", fake_at)
    gates = iter([False, True])  # rung 1 runs, rung 2 hits the deadline
    monkeypatch.setattr(bench, "_deadline_within", lambda margin: next(gates))
    with pytest.raises(bench.DeadlineExceeded, match="RESOURCE_EXHAUSTED"):
        bench._bench_imagenet_fv(small=False)
    assert calls == [(32, 256)]  # no phantom rungs after the abort


def test_imagenet_bench_ladder_reraises_non_oom(monkeypatch):
    import bench

    def fake_at(n_img, size, num_classes, small):
        raise ValueError("not an OOM")

    monkeypatch.setattr(bench, "_imagenet_fv_at", fake_at)
    with pytest.raises(ValueError):
        bench._bench_imagenet_fv(small=False)


def test_bench_workload_registry_consistent():
    import bench

    assert set(bench.WORKLOADS) == set(bench._workload_registry())


# -------------------------------------------------- masked degenerate sizes


def test_masked_sift_image_smaller_than_grid():
    """A bucket member far smaller than the padded shape must yield zero
    valid descriptors at scales its native size can't host, and the valid
    count must match its native-size run."""
    from keystone_tpu.ops.images.sift import SIFTExtractor

    ext = SIFTExtractor(scale_step=1)
    rng = np.random.default_rng(0)
    big, small = 96, 24
    img_small = rng.random((small, small)).astype(np.float32)
    padded = np.pad(img_small, ((0, big - small), (0, big - small)), mode="edge")
    batch = jnp.asarray(padded[None])
    dims = jnp.asarray([[small, small]], jnp.int32)
    desc, valid = ext.apply_arrays_masked(batch, dims)
    native = np.asarray(ext.apply_arrays(jnp.asarray(img_small[None])))
    assert int(valid.sum()) == native.shape[1]
    got = np.asarray(desc)[0][np.asarray(valid)[0]]
    np.testing.assert_allclose(got, native[0], atol=1.0)
    # 99.5%-within-1, the reference's own tolerance (VLFeatSuite.scala:47-52)
    close = np.abs(got - native[0]) <= 1.0
    assert close.mean() > 0.995


def test_masked_lcs_degenerate_size():
    from keystone_tpu.ops.images.lcs import LCSExtractor

    ext = LCSExtractor(stride=4, stride_start=16, sub_patch_size=6)
    rng = np.random.default_rng(1)
    small = 40  # barely above the 2*border minimum
    img = rng.random((small, small, 3)).astype(np.float32)
    padded = np.pad(img, ((0, 24), (0, 24), (0, 0)), mode="edge")
    desc, valid = ext.apply_arrays_masked(
        jnp.asarray(padded[None]), jnp.asarray([[small, small]], jnp.int32)
    )
    native = np.asarray(ext.apply_arrays(jnp.asarray(img[None])))
    assert int(valid.sum()) == native.shape[1]


def test_bucketize_rejects_nothing_but_groups_consistently():
    from keystone_tpu.data.buckets import bucketize_images

    rng = np.random.default_rng(2)
    recs = [
        {"image": rng.random((17, 23, 3)).astype(np.float32), "label": 0},
        {"image": rng.random((17, 23, 3)).astype(np.float32), "label": 1},
        {"image": rng.random((64, 64, 3)).astype(np.float32), "label": 2},
    ]
    buckets = bucketize_images(recs, granularity=32)
    assert sorted(b.bucket_shape for b in buckets) == [(32, 32), (64, 64)]
    assert sum(len(b) for b in buckets) == 3


# ------------------------------------------------------- solver validation


def test_bcd_rejects_non_dividing_block():
    mesh = make_mesh(devices=jax.devices()[:8])
    rng = np.random.default_rng(3)
    a = rng.normal(size=(16, 10)).astype(np.float32)
    y = rng.normal(size=(16, 2)).astype(np.float32)
    with use_mesh(mesh):
        with pytest.raises(ValueError, match="not divisible"):
            linalg.block_coordinate_descent(
                linalg.prepare_row_sharded(a, mesh),
                linalg.prepare_row_sharded(y, mesh),
                reg=0.1, num_epochs=1, block_size=3, mesh=mesh,
            )


def test_bcd2d_rejects_non_dividing_model_blocks():
    mesh = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS), devices=jax.devices()[:8])
    rng = np.random.default_rng(4)
    a = rng.normal(size=(16, 12)).astype(np.float32)
    y = rng.normal(size=(16, 2)).astype(np.float32)
    with pytest.raises(ValueError, match="not divisible"):
        linalg.block_coordinate_descent_2d(
            linalg.prepare_block_sharded(a, mesh),
            linalg.prepare_block_sharded(y, mesh, fine_rows=True),
            reg=0.1, num_epochs=1, block_size=8, mesh=mesh,
        )


def test_conv_block_estimator_rejects_bad_block_size():
    from keystone_tpu.ops.images import (
        Convolver,
        FusedConvFeaturizer,
        Pooler,
        SymmetricRectifier,
    )
    from keystone_tpu.ops.learning.conv_block import (
        ConvBlockLeastSquaresEstimator,
    )

    rng = np.random.default_rng(5)
    fz = FusedConvFeaturizer(
        Convolver(rng.normal(size=(8, 108)).astype(np.float32), 3),
        SymmetricRectifier(alpha=0.25),
        Pooler(13, 14, None, "sum"),
    )
    est = ConvBlockLeastSquaresEstimator(fz, block_size=12)  # 12 % 8 != 0
    mesh = make_mesh(devices=jax.devices()[:8])
    with use_mesh(mesh):
        with pytest.raises(ValueError, match="not divisible"):
            est.fit(
                ArrayDataset(rng.random((16, 32, 32, 3)).astype(np.float32)),
                ArrayDataset(rng.normal(size=(16, 2)).astype(np.float32)),
            )


def test_streaming_threshold_env_override(monkeypatch):
    from keystone_tpu.ops.learning import block as block_mod

    monkeypatch.setenv("KEYSTONE_STREAM_BYTES", "123")
    assert block_mod._host_streaming_threshold_bytes() == 123


def test_solver_precision_env_knob(monkeypatch):
    """KEYSTONE_SOLVER_PRECISION is read per call; invalid values raise
    (a typo'd 'fast mode' must not silently run 6-pass)."""
    import jax.numpy as jnp

    from keystone_tpu.parallel import linalg

    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "default")
    assert linalg.precision() == jax.lax.Precision.DEFAULT
    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "highest")
    assert linalg.precision() == jax.lax.Precision.HIGHEST
    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "bf16")
    with pytest.raises(ValueError, match="KEYSTONE_SOLVER_PRECISION"):
        linalg.solver_mode()
    # Unset → the shipped default: refine mode for the exact solver,
    # HIGHEST for every other solver-grade matmul.
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    assert linalg.solver_mode() == "refine"
    assert linalg.precision() == jax.lax.Precision.HIGHEST


def test_solver_precision_flips_mid_process(monkeypatch):
    """r4 verdict item 8 'Done' criterion: one lifetime for the precision
    knob. Flipping KEYSTONE_SOLVER_PRECISION mid-process must flow into
    (a) ``mm`` itself, (b) the lru-cached compiled-fn factories (mode in
    the cache key — distinct executables per mode, cache hits within a
    mode), and (c) ``mode_jit``-wrapped solver entry points (re-trace on
    flip). Verified structurally via the lowered HLO (numeric checks
    can't see precision on the CPU backend, where every matmul is fp32)."""
    import jax.numpy as jnp

    from keystone_tpu.parallel import linalg
    from keystone_tpu.parallel.mesh import make_mesh

    a = jnp.ones((8, 4))
    b = jnp.ones((4, 4))

    # (a) mm reads the mode at trace time. Fresh jit instances per lower:
    # a SINGLE jax.jit object would replay its cached trace across the
    # flip — which is exactly why every jitted mm caller must go through
    # mode_jit (part c) rather than bare jax.jit.
    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "highest")
    assert "HIGHEST" in jax.jit(lambda p, q: linalg.mm(p, q)).lower(a, b).as_text().upper()
    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "default")
    assert "HIGHEST" not in jax.jit(lambda p, q: linalg.mm(p, q)).lower(a, b).as_text().upper()

    # (b) factory caches key on the mode: distinct per mode, hit within.
    mesh = make_mesh(devices=jax.devices()[:8])
    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "highest")
    f_hi = linalg._gram_fn(mesh)
    assert "HIGHEST" in f_hi.lower(a).as_text().upper()
    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "default")
    f_def = linalg._gram_fn(mesh)
    assert f_def is not f_hi
    assert "HIGHEST" not in f_def.lower(a).as_text().upper()
    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "highest")
    assert linalg._gram_fn(mesh) is f_hi

    # (c) mode_jit re-traces on a flip (and caches within a mode).
    traces = []

    @linalg.mode_jit
    def probe(x):
        traces.append(linalg.solver_mode())
        return linalg.mm(x, x)

    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "highest")
    probe(b)
    probe(b)
    assert traces == ["highest"]
    monkeypatch.setenv("KEYSTONE_SOLVER_PRECISION", "default")
    probe(b)
    assert traces == ["highest", "default"]


def test_persistent_compilation_cache_knob(tmp_path, monkeypatch):
    """enable_persistent_cache honors the env knob: off disables, a path
    selects the dir, and the dir is created + registered with jax."""
    import jax

    from keystone_tpu.utils.compilation_cache import enable_persistent_cache

    saved = (
        jax.config.jax_compilation_cache_dir,
        jax.config.jax_persistent_cache_min_entry_size_bytes,
        jax.config.jax_persistent_cache_min_compile_time_secs,
    )
    try:
        monkeypatch.setenv("KEYSTONE_COMPILATION_CACHE", "off")
        assert enable_persistent_cache() is None

        target = str(tmp_path / "xla-cache")
        monkeypatch.setenv("KEYSTONE_COMPILATION_CACHE", target)
        got = enable_persistent_cache()
        assert got == target
        import os as _os

        assert _os.path.isdir(target)
        assert jax.config.jax_compilation_cache_dir == target
    finally:  # global jax config: restore so later tests don't write a cache
        jax.config.update("jax_compilation_cache_dir", saved[0])
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", saved[1])
        jax.config.update("jax_persistent_cache_min_compile_time_secs", saved[2])


def _fake_child_factory(platform, fail_workloads=()):
    def fake_run_child(env, small, timeout_s, workload=None):
        import bench

        if workload in fail_workloads:
            return None, "boom"
        name = workload or "timit_exact"
        report = {
            "platform": platform, "device_kind": platform,
            "backend_init_s": 0.0, "small_shapes": small,
            "compilation_cache": None,
            name: {"fit_ms": 1.0, "wall_s": 0.1},
        }
        if workload is None:  # small-shapes fallback child: all workloads
            for w in bench.WORKLOADS:
                report[w] = {"fit_ms": 1.0, "wall_s": 0.1}
        return report, ""
    return fake_run_child


def test_bench_parent_cpu_probe_short_circuits(monkeypatch, capsys, tmp_path):
    """A cpu default backend must skip the full-size attempts and land on
    the small-shapes leg (full TIMIT shapes would crawl on a host CPU)."""
    import json

    import bench

    monkeypatch.chdir(tmp_path)

    monkeypatch.setattr(bench, "_probe_backend",
                        lambda env, timeout_s=120: (True, "PROBE_OK cpu 8"))
    monkeypatch.setattr(bench, "_run_child", _fake_child_factory("cpu"))
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["small_shapes"] is True
    assert any("cpu backend" in d for d in out.get("diagnostics", []))


def test_bench_parent_hung_probe_falls_back(monkeypatch, capsys, tmp_path):
    """Deadline exhausted (set to 0 here) → the insurance leg's results
    stand, with the hung-probe and deadline diagnostics recorded."""
    import json

    import bench

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("KEYSTONE_BENCH_DEADLINE", "0")

    monkeypatch.setattr(bench, "_probe_backend",
                        lambda env, timeout_s=120: (False, "backend probe hung >120s"))
    monkeypatch.setattr(bench, "_run_child", _fake_child_factory("cpu"))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["small_shapes"] is True
    assert any("hung" in d for d in out["diagnostics"])
    assert any("deadline exhausted" in d for d in out["diagnostics"])


def test_bench_parent_insurance_runs_before_waiting(monkeypatch, capsys, tmp_path):
    """r4 verdict item 1: on a failed first probe the CPU insurance leg
    runs BEFORE any probe retries/sleeps, so the artifact exists no
    matter when an external kill lands."""
    import json

    import bench

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("KEYSTONE_BENCH_DEADLINE", "3600")

    order = []

    def probe_then_cpu(env, timeout_s=120):
        # Hung first probe (forces insurance), then a healthy host-cpu
        # probe so the waiting loop terminates deterministically.
        order.append("probe")
        if order.count("probe") == 1:
            return False, "backend probe hung >120s"
        return True, "PROBE_OK cpu 8"

    inner = _fake_child_factory("cpu")

    def recording_child(env, small, timeout_s, workload=None):
        order.append("insurance" if small else f"full:{workload}")
        # The insurance child env must be dial-proof and virtual-meshed.
        if small:
            assert "PALLAS_AXON_POOL_IPS" not in env
            assert env["JAX_PLATFORMS"] == "cpu"
            assert "xla_force_host_platform_device_count" in env["XLA_FLAGS"]
            assert env["KEYSTONE_BENCH_CHILD_PARTIAL"].endswith("BENCH_PARTIAL.json")
        return inner(env, small, timeout_s, workload)

    monkeypatch.setattr(bench, "_probe_backend", probe_then_cpu)
    monkeypatch.setattr(bench, "_run_child", recording_child)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert order[0] == "probe"
    assert order[1] == "insurance"  # before any retry probe
    assert out["small_shapes"] is True


def test_bench_dead_relay_yields_artifact(tmp_path):
    """r4 verdict item 1 'Done' criterion, run for real: with the
    accelerator backend unavailable (no registration + JAX_PLATFORMS=
    axon — NOT a blackholed dial, see the env comment below), a
    deadline-bounded `python bench.py`
    prints one JSON line with a measured headline AND leaves a fresh
    finalized BENCH_PARTIAL.json — well inside `timeout 1200`."""
    import json
    import os
    import subprocess
    import sys
    import time as _time

    env = dict(os.environ)
    # Simulate the dead relay WITHOUT dialing: sitecustomize rewrites
    # any PALLAS_AXON_POOL_IPS dial target to 127.0.0.1 (loopback relay
    # override), so a "non-routable" value still dials the LIVE relay —
    # and with a real TPU process running, those claim attempts can kill
    # it (observed r5: this test's probes took down the flagship leg).
    # Instead: no registration at all + JAX_PLATFORMS=axon makes every
    # probe child fail fast with "Backend 'axon' is not in the list of
    # known backends" — the same contract (probe fails, insurance runs,
    # one line prints) with zero relay traffic. The hung-probe variant
    # is covered by the monkeypatched parent tests above.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "axon"
    env["KEYSTONE_BENCH_DEADLINE"] = "150"
    env["KEYSTONE_BENCH_PROBE_TIMEOUT"] = "10"
    env["KEYSTONE_BENCH_PROBE_INTERVAL"] = "2"
    env["KEYSTONE_BENCH_WORKLOADS"] = "timit_exact"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    t0 = _time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=420,
    )
    wall = _time.monotonic() - t0
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert lines, proc.stdout[-2000:]
    out = json.loads(lines[-1])
    assert out["value"] is not None  # insurance headline actually measured
    assert out["platform"] == "cpu"
    partial = json.loads((tmp_path / "BENCH_PARTIAL.json").read_text())
    assert partial["partial"] is False
    assert wall < 400, wall


def test_bench_parent_probe_retries_within_window(monkeypatch, capsys, tmp_path):
    """r3 verdict item 1: a relay that comes back mid-window must be
    caught — two failed probes then success → full-size run, not the
    CPU fallback."""
    import json

    import bench

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("KEYSTONE_BENCH_DEADLINE", "3600")

    calls = []

    def flaky_probe(env, timeout_s=120):
        calls.append(1)
        if len(calls) < 3:
            return False, "backend probe hung >120s"
        return True, "PROBE_OK tpu 1"

    monkeypatch.setattr(bench, "_probe_backend", flaky_probe)
    monkeypatch.setattr(bench, "_run_child", _fake_child_factory("tpu"))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["small_shapes"] is False
    assert len(calls) >= 3
    assert sum("hung" in d for d in out.get("diagnostics", [])) == 2


def test_bench_parent_tpu_runs_full_and_extra_legs(monkeypatch, capsys, tmp_path):
    """Healthy accelerator probe: every workload child runs full-size and
    the two TIMIT precision comparison legs are appended."""
    import json

    import bench

    monkeypatch.setattr(bench, "_probe_backend",
                        lambda env, timeout_s=120: (True, "PROBE_OK tpu 1"))
    monkeypatch.setattr(bench, "_run_child", _fake_child_factory("tpu"))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.chdir(tmp_path)  # partial dump lands outside the repo
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out.get("small_shapes") is False
    for leg in ("timit_exact_highest", "timit_exact_fastmode"):
        assert leg in out, sorted(out)
    assert out["workloads_with_errors"] == []
    # deadline insurance: legs persist incrementally; a COMPLETED run
    # finalizes the artifact with partial=False so a stale file can't
    # masquerade as a later run's progress.
    partial = json.loads(open("BENCH_PARTIAL.json").read())
    assert partial["partial"] is False and "timit_exact_fastmode" in partial


def test_bench_parent_retries_only_failed_workloads(monkeypatch, capsys, tmp_path):
    """Attempt 2 re-runs ONLY workloads that errored on attempt 1 (the
    flaky-tunnel second chance), and surviving errors are recorded."""
    import json

    import bench

    monkeypatch.chdir(tmp_path)

    calls = []
    inner = _fake_child_factory("tpu")

    def failing_once(env, small, timeout_s, workload=None):
        calls.append(workload)
        if workload == "gram_mfu" and calls.count("gram_mfu") == 1:
            return None, "boom"
        if workload == "cifar_random_patch":
            return None, "always down"
        return inner(env, small, timeout_s, workload)

    monkeypatch.setattr(bench, "_probe_backend",
                        lambda env, timeout_s=120: (True, "PROBE_OK tpu 1"))
    monkeypatch.setattr(bench, "_run_child", failing_once)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # gram_mfu recovered on attempt 2; cifar stayed an error
    assert calls.count("gram_mfu") == 2
    assert calls.count("timit_exact") == 1 + 2  # attempt 1 + 2 extra legs
    assert out["workloads_with_errors"] == ["cifar_random_patch"]
    assert "error" not in out["gram_mfu"]


def test_bench_extra_legs_set_precision_modes(monkeypatch, capsys, tmp_path):
    """The comparison legs must actually flip KEYSTONE_SOLVER_PRECISION
    (highest, then default) in the child environment."""
    import json

    import bench

    monkeypatch.chdir(tmp_path)

    modes = []
    inner = _fake_child_factory("tpu")

    def recording(env, small, timeout_s, workload=None):
        modes.append(env.get("KEYSTONE_SOLVER_PRECISION"))
        return inner(env, small, timeout_s, workload)

    monkeypatch.setattr(bench, "_probe_backend",
                        lambda env, timeout_s=120: (True, "PROBE_OK tpu 1"))
    monkeypatch.setattr(bench, "_run_child", recording)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.delenv("KEYSTONE_SOLVER_PRECISION", raising=False)
    assert bench.main() == 0
    json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # the two extra legs are the only calls that set the knob
    assert modes.count("highest") == 1 and modes.count("default") == 1
    assert modes[-2:] == ["highest", "default"]


def test_dryrun_perturbation_makes_legs_fail():
    """r4 verdict item 4 'Done' criterion: a seeded numeric perturbation
    must make dryrun legs report non-ok — proving the MULTICHIP artifact
    certifies numeric correctness, not just that sharded code executes."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS", "XLA_FLAGS")}
    env["KEYSTONE_DRYRUN_PERTURB"] = "1000.0"
    proc = subprocess.run(
        [sys.executable, "-c", "import __graft_entry__ as g; g.dryrun_multichip(2)"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=560,
    )
    assert proc.returncode != 0, proc.stdout[-1500:]
    out = proc.stdout + proc.stderr
    assert "DRYRUN_LEGS" in out, out[-1500:]
    assert out.count("FAIL") >= 5, out[-1500:]  # most legs carry invariants
    assert "rel_err" in out, out[-1500:]


def test_bench_workload_filter_validation(monkeypatch):
    """KEYSTONE_BENCH_WORKLOADS restricts the run; unknown names fail
    loudly (a typo'd leg name must not silently run everything)."""
    import bench

    monkeypatch.setenv("KEYSTONE_BENCH_WORKLOADS", "gram_mfu, ingest")
    assert bench._selected_workloads() == ["gram_mfu", "ingest"]
    monkeypatch.setenv("KEYSTONE_BENCH_WORKLOADS", "timit_exact,nope")
    with pytest.raises(SystemExit, match="nope"):
        bench._selected_workloads()
    # set-but-empty ("", " ", ",") must not silently select ZERO legs (a
    # zero-leg bench run exiting 0 would look like a green measurement) —
    # and an accidentally-empty wrapper var must not run the FULL bench
    for empty in ("", " , ", " "):
        monkeypatch.setenv("KEYSTONE_BENCH_WORKLOADS", empty)
        with pytest.raises(SystemExit, match="no workloads"):
            bench._selected_workloads()
    monkeypatch.delenv("KEYSTONE_BENCH_WORKLOADS")
    assert bench._selected_workloads() == list(bench.WORKLOADS)


def test_bench_measure_budget_skips_and_adopts(monkeypatch, capsys, tmp_path):
    """r5: the healthy path is budget-bounded too (the driver's envelope
    is ~20 min; a cold full-leg run is hours). Legs past
    KEYSTONE_BENCH_MEASURE_BUDGET are marked skipped, and skipped/failed
    legs are adopted from the newest watchdog capture with in-leg file
    provenance and a top-level workloads_from_capture listing."""
    import json
    import time as _t

    import bench

    monkeypatch.chdir(tmp_path)
    capture = {
        "platform": "tpu", "device_kind": "TPU v5 lite",
        "imagenet_flagship": {"wall_s": 1234.0, "top5_err": 0.5},
        "cifar_random_patch": {"end_to_end_fit_s": 99.0},
        "imagenet_fv": {"error": "died on capture day"},  # must NOT adopt
    }
    cap = tmp_path / "cap_onchip_bench.json"
    cap.write_text(json.dumps(capture) + "\n")
    monkeypatch.setenv("KEYSTONE_ONCHIP_CAPTURE", str(cap))
    monkeypatch.setenv("KEYSTONE_BENCH_MEASURE_BUDGET", "0.4")

    inner = _fake_child_factory("tpu")

    def slow_child(env, small, timeout_s, workload=None):
        # Spin (not sleep: time.sleep is no-op'd below) so each leg
        # consumes real measuring budget.
        t0 = _t.monotonic()
        while _t.monotonic() - t0 < 0.15:
            pass
        return inner(env, small, timeout_s, workload)

    monkeypatch.setattr(bench, "_probe_backend",
                        lambda env, timeout_s=120: (True, "PROBE_OK tpu 1"))
    monkeypatch.setattr(bench, "_run_child", slow_child)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    # Early (priority) legs measured live; late legs skipped by budget.
    assert "error" not in out["timit_exact"] and "skipped" not in out["timit_exact"]
    assert out["workloads_skipped_budget"], out
    # Skipped flagship legs adopted from the capture, with provenance;
    # the capture's own errored leg must NOT be adopted.
    assert "imagenet_flagship" in out["workloads_from_capture"]
    assert out["imagenet_flagship"]["top5_err"] == 0.5
    assert out["imagenet_flagship"]["adopted_from_capture"]["source"] == str(cap)
    assert "imagenet_fv" not in out["workloads_from_capture"]
    # The headline itself came from a live measurement, not the capture.
    assert out["value"] == 1.0


def test_adopt_captured_legs_rejects_cpu_and_errored(tmp_path, monkeypatch):
    """Adoption helper filters: a CPU capture adds nothing (never
    adopted); error/skipped legs inside a capture stay dead; the
    this_run reason is recorded for the audit trail."""
    import json

    import bench

    cpu_cap = tmp_path / "cpu_onchip_bench.json"
    cpu_cap.write_text(json.dumps({"platform": "cpu", "ingest": {"ips": 1}}) + "\n")
    monkeypatch.setenv("KEYSTONE_ONCHIP_CAPTURE", str(cpu_cap))
    merged = {"ingest": {"error": "boom"}}
    assert bench._adopt_captured_legs(merged, ["ingest"]) == []
    assert merged["ingest"] == {"error": "boom"}

    tpu_cap = tmp_path / "tpu_onchip_bench.json"
    tpu_cap.write_text(json.dumps({
        "platform": "tpu",
        "ingest": {"ips": 800.0},
        "gram_mfu": {"skipped": "budget"},
    }) + "\n")
    monkeypatch.setenv("KEYSTONE_ONCHIP_CAPTURE", str(tpu_cap))
    merged = {"ingest": {"error": "boom"}, "gram_mfu": {"skipped": "budget"}}
    adopted = bench._adopt_captured_legs(merged, ["ingest", "gram_mfu"])
    assert adopted == ["ingest"]
    assert merged["ingest"]["ips"] == 800.0
    assert merged["ingest"]["adopted_from_capture"]["this_run"] == "boom"
    assert "skipped" in merged["gram_mfu"]  # capture's skipped leg: no adoption


def test_bench_all_live_failures_not_masked_by_capture(monkeypatch, capsys, tmp_path):
    """A run whose every live leg failed must fall back to insurance —
    adopted capture data must not fabricate a clean accelerator run
    (workloads_from_capture stays empty; errors are not laundered)."""
    import json

    import bench

    monkeypatch.chdir(tmp_path)
    cap = tmp_path / "cap_onchip_bench.json"
    cap.write_text(json.dumps({
        "platform": "tpu",
        **{w: {"fit_ms": 7.0} for w in bench.WORKLOADS},
    }) + "\n")
    monkeypatch.setenv("KEYSTONE_ONCHIP_CAPTURE", str(cap))

    monkeypatch.setattr(bench, "_probe_backend",
                        lambda env, timeout_s=120: (True, "PROBE_OK tpu 1"))
    monkeypatch.setattr(
        bench, "_run_child",
        _fake_child_factory("tpu", fail_workloads=tuple(bench.WORKLOADS)))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # Insurance result stands; nothing was adopted into the artifact.
    assert out.get("workloads_from_capture", []) == []
    assert out["small_shapes"] is True  # the insurance child's legs


def test_adopt_captured_legs_preserves_chain(tmp_path, monkeypatch):
    """A capture can itself contain adopted legs (watchdog runs share
    main()); re-adoption must keep the whole provenance chain instead of
    restamping old data as freshly captured."""
    import json

    import bench

    cap = tmp_path / "chain_onchip_bench.json"
    cap.write_text(json.dumps({
        "platform": "tpu",
        "ingest": {
            "ips": 700.0,
            "adopted_from_capture": {"source": "older.json",
                                     "captured_mtime": "2026-07-30",
                                     "this_run": "child timed out"},
        },
    }) + "\n")
    monkeypatch.setenv("KEYSTONE_ONCHIP_CAPTURE", str(cap))
    merged = {"ingest": {"skipped": "budget"}}
    assert bench._adopt_captured_legs(merged, ["ingest"]) == ["ingest"]
    stamp = merged["ingest"]["adopted_from_capture"]
    assert stamp["source"] == str(cap)
    assert stamp["chain"]["source"] == "older.json"


def test_adopt_captured_legs_falls_through_candidates(tmp_path, monkeypatch):
    """Manual capture runs measure different leg subsets per file;
    adoption takes each pending leg from the first (preferred) capture
    that has a good entry, not only from the single newest file."""
    import json
    import os

    import bench

    a = tmp_path / "newer_onchip_bench.json"
    a.write_text(json.dumps({"platform": "tpu",
                             "imagenet_fv": {"solve_ms": 5.0}}) + "\n")
    b = tmp_path / "older_onchip_bench.json"
    b.write_text(json.dumps({"platform": "tpu",
                             "imagenet_fv": {"solve_ms": 9.0},
                             "imagenet_flagship": {"wall_s": 77.0}}) + "\n")
    monkeypatch.setenv("KEYSTONE_ONCHIP_CAPTURE", f"{a}{os.pathsep}{b}")
    merged = {"imagenet_fv": {"error": "x"},
              "imagenet_flagship": {"skipped": "budget"}}
    adopted = bench._adopt_captured_legs(
        merged, ["imagenet_fv", "imagenet_flagship"])
    assert sorted(adopted) == ["imagenet_flagship", "imagenet_fv"]
    assert merged["imagenet_fv"]["solve_ms"] == 5.0  # preferred file wins
    assert merged["imagenet_fv"]["adopted_from_capture"]["source"] == str(a)
    assert merged["imagenet_flagship"]["wall_s"] == 77.0
    assert merged["imagenet_flagship"]["adopted_from_capture"]["source"] == str(b)


def test_adopt_handles_truncated_legs(tmp_path, monkeypatch):
    """Truncated legs (graceful in-leg deadline exits) are a third
    state: a truncated CAPTURE leg is incomplete and never adopted; a
    truncated LIVE leg is adopted over by a complete capture with the
    truncation reason stamped as this_run."""
    import json

    import bench

    cap = tmp_path / "t_onchip_bench.json"
    cap.write_text(json.dumps({
        "platform": "tpu",
        "imagenet_fv": {"sift_ms": 1.0, "truncated": "deadline"},
        "cifar_random_patch": {"end_to_end_fit_s": 42.0},
    }) + "\n")
    monkeypatch.setenv("KEYSTONE_ONCHIP_CAPTURE", str(cap))
    merged = {
        "imagenet_fv": {"error": "x"},
        "cifar_random_patch": {
            "featurize_images_per_sec_device": 5.0,
            "truncated": "child deadline before end-to-end fit",
        },
    }
    adopted = bench._adopt_captured_legs(
        merged, ["imagenet_fv", "cifar_random_patch"])
    assert adopted == ["cifar_random_patch"]
    assert merged["cifar_random_patch"]["end_to_end_fit_s"] == 42.0
    assert merged["cifar_random_patch"]["adopted_from_capture"][
        "this_run"].startswith("truncated:")
    assert "error" in merged["imagenet_fv"]


def test_bench_headline_adoption_is_disclosed(monkeypatch, capsys, tmp_path):
    """When timit_exact fails live but a capture supplies it, the
    headline value comes from the capture — and the artifact must say so
    at the top level (headline_from_capture), not only inside the leg."""
    import json

    import bench

    monkeypatch.chdir(tmp_path)
    cap = tmp_path / "h_onchip_bench.json"
    cap.write_text(json.dumps({
        "platform": "tpu",
        "timit_exact": {"fit_ms": 250.0, "shape": [2_200_000, 1024, 138]},
    }) + "\n")
    monkeypatch.setenv("KEYSTONE_ONCHIP_CAPTURE", str(cap))

    monkeypatch.setattr(bench, "_probe_backend",
                        lambda env, timeout_s=120: (True, "PROBE_OK tpu 1"))
    monkeypatch.setattr(
        bench, "_run_child",
        _fake_child_factory("tpu", fail_workloads=("timit_exact",)))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 250.0
    assert out["headline_from_capture"] is True
    assert "timit_exact" in out["workloads_from_capture"]
    assert out["timit_exact"]["adopted_from_capture"]["source"] == str(cap)


def test_child_deadline_helpers(monkeypatch):
    """_child_deadline_left / _deadline_within: unset -> no deadline;
    set -> counts down from process start; margin comparison inclusive
    of the boundary side that must truncate."""
    import bench

    monkeypatch.delenv("KEYSTONE_BENCH_CHILD_DEADLINE", raising=False)
    assert bench._child_deadline_left() is None
    assert bench._deadline_within(1e9) is False

    # Far-future deadline: plenty left, nothing within a small margin.
    monkeypatch.setenv("KEYSTONE_BENCH_CHILD_DEADLINE", "1000000")
    left = bench._child_deadline_left()
    assert left is not None and left > 900_000
    assert bench._deadline_within(60.0) is False

    # Already-expired deadline (negative: expired before process start
    # regardless of how recently this process imported bench).
    monkeypatch.setenv("KEYSTONE_BENCH_CHILD_DEADLINE", "-5")
    assert bench._deadline_within(0.0) is True
    assert bench._deadline_within(60.0) is True
