"""TIMIT speech pipeline (reference: pipelines/speech/TimitPipeline.scala)."""

import numpy as np

from keystone_tpu.evaluation.multiclass import MulticlassClassifierEvaluator
from keystone_tpu.pipelines import timit as t


def small_config(**kw):
    defaults = dict(num_cosines=2, num_cosine_features=256, reg=5.0, num_epochs=1)
    defaults.update(kw)
    return t.TimitConfig(**defaults)


def test_end_to_end_synthetic():
    config = small_config()
    train = t.synthetic_timit(1024, seed=0)
    pipeline = t.build_pipeline(config, train)
    evaluator = MulticlassClassifierEvaluator(t.NUM_CLASSES)
    metrics = evaluator.evaluate(pipeline(train.data), train.labels)
    # 147 classes → chance error ≈ 99.3%; features must do much better.
    assert metrics.total_error < 0.8, metrics.summary()


def test_featurizer_output_width():
    config = small_config(num_cosines=3)
    train = t.synthetic_timit(64, seed=1)
    feats = t.build_featurizer(config)(train.data).get()
    assert np.asarray(feats.data).shape == (64, 3 * 256)


def test_cauchy_variant_runs():
    config = small_config(rf_type="cauchy")
    train = t.synthetic_timit(256, seed=2)
    pipeline = t.build_pipeline(config, train)
    preds = pipeline(train.data).get()
    assert len(np.asarray(preds.data)) >= 256


def test_timit_loader(tmp_path):
    """Features CSV + 1-indexed sparse label files
    (reference: TimitFeaturesDataLoader.scala:326-390)."""
    rng = np.random.default_rng(0)
    for split in ("train", "test"):
        n = 6 if split == "train" else 4
        feats = rng.normal(size=(n, 5))
        np.savetxt(tmp_path / f"{split}.csv", feats, delimiter=",")
        lines = [f"{i + 1} {(i % 3) + 1}" for i in range(n)]
        (tmp_path / f"{split}.lab").write_text("\n".join(lines) + "\n")
    data = t.load_timit(
        str(tmp_path / "train.csv"),
        str(tmp_path / "train.lab"),
        str(tmp_path / "test.csv"),
        str(tmp_path / "test.lab"),
    )
    assert len(data.train.data) == 6 and len(data.test.data) == 4
    np.testing.assert_array_equal(
        np.asarray(data.train.labels.data), np.array([0, 1, 2, 0, 1, 2])
    )
