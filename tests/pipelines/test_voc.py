"""End-to-end VOC SIFT+Fisher workload test on a generated tiny tar
(reference test model: pipelines run on resource tars, VOCLoaderSuite +
the VOCSIFTFisher driver)."""

import io
import tarfile

import numpy as np
import pytest

from keystone_tpu.data.loaders.voc import DEFAULT_NAME_PREFIX
from keystone_tpu.pipelines.voc import SIFTFisherConfig, run

PIL = pytest.importorskip("PIL")
from PIL import Image as PILImage  # noqa: E402


def _noise_jpeg(rng, size=(72, 72)):
    arr = rng.integers(0, 256, size=(size[1], size[0], 3), dtype=np.uint8)
    img = PILImage.fromarray(arr, "RGB")
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=92)
    return buf.getvalue()


def _make_voc_fixture(tmp_path, n_images=6):
    rng = np.random.default_rng(0)
    tar_path = tmp_path / "voc.tar"
    with tarfile.open(tar_path, "w") as tar:
        for i in range(n_images):
            payload = _noise_jpeg(rng)
            info = tarfile.TarInfo(DEFAULT_NAME_PREFIX + f"{i:06d}.jpg")
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
    rows = ["id,class,a,b,filename"]
    for i in range(n_images):
        # alternate between class 1 and classes 2+3
        if i % 2 == 0:
            rows.append(f'{i},1,x,y,"{i:06d}.jpg"')
        else:
            rows.append(f'{i},2,x,y,"{i:06d}.jpg"')
            rows.append(f'{i},3,x,y,"{i:06d}.jpg"')
    labels_path = tmp_path / "labels.csv"
    labels_path.write_text("\n".join(rows) + "\n")
    return str(tar_path), str(labels_path)


def test_voc_sift_fisher_end_to_end(tmp_path):
    tar_path, labels_path = _make_voc_fixture(tmp_path)
    config = SIFTFisherConfig(
        train_location=tar_path,
        test_location=tar_path,
        label_path=labels_path,
        desc_dim=8,
        vocab_size=2,
        num_pca_samples=600,
        num_gmm_samples=600,
        image_size=(64, 64),
        solver_block_size=16,
        reg=1e-2,
    )
    results = run(config)
    aps = results["per_class_ap"]
    assert aps.shape == (20,)
    assert 0.0 <= results["test_map"] <= 1.0
    # train == test here, so the model should rank its own training labels
    # well above chance for the classes that appear
    assert results["test_map"] > 0.1
