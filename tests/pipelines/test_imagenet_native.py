"""Native-resolution ImageNet flow through the Pipeline API.

Round-2 verdict item 7: the ragged path must run inside the workflow
layer (optimizer/autocache/prefix-reuse), not as a host loop beside it.
These tests drive a BucketedDataset of mixed-size synthetic images
through the full dual-branch pipeline built by
``build_native_resolution_pipeline`` and check both behavior (learns the
training set; bucket-major row order preserved) and parity (the
MaskedExtractor op equals the raw masked extractor it wraps).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.data.buckets import (
    bucket_labels,
    bucketize_images,
    to_bucketed_dataset,
)
from keystone_tpu.data.dataset import ArrayDataset, BucketedDataset
from keystone_tpu.ops.images.native import ConcatBuckets, MaskedExtractor
from keystone_tpu.ops.images.sift import SIFTExtractor
from keystone_tpu.ops.util.labels import ClassLabelIndicators
from keystone_tpu.pipelines.imagenet import (
    ImageNetSiftLcsFVConfig,
    build_native_resolution_pipeline,
    top_k_err_percent,
)


def _records(n=12, lo=64, hi=97, seed=0, num_classes=3):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        x, y = int(rng.integers(lo, hi)), int(rng.integers(lo, hi))
        recs.append(
            {
                "image": (rng.random((x, y, 3)) * 255).astype(np.float32),
                "label": int(i % num_classes),
                "filename": f"im{i}",
            }
        )
    return recs


@pytest.fixture(scope="module")
def bucketed():
    buckets = bucketize_images(_records(), granularity=32)
    return buckets, to_bucketed_dataset(buckets), bucket_labels(buckets)


def test_native_resolution_pipeline_end_to_end(bucketed):
    buckets, bd, labels = bucketed
    cfg = ImageNetSiftLcsFVConfig(
        desc_dim=8, vocab_size=3, num_classes=3,
        num_pca_samples=2000, num_gmm_samples=2000, solver_block_size=64,
    )
    train_labels = ClassLabelIndicators(3).apply_batch(ArrayDataset(labels))
    pipe = build_native_resolution_pipeline(cfg, bd, train_labels)
    out = pipe(bd).get()
    if isinstance(out, BucketedDataset):
        out = out.concat()
    pred = np.asarray(out.data)
    assert pred.shape == (len(labels), 3)
    # Mixture-weighted least squares on 12 separable random images should
    # fit the training set exactly.
    assert top_k_err_percent(pred[:, :1], labels) == 0.0


def test_masked_extractor_op_equals_raw_extractor(bucketed):
    buckets, bd, _ = bucketed
    ext = SIFTExtractor(scale_step=2)
    op = MaskedExtractor(ext)
    out = op.apply_batch(bd)
    assert isinstance(out, BucketedDataset)
    for bucket_ds, bucket in zip(out.buckets, buckets):
        desc, valid = ext.apply_arrays_masked(
            jnp.asarray(bucket.images, jnp.float32), jnp.asarray(bucket.dims)
        )
        # Jit fusion can shift a value across the floor(512·d) quantization
        # boundary; ±1 quantization unit is the reference's own tolerance
        # (VLFeatSuite.scala:47-52).
        np.testing.assert_allclose(
            np.asarray(bucket_ds.data["desc"]), np.asarray(desc), atol=1.0
        )
        np.testing.assert_array_equal(
            np.asarray(bucket_ds.data["valid"]), np.asarray(valid)
        )


def test_bucketed_dataset_concat_order(bucketed):
    buckets, bd, labels = bucketed
    # concat is bucket-major: labels built by bucket_labels line up.
    ids = ConcatBuckets().apply_batch(
        bd.map_datasets(
            lambda b: ArrayDataset({"label": b.data["label"]}, b.num_examples)
        )
    )
    np.testing.assert_array_equal(np.asarray(ids.data["label"]), labels)


def test_column_sampler_masked_on_device(bucketed):
    from keystone_tpu.ops.stats.core import ColumnSampler

    buckets, bd, _ = bucketed
    ext = SIFTExtractor(scale_step=2)
    descs = MaskedExtractor(ext).apply_batch(bd)
    samples = ColumnSampler(5, seed=3).apply_batch(descs)
    arr = np.asarray(samples.data)
    assert arr.shape[1] == 128
    # Each bucket contributes ≤ 5·len(bucket); all sampled rows must be real
    # (valid) descriptors — none of the padded zero rows.
    assert arr.shape[0] <= 5 * len(bd)
    norms = np.linalg.norm(arr, axis=1)
    assert (norms > 0).all()


def test_masked_extractor_pipeline_pickles(tmp_path, bucketed):
    """FittedPipeline.save must work with MaskedExtractor in the graph
    (the jit cache is rebuilt lazily after load, never pickled)."""
    import pickle

    buckets, bd, _ = bucketed
    op = MaskedExtractor(SIFTExtractor(scale_step=2))
    _ = op.apply_batch(bd)  # populate the jit cache
    blob = pickle.dumps(op)
    op2 = pickle.loads(blob)
    out = op2.apply_batch(bd)
    assert isinstance(out, BucketedDataset)
    np.testing.assert_allclose(
        np.asarray(out.buckets[0].data["valid"]),
        np.asarray(op.apply_batch(bd).buckets[0].data["valid"]),
    )
