"""End-to-end CIFAR workload tests on synthetic data.

Mirrors the reference's strategy of running full pipelines in local mode
and asserting they learn (reference: RandomPatchCifar's structure; the
suite-level analog of KernelModelSuite's learnability checks).
"""

import numpy as np
import pytest

from keystone_tpu.data.dataset import ArrayDataset
from keystone_tpu.data.loaders.cifar import decode_cifar_bytes
from keystone_tpu.pipelines import cifar


def make_synthetic_cifar(n, seed=0):
    """Class-dependent mean images + noise: trivially learnable."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    protos = rng.normal(size=(10, 32, 32, 3)) * 40 + 128
    images = protos[labels] + rng.normal(size=(n, 32, 32, 3)) * 10
    images = np.clip(images, 0, 255).astype(np.float32)
    return ArrayDataset({"image": images, "label": labels})


def test_cifar_binary_decode_layout():
    # one record: label 7, R plane all 1, G plane all 2, B plane all 3,
    # except R[x=1,y=2] = 9
    rec = np.zeros(1 + 3072, dtype=np.uint8)
    rec[0] = 7
    rec[1 : 1025] = 1
    rec[1025 : 2049] = 2
    rec[2049 :] = 3
    rec[1 + 1 * 32 + 2] = 9
    ds = decode_cifar_bytes(rec.tobytes())
    img = np.asarray(ds.data["image"])[0]
    assert np.asarray(ds.data["label"])[0] == 7
    assert img[0, 0, 0] == 1 and img[0, 0, 1] == 2 and img[0, 0, 2] == 3
    assert img[1, 2, 0] == 9


def test_linear_pixels_learns():
    # n must exceed the 1024 grayscale features for the OLS normal equations
    # to be well-posed (the reference runs this with n=50000).
    train = make_synthetic_cifar(1536)
    pipeline = cifar.build_linear_pixels(train)
    images = ArrayDataset(train.data["image"], train.num_examples)
    from keystone_tpu.evaluation.multiclass import MulticlassClassifierEvaluator

    ev = MulticlassClassifierEvaluator(10).evaluate(pipeline(images), train.data["label"])
    assert ev.total_error < 0.15


@pytest.mark.parametrize("solver", ["block", "kernel", "conv_block"])
def test_random_patch_cifar_learns(solver):
    train = make_synthetic_cifar(192, seed=1)
    config = cifar.RandomCifarConfig(
        num_filters=32,
        patch_steps=4,
        reg=1.0 if solver in ("block", "conv_block") else 1e-4,
        kernel_block_size=64,
        gamma=1e-3,
    )
    images = ArrayDataset(train.data["image"], train.num_examples)
    filters, whitener = cifar.learn_random_patch_filters(images, config, whitener_size=2000)
    assert filters.shape == (32, 6 * 6 * 3)
    pipeline = cifar.build_random_patch(train, config, filters, whitener, solver=solver)
    from keystone_tpu.evaluation.multiclass import MulticlassClassifierEvaluator

    ev = MulticlassClassifierEvaluator(10).evaluate(pipeline(images), train.data["label"])
    assert ev.total_error < 0.2


def _write_cifar_binary(path, ds):
    """Encode a synthetic ArrayDataset back to CIFAR binary records."""
    images = np.asarray(ds.data["image"]).astype(np.uint8)  # (n, 32, 32, 3)
    labels = np.asarray(ds.data["label"]).astype(np.uint8)
    planes = images.transpose(0, 3, 1, 2).reshape(len(labels), -1)  # (n, 3072)
    records = np.concatenate([labels[:, None], planes], axis=1).astype(np.uint8)
    records.tofile(path)


def test_random_patch_cifar_augmented_learns(tmp_path):
    train = make_synthetic_cifar(96, seed=2)
    path = tmp_path / "cifar_train.bin"
    _write_cifar_binary(str(path), train)
    config = cifar.RandomCifarConfig(
        train_location=str(path),
        test_location=str(path),
        num_filters=24,
        patch_steps=4,
        reg=1.0,
        num_random_images_augment=3,
        seed=3,
    )
    results = cifar.run(config, variant="random_patch_augmented")
    assert results["num_augmented_train"] == 96 * 3
    # train == test and the classes are linearly separable prototypes:
    # augmented voting should beat chance (0.9 error) comfortably
    assert results["test_error"] < 0.5
