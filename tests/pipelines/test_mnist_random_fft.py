"""End-to-end MNIST RandomFFT slice (reference: MnistRandomFFT.scala +
README.md:14-28 config). Exercises API, executor, gather, substrate,
block solver, and the evaluator in one pipeline."""

import numpy as np

from keystone_tpu.evaluation.multiclass import MulticlassClassifierEvaluator
from keystone_tpu.pipelines import mnist_random_fft as m


def test_end_to_end_synthetic():
    config = m.MnistRandomFFTConfig(num_ffts=2, block_size=512, reg=10.0)
    train = m.synthetic_mnist(1024, seed=0)
    pipeline = m.build_pipeline(config, train)
    evaluator = MulticlassClassifierEvaluator(m.NUM_CLASSES)
    metrics = evaluator.evaluate(pipeline(train.data), train.labels)
    # Chance is 90% error; the random-FFT features must do far better.
    assert metrics.total_error < 0.5, metrics.summary()


def test_featurizer_output_width():
    config = m.MnistRandomFFTConfig(num_ffts=3)
    train = m.synthetic_mnist(64, seed=1)
    feats = m.build_featurizer(config)(train.data).get()
    # 784 → pad 1024 → 512 per branch, 3 branches
    assert np.asarray(feats.data).shape == (64, 3 * 512)


def test_fit_returns_reusable_pipeline():
    config = m.MnistRandomFFTConfig(num_ffts=1, block_size=512, reg=10.0)
    train = m.synthetic_mnist(512, seed=2)
    pipeline = m.build_pipeline(config, train)
    fitted = pipeline.fit()
    test = m.synthetic_mnist(128, seed=3)
    preds = fitted.apply_batch(test.data)
    assert len(np.asarray(preds.data)) >= 128
