"""Accuracy-parity protocol, executable part (r4 verdict item 3).

The full flagship path — real-JPEG ingest → SIFT/LCS → PCA/GMM/FV →
weighted solve → top-k → evaluator — runs end-to-end on the reference's
OWN committed archives (reference: src/test/resources/images/imagenet/
n15075141.tar + imagenet-test-labels, images/voc/voctest.tar +
voclabels.csv — the same fixtures ImageNetLoaderSuite/VOCLoaderSuite
use), and the encoded Fisher-vector rows for the real ImageNet JPEGs are
pinned as committed regression goldens. The protocol for full-scale
"equal top-5" is docs/ACCURACY.md; these tests are its every-CI
instantiation at committed-fixture scale.
"""

import json
import os

import numpy as np
import pytest

REF = "/root/reference/src/test/resources"
FIXTURES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "fixtures")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference resources not present"
)


def _ref(*parts):
    return os.path.join(REF, *parts)


def test_imagenet_real_tar_flagship_end_to_end():
    """The flagship driver on the reference's real ImageNet archive:
    5 real JPEGs of synset n15075141 (label 12). Exercises real-JPEG
    decode through the full dual-branch encode + 13-class weighted solve
    + top-5; with train == test the true class must be in every top-5."""
    from keystone_tpu.pipelines.imagenet import ImageNetSiftLcsFVConfig, run

    results = run(ImageNetSiftLcsFVConfig(
        train_location=_ref("images", "imagenet"),
        test_location=_ref("images", "imagenet"),
        label_path=_ref("images", "imagenet-test-labels"),
        desc_dim=8,
        vocab_size=2,
        num_pca_samples=400,
        num_gmm_samples=400,
        num_classes=13,
        image_size=(96, 96),
        solver_block_size=32,
        lcs_border=16,
        reg=1e-3,
    ))
    assert results["test_error_percent"] == 0.0, results["test_error_percent"]


def test_voc_real_tar_fit_and_score():
    """The VOC SIFT+Fisher driver on the reference's real voctest.tar
    (10 real photos, 9 distinct classes, one multi-label image — the
    VOCLoaderSuite fixture): fit-and-score must separate the training
    images nearly perfectly at committed-fixture scale. MAP here is a
    REGRESSION number: a drop means the image path's numerics moved."""
    from keystone_tpu.pipelines.voc import SIFTFisherConfig, run

    results = run(SIFTFisherConfig(
        train_location=_ref("images", "voc"),
        test_location=_ref("images", "voc"),
        label_path=_ref("images", "voclabels.csv"),
        desc_dim=8,
        vocab_size=3,
        num_pca_samples=800,
        num_gmm_samples=800,
        image_size=(96, 96),
        solver_block_size=32,
        reg=1e-3,
    ))
    # train == test on 10 images with huge FV width: near-memorization on
    # every class that HAS positives. 11 of the 20 VOC classes are absent
    # from the fixture and contribute AP 0, so the all-class MAP tops out
    # at 9/20 = 0.45 — evaluate over the present classes.
    aps = np.asarray(results["per_class_ap"])
    present = aps > 0.0
    assert present.sum() == 9, aps
    assert float(aps[present].mean()) >= 0.9, aps
    assert results["test_map"] >= 0.4, results


def test_imagenet_real_fv_rows_match_committed_golden():
    """Committed regression golden: the fused streaming encoder's FV rows
    for the 5 REAL ImageNet JPEGs under a fixed seed/config
    (tests/fixtures/imagenet_real_fv_golden.json, generated on the
    8-virtual-device CPU mesh). Tolerances are direction+magnitude (not
    bitwise) so a TPU run passes while a real numeric regression fails —
    the tolerance style of the reference's VLFeatSuite.scala:47-52."""
    from keystone_tpu.data.buckets import bucketize_images
    from keystone_tpu.data.loaders.imagenet import load_imagenet
    from keystone_tpu.pipelines.imagenet import ImageNetSiftLcsFVConfig
    from keystone_tpu.pipelines.imagenet_streaming import StreamingFlagship

    ds = load_imagenet(
        _ref("images", "imagenet"), _ref("images", "imagenet-test-labels"),
        resize=(128, 128),  # one static shape -> one bucket -> stable order
    )
    recs = sorted(ds.collect(), key=lambda r: r["filename"])
    buckets = bucketize_images(recs, granularity=32, max_rows=8)
    assert len(buckets) == 1

    fs = StreamingFlagship(ImageNetSiftLcsFVConfig(
        desc_dim=8, vocab_size=2, seed=0
    ))
    fs.fit_codebooks(
        ({"image": b.images, "dims": b.dims} for b in buckets), per_image=64
    )
    rows = np.asarray(fs.encode_buckets(
        ({"image": b.images, "dims": b.dims} for b in buckets)
    ), np.float64)

    path = os.path.join(FIXTURES, "imagenet_real_fv_golden.json")
    golden = np.asarray(json.load(open(path))["rows"], np.float64)
    assert rows.shape == golden.shape, (rows.shape, golden.shape)
    for i, (got, want) in enumerate(zip(rows, golden)):
        cos = float(got @ want / (np.linalg.norm(got) * np.linalg.norm(want)))
        norm_ratio = float(np.linalg.norm(got) / np.linalg.norm(want))
        assert cos > 0.99, (i, cos)
        assert 0.95 < norm_ratio < 1.05, (i, norm_ratio)
