"""End-to-end text workload tests on synthetic corpora."""

import json

import numpy as np
import pytest

from keystone_tpu.data.loaders.text import load_amazon_reviews
from keystone_tpu.pipelines import stupid_backoff, text


POS_WORDS = ["great", "excellent", "love", "wonderful", "amazing", "perfect"]
NEG_WORDS = ["terrible", "awful", "hate", "broken", "worst", "refund"]
FILLER = ["the", "product", "arrived", "yesterday", "and", "it", "was", "box"]


def make_reviews(n, seed):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        pos = rng.random() < 0.5
        words = list(rng.choice(POS_WORDS if pos else NEG_WORDS, size=4)) + list(
            rng.choice(FILLER, size=6)
        )
        rng.shuffle(words)
        rows.append(
            {"reviewText": " ".join(words), "overall": 5.0 if pos else 1.0}
        )
    return rows


def write_reviews(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_amazon_reviews_pipeline(tmp_path):
    train_p, test_p = tmp_path / "train.json", tmp_path / "test.json"
    write_reviews(train_p, make_reviews(300, 0))
    write_reviews(test_p, make_reviews(80, 1))
    config = text.AmazonReviewsConfig(
        train_location=str(train_p),
        test_location=str(test_p),
        common_features=500,
        num_iters=30,
    )
    res = text.run_amazon(config)
    assert res["metrics"].accuracy > 0.9


def test_newsgroups_pipeline(tmp_path):
    # two tiny fake newsgroups with distinct vocab
    from keystone_tpu.data.loaders.text import NEWSGROUPS_CLASSES

    rng = np.random.default_rng(2)
    for cls, vocab in [
        ("comp.graphics", ["pixel", "render", "opengl", "shader"]),
        ("rec.autos", ["engine", "wheel", "brake", "clutch"]),
    ]:
        for split in ("train", "test"):
            d = tmp_path / split / cls
            d.mkdir(parents=True, exist_ok=True)
            for i in range(30 if split == "train" else 8):
                words = rng.choice(vocab, size=12)
                (d / f"doc{i}.txt").write_text(" ".join(words))
    config = text.NewsgroupsConfig(
        train_location=str(tmp_path / "train"),
        test_location=str(tmp_path / "test"),
        common_features=200,
    )
    res = text.run_newsgroups(config)
    assert res["metrics"].total_error < 0.1


def test_stupid_backoff_pipeline(tmp_path):
    corpus = tmp_path / "corpus.txt"
    corpus.write_text("the cat sat on the mat\nthe cat ate the fish\n")
    res = stupid_backoff.run(stupid_backoff.StupidBackoffConfig(str(corpus), n=3))
    model = res["model"]
    assert model.num_tokens == 11
    # "the" is the most frequent word -> id 0; "cat" follows "the" 2 of 4 times
    np.testing.assert_allclose(model.score((0, 1)), 0.5)
    for s in model.scores.values():
        assert 0.0 <= s <= 1.0


def test_amazon_loader_threshold(tmp_path):
    p = tmp_path / "r.json"
    write_reviews(p, [{"reviewText": "ok", "overall": 4.0}, {"reviewText": "bad", "overall": 2.0}])
    data = load_amazon_reviews(str(p))
    assert data.labels.collect() == [1, 0]
    assert data.data.collect() == ["ok", "bad"]
