"""Streaming flagship: fused per-bucket encode must agree with the
Pipeline-API ops it fuses, and the end-to-end on-device run must learn."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.data.buckets import bucketize_images
from keystone_tpu.pipelines.imagenet import ImageNetSiftLcsFVConfig
from keystone_tpu.pipelines.imagenet_streaming import (
    StreamingFlagship,
    run_flagship_ondevice,
)


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(0)
    recs = [
        {"image": rng.integers(0, 256, (s, s, 3), dtype=np.uint8)}
        for s in (48, 48, 64, 64, 64, 80)
    ]
    buckets = bucketize_images(recs, granularity=16, max_rows=4)
    fs = StreamingFlagship(ImageNetSiftLcsFVConfig(desc_dim=16, vocab_size=4))
    fs.fit_codebooks(
        ({"image": b.images, "dims": b.dims} for b in buckets), per_image=16
    )
    return fs, buckets


def test_encode_buckets_row_count_and_width(fitted):
    fs, buckets = fitted
    rows = fs.encode_buckets(
        ({"image": b.images, "dims": b.dims} for b in buckets)
    )
    n = sum(len(b) for b in buckets)
    # combined width: 2 branches × descDim × 2·vocab
    assert rows.shape == (n, 2 * 16 * 2 * 4)
    assert np.isfinite(rows).all()
    # normalized rows: unit L2 per branch half after final NormalizeRows
    norms = np.linalg.norm(rows, axis=1)
    assert np.all(norms > 0.1) and np.all(norms < 2.1)


def test_encode_matches_unfused_ops(fitted):
    """The fused per-bucket kernel must equal the op-by-op composition
    (MaskedExtractor → PCA project → FisherVector.apply_arrays_masked →
    norms) it replaces."""
    from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
    from keystone_tpu.ops.stats.core import (
        NormalizeRows,
        SignedHellingerMapper,
    )

    fs, buckets = fitted
    b = buckets[0]
    fused = np.asarray(
        fs._encode_bucket(
            jnp.asarray(b.images), jnp.asarray(b.dims),
            fs.codebooks.sift_pca, fs.codebooks.lcs_pca,
        )
    )

    pix, gray, hell, norm = (
        PixelScaler(), GrayScaler(), SignedHellingerMapper(), NormalizeRows()
    )
    x = jnp.asarray(b.images, jnp.float32)
    g = gray.apply_arrays(pix.apply_arrays(x))
    sd, sv = fs._sift.apply_arrays_masked(g, jnp.asarray(b.dims))
    sd = hell.apply_arrays(sd)
    enc = fs.codebooks.sift_fv.apply_arrays_masked(
        sd @ fs.codebooks.sift_pca, sv
    )
    flat = enc.reshape(enc.shape[0], -1)
    expect_sift = np.asarray(
        norm.apply_arrays(hell.apply_arrays(norm.apply_arrays(flat)))
    )
    half = fused.shape[1] // 2
    np.testing.assert_allclose(fused[:, :half], expect_sift, rtol=2e-4,
                               atol=2e-5)


def test_encode_buckets_mesh_sharded_matches_unsharded(fitted):
    """GSPMD data-parallel encode (bucket rows sharded over the mesh's
    data axis, pad rows dropped at the gather) must match the unsharded
    path numerically."""
    from keystone_tpu.parallel.mesh import make_mesh

    fs, buckets = fitted
    mesh = make_mesh(devices=jax.devices()[:4])
    # One bucket shape keeps the GSPMD compile cost bounded on the 1-core
    # CI host; parity on one shape covers the sharding logic.
    sub = buckets[:1]
    plain = fs.encode_buckets(
        ({"image": b.images, "dims": b.dims} for b in sub)
    )
    sharded = fs.encode_buckets(
        ({"image": b.images, "dims": b.dims} for b in sub), mesh=mesh
    )
    np.testing.assert_allclose(sharded, plain, rtol=2e-4, atol=2e-5)


import os


@pytest.mark.skipif(
    not os.path.isdir("/root/reference/src/test/resources"),
    reason="reference fixtures not available",
)
def test_streaming_runner_on_reference_tar():
    """run_native_resolution_streaming over the reference's real
    tar-of-JPEG archive: native sizes, real label map, end-to-end."""
    from keystone_tpu.pipelines.imagenet_streaming import (
        run_native_resolution_streaming,
    )

    cfg = ImageNetSiftLcsFVConfig(
        train_location="/root/reference/src/test/resources/images/imagenet",
        # Reuse the train archive as the held-out split to exercise the
        # test-evaluation path (5 images, same labels).
        test_location="/root/reference/src/test/resources/images/imagenet",
        label_path="/root/reference/src/test/resources/images/imagenet-test-labels",
        desc_dim=8, vocab_size=3, num_classes=13, solver_block_size=64,
    )
    out = run_native_resolution_streaming(cfg)
    assert out["num_train"] == 5
    assert out["fv_dim_combined"] == 2 * 8 * 2 * 3
    assert out["train_top5_err_percent"] <= 100.0
    assert np.isfinite(out["train_top5_err_percent"])
    assert out["num_test"] == 5
    # Test split == train split here, so held-out error must match train.
    assert out["test_top5_err_percent"] == out["train_top5_err_percent"]


def test_save_load_roundtrip_preserves_encoding(fitted, tmp_path):
    """save/load (the streaming FittedPipeline analog) must reproduce
    identical encodings from the restored codebooks."""
    fs, buckets = fitted
    b = buckets[0]
    before = fs.encode_buckets([{"image": b.images, "dims": b.dims}])

    path = str(tmp_path / "flagship.pkl")
    fs.save(path, model={"note": "anything picklable rides along"})
    fs2, model = StreamingFlagship.load(path)
    assert model == {"note": "anything picklable rides along"}
    after = fs2.encode_buckets([{"image": b.images, "dims": b.dims}])
    np.testing.assert_allclose(after, before, rtol=1e-6, atol=1e-7)


def test_flagship_ondevice_learns_planted_classes():
    out = run_flagship_ondevice(
        num_train=64, num_test=16, num_classes=4, image_size=48, batch=16
    )
    # 4 classes, top-5 window ≥ k: must be well below the ~0% chance
    # ceiling — planted templates are separable, so expect near-zero.
    assert out["top5_err_percent"] <= 25.0
    assert out["encode_images_per_sec"] > 0
    assert out["fv_dim_combined"] == 4096


def test_flagship_deadline_truncates_gracefully():
    """A time-budgeted flagship run (deadline_left_fn) stops at a safe
    boundary and returns measured phases with a truncated marker — the
    mechanism that keeps bench children from being SIGKILLed mid-claim."""
    import time

    from keystone_tpu.pipelines.imagenet_streaming import run_flagship_ondevice

    t0 = time.time()
    r = run_flagship_ondevice(
        num_train=48, num_test=16, num_classes=4, image_size=64, batch=16,
        deadline_left_fn=lambda: 0.0,  # already expired: truncate at once
    )
    assert "truncated" in r
    assert "codebook_fit_s" in r  # phase A was still measured
    assert "top5_err_percent" not in r
    assert time.time() - t0 < 120
