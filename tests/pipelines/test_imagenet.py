"""End-to-end ImageNet SIFT+LCS+FV flagship pipeline test on a generated
tiny tar (reference test model: ImageNetLoaderSuite resource tars + the
ImageNetSiftLcsFV driver)."""

import io
import tarfile

import numpy as np
import pytest

from keystone_tpu.pipelines.imagenet import (
    ImageNetSiftLcsFVConfig,
    run,
    top_k_err_percent,
)

PIL = pytest.importorskip("PIL")
from PIL import Image as PILImage  # noqa: E402


def _class_jpeg(rng, mean_rgb, size=(72, 72)):
    base = rng.integers(0, 80, size=(size[1], size[0], 3))
    arr = np.clip(base + np.asarray(mean_rgb), 0, 255).astype(np.uint8)
    img = PILImage.fromarray(arr, "RGB")
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=92)
    return buf.getvalue()


@pytest.fixture
def imagenet_fixture(tmp_path):
    rng = np.random.default_rng(0)
    class_colors = {"n01": (180, 30, 30), "n02": (30, 30, 180)}
    tar_path = tmp_path / "train.tar"
    with tarfile.open(tar_path, "w") as tar:
        for cls, color in class_colors.items():
            for i in range(4):
                payload = _class_jpeg(rng, color)
                info = tarfile.TarInfo(f"{cls}/img{i}.jpg")
                info.size = len(payload)
                tar.addfile(info, io.BytesIO(payload))
    labels_path = tmp_path / "labels.txt"
    labels_path.write_text("n01 0\nn02 1\n")
    return str(tar_path), str(labels_path)


def test_top_k_err_percent():
    pred = np.array([[0, 1], [2, 3], [4, 5]])
    actual = np.array([1, 0, 4])
    assert top_k_err_percent(pred, actual) == pytest.approx(100.0 / 3.0)


def test_imagenet_sift_lcs_fv_end_to_end(imagenet_fixture):
    tar_path, labels_path = imagenet_fixture
    config = ImageNetSiftLcsFVConfig(
        train_location=tar_path,
        test_location=tar_path,
        label_path=labels_path,
        desc_dim=8,
        vocab_size=2,
        num_pca_samples=400,
        num_gmm_samples=400,
        num_classes=10,
        image_size=(64, 64),
        solver_block_size=32,
        lcs_border=16,
        reg=1e-3,
    )
    results = run(config)
    # train == test: the two color classes must separate in the top-5
    assert results["test_error_percent"] <= 50.0
    pipeline = results["pipeline"]
    assert pipeline is not None


def test_native_resolution_run_end_to_end(tmp_path):
    """image_size=None path: mixed-size JPEGs → buckets → masked dual-branch
    featurization → weighted solve, end to end."""
    import io
    import tarfile

    import pytest

    PIL = pytest.importorskip("PIL")
    from PIL import Image as PILImage

    from keystone_tpu.pipelines.imagenet import (
        ImageNetSiftLcsFVConfig,
        run_native_resolution,
    )

    rng = np.random.default_rng(0)

    def jpeg(w, h):
        arr = (rng.random((h, w, 3)) * 255).astype(np.uint8)
        buf = io.BytesIO()
        PILImage.fromarray(arr).save(buf, format="JPEG", quality=95)
        return buf.getvalue()

    tar_path = tmp_path / "shard.tar"
    sizes = [(48, 48), (50, 44), (72, 64), (48, 48), (60, 70), (44, 50)]
    with tarfile.open(tar_path, "w") as tar:
        for i, (w, h) in enumerate(sizes):
            cls = "n01" if i % 2 == 0 else "n02"
            payload = jpeg(w, h)
            info = tarfile.TarInfo(f"{cls}/img{i}.jpg")
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
    (tmp_path / "labels.txt").write_text("n01 0\nn02 1\n")

    config = ImageNetSiftLcsFVConfig(
        train_location=str(tar_path),
        label_path=str(tmp_path / "labels.txt"),
        desc_dim=8,
        vocab_size=2,
        num_classes=2,
        num_pca_samples=2000,
        num_gmm_samples=2000,
        solver_block_size=64,
        image_size=None,
        lcs_stride=8,
    )
    results = run_native_resolution(config)
    assert results["num_train"] == 6
    assert results["num_buckets"] >= 2
    assert 0.0 <= results["train_error_percent"] <= 100.0
