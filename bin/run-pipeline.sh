#!/usr/bin/env bash
# Workload launcher (the analog of reference: bin/run-pipeline.sh).
#
# The reference picks local JVM vs spark-submit and pins OMP_NUM_THREADS
# because OpenBLAS misbehaves at high thread counts
# (reference: bin/run-pipeline.sh:9-55). Here the accelerator runtime is
# JAX/XLA: the script caps OpenMP threads for the native host kernels the
# same way and forwards everything else to the Python CLI.
#
# Usage: bin/run-pipeline.sh <workload> [--flag value ...]
#        KEYSTONE_PLATFORM=cpu KEYSTONE_DEVICES=8 bin/run-pipeline.sh ...
set -euo pipefail

here="$(cd "$(dirname "$0")/.." && pwd)"
# Prefer the installed package (`pip install -e . --no-build-isolation`,
# see pyproject.toml); fall back to source-tree PYTHONPATH so the script
# still works on an uninstalled checkout.
if ! python -c "import keystone_tpu" 2>/dev/null; then
  export PYTHONPATH="$here${PYTHONPATH:+:$PYTHONPATH}"
fi

# Same policy as the reference: min(32, physical cores / 2), because the
# OpenMP host kernels (SIFT/GMM/ingest) oversubscribe past that.
if [[ -z "${OMP_NUM_THREADS:-}" ]]; then
  cores=$(nproc 2>/dev/null || echo 8)
  half=$(( cores / 2 ))
  [[ $half -lt 1 ]] && half=1
  [[ $half -gt 32 ]] && half=32
  export OMP_NUM_THREADS=$half
fi

extra=()
[[ -n "${KEYSTONE_PLATFORM:-}" ]] && extra+=(--platform "$KEYSTONE_PLATFORM")
[[ -n "${KEYSTONE_DEVICES:-}" ]] && extra+=(--device-count "$KEYSTONE_DEVICES")

# ${extra[@]+...} guard: empty-array expansion under set -u aborts on bash < 4.4
exec python -m keystone_tpu ${extra[@]+"${extra[@]}"} "$@"
