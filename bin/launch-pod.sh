#!/usr/bin/env bash
# Per-host pod-slice launcher — the analog of the reference's cluster
# launch recipe (reference: EC2.md:19-29, bin/keystone-ec2.sh): run the
# SAME command on every host of a TPU pod slice and the hosts coordinate
# into one global device mesh. Runbook: docs/MULTIHOST.md.
#
# Cloud TPU pod slice (coordination auto-detected by the JAX runtime):
#   gcloud compute tpus tpu-vm ssh "$TPU_NAME" --worker=all \
#     --command="cd keystone-tpu && bin/launch-pod.sh timit --num-cosines 4"
#
# Manual cluster (no auto-detection — set the coordination triplet):
#   KEYSTONE_COORDINATOR=host0:9911 KEYSTONE_NUM_HOSTS=4 KEYSTONE_HOST_ID=$i \
#     bin/launch-pod.sh <workload> [--flag value ...]
#
# Sanity check first (prints REHEARSAL_OK per host):
#   bin/launch-pod.sh --rehearse
set -euo pipefail

here="$(cd "$(dirname "$0")/.." && pwd)"

if [[ "${1:-}" == "--rehearse" ]]; then
  shift
  # Same installed-vs-source fallback run-pipeline.sh gives every other
  # entry: an uninstalled checkout must still pass the pre-flight check.
  if ! python -c "import keystone_tpu" 2>/dev/null; then
    export PYTHONPATH="$here${PYTHONPATH:+:$PYTHONPATH}"
  fi
  exec python "$here/scripts/multihost_rehearsal.py" "$@"
fi

# run-pipeline.sh handles OMP caps + install-vs-source import; the flag
# below makes the CLI call distributed_init() before any device use.
export KEYSTONE_DISTRIBUTED=1
exec "$here/bin/run-pipeline.sh" "$@"
